"""Benchmark matrix: cell-update rates (MLUPS) against every reference
baseline family, one JSON line per metric (headline first).

Mirrors the reference's measurement ladder (`SingleGPU/RunAll.m:1-17`
plus the per-project `Run.m` timings archived in BASELINE.md), but
machine-captured instead of hand-pasted into Run.m comments.

Unit: MLUPS = cells * iters * RK_stages / seconds (stage-update rate).
The reference's own "GFLOPS" differs per tier — the MultiGPU and
single-GPU *Burgers* conventions include the x3 RK factor, the
single-GPU *Diffusion* one omits it (BASELINE.md footnote 1) — so every
`vs_baseline` below divides by the reference number converted to the
same stage-update MLUPS.

Prints one JSON line per metric:
  {"metric", "value", "unit", "vs_baseline"}

Timing methodology (sync via device→host fetch, fixed overhead
subtracted): see ``multigpu_advectiondiffusion_tpu/bench/timing.py``.
"""

from __future__ import annotations

import json

def _cases(on_tpu: bool):
    """(metric, make_solver, mode, work, baseline, expected) rows. CPU
    mode shrinks the grids — it validates mechanics only (Pallas runs
    interpreted there)."""
    # Reference baselines in stage-update MLUPS — single source of truth
    # is bench/matrix.py BASELINES_MLUPS (derivations in BASELINE.md).
    # Imported here so main() can set the platform before any jax import.
    from multigpu_advectiondiffusion_tpu.bench.matrix import BASELINES_MLUPS

    B_DIFF3D = BASELINES_MLUPS["diffusion3d_multigpu"][0]
    B_DIFF2D = BASELINES_MLUPS["diffusion2d"][0]
    B_BURG3D = BASELINES_MLUPS["burgers3d_512"][0]
    B_BURG2D = BASELINES_MLUPS["burgers2d_multigpu"][0]
    B_ADR3D = BASELINES_MLUPS["adr3d"][0]
    B_ADR2D = BASELINES_MLUPS["adr2d"][0]
    from multigpu_advectiondiffusion_tpu import (
        ADRConfig,
        ADRSolver,
        BurgersConfig,
        BurgersSolver,
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )

    def diff3d_tiled():
        # Reference interior 400x200x206 (~16.5M cells) re-proportioned
        # to exact (8,128) f32 tiles at the same scale: (nz,ny,nx) =
        # (160,204,508) => padded trailing dims (208,512), zero slack.
        g = (
            Grid.make(508, 204, 160, lengths=(12.7, 5.1, 4.0))
            if on_tpu
            else Grid.make(64, 28, 16, lengths=(1.6, 0.7, 0.4))
        )
        return DiffusionSolver(
            DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                            impl="pallas")
        )

    def diff3d_ref_grid():
        # The literal MultiGPU north-star interior, NOT tile-aligned
        # (padded trailing dims carry slack) — reported next to the
        # headline so the number is not best-case-only.
        g = (
            Grid.make(400, 200, 206, lengths=(10.0, 5.0, 5.15))
            if on_tpu
            else Grid.make(50, 25, 26, lengths=(1.0, 0.5, 0.52))
        )
        return DiffusionSolver(
            DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                            impl="pallas")
        )

    def diff2d():
        # SingleGPU Diffusion2d ladder grid (1001^2).
        g = (
            Grid.make(1001, 1001, lengths=20.0)
            if on_tpu
            else Grid.make(65, 65, lengths=2.0)
        )
        return DiffusionSolver(
            DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                            impl="pallas")
        )

    def burg3d(adaptive: bool):
        def make():
            # SingleGPU Burgers3d_WENO5 512^3 config: WENO5-JS, viscous
            # nu=1e-5 (main.cpp:56-59). adaptive=False reproduces the
            # reference's hard-coded unit wave speed (main.c:193);
            # adaptive=True is the physically-correct default.
            g = (
                Grid.make(512, 512, 512, lengths=2.0)
                if on_tpu
                else Grid.make(24, 16, 16, lengths=2.0)
            )
            return BurgersSolver(
                BurgersConfig(grid=g, nu=1e-5, dtype="float32",
                              adaptive_dt=adaptive, impl="pallas")
            )

        return make

    def burg3d_grid(nx, ny, nz):
        def make():
            # The other two published single-GPU viscous-Burgers
            # workloads (SingleGPU/Burgers3d_WENO5/Run.m:3-13 slab,
            # :27-37 wide), literal grids.
            g = (
                Grid.make(nx, ny, nz, lengths=2.0)
                if on_tpu
                else Grid.make(max(16, nx // 64), max(12, ny // 64),
                               max(8, nz // 8), lengths=2.0)
            )
            return BurgersSolver(
                BurgersConfig(grid=g, nu=1e-5, dtype="float32",
                              adaptive_dt=False, impl="pallas")
            )

        return make

    def burg2d():
        # MultiGPU Burgers2d interior 400x406 (Run.m:4-14), here on one
        # chip via the whole-run VMEM stepper (fixed dt, CUDA parity).
        g = (
            Grid.make(400, 406, lengths=2.0)
            if on_tpu
            else Grid.make(40, 46, lengths=2.0)
        )
        return BurgersSolver(
            BurgersConfig(grid=g, dtype="float32", adaptive_dt=False,
                          impl="pallas")
        )

    def burg3d_multigpu():
        # The reference's MultiGPU Burgers3d headline config
        # (Burgers3d_Baseline/Run.m:4-14): interior 400x400x406 run as
        # 400x400x408 (matrix.py's TPU-friendly z rounding), fixed dt
        # (the CUDA drivers' hard-coded wave speed), on one chip via the
        # fused stepper. x = 400 interior lanes pad to 512 — the same
        # lane tax as the literal diffusion grid.
        g = (
            Grid.make(400, 400, 408, lengths=2.0)
            if on_tpu
            else Grid.make(24, 16, 16, lengths=2.0)
        )
        return BurgersSolver(
            BurgersConfig(grid=g, dtype="float32", adaptive_dt=False,
                          impl="pallas")
        )

    def diff3d_f64():
        # The literal MultiGPU interior (400x200x206, same grid as
        # diff3d_ref_grid) in the reference's own precision (USE_FLOAT
        # false, DiffusionMPICUDA.h:66) — the apples-to-apples row
        # against its 731 MLUPS. Since the slab-run round this rides the
        # fused 3-D path through the f64-storage/f32-compute convention
        # (state at f64, kernels f32 — Mosaic has no f64 vector path;
        # accuracy priced in PARITY.md) instead of falling to
        # generic-xla. Runs under a scoped enable_x64 (see main()).
        g = (
            Grid.make(400, 200, 206, lengths=(10.0, 5.0, 5.15))
            if on_tpu
            else Grid.make(50, 25, 26, lengths=(1.0, 0.5, 0.52))
        )
        return DiffusionSolver(
            DiffusionConfig(grid=g, diffusivity=1.0, dtype="float64",
                            impl="pallas")
        )

    def burg2d_weno7():
        # The 2-D order-7 rung on the MultiGPU Burgers2d workload: the
        # halo-4 whole-run VMEM stepper (LFWENO7FDM2d.m is MATLAB-only,
        # never benchmarked; the anchor is the 2-D order-5 baseline).
        g = (
            Grid.make(400, 406, lengths=2.0)
            if on_tpu
            else Grid.make(40, 46, lengths=2.0)
        )
        return BurgersSolver(
            BurgersConfig(grid=g, weno_order=7, dtype="float32",
                          adaptive_dt=False, impl="pallas")
        )

    def burg3d_weno7():
        # The order-7 rung of the fused family at the flagship 512^3
        # viscous workload (halo-4 kernels). The reference's WENO7 is
        # MATLAB-only (LFWENO7FDM3d.m, never benchmarked); the baseline
        # anchor is its order-5 rate on the same grid.
        g = (
            Grid.make(512, 512, 512, lengths=2.0)
            if on_tpu
            else Grid.make(24, 16, 16, lengths=2.0)
        )
        return BurgersSolver(
            BurgersConfig(grid=g, weno_order=7, nu=1e-5, dtype="float32",
                          adaptive_dt=False, impl="pallas")
        )

    def burg3d_axis():
        # The per-axis Pallas rung at 512^3 — the explicit non-fused
        # ladder rung (the reference benches its non-winning variants
        # too, SingleGPU/RunAll.m).
        g = (
            Grid.make(512, 512, 512, lengths=2.0)
            if on_tpu
            else Grid.make(24, 16, 16, lengths=2.0)
        )
        return BurgersSolver(
            BurgersConfig(grid=g, nu=1e-5, dtype="float32",
                          adaptive_dt=False, impl="pallas_axis")
        )

    def adr3d():
        # the title workload (ISSUE 15): variable-K advection–
        # diffusion–reaction on the fused per-stage rung — same
        # tile-aligned grid class as the diffusion headline
        g = (
            Grid.make(508, 204, 160, lengths=(12.7, 5.1, 4.0))
            if on_tpu
            else Grid.make(64, 28, 16, lengths=(1.6, 0.7, 0.4))
        )
        return ADRSolver(
            ADRConfig(grid=g, dtype="float32", impl="pallas",
                      velocity=0.5, kappa_variation=0.2,
                      reaction_rate=0.25)
        )

    def adr2d():
        # 2-D ADR rides the generic rung (the fused ADR kernel is 3-D
        # only); the row pins that expectation so a future fused 2-D
        # rung shows up as an engagement change, not silently
        g = (
            Grid.make(1001, 1001, lengths=20.0)
            if on_tpu
            else Grid.make(65, 65, lengths=2.0)
        )
        return ADRSolver(
            ADRConfig(grid=g, dtype="float32", impl="xla",
                      velocity=0.5, kappa_variation=0.2,
                      reaction_rate=0.25)
        )

    it = (lambda n: n) if on_tpu else (lambda n: min(n, 4))
    # rows: (metric, make_solver, mode, work, baseline, expected) where
    # mode is "iters" (fixed-count run) or "t_end" (the drivers' native
    # `while t < tEnd` loop; work = equivalent fixed-dt step count) and
    # expected is the set of stepper labels this config may legitimately
    # engage (grids/VMEM budgets differ between CPU smoke mode and TPU,
    # so slab-vs-stage may flip; a silent fall to generic-xla or
    # per-axis-pallas is NEVER legitimate for a fused row and fails the
    # run loudly — the engagement guard, see main()).
    SLAB_OR_STAGE = {"fused-whole-run-slab", "fused-stage"}
    return [
        # ~1 s windows for the 3-D diffusion rows: at ~0.5 s the captured
        # headline sat 15-18% below repeated local runs on tunnel-shared
        # HBM (r3 artifact vs ROUND3.md) — the longer window narrows the
        # band the driver can land in
        ("diffusion3d_mlups", diff3d_tiled, "iters", it(1010), B_DIFF3D,
         SLAB_OR_STAGE),
        ("diffusion3d_ref_grid_mlups", diff3d_ref_grid, "iters", it(606),
         B_DIFF3D, SLAB_OR_STAGE),
        # 20000 iters (~500 ms): the whole-run VMEM stepper finishes 2000
        # in ~50 ms, inside the tunnel's sync-overhead noise band
        # (measured 44k-112k MLUPS run to run at 6000); the window must
        # dwarf the per-call sync jitter for the median to be stable
        ("diffusion2d_mlups", diff2d, "iters", it(20000), B_DIFF2D,
         {"fused-whole-run"}),
        # 60 iters (~2.7 s window): at 20 the per-call dispatch overhead
        # still shaved ~1% off the steady-state rate
        ("burgers3d_mlups", burg3d(False), "iters", it(60), B_BURG3D,
         SLAB_OR_STAGE),
        ("burgers3d_adaptive_mlups", burg3d(True), "iters", it(60), B_BURG3D,
         {"fused-stage"}),
        # the drivers' native t_end mode must run at the fused rate
        # (VERDICT r2 item 1) — captured, not claimed
        ("burgers3d_tend_mlups", burg3d(False), "t_end", it(60), B_BURG3D,
         {"fused-stage"}),
        ("burgers3d_slab_mlups", burg3d_grid(1601, 986, 35), "iters",
         it(60), BASELINES_MLUPS["burgers3d_slab"][0], SLAB_OR_STAGE),
        ("burgers3d_wide_mlups", burg3d_grid(1000, 1000, 200), "iters",
         it(30), BASELINES_MLUPS["burgers3d_wide"][0], SLAB_OR_STAGE),
        # 24000 iters: the 2-D whole-run stepper clears ~30k MLUPS, so
        # the 600-iter window was ~10 ms — pure sync-jitter; ~400 ms
        # makes the median trustworthy
        ("burgers2d_mlups", burg2d, "iters", it(24000), B_BURG2D,
         {"fused-whole-run"}),
        # the reference's MultiGPU 3-D Burgers headline workload — the
        # last published config not driver-captured
        ("burgers3d_multigpu_mlups", burg3d_multigpu, "iters", it(60),
         BASELINES_MLUPS["burgers3d_multigpu"][0], SLAB_OR_STAGE),
        # the reference's own precision (f64) on its literal grid, and
        # the per-axis ladder rung — previously measured but living only
        # in PARITY/README prose (VERDICT r3 item 3b): now driver-captured
        ("diffusion3d_f64_mlups", diff3d_f64, "iters", it(31),
         BASELINES_MLUPS["diffusion3d_multigpu_f64"][0], SLAB_OR_STAGE),
        ("burgers3d_axis_mlups", burg3d_axis, "iters", it(15),
         BASELINES_MLUPS["burgers3d_512_axis"][0], {"per-axis-pallas"}),
        # ~30 iters x 3 stages at ~4.7k MLUPS => ~2.5 s window
        ("burgers3d_weno7_mlups", burg3d_weno7, "iters", it(30),
         BASELINES_MLUPS["burgers3d_512_weno7"][0], SLAB_OR_STAGE),
        # 12000 iters (~0.9 s at ~6.2k MLUPS): the 2-D window rule —
        # whole-run calls must dwarf the per-call sync jitter
        ("burgers2d_weno7_mlups", burg2d_weno7, "iters", it(12000),
         BASELINES_MLUPS["burgers2d_weno7"][0], {"fused-whole-run"}),
        # the title ADR workload (ISSUE 15): 3-D on the fused per-stage
        # rung (engagement-guarded like every fused row), 2-D on the
        # generic rung; baselines are the nearest published diffusion
        # anchors — the reference never shipped ADR (matrix.py note)
        ("adr3d_mlups", adr3d, "iters", it(404), B_ADR3D,
         {"fused-stage"}),
        ("adr2d_mlups", adr2d, "iters", it(2000), B_ADR2D,
         {"generic-xla"}),
    ]


def _ensemble_cases(on_tpu: bool):
    """Batched-ensemble rows (ISSUE 9): (family, make_case) where
    make_case() -> (solver_cls, cfg, iters, member_fn). Each family is
    measured at B in {1, 8, 64} as ONE vmapped dispatch vs the looped
    single-run baseline (same compiled single program dispatched B
    times) — MLUPS*members against MLUPS*members."""
    from multigpu_advectiondiffusion_tpu import (
        BurgersConfig,
        BurgersSolver,
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )

    def diff3d():
        g = (
            Grid.make(256, 128, 64, lengths=(6.4, 3.2, 1.6))
            if on_tpu
            else Grid.make(16, 12, 10, lengths=(1.6, 1.2, 1.0))
        )
        cfg = DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                              impl="pallas", ic="gaussian")
        # member-varying ICs (a width sweep): the parameter-sweep
        # workload, physics uniform so the fused rung engages
        member = lambda i: {  # noqa: E731
            "ic_params": (("width", 0.1 + 0.002 * i),)
        }
        return DiffusionSolver, cfg, (60 if on_tpu else 4), member

    def burg3d():
        g = (
            Grid.make(128, 64, 64, lengths=2.0)
            if on_tpu
            else Grid.make(16, 8, 8, lengths=2.0)
        )
        cfg = BurgersConfig(grid=g, nu=1e-5, dtype="float32",
                            adaptive_dt=False, impl="pallas")
        member = lambda i: {  # noqa: E731
            "ic_params": (("width", 0.1 + 0.002 * i),)
        }
        return BurgersSolver, cfg, (30 if on_tpu else 4), member

    def diff3d_xla():
        # the generic rung under batching in the many-small-problems
        # regime (per-user scenarios; HipBone's batched-small-FEM
        # argument, PAPERS arXiv 2202.12477): per-member programs small
        # enough that launch/dispatch overhead is a real fraction of a
        # run — the regime where one batched dispatch amortizes most
        g = (
            Grid.make(64, 48, 32, lengths=(6.4, 4.8, 3.2))
            if on_tpu
            else Grid.make(12, 10, 8, lengths=(1.2, 1.0, 0.8))
        )
        cfg = DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                              impl="xla", ic="gaussian")
        member = lambda i: {  # noqa: E731
            "ic_params": (("width", 0.1 + 0.002 * i),)
        }
        return DiffusionSolver, cfg, (60 if on_tpu else 2), member

    # impl="pallas" families may legitimately land on EITHER fused
    # batched shape — the per-stage vmap or (since ISSUE 11) the
    # B-folded slab grid: grids/VMEM budgets differ between CPU smoke
    # mode and TPU, so the profitability pick flips like the single-run
    # SLAB_OR_STAGE rows. Generic-xla is still never legitimate here.
    FUSED_BATCH = {
        "ensemble-vmap[fused-stage]",
        "ensemble-fold[fused-whole-run-slab]",
    }
    return [
        ("ensemble_diffusion3d", diff3d, FUSED_BATCH),
        ("ensemble_burgers3d", burg3d, FUSED_BATCH),
        ("ensemble_diffusion3d_xla", diff3d_xla,
         {"ensemble-vmap[generic-xla]"}),
    ]


def _wall_timed(fn, reps: int = 3):
    """Raw wall seconds (median-of-reps, first call untimed warm-up) —
    the ensemble rows compare WHOLE dispatches including their launch
    overhead, because amortizing that overhead is the point."""
    import statistics
    import time

    from multigpu_advectiondiffusion_tpu.bench.timing import sync

    sync(fn())  # compile + warm-up
    times = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        sync(fn())
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    spread = (max(times) - min(times)) / med if med > 0 else 0.0
    return med, spread


def _ensemble_rows(on_tpu: bool):
    """One row per (family, B): MLUPS*members of the batched dispatch,
    with the looped single-run baseline measured on the SAME compiled
    single program (compile excluded from both sides — the batched win
    reported here is dispatch/streaming amortization, not compile; the
    compile-amortization story is the AOT cache's, gated separately in
    out/ensemble_gate.sh)."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.models.state import SolverState
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    rows = []
    for family, make_case, expect in _ensemble_cases(on_tpu):
        solver_cls, cfg, iters, member_fn = make_case()
        for B in (1, 8, 64):
            es = EnsembleSolver(
                solver_cls, cfg, [member_fn(i) for i in range(B)]
            )
            est = es.initial_state()
            batched_s, spread = _wall_timed(
                lambda: es.run(est, iters).u, reps=3
            )
            # looped baseline: ONE single-run solver (compile paid
            # once, outside the timing) dispatched B times over the
            # same member initial states
            single = es.member_solver(0)

            def looped():
                outs = [
                    single.run(
                        SolverState(u=est.u[i], t=est.t[i],
                                    it=est.it[i]),
                        iters,
                    ).u
                    for i in range(B)
                ]
                return jnp.stack(outs)

            looped_s, looped_spread = _wall_timed(looped, reps=3)
            engaged = es.engaged_path()
            rate = mlups(
                cfg.grid.num_cells * B, iters,
                STAGES[cfg.integrator], batched_s,
            )
            looped_rate = mlups(
                cfg.grid.num_cells * B, iters,
                STAGES[cfg.integrator], looped_s,
            )
            row = {
                "metric": f"{family}_b{B}_mlups_members",
                "value": round(rate, 2),
                "unit": "MLUPS*members",
                "ensemble": B,
                "iters": iters,
                "seconds": round(batched_s, 5),
                "spread": round(spread, 4),
                "looped_mlups_members": round(looped_rate, 2),
                "looped_seconds": round(looped_s, 5),
                "looped_spread": round(looped_spread, 4),
                # the amortization headline: batched throughput over
                # the looped single-run baseline
                "vs_looped": round(looped_s / batched_s, 3)
                if batched_s > 0 else None,
                "engaged": engaged["stepper"],
                # member-placement provenance (ISSUE 11): single-device
                # rows carry 1/1 so the bench gate reads one convention
                "member_sharding": engaged.get("member_sharding", 1),
                "devices": engaged.get("devices", 1),
                "mesh": engaged.get("mesh"),
                "tuned": engaged.get("tuned"),
            }
            ok = engaged["stepper"] in expect
            if not ok:
                row["engagement_error"] = {
                    "expected": sorted(expect),
                    "fallback": engaged.get("fallback"),
                }
            rows.append((row, ok))
    return rows


def _ensemble_mesh_rows(on_tpu: bool):
    """Mesh-scale ensemble rows (ISSUE 11): a B=64 uniform-physics
    diffusion ensemble dispatched through ``impl="auto"`` on the
    8-device 'members' mesh. The tuner MEASURES the batched candidate
    space (generic vmap / fused-stage vmap / B-folded slab) at the
    actual B and the row records its decision; the engagement guard
    fails the row if the dispatch silently fell back to the
    single-device path (devices == 1) or the decision was not
    measured. Emits nothing when fewer than 8 devices exist."""
    import jax
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.models.state import (
        EnsembleState,
        SolverState,
    )
    from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    if len(jax.devices()) < 8:
        return []
    # the dispatch-bound many-small-problems regime at its sharpest
    # (one step per request — the serving shape): per-member work is
    # small enough that launch overhead dominates the looped baseline,
    # which is exactly what one batched mesh dispatch amortizes
    g = (
        Grid.make(64, 48, 32, lengths=(6.4, 4.8, 3.2))
        if on_tpu
        else Grid.make(8, 8, 10, lengths=(0.8, 0.8, 1.0))
    )
    cfg = DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                          impl="auto", ic="gaussian")
    iters = 60 if on_tpu else 1
    B = 64
    mesh = make_mesh({"members": 8})
    member = lambda i: {  # noqa: E731
        "ic_params": (("width", 0.1 + 0.002 * i),)
    }
    es = EnsembleSolver(DiffusionSolver, cfg,
                        [member(i) for i in range(B)], mesh=mesh)
    est = es.initial_state()
    batched_s, spread = _wall_timed(lambda: es.run(est, iters).u, reps=3)
    single = es.member_solver(0)
    # the looped baseline follows the _ensemble_rows convention
    # EXACTLY (r06's 5.95x was measured this way: per-member states
    # sliced from the batched state inside the timed loop) — but from
    # an UNSHARDED copy staged outside the timing, so the baseline is
    # never billed for cross-device gathers off the member-sharded
    # array
    import numpy as _np

    est_host = EnsembleState(
        u=jnp.asarray(_np.asarray(est.u)),
        t=jnp.asarray(_np.asarray(est.t)),
        it=jnp.asarray(_np.asarray(est.it)),
    )

    def looped():
        outs = [
            single.run(
                SolverState(u=est_host.u[i], t=est_host.t[i],
                            it=est_host.it[i]),
                iters,
            ).u
            for i in range(B)
        ]
        return jnp.stack(outs)

    looped_s, looped_spread = _wall_timed(looped, reps=3)
    engaged = es.engaged_path()
    rate = mlups(cfg.grid.num_cells * B, iters,
                 STAGES[cfg.integrator], batched_s)
    looped_rate = mlups(cfg.grid.num_cells * B, iters,
                        STAGES[cfg.integrator], looped_s)
    row = {
        "metric": f"ensemble_diffusion3d_mesh_b{B}_mlups_members",
        "value": round(rate, 2),
        "unit": "MLUPS*members",
        "ensemble": B,
        "iters": iters,
        "seconds": round(batched_s, 5),
        "spread": round(spread, 4),
        "looped_mlups_members": round(looped_rate, 2),
        "looped_seconds": round(looped_s, 5),
        "looped_spread": round(looped_spread, 4),
        "vs_looped": round(looped_s / batched_s, 3)
        if batched_s > 0 else None,
        "engaged": engaged["stepper"],
        "member_sharding": engaged.get("member_sharding", 1),
        "devices": engaged.get("devices", 1),
        "mesh": engaged.get("mesh"),
        "tuned": engaged.get("tuned"),
    }
    # engagement guard, mesh edition: a batched row built on a mesh
    # that silently fell back to the single-device path — or whose
    # impl="auto" decision came from anything but measurement at this
    # B — is a mislabeled rate and fails the run loudly
    ok = True
    if row["devices"] < 8 or row["member_sharding"] < 8:
        row["engagement_error"] = {
            "fell_back_to_single_device": {
                "devices": row["devices"],
                "member_sharding": row["member_sharding"],
            }
        }
        ok = False
    elif (engaged.get("tuned") or {}).get("source") not in (
        "measured", "cache"
    ):
        row["engagement_error"] = {
            "decision_not_measured": engaged.get("tuned")
        }
        ok = False
    return [(row, ok)]


def _serving_rows(on_tpu: bool):
    """Request-serving rows (ISSUE 17): requests/sec and latency
    percentiles of the coalesced request server (``service/server.py``,
    one batched EnsembleSolver dispatch per slice) against a sequential
    ``max_batch=1`` server answering the SAME B=8 mixed-width diffusion
    request set. Both rounds run warm (an unmeasured round per
    configuration pays the compiles first) and without journal fsync,
    so the row measures serving mechanics — coalescing vs per-request
    dispatch — not disk latency. On CPU this is a mechanics-grade
    number; the coalesced-beats-sequential guard still applies because
    dispatch amortization is exactly what the tiny-grid regime shows."""
    import os
    import shutil
    import tempfile
    import time

    from multigpu_advectiondiffusion_tpu.service.requests import (
        RequestSpec,
        submit_request_to_spool,
    )
    from multigpu_advectiondiffusion_tpu.service.server import (
        RequestServer,
    )

    B = 8
    n = [64, 64] if on_tpu else [16, 16]
    # horizon in steps, not wall time: the diffusion family starts at
    # its config t0 with a grid-dependent stability dt — derive both so
    # every request marches the same ~3 slices regardless of grid
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig as _DCfg,
        DiffusionSolver as _DSolver,
        Grid as _Grid,
    )

    _probe_cfg = _DCfg(grid=_Grid.make(*n), dtype="float32", impl="xla")
    t_end = float(_probe_cfg.t0) + 24 * float(_DSolver(_probe_cfg).dt)

    def _round(root, max_batch):
        os.makedirs(root, exist_ok=True)
        rids = []
        for i in range(B):
            rid = f"bench-{max_batch}-{i}"
            submit_request_to_spool(root, RequestSpec(
                request_id=rid, model="diffusion", n=list(n),
                t_end=t_end, dtype="float32", ic="gaussian",
                ic_params={"width": 0.08 + 0.01 * i},
            ))
            rids.append(rid)
        srv = RequestServer(root, max_batch=max_batch, slice_steps=8,
                            fsync=False)
        t0 = time.perf_counter()
        out = srv.serve(until_idle=True, poll_seconds=0.001)
        wall = time.perf_counter() - t0
        srv.close()
        # max queue depth comes from the server's own exported gauge
        # watermark (the snapshot the close() above just published)
        max_depth = None
        g = srv.metrics.gauges.get("serve_queue_depth")
        if g is not None and g.max is not None:
            max_depth = int(g.max)
        lat = []
        for rid in rids:
            p = os.path.join(root, "requests", rid, "result.json")
            if os.path.exists(p):
                with open(p) as fh:
                    s = json.load(fh)
                if s.get("seconds") is not None:
                    lat.append(s["seconds"] * 1000.0)
        occ = []
        ev = os.path.join(root, "serve_events.jsonl")
        if os.path.exists(ev):
            with open(ev) as fh:
                for line in fh:
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if (e.get("kind") == "serve"
                            and e.get("name") == "slice"
                            and e.get("occupancy") is not None):
                        occ.append(e["occupancy"])
        done = (out.get("states") or {}).get("done", 0)
        return wall, sorted(lat), occ, done, max_depth

    work = tempfile.mkdtemp(prefix="tpucfd_bench_serve_")
    try:
        # warm round per configuration: pays the B=8 and B=1 compiles
        _round(os.path.join(work, "warm_coal"), B)
        _round(os.path.join(work, "warm_seq"), 1)
        coal_s, lat, occ, coal_done, max_depth = _round(
            os.path.join(work, "coalesced"), B
        )
        seq_s, seq_lat, _, seq_done, _ = _round(
            os.path.join(work, "sequential"), 1
        )
    finally:
        shutil.rmtree(work, ignore_errors=True)

    # one quantile codepath (ISSUE 18): latencies go through the shared
    # fixed-log-boundary histogram — the SAME estimator the fleet's
    # merged snapshots and tpucfd-status report, so a bench row and a
    # dashboard never disagree about what "p99" means
    from multigpu_advectiondiffusion_tpu.telemetry.metrics import (
        Histogram,
    )

    def _pct(ms_values, q):
        h = Histogram("bench_latency_ms")
        for v in ms_values:
            h.observe(v)
        est = h.quantile(q)
        return round(est, 3) if est is not None else None

    row = {
        "metric": f"serving_diffusion2d_b{B}_rps",
        "value": round(B / coal_s, 2) if coal_s > 0 else None,
        "unit": "req/s",
        "requests": B,
        "seconds": round(coal_s, 5),
        "p50_ms": _pct(lat, 0.50),
        "p95_ms": _pct(lat, 0.95),
        "p99_ms": _pct(lat, 0.99),
        "max_queue_depth": max_depth,
        "occupancy": round(sum(occ) / len(occ), 4) if occ else None,
        "sequential_seconds": round(seq_s, 5),
        "sequential_p50_ms": _pct(seq_lat, 0.50),
        "vs_sequential": round(seq_s / coal_s, 3) if coal_s > 0 else None,
        "ensemble": B,
    }
    # serving guard: every request must be answered in both rounds, and
    # the coalesced round must beat the sequential one at B=8 — a server
    # whose batching lost to per-request dispatch is a mislabeled row
    ok = coal_done == B and seq_done == B
    if not ok:
        row["engagement_error"] = {
            "unanswered": {"coalesced_done": coal_done,
                           "sequential_done": seq_done,
                           "expected": B}
        }
    elif not row["vs_sequential"] or row["vs_sequential"] <= 1.0:
        row["engagement_error"] = {
            "coalescing_lost_to_sequential": {
                "coalesced_seconds": row["seconds"],
                "sequential_seconds": row["sequential_seconds"],
            }
        }
        ok = False
    return [(row, ok)]


def _serving_pipelined_rows(on_tpu: bool):
    """Zero-copy pipelined serving rows (ISSUE 19): the SAME B=8
    mixed-width diffusion request set served twice by the coalesced
    server — once synchronous (``pipeline=False``, the ISSUE 17 loop)
    and once pipelined (``pipeline=True``: donated state buffers,
    dispatch-ahead depth 2, non-blocking finished-lane publish) — one
    row per mode with req/s, p50/p99 latency and the measured
    device-idle fraction (``serve_device_idle_fraction``, 1 - busy/wall
    per dissolved batch). Both rounds run warm and without fsync. On
    CPU this is a mechanics-grade number (the overlap hides *host*
    work — publish, journal, health-stat collection — behind dispatch;
    there is no device to keep busy), so the guard checks engagement
    (every request answered in both modes, the pipelined round actually
    dispatched ahead), not a speedup ratio — the on/off perf regression
    gate is ``out/serving_perf_gate.sh``."""
    import os
    import shutil
    import tempfile
    import time

    from multigpu_advectiondiffusion_tpu.service.requests import (
        RequestSpec,
        submit_request_to_spool,
    )
    from multigpu_advectiondiffusion_tpu.service.server import (
        RequestServer,
    )

    B = 8
    n = [64, 64] if on_tpu else [16, 16]
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig as _DCfg,
        DiffusionSolver as _DSolver,
        Grid as _Grid,
    )

    _probe_cfg = _DCfg(grid=_Grid.make(*n), dtype="float32", impl="xla")
    t_end = float(_probe_cfg.t0) + 24 * float(_DSolver(_probe_cfg).dt)

    def _round(root, pipeline):
        os.makedirs(root, exist_ok=True)
        rids = []
        for i in range(B):
            rid = f"bench-pl{int(pipeline)}-{i}"
            submit_request_to_spool(root, RequestSpec(
                request_id=rid, model="diffusion", n=list(n),
                t_end=t_end, dtype="float32", ic="gaussian",
                ic_params={"width": 0.08 + 0.01 * i},
            ))
            rids.append(rid)
        srv = RequestServer(root, max_batch=B, slice_steps=8,
                            fsync=False, pipeline=pipeline,
                            pipeline_depth=2)
        t0 = time.perf_counter()
        out = srv.serve(until_idle=True, poll_seconds=0.001)
        wall = time.perf_counter() - t0
        srv.close()
        lat = []
        for rid in rids:
            p = os.path.join(root, "requests", rid, "result.json")
            if os.path.exists(p):
                with open(p) as fh:
                    s = json.load(fh)
                if s.get("seconds") is not None:
                    lat.append(s["seconds"] * 1000.0)
        idle = srv.metrics.histograms.get("serve_device_idle_fraction")
        idle_frac = (round(idle.mean(), 4)
                     if idle is not None and idle.count else None)
        stall = srv.metrics.histograms.get(
            "serve_pipeline_stall_seconds"
        )
        overlap = srv.metrics.histograms.get(
            "serve_pipeline_overlap_fraction"
        )
        disp = srv.metrics.counters.get(
            "serve_pipeline_dispatches_total"
        )
        done = (out.get("states") or {}).get("done", 0)
        return {
            "wall": wall,
            "lat": sorted(lat),
            "done": done,
            "idle_frac": idle_frac,
            "stall_s": (round(stall.sum, 5)
                        if stall is not None and stall.count else None),
            "overlap": (round(overlap.mean(), 4)
                        if overlap is not None and overlap.count
                        else None),
            "dispatches": disp.value if disp is not None else 0,
        }

    work = tempfile.mkdtemp(prefix="tpucfd_bench_pipe_")
    try:
        # warm round per mode: pays the B=8 compile (donated and
        # undonated executables key separately in the dispatch cache)
        _round(os.path.join(work, "warm_sync"), False)
        _round(os.path.join(work, "warm_pipe"), True)
        sync = _round(os.path.join(work, "sync"), False)
        pipe = _round(os.path.join(work, "pipelined"), True)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    from multigpu_advectiondiffusion_tpu.telemetry.metrics import (
        Histogram,
    )

    def _pct(ms_values, q):
        h = Histogram("bench_latency_ms")
        for v in ms_values:
            h.observe(v)
        est = h.quantile(q)
        return round(est, 3) if est is not None else None

    rows = []
    for mode, r in (("sync", sync), ("pipelined", pipe)):
        row = {
            "metric": f"serving_diffusion2d_b{B}_{mode}_rps",
            "value": (round(B / r["wall"], 2)
                      if r["wall"] > 0 else None),
            "unit": "req/s",
            "requests": B,
            "seconds": round(r["wall"], 5),
            "p50_ms": _pct(r["lat"], 0.50),
            "p99_ms": _pct(r["lat"], 0.99),
            "device_idle_frac": r["idle_frac"],
            "pipeline": mode == "pipelined",
            "ensemble": B,
        }
        if mode == "pipelined":
            row["pipeline_depth"] = 2
            row["stall_seconds"] = r["stall_s"]
            row["overlap_fraction"] = r["overlap"]
            row["vs_sync"] = (round(sync["wall"] / r["wall"], 3)
                              if r["wall"] > 0 else None)
        ok = r["done"] == B
        if not ok:
            row["engagement_error"] = {
                "unanswered": {"done": r["done"], "expected": B}
            }
        elif mode == "pipelined" and r["dispatches"] <= 0:
            # a "pipelined" row whose loop never dispatched ahead is a
            # mislabeled synchronous row
            row["engagement_error"] = {"pipeline_never_engaged": {
                "dispatches": r["dispatches"],
            }}
            ok = False
        rows.append((row, ok))
    return rows


def main() -> None:
    import os
    import sys
    import tempfile

    from multigpu_advectiondiffusion_tpu.utils.platform_env import (
        honor_platform_env,
    )

    honor_platform_env()
    # the mesh-scale ensemble rows need a device mesh: CPU rounds get
    # the test suite's 8 virtual devices (a real TPU topology provides
    # its own); must land before the first jax import initializes the
    # backend
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    # telemetry rides every bench run: the stream is the forensic record
    # an engagement-guard failure prints (see the tail dump below) — a
    # degraded/fell-back row is diagnosable from the bench output alone.
    # TPUCFD_BENCH_METRICS overrides the default tempfile destination.
    from multigpu_advectiondiffusion_tpu import telemetry

    metrics_path = os.environ.get("TPUCFD_BENCH_METRICS") or os.path.join(
        tempfile.gettempdir(), f"bench_telemetry_{os.getpid()}.jsonl"
    )
    sink = telemetry.install(metrics_path)

    # measured dispatch rides every bench run: rows built with
    # impl="auto" (the multichip scaling rows) may measure their
    # (rung x steps_per_exchange) candidates on a cache miss and
    # persist the decision — the tune:* events land in the same stream
    from multigpu_advectiondiffusion_tpu import tuning

    tuning.configure(enabled=True)
    if jax.default_backend() == "cpu":
        # CPU mechanics rounds: smoke-grade measurement cost for the
        # batched candidate races (interpret-mode Pallas candidates at
        # B=64 would otherwise dominate the round); env overrides win
        tuning.configure(
            measure_iters=int(os.environ.get("TPUCFD_TUNE_ITERS", "4")),
            measure_reps=int(os.environ.get("TPUCFD_TUNE_REPS", "2")),
        )

    from multigpu_advectiondiffusion_tpu.bench.timing import (
        timed_advance,
        timed_run,
    )
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import STAGES
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    from jax.experimental import enable_x64

    on_tpu = jax.default_backend() != "cpu"
    mismatches = []
    for metric, make_solver, mode, work, baseline, expect in _cases(on_tpu):
        # x64 scoped per row (jax.experimental.enable_x64 — the
        # top-level alias was removed): a process-wide flip would poison
        # the f32 Pallas rows' Mosaic lowering with i64 constants
        with enable_x64(metric.endswith("_f64_mlups")):
            solver = make_solver()
            state = solver.initial_state()
            if mode == "t_end":
                # fixed-dt equivalent of `work` steps, landing exactly —
                # the solver's own fixed dt, not a re-derivation of its
                # formula (which would silently diverge for solvers whose
                # fixed dt is not cfl*min(spacing), e.g. diffusion)
                dt = solver.dt
                assert dt is not None, f"{metric}: t_end rows need fixed dt"
                adv = timed_advance(solver, state, work * dt, reps=5)
                timing, iters = adv.timing, adv.steps
            else:
                timing = timed_run(solver, state, work, reps=5)
                iters = work
        # median-of-5 with the observed spread AND discarded-stall count
        # recorded: the artifact is self-qualifying, and a tunnel stall
        # can no longer sit inside the median (VERDICT r3 weak item 1)
        rate = mlups(
            solver.grid.num_cells, iters, STAGES[solver.cfg.integrator],
            timing.median_seconds,
        )
        # the artifact records which kernel path actually ran — a row
        # that silently fell back to the generic path would say so
        # instead of publishing a mislabeled rate
        engaged = solver.engaged_path(
            "t_end" if mode == "t_end" else "iters"
        )
        # roofline efficiency of the measured rate on the engaged rung's
        # static bytes/FLOPs model (telemetry/costmodel): the row says
        # how close to the hardware roof it ran, not just how fast
        from multigpu_advectiondiffusion_tpu.telemetry import costmodel

        cost = costmodel.summarize_run(
            solver, engaged["stepper"], iters, timing.median_seconds
        )
        # measured introspection beside the modeled columns: the
        # compiled executable's own XLA-reported per-step flops/bytes
        # and peak-footprint estimate (telemetry/xprof; None when no
        # executable was captured). Coverage-checked but non-gating in
        # bench/compare.py — measurement provenance, not a pass bar.
        from multigpu_advectiondiffusion_tpu.telemetry import xprof

        meas = xprof.measured_summary(
            solver, iters, timing.median_seconds
        ) or {}
        row = {
            "metric": metric,
            "value": round(rate, 2),
            "unit": "MLUPS",
            "vs_baseline": round(rate / baseline, 3),
            "spread": round(timing.spread, 4),
            "outliers": timing.outliers,
            # pre-filter dispersion incl. discarded stalls, so
            # the artifact keeps the full evidence (ADVICE r4)
            "raw_spread": round(timing.raw_spread, 4),
            "engaged": engaged["stepper"],
            # comm-avoiding exchange cadence + tuner provenance: a row
            # whose configuration was MEASURED into place says so, and
            # says what the tuner picked (ISSUE 4)
            "steps_per_exchange": engaged.get("steps_per_exchange", 1),
            # halo transport actually engaged (collective ppermute vs
            # in-kernel remote DMA) — sharded rows only ever publish
            # the transport that really ran (ISSUE 13)
            "exchange": engaged.get("exchange", "collective"),
            "tuned": engaged.get("tuned"),
            "roofline_pct": (cost or {}).get("roofline_pct"),
            # measured XLA columns (per step; peak_bytes = executable
            # footprint estimate) beside the modeled roofline_pct
            "xla_flops": meas.get("xla_flops_per_step"),
            "xla_bytes": meas.get("xla_bytes_per_step"),
            "peak_bytes": meas.get("peak_bytes"),
            # single-run rows carry the member count explicitly so the
            # bench gate reads one convention across rounds (older
            # rounds without the field read as 1 — bench/compare.py)
            "ensemble": 1,
        }
        # engagement guard: a row running on an unexpected (slower)
        # stepper is recorded AND fails the run — a silent fallback to
        # generic-xla/per-axis-pallas must not just publish a slow rate.
        # A run that DEGRADED off its requested rung mid-measurement
        # (resilience ladder: Mosaic failure -> lower rung) fails the
        # bench the same way even when the landing rung is in `expect`:
        # the row would otherwise silently record the slower rung's rate
        # under the headline metric name.
        if engaged["stepper"] not in expect or engaged.get("degraded"):
            row["engagement_error"] = {
                "expected": sorted(expect),
                "fallback": engaged["fallback"],
                "degraded": engaged.get("degraded"),
            }
            mismatches.append(metric)
        # tuned-regression guard: a tuner-selected configuration that
        # lands BELOW the reference baseline (BASELINE.md) is a silent
        # regression dressed up as a decision — fail the run, don't
        # just publish it (TPU rows only; CPU mode validates mechanics)
        elif on_tpu and engaged.get("tuned") and rate < baseline:
            row["engagement_error"] = {
                "tuned_below_baseline": {
                    "baseline_mlups": baseline,
                    "tuned": engaged.get("tuned"),
                }
            }
            mismatches.append(metric)
        print(json.dumps(row), flush=True)

    # Multi-chip strong-scaling rows: engage automatically whenever the
    # live topology has > 1 device (the reference's headline artifact is
    # measured 2-GPU scaling, MultiGPU/Diffusion3d_Baseline/Run.m:4-13);
    # a single chip emits nothing. Mechanics are CPU-mesh tested
    # (tests/test_cli.py), so the first real multi-chip session
    # produces scaling numbers with zero new code.
    from multigpu_advectiondiffusion_tpu.bench.scaling import scaling_rows

    for row in scaling_rows(on_tpu=on_tpu):
        # the multichip rows dispatch through impl="auto": the tuner's
        # measured (rung, steps_per_exchange) must not silently regress
        # below the reference's published multi-GPU rate
        if on_tpu and row.get("tuned") and row["vs_baseline"] < 1.0:
            row["engagement_error"] = {
                "tuned_below_baseline": row.get("tuned")
            }
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    # In-kernel halo exchange head-to-head (ISSUE 13): the dma rung vs
    # the split-overlap collective rung, pinned, on the 2-way z-slab
    # mesh (the reference's own 2-GPU artifact shape). A dma row that
    # SILENTLY degraded off the in-kernel transport fails the run; a
    # config that declined loudly (e.g. no dma-capable backend) is
    # recorded as declined, not failed.
    from multigpu_advectiondiffusion_tpu.bench.scaling import (
        exchange_head_to_head_rows,
    )

    for row in exchange_head_to_head_rows(on_tpu=on_tpu):
        if row.get("engagement_error"):
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    # Batched-ensemble rows (ISSUE 9): MLUPS*members of one vmapped
    # dispatch vs the looped single-run baseline at B in {1, 8, 64} —
    # engagement-guarded like every other row (a row that silently fell
    # off the vmapped fused rung fails the run, it does not just
    # publish a slow amortization ratio)
    for row, ok in _ensemble_rows(on_tpu):
        if not ok:
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    # Mesh-scale ensemble row (ISSUE 11): B=64 on the 8-device members
    # mesh through impl="auto" — the tuner measures the batched
    # candidate space at the actual B; the guard fails a row that fell
    # back to one device or served an unmeasured decision
    for row, ok in _ensemble_mesh_rows(on_tpu):
        if not ok:
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    # Request-serving rows (ISSUE 17): requests/sec, latency
    # percentiles and batch occupancy of the coalesced request server
    # vs a sequential max_batch=1 server over the same request set —
    # guarded on every request being answered and on coalescing
    # actually beating sequential dispatch at B=8
    for row, ok in _serving_rows(on_tpu):
        if not ok:
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    # Pipelined-serving head-to-head (ISSUE 19): the same request set
    # served synchronous vs pipelined (donated buffers, dispatch-ahead,
    # async publish) — one row per mode with req/s, p50/p99 and the
    # measured device-idle fraction; engagement-guarded on every
    # request answered and the pipeline actually dispatching ahead
    for row, ok in _serving_pipelined_rows(on_tpu):
        if not ok:
            mismatches.append(row["metric"])
        print(json.dumps(row), flush=True)

    if mismatches:
        # forensic dump: the tail of the run's telemetry event stream
        # (dispatch builds, ladder degrades, spans) so a degraded or
        # fell-back row is diagnosable from the bench artifact alone
        print(
            f"engagement guard tripped; last telemetry events "
            f"(full stream: {metrics_path}):",
            file=sys.stderr,
        )
        for ev in sink.tail(30):
            print(json.dumps(ev), file=sys.stderr)
        raise SystemExit(
            "engagement guard: unexpected stepper for "
            + ", ".join(mismatches)
        )


if __name__ == "__main__":
    main()
