"""Headline benchmark: 3-D diffusion cell-update rate (MLUPS) on one chip.

Mirrors the reference's north-star measurement — the 4th-order 13-point
Laplacian + SSP-RK3 hot loop of ``MultiGPU/Diffusion3d_Baseline``
(401×201×207 including reference halo, 101 iters, 5.87 "GFLOPS" on
2 GPUs ≈ 731 MLUPS total, ``Run.m:4-13``; derivation in BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Timing methodology (sync via device→host fetch, fixed overhead
subtracted): see ``multigpu_advectiondiffusion_tpu/bench/timing.py``.
"""

from __future__ import annotations

import json


BASELINE_MLUPS = 731.0  # MultiGPU Diffusion3d, 2 GPUs total (BASELINE.md)


def main() -> None:
    from multigpu_advectiondiffusion_tpu.utils.platform_env import (
        honor_platform_env,
    )

    honor_platform_env()
    from multigpu_advectiondiffusion_tpu.bench.timing import timed_run
    from multigpu_advectiondiffusion_tpu import DiffusionConfig, DiffusionSolver, Grid
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import STAGES
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    # Reference interior grid 400x200x206 (z,y,x) = (206,200,400),
    # ~16.5M cells, re-proportioned to TPU tile sizes at the same scale:
    # (nz,ny,nx) = (160,204,508) => padded trailing dims (208, 512) are
    # exact (8,128) f32 tiles (zero slack traffic), 16.58M cells.
    # Double precision in the reference, f32 here (the framework's TPU
    # dtype policy, core/dtypes.py). MLUPS is per-cell-update, so the
    # slight size difference does not bias the rate.
    grid = Grid.make(508, 204, 160, lengths=(12.7, 5.1, 4.0))
    cfg = DiffusionConfig(grid=grid, diffusivity=1.0, dtype="float32",
                          impl="pallas")
    solver = DiffusionSolver(cfg)
    state = solver.initial_state()

    # 5x the reference's 101 iters: at ~18 Gsteps/s the 101-iter net time
    # (~55 ms) is the same order as the tunnel's per-fetch sync overhead
    # (~100 ms), so the subtraction is noise-dominated; MLUPS is a rate,
    # unaffected by the count. On CPU (mechanics validation only — the
    # Pallas kernels run in interpret mode there) a handful suffices.
    import jax

    iters = 505 if jax.default_backend() != "cpu" else 5
    elapsed = timed_run(solver, state, iters).seconds
    rate = mlups(grid.num_cells, iters, STAGES[cfg.integrator], elapsed)
    print(
        json.dumps(
            {
                "metric": "diffusion3d_mlups",
                "value": round(rate, 2),
                "unit": "MLUPS",
                "vs_baseline": round(rate / BASELINE_MLUPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
