#!/usr/bin/env bash
# Soak the distributed chaos suite: loop the 2-process kill/stall/torn-
# checkpoint tests N times (default 5) and fail on ANY flake — the
# recovery paths must be deterministic, not merely usually-working.
#
#   ./out/soak_resilience.sh        # 5 rounds of the fast chaos suite
#   ./out/soak_resilience.sh 20     # longer soak
#   SOAK_SLOW=1 ./out/soak_resilience.sh 3   # include the slow soak test
#   BENCH_GATE=1 ./out/soak_resilience.sh    # also run the bench
#                                   # regression-gate self-test after
#   SCIENCE_GATE=1 ./out/soak_resilience.sh  # also run the science
#                                   # regression-gate self-test after
#   LINT_GATE=1 ./out/soak_resilience.sh     # also run the static-
#                                   # analysis gate (clean tree +
#                                   # rule selftests) after
#   SERVE_GATE=1 ./out/soak_resilience.sh    # also run the request-
#                                   # serving kill/replay gate and its
#                                   # selftest after (out/serve_gate.sh)
#   DRAIN_GATE=1 ./out/soak_resilience.sh    # also run the SIGTERM-
#                                   # drain handover gate and its
#                                   # selftest after (out/drain_gate.sh)
#
# Runs on the virtual CPU backend (no TPU needed), same as tier-1.
set -euo pipefail
N="${1:-5}"
cd "$(dirname "$0")/.."

MARKER="chaos and not slow"
if [[ "${SOAK_SLOW:-0}" == "1" ]]; then
  MARKER="chaos"
fi

for i in $(seq 1 "$N"); do
  echo "=== soak_resilience: round $i/$N ==="
  JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
    -m "$MARKER" -p no:cacheprovider -p no:randomly \
    || { echo "soak_resilience: FLAKE in round $i/$N" >&2; exit 1; }
done
echo "soak_resilience: $N round(s) clean"

if [[ "${BENCH_GATE:-0}" == "1" ]]; then
  # close the loop on the bench trajectory too: the regression gate's
  # self-test (trips on an injected 20% slowdown, passes the newest
  # unmodified round) — see out/bench_gate.sh
  JAX_PLATFORMS=cpu ./out/bench_gate.sh --selftest
fi

if [[ "${SCIENCE_GATE:-0}" == "1" ]]; then
  # and on the numerics: the science gate's self-test (trips on an
  # injected 2% diffusivity perturbation, passes an unmodified round)
  # — see out/science_gate.sh
  JAX_PLATFORMS=cpu ./out/science_gate.sh --selftest
fi

if [[ "${LINT_GATE:-0}" == "1" ]]; then
  # and on the invariants: tpucfd-check clean-tree pass + every rule's
  # seeded-violation selftest + the halo verifier's injected
  # off-by-one — see out/lint_gate.sh
  JAX_PLATFORMS=cpu ./out/lint_gate.sh
fi

if [[ "${SERVE_GATE:-0}" == "1" ]]; then
  # and on the request server: its assertion teeth (dropped-request +
  # torn-spool fixtures), then the SIGKILL-mid-batch kill/replay gate
  # — see out/serve_gate.sh
  JAX_PLATFORMS=cpu ./out/serve_gate.sh --selftest
  JAX_PLATFORMS=cpu ./out/serve_gate.sh
fi

if [[ "${DRAIN_GATE:-0}" == "1" ]]; then
  # and on the graceful handover: the gate's assertion teeth (injected
  # double-serve with the lease disabled + dropped in-flight request),
  # then the live SIGTERM-drain + successor exactly-once proof
  # — see out/drain_gate.sh
  JAX_PLATFORMS=cpu ./out/drain_gate.sh --selftest
  JAX_PLATFORMS=cpu ./out/drain_gate.sh
fi
