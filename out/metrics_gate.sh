#!/usr/bin/env bash
# Fleet-metrics gate (ISSUE 18): the observability layer's end-to-end
# chaos proof, runnable in CI.
#
# 1. Kill/replay gate: submit 4 requests (one with an SLO deadline),
#    start the server with frequent metric exports, SIGKILL it after
#    it has BOTH marched a slice and published a snapshot, then
#    restart it --until-idle and assert:
#      (a) the pre-kill snapshot is still parseable (atomic publish —
#          a SIGKILL between writes can never tear it),
#      (b) the merged union across BOTH incarnation snapshot dirs
#          reports every request exactly once: the request-lifecycle
#          counters (received/done/failed/shed/requeued) reconcile
#          bit-for-bit against the counters the replay adapter
#          derives from the journal + event stream, and the latency
#          histogram is bucket-identical between the two feeds,
#      (c) every metrics.prom parses as Prometheus text and the
#          done_total samples sum to the journal's done count,
#      (d) `tpucfd-status --once --json` renders a populated frame.
#    Slice/occupancy counters are deliberately NOT reconciled across
#    a SIGKILL: increments between the dead life's last export and
#    the kill are correctly absent from its final snapshot.
# 2. `--selftest`: proves the gate's assertions have teeth — after a
#    healthy round passes the check, a corrupted metrics.json, a
#    stale snapshot (wall_time rewound past the freshness bound) and
#    a missing snapshot dir must each trip it nonzero.
#
#   ./out/metrics_gate.sh             # the kill/replay gate
#   ./out/metrics_gate.sh --selftest  # corrupt/stale/missing proofs
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

CLI=(python -m multigpu_advectiondiffusion_tpu.cli)
REQ=(request --model diffusion --n 12 12 --ic gaussian)

# The gate's core assertion, shared with --selftest: the merged
# snapshot union must be fresh, complete and bit-for-bit consistent
# with what the replay adapter derives from the journalled streams.
check_root() {
    python - "$1" <<'PY'
import json, os, sys, time

from multigpu_advectiondiffusion_tpu.telemetry import metrics as M

root = sys.argv[1]
merged = M.merge_snapshot_dirs(os.path.join(root, "metrics"))
assert not merged["skipped"], \
    f"corrupted snapshot(s) skipped: {merged['skipped']}"
assert merged["snapshots"] >= 1, "no metrics snapshots published"
age = time.time() - merged["wall_time"]
assert age < 600.0, f"stale snapshot: newest is {age:.0f}s old"

records = [json.loads(l) for l in open(os.path.join(
    root, "journal.jsonl")) if l.strip()]
recs = [r.get("record", r) for r in records]
journal_done = {r["job"] for r in recs if r.get("type") == "state"
                and r.get("to") == "done"}

replay = M.registry_from_streams([root])
derived = {k: c.value for k, c in replay.counters.items()}
lifecycle = ("serve_requests_received_total",
             "serve_requests_done_total",
             "serve_requests_failed_total",
             "serve_requests_shed_total",
             "serve_requests_requeued_total")
for key in lifecycle:
    live = merged["counters"].get(key, 0)
    rep = derived.get(key, 0)
    assert live == rep, f"{key}: merged snapshot {live} != replayed {rep}"
assert merged["counters"].get("serve_requests_done_total", 0) \
    == len(journal_done), \
    f"done counter {merged['counters'].get('serve_requests_done_total')}" \
    f" != journal's {len(journal_done)} done requests"

lat = M.snapshot_histogram(merged, "serve_request_latency_seconds")
rep_lat = replay.histograms.get("serve_request_latency_seconds")
assert lat is not None and rep_lat is not None, "no latency histogram"
assert lat.counts == rep_lat.counts, \
    "latency histogram buckets diverge between snapshot and replay"

prom_done = 0.0
for proc in sorted(os.listdir(os.path.join(root, "metrics"))):
    text = open(os.path.join(root, "metrics", proc,
                             "metrics.prom")).read()
    samples = M.parse_prometheus(text)
    prom_done += samples.get("tpucfd_serve_requests_done_total", 0.0)
assert prom_done == len(journal_done), \
    f"prometheus done samples sum to {prom_done}, " \
    f"journal says {len(journal_done)}"
print(f"metrics_gate: check OK — {merged['snapshots']} snapshots, "
      f"{len(journal_done)} requests counted exactly once")
PY
}

if [[ "${1:-}" == "--selftest" ]]; then
    echo "metrics_gate: selftest — a healthy round must pass first"
    ROOT="$TMP/self"
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id s1 --t-end 0.15
    "${CLI[@]}" serve-requests --root "$ROOT" --until-idle \
        --max-batch 2 --slice-steps 4 --poll 0.02 --metrics-every 0.01
    check_root "$ROOT"
    SNAP="$(ls -d "$ROOT"/metrics/server-*)"
    cp "$SNAP/metrics.json" "$TMP/metrics.json.good"

    echo "metrics_gate: selftest 1 — a corrupted snapshot must trip"
    head -c 40 "$TMP/metrics.json.good" > "$SNAP/metrics.json"
    if check_root "$ROOT" > "$TMP/corrupt.out" 2>&1; then
        echo "metrics_gate: SELFTEST FAILED — corrupted metrics.json" \
             "passed the gate" >&2
        exit 1
    fi
    grep -qi "corrupt" "$TMP/corrupt.out" || {
        echo "metrics_gate: SELFTEST FAILED — wrong trip reason:" >&2
        cat "$TMP/corrupt.out" >&2
        exit 1
    }
    echo "metrics_gate: selftest 1 OK — corruption tripped the gate"

    echo "metrics_gate: selftest 2 — a stale snapshot must trip"
    python - "$TMP/metrics.json.good" "$SNAP/metrics.json" <<'PY'
import json, sys
snap = json.load(open(sys.argv[1]))
snap["wall_time"] -= 1.0e6  # rewind past the freshness bound
open(sys.argv[2], "w").write(json.dumps(snap))
PY
    if check_root "$ROOT" > "$TMP/stale.out" 2>&1; then
        echo "metrics_gate: SELFTEST FAILED — stale snapshot passed" \
             "the gate" >&2
        exit 1
    fi
    grep -qi "stale" "$TMP/stale.out" || {
        echo "metrics_gate: SELFTEST FAILED — wrong trip reason:" >&2
        cat "$TMP/stale.out" >&2
        exit 1
    }
    echo "metrics_gate: selftest 2 OK — staleness tripped the gate"

    echo "metrics_gate: selftest 3 — a missing snapshot dir must trip"
    rm -rf "$ROOT/metrics"
    if check_root "$ROOT" > "$TMP/missing.out" 2>&1; then
        echo "metrics_gate: SELFTEST FAILED — missing snapshots" \
             "passed the gate" >&2
        exit 1
    fi
    grep -qi "no metrics snapshots" "$TMP/missing.out" || {
        echo "metrics_gate: SELFTEST FAILED — wrong trip reason:" >&2
        cat "$TMP/missing.out" >&2
        exit 1
    }
    echo "metrics_gate: selftest 3 OK — missing snapshots tripped" \
         "the gate"
    echo "metrics_gate: selftest PASS"
    exit 0
fi

ROOT="$TMP/root"
echo "metrics_gate: submitting 4 requests (one with an SLO deadline)"
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r1 --t-end 0.5 \
    --ic-param width=0.08
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r2 --t-end 0.5 \
    --ic-param width=0.10
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r3 --t-end 0.4 \
    --priority 5
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r4 --t-end 0.45 \
    --deadline 300

echo "metrics_gate: server up; waiting for a marched slice AND a" \
     "published snapshot"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02 --metrics-every 0.05 \
    > "$TMP/server1.out" 2>&1 &
SERVER=$!
for _ in $(seq 1 2400); do
    if grep -q '"slice"' "$ROOT/serve_events.jsonl" 2> /dev/null \
        && ls "$ROOT"/metrics/server-*/metrics.json > /dev/null 2>&1
    then
        break
    fi
    if ! kill -0 "$SERVER" 2> /dev/null; then
        echo "metrics_gate: server exited before the kill window:" >&2
        cat "$TMP/server1.out" >&2
        exit 1
    fi
    sleep 0.05
done
ls "$ROOT"/metrics/server-*/metrics.json > /dev/null 2>&1 || {
    echo "metrics_gate: server never published a snapshot" >&2
    exit 1
}

echo "metrics_gate: SIGKILL the server mid-batch (pid $SERVER)"
kill -9 "$SERVER"
wait "$SERVER" 2> /dev/null || true

echo "metrics_gate: the pre-kill snapshot must still parse"
python - "$ROOT" <<'PY'
import glob, os, sys

from multigpu_advectiondiffusion_tpu.telemetry import metrics as M

root = sys.argv[1]
snaps = sorted(glob.glob(os.path.join(root, "metrics", "server-*")))
assert len(snaps) == 1, f"want 1 pre-kill incarnation dir, got {snaps}"
snap = M.load_snapshot(os.path.join(snaps[0], "metrics.json"))
samples = M.parse_prometheus(
    open(os.path.join(snaps[0], "metrics.prom")).read())
assert snap["counters"].get("serve_requests_received_total") == 4
assert samples["tpucfd_serve_requests_received_total"] == 4
print("metrics_gate: pre-kill snapshot parses — "
      f"{len(snap['counters'])} counters intact")
PY

echo "metrics_gate: restart — the union across both lives must" \
     "reconcile"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02 --metrics-every 0.05
"${CLI[@]}" serve-requests --root "$ROOT" --verify --require-complete

check_root "$ROOT"

echo "metrics_gate: tpucfd-status --once --json must be populated"
"${CLI[@]}" status --root "$ROOT" --once --json > "$TMP/status.json"
python - "$TMP/status.json" <<'PY'
import json, sys

frame = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert frame["requests"].get("done") == 4, frame["requests"]
assert frame["metrics"]["snapshots"] >= 2, \
    f"want snapshots from both lives: {frame['metrics']['snapshots']}"
assert frame["metrics"]["counters"]["serve_requests_done_total"] == 4
assert "serve_request_latency_seconds" in frame["quantiles"]
print("metrics_gate: status frame populated — "
      f"{frame['metrics']['snapshots']} snapshots, 4 done")
PY
echo "metrics_gate: PASS"
