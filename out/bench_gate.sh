#!/usr/bin/env bash
# Measured bench regression gate: diff a fresh bench artifact against
# the newest archived round (BENCH_r0*.json) with bench/compare.py's
# per-row noise thresholds; nonzero exit on any regression.
#
#   ./out/bench_gate.sh NEW.json          # gate NEW against newest round
#   ./out/bench_gate.sh NEW.json PRIOR    # explicit prior round
#   ./out/bench_gate.sh --selftest        # prove the gate trips on a
#                                         # synthetic 20% slowdown AND
#                                         # passes the unmodified round
set -euo pipefail
cd "$(dirname "$0")/.."

newest_round() {
  ls BENCH_r0*.json 2>/dev/null | sort | tail -1
}

if [[ "${1:-}" == "--selftest" ]]; then
  PRIOR="$(newest_round)"
  [[ -n "$PRIOR" ]] || { echo "bench_gate: no BENCH_r0*.json to self-test against" >&2; exit 1; }
  TMP="$(mktemp -d)"
  trap 'rm -rf "$TMP"' EXIT
  # inject a 20% throughput regression into the round's LOWEST-spread
  # value row: a row whose own measured noise already covers 20% (CPU
  # mechanics-grade rounds have such rows) would legitimately absorb
  # the injection — the selftest must prove the gate trips where a
  # real 20% loss would be a real regression
  python - "$PRIOR" "$TMP/slowed.json" <<'PY'
import json, sys
from multigpu_advectiondiffusion_tpu.bench.compare import (
    load_rows, row_spread,
)
rows = list(load_rows(sys.argv[1]).values())
assert rows, "no rows parsed from the prior round"
victims = sorted(
    (r for r in rows if "value" in r), key=row_spread
)
assert victims, "no value row to slow down"
victim = victims[0]
assert 2 * row_spread(victim) < 0.20, (
    "even the quietest row's noise threshold covers 20%: "
    f"{victim['metric']} spread {row_spread(victim)}"
)
victim["value"] = round(victim["value"] * 0.8, 2)  # -20%
with open(sys.argv[2], "w") as f:
    f.write("\n".join(json.dumps(r) for r in rows) + "\n")
PY
  echo "bench_gate selftest: unmodified round must PASS"
  python -m multigpu_advectiondiffusion_tpu.bench.compare "$PRIOR" "$PRIOR"
  echo "bench_gate selftest: injected 20% slowdown must FAIL"
  if python -m multigpu_advectiondiffusion_tpu.bench.compare "$TMP/slowed.json" "$PRIOR"; then
    echo "bench_gate selftest: gate FAILED to trip on a 20% regression" >&2
    exit 1
  fi
  echo "bench_gate selftest: OK (gate trips on -20%, passes unmodified)"
  exit 0
fi

NEW="${1:?usage: bench_gate.sh NEW.json [PRIOR.json] | --selftest}"
PRIOR="${2:-$(newest_round)}"
[[ -n "$PRIOR" ]] || { echo "bench_gate: no BENCH_r0*.json prior round found" >&2; exit 1; }
echo "bench_gate: $NEW vs $PRIOR"
exec python -m multigpu_advectiondiffusion_tpu.bench.compare "$NEW" "$PRIOR"
