#!/usr/bin/env bash
# Request-serving gate (ISSUE 17): the continuous-batching server's
# end-to-end chaos proof, runnable in CI.
#
# 1. Kill/replay gate: submit 4 coalescible requests (mixed widths,
#    priorities and an SLO deadline), start the server, SIGKILL it the
#    moment a batch has marched at least one slice, restart it, and
#    assert (a) every request reached `done` EXACTLY once across both
#    server lives, (b) the request journal linearizes
#    (`serve-requests --verify --require-complete`), (c) the second
#    incarnation journaled a crash_recovery requeue, and (d) every
#    request published a result.bin and a `done` verdict.
# 2. `--selftest`: proves the gate's assertions have teeth —
#    a dropped-request fixture (a request the server admitted but
#    never answered) must trip `--verify --require-complete` while
#    plain `--verify` still passes, and a torn spool file (the
#    half-written JSON a crashed client leaves) must be quarantined
#    as `<name>.bad` with a named journal record, not crash the
#    server or block its neighbours.
#
#   ./out/serve_gate.sh             # the kill/replay gate
#   ./out/serve_gate.sh --selftest  # dropped-request + torn-spool proofs
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

CLI=(python -m multigpu_advectiondiffusion_tpu.cli)
REQ=(request --model diffusion --n 12 12 --ic gaussian)

if [[ "${1:-}" == "--selftest" ]]; then
    echo "serve_gate: selftest 1 — a dropped request must trip" \
         "--require-complete"
    ROOT="$TMP/dropped"
    # a horizon the 1.5s serving window cannot reach: the request is
    # admitted and marching (journalled, non-terminal) when the server
    # stops — exactly the state a lost request leaves behind
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id drop1 \
        --t-end 50.0
    "${CLI[@]}" serve-requests --root "$ROOT" --max-batch 2 \
        --slice-steps 1 --poll 0.02 --max-seconds 1.5
    # the journal still linearizes (every transition legal) ...
    "${CLI[@]}" serve-requests --root "$ROOT" --verify
    # ... but completeness must trip on the unanswered request
    if "${CLI[@]}" serve-requests --root "$ROOT" --verify \
        --require-complete > "$TMP/drop.out" 2>&1; then
        echo "serve_gate: SELFTEST FAILED — dropped request passed" \
             "--require-complete" >&2
        exit 1
    fi
    grep -q "terminal" "$TMP/drop.out" || {
        echo "serve_gate: SELFTEST FAILED — wrong trip reason:" >&2
        cat "$TMP/drop.out" >&2
        exit 1
    }
    echo "serve_gate: selftest 1 OK — dropped request tripped the gate"

    echo "serve_gate: selftest 2 — a torn spool file must be" \
         "quarantined, not served or fatal"
    ROOT="$TMP/torn"
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id good1 \
        --t-end 0.15
    # the torn tail a crashed client leaves mid-write
    printf '{"request_id": "torn1", "model": "diff' \
        > "$ROOT/spool/zz-torn.json"
    "${CLI[@]}" serve-requests --root "$ROOT" --max-batch 2 \
        --slice-steps 4 --poll 0.02 --until-idle
    [[ -f "$ROOT/spool/zz-torn.json.bad" ]] || {
        echo "serve_gate: SELFTEST FAILED — torn spool file not" \
             "quarantined as .bad" >&2
        exit 1
    }
    grep -q '"spool_skip"' "$ROOT/journal.jsonl" || {
        echo "serve_gate: SELFTEST FAILED — no spool_skip journal" \
             "record for the torn file" >&2
        exit 1
    }
    python - "$ROOT" <<'PY'
import json, sys
v = json.load(open(f"{sys.argv[1]}/requests/good1/verdict.json"))
assert v["status"] == "done", f"good neighbour not served: {v}"
PY
    "${CLI[@]}" serve-requests --root "$ROOT" --verify --require-complete
    echo "serve_gate: selftest 2 OK — torn spool quarantined," \
         "neighbour served"
    echo "serve_gate: selftest PASS"
    exit 0
fi

ROOT="$TMP/root"
echo "serve_gate: submitting 4 coalescible requests (mixed widths," \
     "priorities, one SLO deadline)"
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r1 --t-end 0.5 \
    --ic-param width=0.08
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r2 --t-end 0.5 \
    --ic-param width=0.10
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r3 --t-end 0.4 \
    --priority 5
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id r4 --t-end 0.45 \
    --deadline 300

echo "serve_gate: server up; waiting for the first marched slice"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02 > "$TMP/server1.out" 2>&1 &
SERVER=$!
for _ in $(seq 1 2400); do
    if grep -q '"slice"' "$ROOT/serve_events.jsonl" 2> /dev/null; then
        break
    fi
    if ! kill -0 "$SERVER" 2> /dev/null; then
        echo "serve_gate: server exited before the kill window:" >&2
        cat "$TMP/server1.out" >&2
        exit 1
    fi
    sleep 0.05
done
grep -q '"slice"' "$ROOT/serve_events.jsonl" || {
    echo "serve_gate: server never marched a slice" >&2
    exit 1
}

echo "serve_gate: SIGKILL the server mid-batch (pid $SERVER)"
kill -9 "$SERVER"
wait "$SERVER" 2> /dev/null || true

echo "serve_gate: restart — journal replay must answer every request"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02

echo "serve_gate: verify the request journal linearizes, complete"
"${CLI[@]}" serve-requests --root "$ROOT" --verify --require-complete

python - "$ROOT" <<'PY'
import json, os, sys

root = sys.argv[1]
records = [json.loads(l) for l in open(os.path.join(
    root, "journal.jsonl")) if l.strip()]
recs = [r.get("record", r) for r in records]
rids = ("r1", "r2", "r3", "r4")
for rid in rids:
    dones = [r for r in recs if r.get("type") == "state"
             and r.get("job") == rid and r.get("to") == "done"]
    assert len(dones) == 1, \
        f"{rid}: answered {len(dones)} times, want exactly once"
    assert os.path.exists(os.path.join(
        root, "requests", rid, "result.bin")), f"{rid}: no result.bin"
    v = json.load(open(os.path.join(root, "requests", rid,
                                    "verdict.json")))
    assert v["status"] == "done", f"{rid}: verdict {v}"
requeues = [r for r in recs if r.get("type") == "state"
            and r.get("reason") == "crash_recovery"]
assert requeues, "no crash_recovery requeue journalled on restart"
evs = [json.loads(l) for l in open(os.path.join(
    root, "serve_events.jsonl")) if l.strip()]
recover = [e for e in evs
           if e["kind"] == "serve" and e["name"] == "recover"]
assert recover, "second server life journalled no serve:recover"
print(f"serve_gate: OK — {len(rids)} requests answered exactly once, "
      f"{len(requeues)} requeued after SIGKILL, journal complete")
PY
echo "serve_gate: PASS"
