"""MXU-offload experiment for the WENO x sweep (VERDICT r4 item 3).

The fused Burgers kernels are bound by the VPU's shift/permute unit
(PARITY.md ablations: removing ~8% of ALU moved the rate 0%; one lane
tile moved it 14%), and the x sweep prices at ~1.5x the y sweep because
lane-axis shifts are the permute unit's most expensive op. The MXU sits
idle in these kernels. Candidate: express the x sweep's circular window
shifts as permutation matmuls on the MXU — `roll(v, k)` is exactly
`v @ P_k` with `P_k[j, i] = [j == (i + k) mod W]` — so every shift the
x sweep issues moves from the permute unit to the (idle) systolic
array. Permutation matmuls are bit-exact even through XLA's bf16x3 f32
path: each output element is `1.0 * x + zeros`, and the bf16 hi/lo
split of `x` re-sums exactly.

The arithmetic says dense-matmul shifts are priced at W MACs/element
against the roll's ~1 permute-op/element — a ~640x op-count inflation
the MXU's ~30x throughput advantage over the VPU cannot absorb — but
the ladder's ethos is to measure the other unit before declaring the
roof (the transpose-x-sweep rejection was measured too, and tied). So:
monkeypatch `fused_burgers._div_x` with the MXU variant, verify
equality, and time both at 512^3 viscous fixed-dt. Accept if >5% over
the production rate; table lands in PARITY.md.

Run: python out/mxu_offload_exp.py  (real TPU; ~4 min)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from multigpu_advectiondiffusion_tpu.bench.timing import _timed
from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.burgers import (
    BurgersConfig,
    BurgersSolver,
)
from multigpu_advectiondiffusion_tpu.ops.pallas import fused_burgers as fb

ITERS = 20
REPS = 3


def _shift_mxu(v, off: int):
    """Circular ``result[i] = v[..., i + off]`` on the lane axis as a
    permutation matmul (MXU), replacing the VPU lane roll."""
    W = v.shape[-1]
    if off % W == 0:
        return v
    i = lax.broadcasted_iota(jnp.int32, (W, W), 0)  # input lane j
    j = lax.broadcasted_iota(jnp.int32, (W, W), 1)  # output lane i
    P = (i == lax.rem(j + off + 4 * W, W)).astype(v.dtype)
    flat = v.reshape(-1, W)
    out = lax.dot_general(
        flat, P, (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=v.dtype,
    )
    return out.reshape(v.shape)


def _div_x_mxu(vp, vm, inv_dx, variant, order=5):
    """fused_burgers._div_x with every lane shift routed to the MXU."""
    from multigpu_advectiondiffusion_tpu.ops.weno import (
        _weno5_side_nd_e,
        _weno7_side_nd_e,
    )

    sh = _shift_mxu
    ep = sh(vp, 1) - vp
    em = sh(vm, 1) - vm
    if order == 7:
        nm, dm = _weno7_side_nd_e(*(sh(ep, j - 3) for j in range(6)), "minus")
        np_, dp = _weno7_side_nd_e(*(sh(em, j - 2) for j in range(6)), "plus")
    else:
        nm, dm = _weno5_side_nd_e(
            *(sh(ep, j - 2) for j in range(4)), variant, "minus"
        )
        np_, dp = _weno5_side_nd_e(
            *(sh(em, j - 1) for j in range(4)), variant, "plus"
        )
    h = (vp + sh(vm, 1)) + (nm * fb._recip(dm) + np_ * fb._recip(dp))
    return (h - sh(h, -1)) * inv_dx


def make_solver(n):
    grid = Grid.make(n, n, n, lengths=2.0)
    return BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                      adaptive_dt=False, impl="pallas")
    )


def run_variant(n, iters, reps):
    s = make_solver(n)
    fused = s._fused_stepper()
    assert fused is not None
    st = s.initial_state()
    u0, t0 = st.u, st.t
    run = jax.jit(lambda u, t: fused.run(u, t, iters)[0])
    zero = jax.jit(lambda u, t: fused.run(u, t, 0)[0])
    tr = _timed(lambda: run(u0, t0), lambda: zero(u0, t0), reps)
    return n**3 * iters * 3 / tr.seconds / 1e6, np.asarray(run(u0, t0))


def main():
    orig = fb._div_x

    # equality first, at a size where the slow variant is cheap
    _, a = run_variant(64, 5, 1)
    fb._div_x = _div_x_mxu
    try:
        _, b = run_variant(64, 5, 1)
        scale = float(np.max(np.abs(a)))
        dev = float(np.max(np.abs(a - b))) / scale
        print(f"64^3 5-step max-diff/scale (MXU vs roll): {dev:.2e}")
        assert dev <= 32 * np.finfo(np.float32).eps, dev

        mxu_rate, _ = run_variant(512, ITERS, REPS)
    finally:
        fb._div_x = orig
    roll_rate, _ = run_variant(512, ITERS, REPS)

    print(f"\n512^3 viscous WENO5-JS, fixed dt, one chip "
          f"({jax.devices()[0].platform}):")
    print(f"{'x-sweep shifts':<34} {'MLUPS':>8}")
    print(f"{'VPU lane rolls (production)':<34} {roll_rate:>8.0f}")
    print(f"{'MXU permutation matmuls':<34} {mxu_rate:>8.0f}")
    print(f"\nMXU/roll: {mxu_rate / roll_rate:.3f}x "
          f"(accept threshold: > 1.05x)")


if __name__ == "__main__":
    main()
