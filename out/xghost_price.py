"""Price the stored-x-ghost layout of the fused 3-D Burgers stepper.

The lane-aligned default layout stores no x ghosts (every transfer and
non-x op runs at round128(nx) lanes); the x-sharded layout stores real
ghost lanes at round128(nx + 2r), paying one extra lane tile at the
bench shape. This script measures that tax on one chip at 512^3 viscous
fixed-dt (the ladder's flagship workload) and compares it against what
an x-sharded mesh would otherwise get — the generic XLA path — so the
engage-or-decline decision in models/burgers.py is evidence, not
argument. Table lands in PARITY.md ("x-sharded fused Burgers").

Run: python out/xghost_price.py  (real TPU; ~2 min)
     python out/xghost_price.py --sweep  (block sweep for the 640-lane
     layout; the order-5 preference (8,64) ties the best block there
     within run-to-run drift — see sweep()'s docstring)
"""

import dataclasses
import os
import sys

# repo-root import bootstrap (PYTHONPATH breaks the axon PJRT plugin
# discovery on this rig; an in-process path insert does not)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from multigpu_advectiondiffusion_tpu.bench.timing import _timed
from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.burgers import (
    BurgersConfig,
    BurgersSolver,
)
from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
    FusedBurgersStepper,
)

N = 512
ITERS = 50
REPS = 5


def mlups(tr, iters=ITERS):
    # stage-update convention (3 RK stages/step), as everywhere else
    return N**3 * iters * 3 / tr.seconds / 1e6


def sweep():
    """Block sweep of the stored-x-ghost layout at 512^3 (the default
    preference was tuned on the 512-lane layout; this checks it holds
    at 640 lanes). Measured 2026-07-31 over 4 independent passes:
    (8,64) and (16,32) tie within run-to-run drift (8,067-8,399 vs
    8,146-8,602 MLUPS, means ~1% apart); the rest are clearly behind
    ((8,32) ~8,0xx > (4,64) ~7,9xx > (8,16)/(16,16) ~7,3-7,8xx >
    (16,64) ~7,1-7,3xx) — the production preference stays correct."""
    grid = Grid.make(N, N, N, lengths=2.0)
    dt = 0.4 * min(grid.spacing)
    u0 = jnp.zeros((N, N, N), jnp.float32)
    t0 = jnp.zeros((), jnp.float32)
    iters = 20
    for blk in [(8, 64), (8, 32), (16, 32), (8, 16), (4, 64), (16, 64)]:
        try:
            st = FusedBurgersStepper(
                (N, N, N), jnp.float32, grid.spacing,
                flux_lib.get("burgers"), "js", 1e-5, dt=dt,
                x_sharded=True, block=blk,
            )
        except ValueError as e:  # the constructor's documented decline
            print(blk, "unsupported:", e)
            continue
        run = jax.jit(lambda u, t, s=st: s.run(u, t, iters)[0])
        zero = jax.jit(lambda u, t, s=st: s.run(u, t, 0)[0])
        tr = _timed(lambda: run(u0, t0), lambda: zero(u0, t0), 3)
        print(blk, f"{mlups(tr, iters):.0f} MLUPS")


def main():
    grid = Grid.make(N, N, N, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=False, impl="pallas")
    solver = BurgersSolver(cfg)
    state = solver.initial_state()
    u0, t0 = state.u, state.t

    rows = []

    def time_stepper(label, stepper):
        run = jax.jit(lambda u, t: stepper.run(u, t, ITERS)[0])
        zero = jax.jit(lambda u, t: stepper.run(u, t, 0)[0])
        tr = _timed(lambda: run(u0, t0), lambda: zero(u0, t0), REPS)
        rows.append((label, mlups(tr), tr.spread, stepper.padded_shape[2]))
        return run

    fused = solver._fused_stepper()
    assert fused is not None and not fused.x_sharded
    run_std = time_stepper("fused lane-aligned (default)", fused)

    xg = FusedBurgersStepper(
        (N, N, N), jnp.float32, grid.spacing, flux_lib.get("burgers"),
        "js", 1e-5, dt=solver.dt, x_sharded=True,
    )
    run_xg = time_stepper("fused stored-x-ghost", xg)

    # trajectory equality: same kernels, different x layout
    a = np.asarray(run_std(u0, t0))
    b = np.asarray(run_xg(u0, t0))
    scale = float(np.max(np.abs(a)))
    err = float(np.max(np.abs(a - b))) / scale
    assert err <= 32 * np.finfo(np.float32).eps, err

    # generic path via the solver API (jit cache inside the solver)
    from multigpu_advectiondiffusion_tpu.bench.timing import timed_run

    xs = BurgersSolver(dataclasses.replace(cfg, impl="xla"))
    tr = timed_run(xs, state, ITERS, reps=REPS)
    rows.append(("generic XLA (the x-sharded fallback before)",
                 mlups(tr), tr.spread, N))

    print(f"\n512^3 viscous Burgers, fixed dt, f32, one chip "
          f"({jax.devices()[0].platform}):")
    print(f"{'path':<44} {'MLUPS':>8} {'spread':>7} {'lanes':>6}")
    for label, rate, spread, px in rows:
        print(f"{label:<44} {rate:>8.0f} {spread:>7.2f} {px:>6}")
    std = rows[0][1]
    print(f"\nx-ghost tax vs default: {(1 - rows[1][1] / std) * 100:.1f}%  "
          f"(layout {rows[1][3]} vs {rows[0][3]} lanes)")
    print(f"x-ghost vs generic: {rows[1][1] / rows[2][1]:.2f}x")
    print(f"max-trajectory-diff/scale after {ITERS} steps: {err:.2e}")


if __name__ == "__main__":
    if "--sweep" in sys.argv:
        sweep()
    else:
        main()
