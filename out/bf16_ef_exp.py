"""Measure the bf16 error-feedback (compensated-storage) diffusion rung.

PARITY.md's bf16-storage section rejects plain bf16 state on accuracy
(the stability-dt update rounds away against bf16's quantum) and argued
— without numbers — that error-feedback storage "would need a second
buffer and give the traffic win back". VERDICT r4 item 6 asks for the
measurement. This script implements the scheme honestly and times it:

* the natural home is the WHOLE-STEP kernel (fused_diffusion_step): the
  three RK stages live in VMEM at f32, so the state is quantized ONCE
  per step — per-stage error feedback cannot work at all, since T1/T2
  themselves stagnate when stored plain-bf16;
* state q (bf16) + residual e (bf16), reconstructed x = f32(q) + f32(e)
  at load (both slabs read WITH the z halo — neighbors need precision
  too), compensated re-split on store: q' = bf16(x'), e' = bf16(x' - q').

Byte accounting per cell-step (the whole point): read 2+2, write 2+2 =
f32's 4+4 — the traffic win is exactly given back, so on an HBM-bound
kernel the expected rate is the f32 whole-step rate, not the 1.6x of
plain bf16. The accuracy column shows what the compensation buys back
(two bf16s carry ~16 mantissa bits, not f32's 24).

Table rows (same grid, 400x200x208 — z rounded to a whole-step-friendly
block multiple of the literal 400x200x206 north-star — 303 iters, one
chip): f32 per-stage | plain bf16 per-stage | f32 whole-step |
bf16+EF whole-step. Lands in PARITY.md next to the existing bf16 table.

Run: python out/bf16_ef_exp.py  (real TPU; ~3 min)
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.bench.timing import _timed
from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.diffusion import (
    DiffusionConfig,
    DiffusionSolver,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import _STAGES
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (
    ZGHOST,
    _stage_rows,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    R,
    SUBLANE,
    VMEM_LIMIT,
    compiler_params,
    interpret_mode,
    pick_block,
    round_up,
)

ITERS = 303
REPS = 5


def _ef_step_kernel(q_hbm, e_hbm, _tq, _te, outq_hbm, oute_hbm,
                    qs, es, rq, re_, sem_q, sem_e, sem_wq, sem_we, *,
                    bz, n_blocks, interior_shape, scales, dt, band,
                    bc_value):
    """One z-block of one full EF step; DMA discipline mirrors
    fused_diffusion_step._step_kernel, doubled for the (q, e) pair."""
    k = pl.program_id(0)
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))
    halo = 3 * R

    def copy_in(hbm, buf, sem, j, s):
        return pltpu.make_async_copy(
            hbm.at[pl.ds((ZGHOST - halo) + j * bz, bz + 2 * halo)],
            buf.at[s], sem.at[s],
        )

    def copy_out(buf, hbm, sem, j, s):
        return pltpu.make_async_copy(
            buf.at[s], hbm.at[pl.ds(ZGHOST + j * bz, bz)], sem.at[s]
        )

    @pl.when(k == 0)
    def _():
        copy_in(q_hbm, qs, sem_q, 0, 0).start()
        copy_in(e_hbm, es, sem_e, 0, 0).start()

    @pl.when(k + 1 < n_blocks)
    def _():
        copy_in(q_hbm, qs, sem_q, k + 1, nslot).start()
        copy_in(e_hbm, es, sem_e, k + 1, nslot).start()

    copy_in(q_hbm, qs, sem_q, k, slot).wait()
    copy_in(e_hbm, es, sem_e, k, slot).wait()

    # reconstruct the f32 state: two bf16s ~ 16 mantissa bits
    v = qs[slot].astype(jnp.float32) + es[slot].astype(jnp.float32)

    stage = functools.partial(
        _stage_rows, interior_shape=tuple(interior_shape),
        scales=tuple(scales), dt=dt, band=band, bc_value=bc_value,
    )
    (a1, b1), (a2, b2), (a3, b3) = _STAGES
    base = k * bz - halo
    t1 = stage(v, None, gz0=base + R, a=a1, b=b1)
    t2 = stage(t1, v[2 * R : 2 * R + bz + 4], gz0=base + 2 * R, a=a2, b=b2)
    t3 = stage(t2, v[3 * R : 3 * R + bz], gz0=base + 3 * R, a=a3, b=b3)

    # compensated split: e' carries what bf16(x') rounds away
    q = t3.astype(jnp.bfloat16)
    e = (t3 - q.astype(jnp.float32)).astype(jnp.bfloat16)

    @pl.when(k >= 2)
    def _():
        copy_out(rq, outq_hbm, sem_wq, k - 2, slot).wait()
        copy_out(re_, oute_hbm, sem_we, k - 2, slot).wait()

    rq[slot] = q
    re_[slot] = e
    copy_out(rq, outq_hbm, sem_wq, k, slot).start()
    copy_out(re_, oute_hbm, sem_we, k, slot).start()

    @pl.when(k == n_blocks - 1)
    def _():
        copy_out(rq, outq_hbm, sem_wq, k, slot).wait()
        copy_out(re_, oute_hbm, sem_we, k, slot).wait()
        if n_blocks >= 2:
            copy_out(rq, outq_hbm, sem_wq, k - 1, nslot).wait()
            copy_out(re_, oute_hbm, sem_we, k - 1, nslot).wait()


class EFStepStepper:
    """bf16 state + bf16 residual, f32 compute, one quantization per
    step. Interface mirrors StepFusedDiffusionStepper."""

    def __init__(self, interior_shape, spacing, diffusivity, dt, band,
                 bc_value, block_z=None):
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        sub = SUBLANE * 2  # bf16 (16, 128) tiles
        self.padded_shape = (
            nz + 2 * ZGHOST,
            round_up(ny + 2 * R, sub),
            round_up(nx + 2 * R, LANE),
        )
        self.bc_value = float(bc_value)
        row_f32 = self.padded_shape[1] * self.padded_shape[2] * 4
        if block_z is None:
            # ~12 live f32-row-equivalents per block row (f32 slab + two
            # bf16 slab pairs + stage windows) + ~140 fixed rows
            budget = (VMEM_LIMIT // row_f32 - 140) // 12
            block_z = pick_block(nz, max(1, min(20, int(budget))))
        if nz % block_z != 0:
            raise ValueError(f"block_z={block_z} must divide nz={nz}")
        self.block_z = bz = block_z
        n_blocks = nz // bz
        scales = [
            float(diffusivity) / (12.0 * spacing[i] * spacing[i])
            for i in range(3)
        ]
        kern = functools.partial(
            _ef_step_kernel, bz=bz, n_blocks=n_blocks,
            interior_shape=self.interior_shape, scales=tuple(scales),
            dt=float(dt), band=band, bc_value=float(bc_value),
        )
        halo = 3 * R
        bf16 = jnp.bfloat16
        self._step_call = pl.pallas_call(
            kern,
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
            out_specs=(
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ),
            out_shape=(
                jax.ShapeDtypeStruct(self.padded_shape, bf16),
                jax.ShapeDtypeStruct(self.padded_shape, bf16),
            ),
            scratch_shapes=[
                pltpu.VMEM((2, bz + 2 * halo) + self.padded_shape[1:], bf16),
                pltpu.VMEM((2, bz + 2 * halo) + self.padded_shape[1:], bf16),
                pltpu.VMEM((2, bz) + self.padded_shape[1:], bf16),
                pltpu.VMEM((2, bz) + self.padded_shape[1:], bf16),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            input_output_aliases={2: 0, 3: 1},
            compiler_params=None if interpret_mode() else compiler_params(),
            interpret=interpret_mode(),
        )
        self.dt = float(dt)

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, jnp.float32)
        P = lax.dynamic_update_slice(
            full, u.astype(jnp.float32), (ZGHOST, R, R)
        )
        q = P.astype(jnp.bfloat16)
        e = (P - q.astype(jnp.float32)).astype(jnp.bfloat16)
        return q, e

    def extract(self, Sq, Se):
        nz, ny, nx = self.interior_shape
        x = Sq.astype(jnp.float32) + Se.astype(jnp.float32)
        return lax.slice(
            x, (ZGHOST, R, R), (ZGHOST + nz, R + ny, R + nx)
        )

    def run(self, u, t, num_iters: int):
        Sq, Se = self.embed(u)
        Tq, Te = Sq, Se

        def body(i, carry):
            Sq, Se, Tq, Te, t = carry
            Tq, Te = self._step_call(Sq, Se, Tq, Te)
            return Tq, Te, Sq, Se, t + self.dt

        Sq, Se, Tq, Te, t = lax.fori_loop(
            0, num_iters, body, (Sq, Se, Tq, Te, t)
        )
        return self.extract(Sq, Se), t


def main():
    grid = Grid.make(400, 200, 208, lengths=(10.0, 5.0, 5.2))
    cells = grid.num_cells

    rows = []

    def solver_row(label, **kw):
        cfg = DiffusionConfig(grid=grid, diffusivity=1.0, **kw)
        s = DiffusionSolver(cfg)
        assert s._fused_stepper() is not None, (label, s._fused_fallback)
        st = s.initial_state()
        from multigpu_advectiondiffusion_tpu.bench.timing import timed_run

        tr = timed_run(s, st, ITERS, reps=REPS)
        out = s.run(st, ITERS)
        n = s.error_norms(out)
        # stage-update MLUPS (3 RK stages/step), as everywhere else
        rows.append((label, cells * ITERS * 3 / tr.seconds / 1e6,
                     n.l1, n.linf))
        return s, out

    s_f32, out_f32 = solver_row("f32 per-stage", dtype="float32",
                                impl="pallas")
    solver_row("bf16 per-stage (plain)", dtype="bfloat16", impl="pallas")
    solver_row("f32 whole-step", dtype="float32", impl="pallas_step")

    # the EF whole-step experiment, driven like the solver drives its
    # fused steppers (same dt, same walls)
    cfg = s_f32.cfg
    ef = EFStepStepper(grid.shape, grid.spacing, 1.0, s_f32.dt,
                       cfg.boundary_band, 0.0)
    st = s_f32.initial_state()
    u0, t0 = st.u, st.t
    run = jax.jit(lambda u, t: ef.run(u, t, ITERS)[0])
    zero = jax.jit(lambda u, t: ef.run(u, t, 0)[0])
    tr = _timed(lambda: run(u0, t0), lambda: zero(u0, t0), REPS)
    u_end = run(u0, t0)
    t_end = float(t0) + ITERS * ef.dt
    from multigpu_advectiondiffusion_tpu.utils import metrics

    n = metrics.error_norms(u_end, s_f32.exact_solution(t_end),
                            grid.spacing)
    rows.append((f"bf16+EF whole-step (bz={ef.block_z})",
                 cells * ITERS * 3 / tr.seconds / 1e6, n.l1, n.linf))

    import numpy as np

    dev = np.max(np.abs(np.asarray(u_end) - np.asarray(out_f32.u)))

    print(f"\n400x200x208, {ITERS} iters, stability dt, one chip "
          f"({jax.devices()[0].platform}):")
    print(f"{'storage':<30} {'MLUPS':>8} {'vs f32':>7} {'L1':>10} {'Linf':>10}")
    base = rows[0][1]
    for label, rate, l1, linf in rows:
        print(f"{label:<30} {rate:>8.0f} {rate / base:>6.2f}x "
              f"{l1:>10.2e} {linf:>10.2e}")
    print(f"\nmax |EF - f32-per-stage| after {ITERS} steps: {dev:.2e}")


if __name__ == "__main__":
    main()
