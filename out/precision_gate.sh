#!/usr/bin/env bash
# Precision regression gate: run the canonical diagnostic round at the
# bf16 storage rung (--precision bf16: HBM state and every halo wire
# byte in bfloat16, all arithmetic in f32 with compensated accumulation
# on the generic path) and diff its observable trajectories against the
# newest archived bf16 round (PRECISION_r0*.json) with
# diagnostics/compare.py's PER-STORAGE-DTYPE tolerance bands — the runs'
# meta carries storage_dtype=bfloat16, so the gate judges them against
# the wider bf16 bands, not f32's. Nonzero exit on drift beyond those
# bands: a numerics change that the bandwidth rung can't absorb (a
# dropped compensation carry, a downcast moved inside the RK loop)
# trips THIS gate even while out/science_gate.sh's native round stays
# green.
#
#   ./out/precision_gate.sh                 # fresh bf16 round vs newest PRECISION_r0*.json
#   ./out/precision_gate.sh NEW.json        # gate an existing artifact
#   ./out/precision_gate.sh NEW.json PRIOR  # explicit prior round
#   ./out/precision_gate.sh --record OUT    # run the round, archive the artifact
#   ./out/precision_gate.sh --selftest      # prove an unmodified bf16 round
#                                           # PASSES and a carry-off round
#                                           # (TPUCFD_BF16_NO_CARRY=1 — plain
#                                           # bf16 accumulation, no hi/lo
#                                           # compensation) FAILS
#
# Runs on the virtual CPU backend (no TPU needed), same as tier-1.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

newest_round() {
  ls PRECISION_r0*.json 2>/dev/null | sort | tail -1
}

# run_round OUT.json — the canonical bf16 diagnostic round: the same
# supervised diffusion3d + burgers1d solves as out/science_gate.sh, at
# --precision bf16. Longer horizons than the science round on purpose:
# the compensation carry's value is cumulative, so the carry-off
# self-test needs enough steps for uncompensated rounding to leave the
# bf16 bands. TPUCFD_BF16_NO_CARRY=1 in the environment is the
# self-test's injection point (core.dtypes.bf16_carry_enabled).
run_round() {
  local out="$1"
  local tmp
  tmp="$(mktemp -d)"
  python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
    --n 16 14 12 --iters 120 --precision bf16 \
    --sentinel-every 10 --diag-every 2 --save "$tmp/d3" >/dev/null
  python -m multigpu_advectiondiffusion_tpu.cli burgers1d \
    --n 128 --iters 120 --fixed-dt --precision bf16 \
    --sentinel-every 10 --diag-every 2 --save "$tmp/b1" >/dev/null
  python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
    --extract "$tmp/d3/summary.json" "$tmp/b1/summary.json" -o "$out"
  rm -rf "$tmp"
}

if [[ "${1:-}" == "--record" ]]; then
  OUT="${2:?usage: precision_gate.sh --record OUT.json}"
  run_round "$OUT"
  exit 0
fi

if [[ "${1:-}" == "--selftest" ]]; then
  TMP="$(mktemp -d)"
  trap 'rm -rf "$TMP"' EXIT
  echo "precision_gate selftest: recording the reference bf16 round"
  run_round "$TMP/base.json"
  echo "precision_gate selftest: an unmodified bf16 round must PASS"
  run_round "$TMP/clean.json"
  python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
    "$TMP/clean.json" "$TMP/base.json"
  echo "precision_gate selftest: a carry-off round (TPUCFD_BF16_NO_CARRY=1) must FAIL"
  TPUCFD_BF16_NO_CARRY=1 run_round "$TMP/nocarry.json"
  if python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
      "$TMP/nocarry.json" "$TMP/base.json"; then
    echo "precision_gate selftest: gate FAILED to trip with the compensation carry disabled" >&2
    exit 1
  fi
  echo "precision_gate selftest: OK (gate trips carry-off, passes unmodified)"
  exit 0
fi

if [[ -n "${1:-}" ]]; then
  NEW="$1"
else
  NEW="$(mktemp -d)/precision_new.json"
  echo "precision_gate: running the canonical bf16 diagnostic round"
  run_round "$NEW"
fi
PRIOR="${2:-$(newest_round)}"
[[ -n "$PRIOR" ]] || { echo "precision_gate: no PRECISION_r0*.json prior round found (record one with --record PRECISION_r01.json)" >&2; exit 1; }
echo "precision_gate: $NEW vs $PRIOR"
exec python -m multigpu_advectiondiffusion_tpu.diagnostics.compare "$NEW" "$PRIOR"
