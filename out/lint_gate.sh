#!/usr/bin/env bash
# Lint gate: the static-analysis counterpart of out/bench_gate.sh
# (measured perf) and out/science_gate.sh (numerics). Two halves:
#
#   1. clean-tree pass — tpucfd-check must exit 0 on the shipped
#      package: every AST lint rule silent (closure constants, host
#      syncs in traced code, non-atomic artifact writes, unregistered
#      telemetry emissions, rank-divergent collectives/effects, and
#      registry completeness — every register_model()'d solver class
#      declares the full stencil_spec/diagnostics_spec/
#      ensemble_operands/cfl_rule plugin contract), the stencil/halo
#      verifier proving every admitted (rung, order, k) combination
#      for every REGISTERED family (a registered family with no combo
#      battery, or a battery whose size drifts from the expected
#      matrix count, is a coverage violation), and the
#      collective-schedule verifier proving the distributed layer
#      rank-uniform (unique rendezvous tags, no divergent joins,
#      declared-tag drift, sharding-case registry);
#   2. --selftest — every rule (incl. registry-completeness, whose
#      seeded bad fixture registers a half-wired ToySolver) must TRIP
#      on its seeded violation
#      fixture (and pass the clean twin), the halo verifier must fail
#      an injected off-by-one ghost depth naming kernel/axis/depth
#      AND an injected overlapping remote-DMA recv window (a neighbor
#      push landing over rows the receiver is still computing) naming
#      kernel/axis/rows, and the collective verifier must fail its
#      seeded deadlock fixtures (rank-guarded barrier, duplicate tag,
#      divergent join), sharding fixtures (bad PartitionSpec axis,
#      member-in-spatial), a bad remote-DMA window, and a
#      non-linearized measured schedule — so a green gate means
#      "checked and clean", never "checker silently broke".
#
# The dynamic half of the collective proof — the 2-proc schedule
# tracer asserting the MEASURED collective sequence linearizes the
# static schedule — lives in tests/test_chaos.py
# (test_schedule_tracer_matches_static_schedule); replay any captured
# pair of streams by hand with:
#   python -m multigpu_advectiondiffusion_tpu.analysis \
#       --schedule-trace run/events_p0.jsonl run/events_p1.jsonl
#
#   ./out/lint_gate.sh              # both halves
#   ./out/lint_gate.sh --selftest   # selftest only
#
# Runs on the virtual CPU backend (no TPU needed), same as tier-1.
# Hooked into out/soak_resilience.sh behind LINT_GATE=1.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [[ "${1:-}" == "--selftest" ]]; then
  python -m multigpu_advectiondiffusion_tpu.analysis --selftest
  exit 0
fi

echo "=== lint_gate: clean-tree pass ==="
python -m multigpu_advectiondiffusion_tpu.analysis

echo "=== lint_gate: rule selftests ==="
python -m multigpu_advectiondiffusion_tpu.analysis --selftest

echo "lint_gate: PASS"
