#!/usr/bin/env bash
# Measured-introspection smoke: run a short supervised solve end-to-end
# and prove the xprof layer produced its evidence — per-executable
# xla:cost events, chunk-cadence mem:watermark samples, the
# xla:measured reconciliation and a persisted calibration write — in
# the --metrics stream, the summary JSON and the calibration file.
# Exits nonzero the moment any of them is missing.
#
#   ./out/profile_smoke.sh            # CPU (JAX_PLATFORMS honored)
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export TPUCFD_CALIBRATION_PATH="$TMP/calibration.json"

echo "profile_smoke: supervised diffusion3d solve (metrics -> $TMP)"
python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
    --n 16 12 8 --iters 8 --sentinel-every 2 \
    --save "$TMP/run" --metrics "$TMP/events.jsonl"

python - "$TMP/events.jsonl" "$TMP/run/summary.json" \
         "$TMP/calibration.json" <<'PY'
import json, sys

events_path, summary_path, calib_path = sys.argv[1:4]
events = [json.loads(line) for line in open(events_path)]
have = {(e["kind"], e["name"]) for e in events}

missing = []
def need(kind, name, check=None, what=""):
    rows = [e for e in events if (e["kind"], e["name"]) == (kind, name)]
    if not rows or (check and not all(check(e) for e in rows)):
        missing.append(f"{kind}:{name} {what}".strip())
    return rows

need("xla", "cost",
     lambda e: e.get("flops", 0) > 0 and e.get("bytes_accessed", 0) > 0,
     "(nonzero XLA flops/bytes)")
need("mem", "watermark", lambda e: e.get("bytes_in_use", 0) > 0,
     "(nonzero bytes in use)")
need("xla", "measured")
need("calib", "update", lambda e: e.get("backend"), "(calibration write)")

summary = json.load(open(summary_path))
if not (summary.get("memory") or {}).get("peak_bytes_in_use"):
    missing.append("summary.memory.peak_bytes_in_use")
if not (summary.get("xla") or {}).get("xla_bytes_per_step"):
    missing.append("summary.xla.xla_bytes_per_step")
try:
    calib = json.load(open(calib_path))
    if not calib.get("entries"):
        missing.append("calibration file has no entries")
except Exception as exc:
    missing.append(f"calibration file unreadable: {exc}")

if missing:
    print("profile_smoke: FAIL — missing measured evidence:")
    for m in missing:
        print(f"  - {m}")
    sys.exit(1)
print("profile_smoke: OK — xla:cost, mem:watermark, xla:measured and "
      "the calibration write all present")
PY

echo "profile_smoke: tpucfd-trace measured-vs-modeled section"
python -m multigpu_advectiondiffusion_tpu.cli trace "$TMP/events.jsonl" \
    > "$TMP/trace_report.txt"
grep -q "measured vs modeled" "$TMP/trace_report.txt" \
    || { echo "profile_smoke: trace report lacks the measured section" >&2; exit 1; }
echo "profile_smoke: OK"
