#!/usr/bin/env bash
# Science regression gate: run the canonical diagnostic round (small
# supervised diffusion + Burgers solves with the in-situ physics suite
# armed) and diff its observable trajectories against the newest
# archived round (SCIENCE_r0*.json) with diagnostics/compare.py's
# per-observable tolerance bands; nonzero exit on any drift. The
# numerics counterpart of out/bench_gate.sh — a perturbed coefficient
# or dt that leaves MLUPS intact trips THIS gate.
#
#   ./out/science_gate.sh                 # fresh round vs newest SCIENCE_r0*.json
#   ./out/science_gate.sh NEW.json        # gate an existing artifact
#   ./out/science_gate.sh NEW.json PRIOR  # explicit prior round
#   ./out/science_gate.sh --record OUT    # run the round, archive the artifact
#   ./out/science_gate.sh --selftest      # prove the gate passes an
#                                         # unmodified round AND trips on an
#                                         # injected 2% diffusivity perturbation
#
# Runs on the virtual CPU backend (no TPU needed), same as tier-1.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

newest_round() {
  ls SCIENCE_r0*.json 2>/dev/null | sort | tail -1
}

# run_round OUT.json — the canonical diagnostic round: one supervised
# diffusion3d and one supervised burgers1d solve with --diag-every 1,
# trajectories extracted into one artifact. SCIENCE_K / SCIENCE_CFL
# override the physics knobs (the self-test's injection point).
run_round() {
  local out="$1"
  local tmp
  tmp="$(mktemp -d)"
  python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
    --n 16 14 12 --iters 30 --K "${SCIENCE_K:-1.0}" \
    --sentinel-every 5 --diag-every 1 --save "$tmp/d3" >/dev/null
  python -m multigpu_advectiondiffusion_tpu.cli burgers1d \
    --n 128 --iters 60 --fixed-dt --cfl "${SCIENCE_CFL:-0.4}" \
    --sentinel-every 5 --diag-every 1 --save "$tmp/b1" >/dev/null
  python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
    --extract "$tmp/d3/summary.json" "$tmp/b1/summary.json" -o "$out"
  rm -rf "$tmp"
}

if [[ "${1:-}" == "--record" ]]; then
  OUT="${2:?usage: science_gate.sh --record OUT.json}"
  run_round "$OUT"
  exit 0
fi

if [[ "${1:-}" == "--selftest" ]]; then
  TMP="$(mktemp -d)"
  trap 'rm -rf "$TMP"' EXIT
  echo "science_gate selftest: recording the reference round"
  run_round "$TMP/base.json"
  echo "science_gate selftest: an unmodified round must PASS"
  run_round "$TMP/clean.json"
  python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
    "$TMP/clean.json" "$TMP/base.json"
  echo "science_gate selftest: a 2% diffusivity perturbation must FAIL"
  SCIENCE_K=1.02 run_round "$TMP/perturbed.json"
  if python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \
      "$TMP/perturbed.json" "$TMP/base.json"; then
    echo "science_gate selftest: gate FAILED to trip on the perturbation" >&2
    exit 1
  fi
  echo "science_gate selftest: OK (gate trips on the perturbation, passes unmodified)"
  exit 0
fi

if [[ -n "${1:-}" ]]; then
  NEW="$1"
else
  NEW="$(mktemp -d)/science_new.json"
  echo "science_gate: running the canonical diagnostic round"
  run_round "$NEW"
fi
PRIOR="${2:-$(newest_round)}"
[[ -n "$PRIOR" ]] || { echo "science_gate: no SCIENCE_r0*.json prior round found (record one with --record SCIENCE_r01.json)" >&2; exit 1; }
echo "science_gate: $NEW vs $PRIOR"
exec python -m multigpu_advectiondiffusion_tpu.diagnostics.compare "$NEW" "$PRIOR"
