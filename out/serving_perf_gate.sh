#!/usr/bin/env bash
# Pipelined-serving perf gate (ISSUE 19): the zero-copy pipeline's
# on/off head-to-head plus the group-commit consistency proof,
# runnable in CI.
#
# 1. Head-to-head: serve the SAME B=8 mixed-width diffusion request
#    set synchronous and pipelined (donated buffers, dispatch-ahead
#    depth 2, async publish) through the bench's own row builder, and
#    fail if the pipelined round's req/s or p99 latency REGRESSES
#    against the synchronous round beyond a CPU-noise tolerance
#    (pipelined req/s >= 0.70x sync, pipelined p99 <= 1.50x sync).
#    On CPU this is mechanics-grade — the overlap hides host work, not
#    device work, and on a 1-core CI box the sync round itself moves
#    +/-25% run to run — so the floors only catch a pipeline that
#    PATHOLOGICALLY loses to the synchronous loop it wraps, which is a
#    regression on every backend. The tight on/off comparison belongs
#    to a TPU bench round, where the device-idle win is the signal.
# 2. Group-commit consistency: run a pipelined server with
#    --group-commit-ms 5 (batched fsyncs) to completion and assert the
#    ack ordering held — every request whose verdict.json says `done`
#    has a journalled `done` transition (no ack escaped ahead of its
#    record's fsync barrier).
# 3. `--selftest`: proves check 2 has teeth — rerun it with
#    TPUCFD_FAULT_ACK_BEFORE_FSYNC=1 (the server acks BEFORE the
#    journal write, and the record is dropped — the power-loss window
#    group commit must never widen) and require the consistency check
#    to TRIP on the acked-but-unjournaled requests.
#
#   ./out/serving_perf_gate.sh             # head-to-head + consistency
#   ./out/serving_perf_gate.sh --selftest  # ack-before-fsync proof
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

CLI=(python -m multigpu_advectiondiffusion_tpu.cli)
REQ=(request --model diffusion --n 12 12 --ic gaussian)

# Every verdict.json that says done must have a journalled done
# transition: the group-commit ack barrier's observable contract.
cat > "$TMP/check_acks.py" <<'PY'
import glob, json, os, sys

root = sys.argv[1]
recs = [json.loads(l) for l in open(os.path.join(root, "journal.jsonl"))
        if l.strip()]
recs = [r.get("record", r) for r in recs]
journaled_done = {r.get("job") for r in recs
                  if r.get("type") == "state" and r.get("to") == "done"}
acked = set()
for p in glob.glob(os.path.join(root, "requests", "*", "verdict.json")):
    v = json.load(open(p))
    if v.get("status") == "done":
        acked.add(os.path.basename(os.path.dirname(p)))
orphans = sorted(acked - journaled_done)
if orphans:
    print(f"acked-but-unjournaled requests: {orphans}", file=sys.stderr)
    sys.exit(1)
print(f"ack consistency OK: {len(acked)} acked, all journalled")
PY

submit_four() {
    local root="$1" tag="$2"
    "${CLI[@]}" "${REQ[@]}" --root "$root" --request-id "${tag}1" \
        --t-end 0.5 --ic-param width=0.08
    "${CLI[@]}" "${REQ[@]}" --root "$root" --request-id "${tag}2" \
        --t-end 0.5 --ic-param width=0.10
    "${CLI[@]}" "${REQ[@]}" --root "$root" --request-id "${tag}3" \
        --t-end 0.45 --ic-param width=0.12
    "${CLI[@]}" "${REQ[@]}" --root "$root" --request-id "${tag}4" \
        --t-end 0.4 --ic-param width=0.14
}

if [[ "${1:-}" == "--selftest" ]]; then
    echo "serving_perf_gate: selftest — ack-before-fsync fault must" \
         "trip the consistency check"
    ROOT="$TMP/fault"
    submit_four "$ROOT" f
    TPUCFD_FAULT_ACK_BEFORE_FSYNC=1 "${CLI[@]}" serve-requests \
        --root "$ROOT" --until-idle --max-batch 4 --slice-steps 4 \
        --poll 0.02 --pipeline --group-commit-ms 5
    if python "$TMP/check_acks.py" "$ROOT" > "$TMP/fault.out" 2>&1; then
        echo "serving_perf_gate: SELFTEST FAILED — acks escaped the" \
             "fsync barrier and the consistency check did not trip" >&2
        exit 1
    fi
    grep -q "acked-but-unjournaled" "$TMP/fault.out" || {
        echo "serving_perf_gate: SELFTEST FAILED — wrong trip" \
             "reason:" >&2
        cat "$TMP/fault.out" >&2
        exit 1
    }
    echo "serving_perf_gate: selftest PASS — injected ack-before-fsync" \
         "detected as acked-but-unjournaled"
    exit 0
fi

echo "serving_perf_gate: head-to-head — sync vs pipelined over the" \
     "same B=8 request set"
python - <<'PY'
import json

import bench

rows = bench._serving_pipelined_rows(on_tpu=False)
by = {}
for row, ok in rows:
    print(json.dumps(row))
    assert ok, f"engagement guard tripped: {row.get('engagement_error')}"
    by["pipelined" if row["pipeline"] else "sync"] = row

sync, pipe = by["sync"], by["pipelined"]
assert pipe["value"] and sync["value"], "missing req/s"
assert pipe["value"] >= 0.70 * sync["value"], (
    f"pipelined req/s regressed: {pipe['value']} vs sync "
    f"{sync['value']} (floor 0.70x)"
)
assert pipe["p99_ms"] and sync["p99_ms"], "missing p99"
assert pipe["p99_ms"] <= 1.50 * sync["p99_ms"], (
    f"pipelined p99 regressed: {pipe['p99_ms']}ms vs sync "
    f"{sync['p99_ms']}ms (cap 1.50x)"
)
print(
    f"serving_perf_gate: head-to-head OK — pipelined "
    f"{pipe['value']} req/s (sync {sync['value']}), p99 "
    f"{pipe['p99_ms']}ms (sync {sync['p99_ms']}ms), device idle "
    f"{pipe['device_idle_frac']} (sync {sync['device_idle_frac']})"
)
PY

echo "serving_perf_gate: group-commit consistency — pipelined server" \
     "with batched fsyncs, every ack must be journalled"
ROOT="$TMP/gc"
submit_four "$ROOT" g
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 4 --poll 0.02 --pipeline --group-commit-ms 5
python "$TMP/check_acks.py" "$ROOT"
"${CLI[@]}" serve-requests --root "$ROOT" --verify --require-complete
grep -q '"serve_journal_fsync_batch_records"' \
    "$ROOT"/metrics/*/metrics.json || {
    echo "serving_perf_gate: FAILED — no fsync batch-size histogram" \
         "in the metrics snapshot (group commit never engaged?)" >&2
    exit 1
}
echo "serving_perf_gate: PASS"
