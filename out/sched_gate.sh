#!/usr/bin/env bash
# Crash-safe scheduler gate (ISSUE 14): the service layer's end-to-end
# chaos proof, runnable in CI.
#
# 1. Kill/replay selftest: submit 3 jobs (j1/j3 identical — the warm-
#    admission pair; j2 long), start the daemon, SIGKILL it the moment
#    j2 is running with a committed checkpoint, restart it, and assert
#    (a) every job reached `done`, (b) the write-ahead journal
#    linearizes (`serve --verify --require-complete`), (c) the second
#    incarnation's sched:recover event replayed + requeued in-flight
#    work, and (d) j3 admitted WARM and served every dispatch from the
#    shared AOT cache (aot_cache:hit, zero miss/store).
# 2. `--selftest`: proves the gate's journal assertion has teeth — a
#    truncated-journal fixture (the torn mid-write tail a crash
#    leaves) must make `serve --verify --require-complete` exit
#    nonzero.
#
#   ./out/sched_gate.sh             # the kill/replay gate
#   ./out/sched_gate.sh --selftest  # truncated-journal trip proof
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

CLI=(python -m multigpu_advectiondiffusion_tpu.cli)
JOB=(diffusion2d --n 24 16 --checkpoint-every 500 --iters 50000)

if [[ "${1:-}" == "--selftest" ]]; then
    echo "sched_gate: selftest — a truncated journal must trip --verify"
    ROOT="$TMP/self"
    "${CLI[@]}" submit --root "$ROOT" --job-id s1 -- \
        diffusion2d --n 16 12 --iters 20 --checkpoint-every 10
    "${CLI[@]}" serve --root "$ROOT" --until-idle --poll 0.05
    "${CLI[@]}" serve --root "$ROOT" --verify --require-complete
    # tear the tail: drop the final commit record and leave a torn line
    python - "$ROOT/journal.jsonl" <<'PY'
import sys
lines = open(sys.argv[1]).read().splitlines()
with open(sys.argv[1], "w") as f:
    f.write("\n".join(lines[:-1]) + "\n" + lines[-1][:23])
PY
    if "${CLI[@]}" serve --root "$ROOT" --verify --require-complete \
        > "$TMP/self.out" 2>&1; then
        echo "sched_gate: SELFTEST FAILED — truncated journal passed" >&2
        exit 1
    fi
    grep -q "terminal" "$TMP/self.out" || {
        echo "sched_gate: SELFTEST FAILED — wrong trip reason:" >&2
        cat "$TMP/self.out" >&2
        exit 1
    }
    echo "sched_gate: selftest OK — truncated journal tripped --verify"
    exit 0
fi

ROOT="$TMP/root"
echo "sched_gate: submitting 3 jobs (j1/j3 identical, j2 the victim)"
"${CLI[@]}" submit --root "$ROOT" --job-id j1 -- "${JOB[@]}"
"${CLI[@]}" submit --root "$ROOT" --job-id j2 -- "${JOB[@]}" --K 0.7
"${CLI[@]}" submit --root "$ROOT" --job-id j3 -- "${JOB[@]}"

echo "sched_gate: daemon up; waiting for j2's first committed checkpoint"
"${CLI[@]}" serve --root "$ROOT" --until-idle --poll 0.05 \
    > "$TMP/daemon1.out" 2>&1 &
DAEMON=$!
for _ in $(seq 1 2400); do
    if compgen -G "$ROOT/jobs/j2/checkpoint_*.ckpt" > /dev/null; then
        break
    fi
    if ! kill -0 "$DAEMON" 2> /dev/null; then
        echo "sched_gate: daemon exited before the kill window:" >&2
        cat "$TMP/daemon1.out" >&2
        exit 1
    fi
    sleep 0.1
done
compgen -G "$ROOT/jobs/j2/checkpoint_*.ckpt" > /dev/null || {
    echo "sched_gate: j2 never checkpointed" >&2
    exit 1
}

echo "sched_gate: SIGKILL the daemon mid-job-2 (pid $DAEMON)"
kill -9 "$DAEMON"
wait "$DAEMON" 2> /dev/null || true

echo "sched_gate: restart — journal replay must finish the queue"
"${CLI[@]}" serve --root "$ROOT" --until-idle --poll 0.05

echo "sched_gate: verify the journal linearizes and every job is done"
"${CLI[@]}" serve --root "$ROOT" --verify --require-complete

python - "$ROOT" <<'PY'
import json, os, sys

root = sys.argv[1]
for jid in ("j1", "j2", "j3"):
    assert os.path.exists(os.path.join(root, "jobs", jid, "result.bin")), \
        f"{jid} produced no result"
evs = [json.loads(l) for l in open(os.path.join(
    root, "sched_events.jsonl")) if l.strip()]
recover = [e for e in evs
           if e["kind"] == "sched" and e["name"] == "recover"][-1]
assert recover["requeued"] >= 1, f"nothing requeued on replay: {recover}"
admits = {e["job"]: e for e in evs
          if e["kind"] == "sched" and e["name"] == "admit"}
assert admits["j3"]["warm"] is True, f"j3 not warm-admitted: {admits['j3']}"
aot = [e["name"] for e in (json.loads(l) for l in open(os.path.join(
    root, "jobs", "j3", "events.jsonl")) if l.strip())
    if e["kind"] == "aot_cache"]
assert "hit" in aot and not [n for n in aot if n in ("miss", "store")], \
    f"warm job recompiled: {aot}"
saved = admits["j3"].get("expected_compile_seconds_saved") or 0
print(f"sched_gate: OK — {recover['records']} journal records replayed, "
      f"{recover['requeued']} requeued, j3 warm-admitted "
      f"({saved:.3f}s compile expected saved, {aot.count('hit')} AOT "
      "hit(s), zero recompiles)")
PY
echo "sched_gate: PASS"
