"""Measure what bounds the f64 diffusion row (VERDICT r4 item 8).

The apples-to-apples `diffusion3d_f64` bench row (literal 400x200x206
grid, XLA path) holds the table's slimmest margin (~1.96x the
reference's own f64 rate). This script pins down WHY the rate is what
it is — emulation op mix vs HBM bytes — with three direct measurements
on one chip:

1. stream roof, f32 vs f64: a fused elementwise pass (y = x*a+b) at
   fixed element count — if f64 were bandwidth-bound it would run at
   half the f32 element rate (2x bytes);
2. ALU roof, f32 vs f64: a 64-deep in-register multiply-add chain per
   element (XLA fuses it into one pass, traffic amortized away) — the
   f32/f64 rate ratio IS the chip's f64 software-emulation factor;
3. the diffusion solver itself, f32 vs f64, same grid and XLA path —
   whichever ratio (bytes 2x vs emulation Nx) the solver ratio lands on
   names the binding resource.

Also probes whether an XLA-level knob moves the f64 row (the only
plausible lever once the bound is arithmetic): `xla_allow_excess
_precision`-style flags don't apply to true f64 semantics, and there is
no "fast-f64" mode in XLA:TPU — the probe documents the absence rather
than asserting it. Findings land in PARITY.md's precision story.

Run: python out/f64_ceiling.py  (real TPU; ~3 min)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from multigpu_advectiondiffusion_tpu.bench.timing import _timed, timed_run
from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.diffusion import (
    DiffusionConfig,
    DiffusionSolver,
)

REPS = 5
N_ELEMS = 64 * 1024 * 1024  # 256 MiB f32 / 512 MiB f64 per operand
CHAIN = 64
ITERS = 50


K = 100  # in-jit repetitions: amortize dispatch/fetch over many passes


def _rate(dtype, body, n=N_ELEMS):
    """Gelem/s per pass of ``body``, measured as K fori_loop passes
    inside ONE jit (each pass reads + writes the carry through HBM) so
    the tunnel's dispatch/host-fetch overhead — which the sync-fetch
    timing discipline (bench/timing.py) pays once per program — is
    amortized to nothing. Returns element rate per pass."""
    from jax import lax

    x = jnp.arange(n, dtype=dtype) * jnp.asarray(1e-9, dtype)

    def loop(v, k):
        return lax.fori_loop(0, k, lambda i, u: body(u), v)

    f = jax.jit(lambda v: loop(v, K))
    z = jax.jit(lambda v: loop(v, 0))
    tr = _timed(lambda: f(x), lambda: z(x), REPS)
    return n * K / tr.seconds / 1e9  # Gelem/s per pass


def main():
    print(f"device: {jax.devices()[0]}\n")

    # 1) stream: one fused elementwise pass, read + write
    stream = {}
    for dt in ("float32", "float64"):
        # dtype-typed constants: a python-float literal would promote
        # the f32 row to f64 under enable_x64
        g = _rate(
            dt,
            lambda v: v * jnp.asarray(1.000001, v.dtype)
            + jnp.asarray(0.5, v.dtype),
        )
        stream[dt] = g
        bytes_per = 2 * jnp.dtype(dt).itemsize
        print(f"stream  {dt}: {g:6.1f} Gelem/s = {g * bytes_per:6.0f} GB/s")
    print(f"stream f32/f64 element-rate ratio: "
          f"{stream['float32'] / stream['float64']:.2f}x "
          f"(pure bytes would be 2.00x)\n")

    # 2) ALU chain: 64 multiply-adds per element, fused into one pass
    def chain(v):
        a = jnp.asarray(1.000001, v.dtype)
        b = jnp.asarray(1e-12, v.dtype)
        for _ in range(CHAIN):
            v = v * a + b
        return v

    alu = {}
    for dt in ("float32", "float64"):
        g = _rate(dt, chain, n=N_ELEMS // 16)
        alu[dt] = g
        print(f"alu-chain  {dt}: {g * CHAIN:7.1f} GFMA/s")
    emu = alu["float32"] / alu["float64"]
    print(f"f64 emulation factor (ALU): {emu:.1f}x\n")

    # 3) the solver row itself, f32 vs f64, literal grid, XLA path
    grid = Grid.make(400, 200, 206, lengths=(10.0, 5.0, 5.15))
    rates = {}
    for dt in ("float32", "float64"):
        s = DiffusionSolver(
            DiffusionConfig(grid=grid, diffusivity=1.0, dtype=dt,
                            impl="xla")
        )
        tr = timed_run(s, s.initial_state(), ITERS, reps=REPS)
        rates[dt] = grid.num_cells * ITERS * 3 / tr.seconds / 1e6
        print(f"diffusion XLA {dt}: {rates[dt]:7.0f} MLUPS "
              f"(spread {tr.spread:.2f})")
    ratio = rates["float32"] / rates["float64"]
    print(f"solver f32/f64 ratio: {ratio:.1f}x "
          f"(bytes-bound would be ~2x; emulation-bound ~{emu:.0f}x)")

    # implied HBM traffic of the f64 row at a conservative >= 2
    # passes/stage (stage read + write; XLA materializes more)
    gbs = rates["float64"] * 1e6 * 2 * 8 / 1e9
    print(f"f64 row implied HBM floor: {gbs:.0f} GB/s "
          f"(vs ~819 GB/s pin) -> "
          f"{'HBM-bound' if gbs > 600 else 'NOT HBM-bound'}")


if __name__ == "__main__":
    main()
