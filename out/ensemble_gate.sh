#!/usr/bin/env bash
# Ensemble-engine gate (ISSUE 9 + the ISSUE 11 mesh round):
#
# 1. Cold-vs-warm AOT executable cache selftest: the same batched
#    ensemble CLI request is run twice against a fresh TPUCFD_AOT_CACHE.
#    The cold run must compile and STORE every dispatch program; the
#    warm run must HIT for every program — zero misses, zero stores,
#    i.e. zero recompiles of the cached executables — and its xla:cost
#    events must record the compile seconds saved.
# 2. bench/compare.py coverage selftest for the ensemble_* rows: new
#    rounds carrying the `ensemble`/`vs_looped` columns must compare
#    cleanly against pre-ensemble rounds (BENCH_r01-r05 rows have
#    neither field), and a dropped ensemble column must surface as a
#    non-gating coverage note (the MEASURED_FIELDS discipline).
# 3. Member-sharded mesh selftest (ISSUE 11): the same batched request
#    on an 8-virtual-device 'members' mesh — the ensemble:dispatch
#    events must record the member sharding (no silent single-device
#    fallback), and the warm run must AOT-HIT the member-sharded
#    executable with zero misses/stores.
#
#   ./out/ensemble_gate.sh          # run all three selftests
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT
export TPUCFD_AOT_CACHE="$TMP/aot"

CMD=(python -m multigpu_advectiondiffusion_tpu.cli diffusion3d
     --n 20 16 12 --iters 4 --ensemble 4 --sweep K=0.5:2.0 --impl xla)

echo "ensemble_gate: cold run (compile + store)"
"${CMD[@]}" --metrics "$TMP/cold.jsonl" > "$TMP/cold.out"
echo "ensemble_gate: warm run (must hit the AOT cache, zero recompiles)"
"${CMD[@]}" --metrics "$TMP/warm.jsonl" > "$TMP/warm.out"

python - "$TMP/cold.jsonl" "$TMP/warm.jsonl" <<'PY'
import json, sys

def events(path):
    return [json.loads(line) for line in open(path) if line.strip()]

cold = [e for e in events(sys.argv[1]) if e["kind"] == "aot_cache"]
warm = [e for e in events(sys.argv[2]) if e["kind"] == "aot_cache"]
stores = [e for e in cold if e["name"] == "store" and e.get("persisted")]
assert stores, f"cold run persisted nothing: {cold}"
assert not [e for e in cold if e["name"] == "hit"], \
    "cold run hit a fresh cache?"
hits = [e for e in warm if e["name"] == "hit"]
assert hits, f"warm run must emit aot_cache:hit; got {warm}"
recompiles = [e for e in warm if e["name"] in ("miss", "store")]
assert not recompiles, f"warm run recompiled: {recompiles}"
xla = [e for e in events(sys.argv[2])
       if e["kind"] == "xla" and e["name"] == "cost"]
not_loaded = [e["key"] for e in xla if e.get("aot") != "hit"]
assert not not_loaded, \
    f"warm xla:cost events not served from the AOT cache: {not_loaded}"
saved = sum(e.get("compile_seconds_saved") or 0 for e in hits)
print(f"ensemble_gate: AOT selftest OK — {len(stores)} store(s) cold, "
      f"{len(hits)} hit(s) warm, {saved:.3f}s of compile skipped")
PY

echo "ensemble_gate: bench/compare.py ensemble-row coverage selftest"
python - "$TMP" <<'PY'
import json, os, sys

from multigpu_advectiondiffusion_tpu.bench import compare as cmp

tmp = sys.argv[1]
old_rows = [  # a pre-ensemble round: no ensemble/vs_looped fields
    {"metric": "diffusion3d_mlups", "value": 100.0, "spread": 0.01},
]
new_rows = [
    {"metric": "diffusion3d_mlups", "value": 101.0, "spread": 0.01,
     "ensemble": 1},
    {"metric": "ensemble_diffusion3d_b64_mlups_members", "value": 900.0,
     "spread": 0.02, "ensemble": 64, "vs_looped": 3.4},
]
def write(path, rows):
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(r) for r in rows) + "\n")
write(os.path.join(tmp, "old.jsonl"), old_rows)
write(os.path.join(tmp, "new.jsonl"), new_rows)
res = cmp.compare(cmp.load_rows(os.path.join(tmp, "new.jsonl")),
                  cmp.load_rows(os.path.join(tmp, "old.jsonl")))
assert res.ok, res.format_text()
assert not res.notes, f"pre-ensemble rounds must not note: {res.notes}"
assert [r for r in res.rows if r.status == "added"], \
    "new ensemble rows must read as added, not regressions"
# a later round that silently DROPS the ensemble columns gets a note
# (non-gating), the MEASURED_FIELDS discipline
stripped = [dict(new_rows[0]), dict(new_rows[1])]
del stripped[1]["ensemble"]; del stripped[1]["vs_looped"]
write(os.path.join(tmp, "stripped.jsonl"), stripped)
res2 = cmp.compare(cmp.load_rows(os.path.join(tmp, "stripped.jsonl")),
                   cmp.load_rows(os.path.join(tmp, "new.jsonl")))
assert res2.ok, "dropped provenance columns must not gate"
assert any("vs_looped" in n for n in res2.notes), res2.notes
# member-placement drift is surfaced as a non-gating note (ISSUE 11)
drift = [dict(new_rows[0]), dict(new_rows[1])]
drift[1]["member_sharding"] = 8
write(os.path.join(tmp, "drift.jsonl"), drift)
res3 = cmp.compare(cmp.load_rows(os.path.join(tmp, "drift.jsonl")),
                   cmp.load_rows(os.path.join(tmp, "new.jsonl")))
assert res3.ok, "member-placement drift must not gate"
assert any("member placement" in n for n in res3.notes), res3.notes
print("ensemble_gate: compare coverage selftest OK")
PY

echo "ensemble_gate: member-sharded mesh selftest (8 virtual devices)"
MESH_ENV=(env JAX_PLATFORMS=cpu
          XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}"
          TPUCFD_AOT_CACHE="$TMP/aot_mesh")
MCMD=(python -m multigpu_advectiondiffusion_tpu.cli diffusion3d
      --n 16 12 8 --iters 3 --ensemble 8 --mesh members=8
      --sweep K=0.5:2.0 --impl xla)
"${MESH_ENV[@]}" "${MCMD[@]}" --metrics "$TMP/mesh_cold.jsonl" \
    > "$TMP/mesh_cold.out"
"${MESH_ENV[@]}" "${MCMD[@]}" --metrics "$TMP/mesh_warm.jsonl" \
    > "$TMP/mesh_warm.out"

python - "$TMP/mesh_cold.jsonl" "$TMP/mesh_warm.jsonl" <<'PY'
import json, sys

def events(path):
    return [json.loads(line) for line in open(path) if line.strip()]

for path in sys.argv[1:]:
    disp = [e for e in events(path) if e["kind"] == "ensemble"]
    assert disp, f"{path}: no ensemble:dispatch events"
    for e in disp:
        assert e["member_sharding"] == 8 and e["devices"] == 8, (
            f"{path}: batched dispatch fell back off the mesh: {e}"
        )
cold = [e for e in events(sys.argv[1]) if e["kind"] == "aot_cache"]
warm = [e for e in events(sys.argv[2]) if e["kind"] == "aot_cache"]
assert [e for e in cold if e["name"] == "store" and e.get("persisted")], \
    f"cold mesh run persisted nothing: {cold}"
hits = [e for e in warm if e["name"] == "hit"]
assert hits, f"warm mesh run must hit the AOT cache: {warm}"
recompiles = [e for e in warm if e["name"] in ("miss", "store")]
assert not recompiles, f"warm mesh run recompiled: {recompiles}"
saved = sum(e.get("compile_seconds_saved") or 0 for e in hits)
print(f"ensemble_gate: mesh selftest OK — member-sharded dispatch over "
      f"8 devices, {len(hits)} warm AOT hit(s), {saved:.3f}s of "
      "compile skipped")
PY

echo "ensemble_gate: OK"
