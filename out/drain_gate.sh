#!/usr/bin/env bash
# Graceful-drain handover gate (ISSUE 20): continuous submission across
# a SIGTERM drain + successor start, provable in CI.
#
# 1. Drain gate: submit 4 coalescible requests, start the serving
#    daemon (single-writer lease ON — the CLI default), keep submitting
#    while its first batch marches, then drain it via the operator verb
#    (`serve-requests --root DIR --drain` SIGTERMs the lease holder).
#    Assert (a) the daemon exits 0 with `shutdown clean=true` as the
#    journal's LAST record and the lease released, (b) a request
#    submitted BETWEEN the two incarnations is inherited from the
#    spool, (c) the successor starts with ZERO crash-recovery requeues
#    (the clean-handover fast start), and (d) every request across the
#    whole timeline — before, during and after the handover — is
#    answered EXACTLY once with a published result, journal complete.
# 2. `--selftest`: proves the gate's assertions have teeth —
#    the duplicate `done` record a second un-leased server interleaves
#    (with the lease ON it would exit 78 before writing a byte; the
#    selftest disables it and forges the double-serve) must trip the
#    exactly-once check, and a dropped in-flight request (admitted,
#    marching, never answered) must trip `--verify --require-complete`.
#
#   ./out/drain_gate.sh             # the drain/handover gate
#   ./out/drain_gate.sh --selftest  # double-serve + dropped-request proofs
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

CLI=(python -m multigpu_advectiondiffusion_tpu.cli)
REQ=(request --model diffusion --n 12 12 --ic gaussian)

# exactly-once over a comma-separated id list: exit 1 on any request
# answered zero or 2+ times
check_exactly_once() {
    python - "$1" "$2" <<'PY'
import json, sys
root, ids = sys.argv[1], sys.argv[2].split(",")
done = {}
for line in open(f"{root}/journal.jsonl"):
    try:
        r = json.loads(line)
    except ValueError:
        continue
    if r.get("type") == "state" and r.get("to") == "done":
        done[r["job"]] = done.get(r["job"], 0) + 1
bad = {i: done.get(i, 0) for i in ids if done.get(i, 0) != 1}
if bad:
    print(f"drain_gate: NOT exactly once: {bad}", file=sys.stderr)
    sys.exit(1)
PY
}

if [[ "${1:-}" == "--selftest" ]]; then
    echo "drain_gate: selftest 1 — an injected double-serve (lease" \
         "disabled) must trip the exactly-once check"
    ROOT="$TMP/double"
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id ds1 \
        --t-end 0.15
    "${CLI[@]}" serve-requests --root "$ROOT" --no-lease --until-idle \
        --max-batch 2 --slice-steps 4 --poll 0.02
    check_exactly_once "$ROOT" ds1
    # the record stream a SECOND un-leased server would interleave:
    # it replays the journal concurrently with the first, re-marches
    # ds1, and appends its own done. With the lease on, that writer
    # exits 78 before this record can exist.
    python - "$ROOT" <<'PY'
import sys
from multigpu_advectiondiffusion_tpu.service.journal import Journal
j = Journal(f"{sys.argv[1]}/journal.jsonl", fsync=False)
j.append("state", job="ds1", **{"from": "running", "to": "done"})
j.close()
PY
    if check_exactly_once "$ROOT" ds1 2> /dev/null; then
        echo "drain_gate: SELFTEST FAILED — double-serve passed the" \
             "exactly-once check" >&2
        exit 1
    fi
    echo "drain_gate: selftest 1 OK — double-serve tripped the gate"

    echo "drain_gate: selftest 2 — a dropped in-flight request must" \
         "trip --verify --require-complete"
    ROOT="$TMP/dropped"
    # a horizon the 1.5s serving window cannot reach: admitted and
    # marching (journalled, non-terminal) when the server stops —
    # exactly what a lost in-flight request leaves behind
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id drop1 \
        --t-end 50.0
    "${CLI[@]}" serve-requests --root "$ROOT" --no-lease --max-batch 2 \
        --slice-steps 1 --poll 0.02 --max-seconds 1.5
    "${CLI[@]}" serve-requests --root "$ROOT" --verify
    if "${CLI[@]}" serve-requests --root "$ROOT" --verify \
        --require-complete > "$TMP/drop.out" 2>&1; then
        echo "drain_gate: SELFTEST FAILED — dropped in-flight request" \
             "passed --require-complete" >&2
        exit 1
    fi
    echo "drain_gate: selftest 2 OK — dropped request tripped the gate"
    echo "drain_gate: selftest PASS"
    exit 0
fi

ROOT="$TMP/root"
echo "drain_gate: submitting 4 coalescible requests"
# a horizon long enough (~2400 steps, ~1200 slices) that the drain
# verb's own interpreter startup still lands mid-march
for i in 1 2 3 4; do
    "${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id "g$i" \
        --t-end 20.0 --ic-param "width=0.$((6 + 2 * i))"
done

echo "drain_gate: server 1 up (lease on); waiting for a marched slice"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02 > "$TMP/server1.out" 2>&1 &
SERVER=$!
for _ in $(seq 1 2400); do
    if grep -q '"slice"' "$ROOT/serve_events.jsonl" 2> /dev/null; then
        break
    fi
    if ! kill -0 "$SERVER" 2> /dev/null; then
        echo "drain_gate: server exited before the drain window:" >&2
        cat "$TMP/server1.out" >&2
        exit 1
    fi
    sleep 0.05
done
grep -q '"slice"' "$ROOT/serve_events.jsonl" || {
    echo "drain_gate: server never marched a slice" >&2
    exit 1
}

echo "drain_gate: submitting g5 mid-flight, then draining the holder"
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id g5 --t-end 20.0
"${CLI[@]}" serve-requests --root "$ROOT" --drain
if ! wait "$SERVER"; then
    echo "drain_gate: drained server exited non-zero:" >&2
    cat "$TMP/server1.out" >&2
    exit 1
fi

python - "$ROOT" <<'PY'
import json, os, sys
root = sys.argv[1]
records = []
for line in open(os.path.join(root, "journal.jsonl")):
    try:
        records.append(json.loads(line))
    except ValueError:
        pass
last = records[-1]
assert last.get("type") == "note" and last.get("note") == "shutdown" \
    and last.get("clean") is True, \
    f"journal does not end with shutdown clean=true: {last}"
assert not os.path.exists(os.path.join(root, "lease.json")), \
    "lease.json survived the drain"
print("drain_gate: clean shutdown marker + lease released")
PY

echo "drain_gate: submitting g6 between incarnations"
"${CLI[@]}" "${REQ[@]}" --root "$ROOT" --request-id g6 --t-end 20.0

echo "drain_gate: successor up — must inherit spool + parked work"
"${CLI[@]}" serve-requests --root "$ROOT" --until-idle --max-batch 4 \
    --slice-steps 2 --poll 0.02 > "$TMP/server2.out" 2>&1

echo "drain_gate: verify journal linearizes, complete"
"${CLI[@]}" serve-requests --root "$ROOT" --verify --require-complete
check_exactly_once "$ROOT" g1,g2,g3,g4,g5,g6

python - "$ROOT" <<'PY'
import json, os, sys
root = sys.argv[1]
evs = [json.loads(l) for l in open(os.path.join(
    root, "serve_events.jsonl")) if l.strip()]
recover = [e for e in evs
           if e["kind"] == "serve" and e["name"] == "recover"]
assert recover, "successor journalled no serve:recover"
final = recover[-1]
assert final["clean_shutdown"] is True, \
    f"successor did not see a clean shutdown: {final}"
assert final["requeued"] == 0, \
    f"clean handover still paid crash-recovery requeues: {final}"
for rid in ("g1", "g2", "g3", "g4", "g5", "g6"):
    assert os.path.exists(os.path.join(
        root, "requests", rid, "result.bin")), f"{rid}: no result.bin"
    v = json.load(open(os.path.join(root, "requests", rid,
                                    "verdict.json")))
    assert v["status"] == "done", f"{rid}: verdict {v}"
print("drain_gate: OK — 6 requests answered exactly once across the "
      "handover, successor started with zero requeues")
PY
echo "drain_gate: PASS"
