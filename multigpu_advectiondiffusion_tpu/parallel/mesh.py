"""Device meshes and domain decompositions.

The reference decomposes 1-D slabs along the last axis only, one MPI rank
per GPU (``MultiGPU/Diffusion3d_Baseline/main.c:69``,
``Util.cu:66-74`` ``AssignDevices``). Here a decomposition is a mapping
from grid axes to named ``jax.sharding.Mesh`` axes — 1-D slabs, 2-D pencils
or full 3-D blocks — and device placement is XLA's.
"""

from __future__ import annotations

import dataclasses
import inspect
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax >= 0.4.35 promoted shard_map out of experimental
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore  # noqa: E501

# the replication checker kwarg was renamed check_rep -> check_vma
_CHECK_KWARG = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)

# Reserved mesh-axis name for the batched ensemble engine's member
# dimension (ROADMAP item 1: members x devices). The member axis shards
# the LEADING axis of a (B, *grid) batched state — members are
# embarrassingly parallel, so the axis is halo-free by construction and
# never appears in a spatial Decomposition (statically proven by
# analysis/halo_verify.verify_member_mesh).
MEMBER_AXIS = "members"


def member_extent(mesh) -> int:
    """Shard count of the ensemble member axis (1 when the mesh is
    ``None`` or carries no ``members`` axis)."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(MEMBER_AXIS, 1))


def shard_map(*args, check: bool = True, **kwargs):
    """Project ``shard_map``. ``check=False`` disables the
    varying-across-mesh-axes/replication checker — needed only for
    programs containing ``pallas_call`` (whose output avals carry no
    ``vma`` typing); everything else keeps the checker on."""
    kwargs.setdefault(_CHECK_KWARG, check)
    return _shard_map(*args, **kwargs)


def make_mesh(
    axis_sizes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh, e.g. ``make_mesh({'dz': 4, 'dy': 2})``.

    Axis order follows dict order; total size must divide the device count
    (or equal it when ``devices`` is None).
    """
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    if devices is None:
        devices = jax.devices()
    need = math.prod(sizes)
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, only {len(devices)} available")
    return jax.make_mesh(sizes, names, devices=tuple(devices[:need]))


def axis_extent(sizes, name) -> int:
    """Shard count of a mesh-axis spec: a single axis name, or a tuple of
    names (compound axis) whose extents multiply."""
    if isinstance(name, tuple):
        return math.prod(sizes[n] for n in name)
    return sizes[name]


def reduce_axis_names(decomp: "Decomposition", axis_sizes) -> Tuple[str, ...]:
    """The pmax/psum axis-name set of a decomposition under the given
    mesh extents: every individual mesh axis in use whose extent
    exceeds 1. The SINGLE source of the cross-shard reduction set —
    ``SolverBase.mesh_reduce_max``/``mesh_reduce_sum`` and the static
    sharding pass (``analysis/collective_verify``) both derive from
    here, so the reduction a step performs and the one the verifier
    proves cannot fork."""
    sizes = dict(axis_sizes)
    return tuple(
        n for n in decomp.mesh_axis_names() if sizes.get(n, 1) > 1
    )


@dataclasses.dataclass(frozen=True)
class Decomposition:
    """Maps array axes of the grid to mesh axes.

    ``axes[array_axis] = mesh_axis_name`` (axes not present are unsharded).
    The reference's slab split is ``Decomposition.slab(ndim)``: last-array-
    axis... i.e. z in 3-D, matching ``_Nz = Nz/np`` (``main.c:69``) — note
    the reference splits the *z* axis, which in this framework's
    ``(z, y, x)`` array order is axis 0.

    A mesh-axis entry may also be a *tuple* of mesh axis names — a
    compound axis splitting one grid axis over several mesh axes,
    outermost first. This is the multi-host layout: z over
    ``('dz_dcn', 'dz_ici')`` puts shard blocks on hosts (DCN hops between
    blocks) with consecutive shards inside each host riding ICI
    (:mod:`parallel.multihost`). ``ppermute``/``axis_index`` address the
    compound axis by its flattened row-major index, so the halo-exchange
    program is unchanged.
    """

    axes: Tuple[Tuple[int, object], ...]

    @staticmethod
    def of(mapping: Dict[int, object]) -> "Decomposition":
        norm = {
            ax: tuple(n) if isinstance(n, (list, tuple)) else n
            for ax, n in mapping.items()
        }
        return Decomposition(tuple(sorted(norm.items())))

    @staticmethod
    def slab(mesh_axis: str = "dz") -> "Decomposition":
        """Reference-style 1-D slab decomposition along z (array axis 0)."""
        return Decomposition.of({0: mesh_axis})

    @property
    def mapping(self) -> Dict[int, str]:
        return dict(self.axes)

    def mesh_axis(self, array_axis: int):
        return self.mapping.get(array_axis)

    def mesh_axis_names(self) -> Tuple[str, ...]:
        """All individual mesh axis names in use (compound axes flattened)."""
        out = []
        for _, name in self.axes:
            out.extend(name if isinstance(name, tuple) else (name,))
        return tuple(out)

    def partition_spec(self, ndim: int) -> PartitionSpec:
        return PartitionSpec(*[self.mapping.get(ax) for ax in range(ndim)])

    def sharding(self, mesh: Mesh, ndim: int) -> NamedSharding:
        return NamedSharding(mesh, self.partition_spec(ndim))

    def validate(self, mesh: Mesh, global_shape: Sequence[int]) -> None:
        """Startup topology assertions (the reference's ``MPIDeviceCheck``
        analog, ``Util.cu:43-61``) — every sharded axis must divide evenly
        and leave at least one stencil-halo worth of cells per shard."""
        for ax, name in self.axes:
            for n in name if isinstance(name, tuple) else (name,):
                if n not in mesh.shape:
                    raise ValueError(f"mesh has no axis {n!r}")
            parts = axis_extent(mesh.shape, name)
            if global_shape[ax] % parts:
                raise ValueError(
                    f"axis {ax} size {global_shape[ax]} not divisible by "
                    f"mesh axis {name!r} ({parts} shards)"
                )

    def local_shape(self, mesh: Mesh, global_shape: Sequence[int]) -> Tuple[int, ...]:
        out = list(global_shape)
        for ax, name in self.axes:
            out[ax] //= axis_extent(mesh.shape, name)
        return tuple(out)
