"""Multi-host (DCN) execution support.

The reference scales across nodes by launching MPI ranks under
``mpirun`` with host-staged point-to-point messaging
(``MultiGPU/*/main.c``, OpenMPI/MVAPICH2 — ``DiffusionMPICUDA.h:75-81``).
The TPU-native equivalent: one Python process per host calls
:func:`initialize` (``jax.distributed``), every host sees the global
device set, and a *hybrid* mesh places the outermost decomposition axis
on DCN while inner axes ride ICI. The same ``shard_map`` halo-exchange
program then runs unchanged — XLA routes each ``ppermute`` hop over ICI
or DCN by device placement.

Single-host runs never need this module; it is the opt-in scale-out
layer (SURVEY §2.4 multi-node row).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    attempts: Optional[int] = None,
    backoff_seconds: Optional[float] = None,
    timeout_seconds: Optional[float] = None,
) -> None:
    """Bring up the jax.distributed runtime (InitializeMPI analog,
    ``Tools.c:228-234``). On managed TPU pods all arguments auto-detect;
    on hand-rolled clusters pass coordinator/process info explicitly.

    Coordinator join is retried under exponential backoff: on a real
    cluster the coordinator process routinely comes up seconds after the
    workers (restart/preemption races), and a single failed dial must
    not kill a rank that a 2-second wait would have saved. Defaults —
    3 ``attempts``, ``backoff_seconds`` 2.0 doubling per retry — are
    overridable per call or via ``TPUCFD_DIST_ATTEMPTS`` /
    ``TPUCFD_DIST_BACKOFF`` / ``TPUCFD_DIST_TIMEOUT`` (the last maps to
    jax's ``initialization_timeout`` where supported). A runtime that is
    already initialized is success, not an error (idempotent under the
    supervisor's retry paths).

    On the CPU backend (the virtual-device demo/test world) JAX ships no
    default cross-process collective transport — every multiprocess
    computation fails with "not implemented" unless the gloo transport
    is selected before the runtime comes up."""
    import os
    import time

    plats = (
        os.environ.get("JAX_PLATFORMS", "") or jax.default_backend()
    )
    if "cpu" in plats:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jax: flag absent, gloo is the default
            pass

    if attempts is None:
        attempts = int(os.environ.get("TPUCFD_DIST_ATTEMPTS", "3"))
    if backoff_seconds is None:
        backoff_seconds = float(os.environ.get("TPUCFD_DIST_BACKOFF", "2.0"))
    if timeout_seconds is None:
        env = os.environ.get("TPUCFD_DIST_TIMEOUT")
        timeout_seconds = float(env) if env else None

    kwargs = dict(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    if timeout_seconds is not None:
        import inspect

        try:
            params = inspect.signature(jax.distributed.initialize).parameters
            if "initialization_timeout" in params:
                kwargs["initialization_timeout"] = int(timeout_seconds)
        except (TypeError, ValueError):
            pass  # unsignaturable wrapper: retry loop carries the policy

    # retry telemetry: every attempt/backoff/outcome is an ordered event
    # in the --metrics stream (the CLI installs the sink BEFORE joining
    # the distributed runtime), so a rank that spun on a dead
    # coordinator is diagnosable from its artifact instead of silent
    from multigpu_advectiondiffusion_tpu import telemetry

    attempts = max(1, attempts)
    last_exc = None
    for attempt in range(attempts):
        telemetry.event(
            "dist_init", "attempt",
            attempt=attempt + 1, attempts=attempts,
            coordinator=coordinator_address, process_id=process_id,
        )
        try:
            jax.distributed.initialize(**kwargs)
            telemetry.event("dist_init", "ok", attempt=attempt + 1)
            return
        except RuntimeError as exc:
            if "already initialized" in str(exc).lower():
                telemetry.event(
                    "dist_init", "ok", attempt=attempt + 1,
                    already_initialized=True,
                )
                return  # idempotent re-entry (supervisor retry paths)
            last_exc = exc
        except Exception as exc:  # transient dial/handshake failures
            last_exc = exc
        if attempt + 1 < attempts:
            delay = backoff_seconds * (2 ** attempt)
            telemetry.event(
                "dist_init", "retry",
                attempt=attempt + 1, backoff_seconds=delay,
                error=f"{type(last_exc).__name__}: {last_exc}"[:300],
            )
            time.sleep(delay)
    telemetry.event(
        "dist_init", "failed",
        attempts=attempts,
        error=f"{type(last_exc).__name__}: {last_exc}"[:300],
    )
    raise RuntimeError(
        f"jax.distributed.initialize failed after {attempts} attempt(s) "
        f"(coordinator={coordinator_address!r}): {last_exc}"
    ) from last_exc


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
) -> Mesh:
    """Mesh whose ``dcn_axes`` cross host (slice) boundaries and whose
    ``ici_axes`` stay within a slice.

    Example for 4 hosts of 8 chips solving a z-slab problem:
    ``hybrid_mesh({'dz_ici': 8}, {'dz_dcn': 4})`` then decompose z over
    ``('dz_dcn', 'dz_ici')``.
    """
    from jax.experimental import mesh_utils

    dcn_sizes = tuple(dcn_axes.values())
    ici_sizes = tuple(ici_axes.values())
    names = tuple(dcn_axes) + tuple(ici_axes)

    total = 1
    for s in dcn_sizes + ici_sizes:
        total *= s
    dcn_total = 1
    for s in dcn_sizes:
        dcn_total *= s

    devs = list(jax.devices())
    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    n_slices = len(slice_ids) if None not in slice_ids else 0
    n_procs = len({d.process_index for d in devs})
    if n_slices == dcn_total:
        # Topology-aware placement: orders devices along the ICI torus so
        # ppermute halo neighbors are physically adjacent. Real
        # misconfigurations (axis sizes vs device count etc.) raise from
        # here and stay loud.
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs
        )
    elif n_procs == dcn_total:
        # The slice topology does not match the requested DCN extent
        # (e.g. multi-process CPU, where every device reports slice 0),
        # but the process count does: one process = one DCN granule —
        # the MPI-rank view of the world (Tools.c:228-242).
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs, process_is_granule=True,
        )
    elif dcn_total == 1:
        # Last-resort single-granule fallback. NOTE a single process
        # never reaches here (n_procs == 1 == dcn_total matches the
        # branch above); this covers only multi-process platforms whose
        # devices carry neither a matching slice topology nor a matching
        # process count, where a plain row-major mesh over ALL devices
        # is still a valid, if unoptimized, hybrid mesh.
        if total != len(devs):
            raise ValueError(
                f"hybrid_mesh axes need {total} devices, have {len(devs)}"
            )
        devices = np.asarray(devs).reshape(dcn_sizes + ici_sizes)
        return Mesh(devices, names)
    else:
        raise ValueError(
            f"cannot place DCN extent {dcn_total}: platform reports "
            f"{n_slices} slice(s) and {n_procs} process(es)"
        )
    # create_hybrid_device_mesh returns the devices in dcn-major order
    # (some backends flatten) — impose the dcn_sizes + ici_sizes shape
    return Mesh(np.asarray(devices).reshape(dcn_sizes + ici_sizes), names)


def process_local_devices() -> Sequence:
    """Devices attached to this process (AssignDevices analog — the
    reference binds rank -> GPU, ``Util.cu:66-74``; JAX binds
    process -> local chips automatically)."""
    return jax.local_devices()


def is_coordinator() -> bool:
    """True on process 0 (the reference's ``rank == 0`` I/O gate,
    ``main.c:82-86``)."""
    return jax.process_index() == 0
