"""Multi-host (DCN) execution support.

The reference scales across nodes by launching MPI ranks under
``mpirun`` with host-staged point-to-point messaging
(``MultiGPU/*/main.c``, OpenMPI/MVAPICH2 — ``DiffusionMPICUDA.h:75-81``).
The TPU-native equivalent: one Python process per host calls
:func:`initialize` (``jax.distributed``), every host sees the global
device set, and a *hybrid* mesh places the outermost decomposition axis
on DCN while inner axes ride ICI. The same ``shard_map`` halo-exchange
program then runs unchanged — XLA routes each ``ppermute`` hop over ICI
or DCN by device placement.

Single-host runs never need this module; it is the opt-in scale-out
layer (SURVEY §2.4 multi-node row).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the jax.distributed runtime (InitializeMPI analog,
    ``Tools.c:228-234``). On managed TPU pods all arguments auto-detect;
    on hand-rolled clusters pass coordinator/process info explicitly."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
) -> Mesh:
    """Mesh whose ``dcn_axes`` cross host (slice) boundaries and whose
    ``ici_axes`` stay within a slice.

    Example for 4 hosts of 8 chips solving a z-slab problem:
    ``hybrid_mesh({'dz_ici': 8}, {'dz_dcn': 4})`` then decompose z over
    ``('dz_dcn', 'dz_ici')``.
    """
    from jax.experimental import mesh_utils

    dcn_sizes = tuple(dcn_axes.values())
    ici_sizes = tuple(ici_axes.values())
    names = tuple(dcn_axes) + tuple(ici_axes)

    total = 1
    for s in dcn_sizes + ici_sizes:
        total *= s
    dcn_total = 1
    for s in dcn_sizes:
        dcn_total *= s

    devs = list(jax.devices())
    has_slice_topology = all(
        getattr(d, "slice_index", None) is not None for d in devs
    )
    if has_slice_topology:
        # Topology-aware placement: orders devices along the ICI torus so
        # ppermute halo neighbors are physically adjacent. Real
        # misconfigurations (axis sizes vs device count etc.) raise from
        # here and stay loud.
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs
        )
    elif dcn_total == 1:
        # Platforms whose devices carry no slice topology (e.g. the
        # virtual-CPU test mesh): with no cross-slice axis a plain
        # row-major mesh over ALL devices is a valid, if unoptimized,
        # hybrid mesh.
        if total != len(devs):
            raise ValueError(
                f"hybrid_mesh axes need {total} devices, have {len(devs)}"
            )
        devices = np.asarray(devs).reshape(dcn_sizes + ici_sizes)
        return Mesh(devices, names)
    else:
        # Devices without slice topology but a real DCN extent: group by
        # process instead (raises a clear ValueError if the process count
        # cannot satisfy dcn_sizes).
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs, process_is_granule=True,
        )
    # create_hybrid_device_mesh returns shape dcn_sizes + ici_sizes
    return Mesh(np.asarray(devices), names)


def process_local_devices() -> Sequence:
    """Devices attached to this process (AssignDevices analog — the
    reference binds rank -> GPU, ``Util.cu:66-74``; JAX binds
    process -> local chips automatically)."""
    return jax.local_devices()


def is_coordinator() -> bool:
    """True on process 0 (the reference's ``rank == 0`` I/O gate,
    ``main.c:82-86``)."""
    return jax.process_index() == 0
