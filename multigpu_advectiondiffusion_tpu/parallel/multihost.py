"""Multi-host (DCN) execution support.

The reference scales across nodes by launching MPI ranks under
``mpirun`` with host-staged point-to-point messaging
(``MultiGPU/*/main.c``, OpenMPI/MVAPICH2 — ``DiffusionMPICUDA.h:75-81``).
The TPU-native equivalent: one Python process per host calls
:func:`initialize` (``jax.distributed``), every host sees the global
device set, and a *hybrid* mesh places the outermost decomposition axis
on DCN while inner axes ride ICI. The same ``shard_map`` halo-exchange
program then runs unchanged — XLA routes each ``ppermute`` hop over ICI
or DCN by device placement.

Single-host runs never need this module; it is the opt-in scale-out
layer (SURVEY §2.4 multi-node row).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    attempts: Optional[int] = None,
    backoff_seconds: Optional[float] = None,
    timeout_seconds: Optional[float] = None,
) -> None:
    """Bring up the jax.distributed runtime (InitializeMPI analog,
    ``Tools.c:228-234``). On managed TPU pods all arguments auto-detect;
    on hand-rolled clusters pass coordinator/process info explicitly.

    Coordinator join is retried under exponential backoff: on a real
    cluster the coordinator process routinely comes up seconds after the
    workers (restart/preemption races), and a single failed dial must
    not kill a rank that a 2-second wait would have saved. Defaults —
    3 ``attempts``, ``backoff_seconds`` 2.0 doubling per retry — are
    overridable per call or via ``TPUCFD_DIST_ATTEMPTS`` /
    ``TPUCFD_DIST_BACKOFF`` / ``TPUCFD_DIST_TIMEOUT`` (the last maps to
    jax's ``initialization_timeout`` where supported). A runtime that is
    already initialized is success, not an error (idempotent under the
    supervisor's retry paths).

    On the CPU backend (the virtual-device demo/test world) JAX ships no
    default cross-process collective transport — every multiprocess
    computation fails with "not implemented" unless the gloo transport
    is selected before the runtime comes up."""
    import os
    import time

    plats = (
        os.environ.get("JAX_PLATFORMS", "") or jax.default_backend()
    )
    if "cpu" in plats:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jax: flag absent, gloo is the default
            pass

    if attempts is None:
        attempts = int(os.environ.get("TPUCFD_DIST_ATTEMPTS", "3"))
    if backoff_seconds is None:
        backoff_seconds = float(os.environ.get("TPUCFD_DIST_BACKOFF", "2.0"))
    if timeout_seconds is None:
        env = os.environ.get("TPUCFD_DIST_TIMEOUT")
        timeout_seconds = float(env) if env else None

    kwargs = dict(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    if timeout_seconds is not None:
        import inspect

        try:
            params = inspect.signature(jax.distributed.initialize).parameters
            if "initialization_timeout" in params:
                kwargs["initialization_timeout"] = int(timeout_seconds)
        except (TypeError, ValueError):
            pass  # unsignaturable wrapper: retry loop carries the policy

    # retry telemetry: every attempt/backoff/outcome is an ordered event
    # in the --metrics stream (the CLI installs the sink BEFORE joining
    # the distributed runtime), so a rank that spun on a dead
    # coordinator is diagnosable from its artifact instead of silent
    from multigpu_advectiondiffusion_tpu import telemetry

    attempts = max(1, attempts)
    last_exc = None
    for attempt in range(attempts):
        telemetry.event(
            "dist_init", "attempt",
            attempt=attempt + 1, attempts=attempts,
            coordinator=coordinator_address, process_id=process_id,
        )
        try:
            jax.distributed.initialize(**kwargs)
            telemetry.event("dist_init", "ok", attempt=attempt + 1)
            return
        except RuntimeError as exc:
            if "already initialized" in str(exc).lower():
                telemetry.event(
                    "dist_init", "ok", attempt=attempt + 1,
                    already_initialized=True,
                )
                return  # idempotent re-entry (supervisor retry paths)
            last_exc = exc
        except Exception as exc:  # transient dial/handshake failures
            last_exc = exc
        if attempt + 1 < attempts:
            delay = backoff_seconds * (2 ** attempt)
            telemetry.event(
                "dist_init", "retry",
                attempt=attempt + 1, backoff_seconds=delay,
                error=f"{type(last_exc).__name__}: {last_exc}"[:300],
            )
            time.sleep(delay)
    telemetry.event(
        "dist_init", "failed",
        attempts=attempts,
        error=f"{type(last_exc).__name__}: {last_exc}"[:300],
    )
    raise RuntimeError(
        f"jax.distributed.initialize failed after {attempts} attempt(s) "
        f"(coordinator={coordinator_address!r}): {last_exc}"
    ) from last_exc


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
) -> Mesh:
    """Mesh whose ``dcn_axes`` cross host (slice) boundaries and whose
    ``ici_axes`` stay within a slice.

    Example for 4 hosts of 8 chips solving a z-slab problem:
    ``hybrid_mesh({'dz_ici': 8}, {'dz_dcn': 4})`` then decompose z over
    ``('dz_dcn', 'dz_ici')``.
    """
    from jax.experimental import mesh_utils

    dcn_sizes = tuple(dcn_axes.values())
    ici_sizes = tuple(ici_axes.values())
    names = tuple(dcn_axes) + tuple(ici_axes)

    total = 1
    for s in dcn_sizes + ici_sizes:
        total *= s
    dcn_total = 1
    for s in dcn_sizes:
        dcn_total *= s

    devs = list(jax.devices())
    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    n_slices = len(slice_ids) if None not in slice_ids else 0
    n_procs = len({d.process_index for d in devs})
    if n_slices == dcn_total:
        # Topology-aware placement: orders devices along the ICI torus so
        # ppermute halo neighbors are physically adjacent. Real
        # misconfigurations (axis sizes vs device count etc.) raise from
        # here and stay loud.
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs
        )
    elif n_procs == dcn_total:
        # The slice topology does not match the requested DCN extent
        # (e.g. multi-process CPU, where every device reports slice 0),
        # but the process count does: one process = one DCN granule —
        # the MPI-rank view of the world (Tools.c:228-242).
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devs, process_is_granule=True,
        )
    elif dcn_total == 1:
        # Last-resort single-granule fallback. NOTE a single process
        # never reaches here (n_procs == 1 == dcn_total matches the
        # branch above); this covers only multi-process platforms whose
        # devices carry neither a matching slice topology nor a matching
        # process count, where a plain row-major mesh over ALL devices
        # is still a valid, if unoptimized, hybrid mesh.
        if total != len(devs):
            raise ValueError(
                f"hybrid_mesh axes need {total} devices, have {len(devs)}"
            )
        devices = np.asarray(devs).reshape(dcn_sizes + ici_sizes)
        return Mesh(devices, names)
    else:
        raise ValueError(
            f"cannot place DCN extent {dcn_total}: platform reports "
            f"{n_slices} slice(s) and {n_procs} process(es)"
        )
    # create_hybrid_device_mesh returns the devices in dcn-major order
    # (some backends flatten) — impose the dcn_sizes + ici_sizes shape
    return Mesh(np.asarray(devices).reshape(dcn_sizes + ici_sizes), names)


def process_local_devices() -> Sequence:
    """Devices attached to this process (AssignDevices analog — the
    reference binds rank -> GPU, ``Util.cu:66-74``; JAX binds
    process -> local chips automatically)."""
    return jax.local_devices()


def is_coordinator() -> bool:
    """True on process 0 (the reference's ``rank == 0`` I/O gate,
    ``main.c:82-86``)."""
    return jax.process_index() == 0


def collective_spec() -> Dict[str, tuple]:
    """Queryable collective metadata — the ``stencil_spec()``
    discipline applied to the host-collective layer: every
    ``barrier``/``agree`` tag namespace the framework issues, declared
    by its issuing module and aggregated here, plus the telemetry
    events each rendezvous emits. The static collective-schedule
    verifier (``analysis/collective_verify``) holds the extracted call
    sites to this registry in BOTH directions (an undeclared tag is
    schema drift; a declared-but-never-issued tag is a stale
    contract), and its dynamic cross-check reads the listed events
    back out of the 2-proc chaos streams. ``*`` in a tag is the
    wildcard for a runtime interpolation (the checkpoint directory)."""
    from multigpu_advectiondiffusion_tpu.parallel.halo import (
        remote_dma_spec,
    )
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        AGREE_TAGS,
    )
    from multigpu_advectiondiffusion_tpu.utils.io import (
        CKPTD_BARRIER_TAGS,
    )

    return {
        "barrier": tuple(CKPTD_BARRIER_TAGS),
        "agree": tuple(AGREE_TAGS),
        "events": (("sync", "barrier"), ("resilience", "agree")),
        # in-kernel remote-DMA exchange (the slab rung's dma mode):
        # the rendezvous is a Pallas make_async_remote_copy, not a
        # barrier/agree tag — declared here so the static pass proves
        # the kernel sites and this registry agree BOTH directions
        # (an undeclared remote-DMA site is schema drift; a declared
        # transport with no kernel site is a stale contract)
        "remote_dma": remote_dma_spec(),
    }


# --------------------------------------------------------------------- #
# Rank-liveness watchdog + timeout-wrapped collectives.
#
# MPI's failure model — which the reference inherits wholesale — is that
# one dead or wedged rank hangs (or aborts) the whole job: a gloo/ICI
# collective whose peer never arrives blocks forever inside C++ where
# Python cannot interrupt it. The watchdog gives every process two
# defenses:
#
# 1. per-process HEARTBEAT RECORDS in a shared directory (one JSON file
#    per rank, rewritten atomically at interval cadence) plus a monitor
#    thread that checks the peers': a record whose pid is dead (same
#    host) or whose timestamp went stale past the timeout identifies
#    the offending rank. Because the main thread may be stuck inside a
#    collective, the monitor's default response is a structured report
#    (telemetry `rank:failure` event + `rank_failure_p<K>.json`), a
#    sink flush, and `os._exit(EXIT_RANK_FAILURE)` — the survivor exits
#    with the documented code within the timeout instead of hanging;
# 2. TIMEOUT-WRAPPED COLLECTIVE ENTRY POINTS (`barrier`, `agree`,
#    `call_with_timeout`) for host-side collectives the framework
#    itself issues (checkpoint-commit barriers, rollback agreement):
#    the collective runs in a worker thread and a timeout converts an
#    indefinite wait into a RankFailureError naming the suspect rank
#    from the heartbeat records.
#
# Staleness compares the record's wall-clock stamp against the reader's
# clock — exact on one host (the test rig) and right to within NTP skew
# across hosts; the pid-liveness check (instant detection of a SIGKILLed
# peer) applies only to same-host records.
# --------------------------------------------------------------------- #

import contextlib as _contextlib  # noqa: E402
import json as _json  # noqa: E402
import os as _os  # noqa: E402
import socket as _socket  # noqa: E402
import threading as _threading  # noqa: E402
import time as _time  # noqa: E402

from multigpu_advectiondiffusion_tpu.resilience.errors import (  # noqa: E402
    EXIT_RANK_FAILURE,
    CoordinationError,
    RankFailureError,
)

_current_watchdog: Optional["RankWatchdog"] = None


def install_watchdog(watchdog: Optional["RankWatchdog"]) -> None:
    """Register ``watchdog`` as the process-wide current watchdog (the
    run driver installs it for the run's duration); ``None`` clears it.
    Timeout-wrapped collectives consult it for default timeouts and
    suspect attribution."""
    global _current_watchdog
    _current_watchdog = watchdog


def current_watchdog() -> Optional["RankWatchdog"]:
    return _current_watchdog


def _collective_timeout() -> float:
    """Default timeout for framework-issued collectives: the
    ``TPUCFD_COLLECTIVE_TIMEOUT`` env var, else 10x the installed
    watchdog's timeout (a barrier legitimately waits for the slowest
    peer's shard writes; the heartbeat monitor is the fast detector),
    else 0 (no timeout — single runs without a watchdog keep MPI
    semantics)."""
    env = _os.environ.get("TPUCFD_COLLECTIVE_TIMEOUT")
    if env:
        return float(env)
    wd = _current_watchdog
    if wd is not None and wd.timeout > 0:
        return max(10.0 * wd.timeout, 30.0)
    return 0.0


def call_with_timeout(fn, timeout_seconds: Optional[float], tag: str):
    """Run ``fn()`` (typically a host-side collective) in a worker
    thread and wait at most ``timeout_seconds``; on timeout raise a
    :class:`RankFailureError` naming the suspect rank from the current
    watchdog's heartbeat records. ``timeout_seconds`` of ``None``/0
    calls ``fn`` inline (no wrapping)."""
    if not timeout_seconds or timeout_seconds <= 0:
        return fn()
    result: dict = {}
    done = _threading.Event()

    def target():
        try:
            result["value"] = fn()
        except BaseException as exc:  # re-raised in the caller's thread
            result["error"] = exc
        finally:
            done.set()

    worker = _threading.Thread(
        target=target, daemon=True, name=f"tpucfd-collective-{tag}"
    )
    worker.start()
    if not done.wait(timeout_seconds):
        wd = _current_watchdog
        suspects = wd.suspects() if wd is not None else []
        rank = suspects[0]["rank"] if suspects else None
        raise RankFailureError(
            rank,
            f"collective {tag!r} did not complete within "
            f"{timeout_seconds:g}s",
            detected_by=jax.process_index(),
            suspects=suspects,
        )
    if "error" in result:
        raise result["error"]
    return result.get("value")


def barrier(tag: str, timeout_seconds: Optional[float] = None) -> None:
    """Cross-process barrier (``sync_global_devices``) with hang
    defense: when a watchdog is installed (or
    ``TPUCFD_COLLECTIVE_TIMEOUT`` is set) the wait is bounded and a
    timeout raises :class:`RankFailureError` instead of blocking
    forever. No-op with one process."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    if timeout_seconds is None:
        timeout_seconds = _collective_timeout()
    call_with_timeout(
        lambda: multihost_utils.sync_global_devices(tag),
        timeout_seconds,
        f"barrier:{tag}",
    )
    # sync anchor: every rank emits this immediately after the SAME
    # barrier released — the trace analyzer's clock-alignment points
    # (telemetry/analyze.py align_clocks), alongside dist_init:ok and
    # resilience:agree
    from multigpu_advectiondiffusion_tpu import telemetry

    telemetry.event("sync", "barrier", tag=tag)


def agree(tag: str, values, timeout_seconds: Optional[float] = None):
    """Explicit cross-rank agreement: allgather ``values`` (a small
    numeric vector) from every process and assert all ranks proposed
    the same — the supervisor's rollback/checkpoint decisions call this
    so coordinated recovery is ASSERTED, never inferred. Returns the
    agreed vector. Raises :class:`CoordinationError` on a mismatch and
    :class:`RankFailureError` when a peer never shows up.

    Values ride an f32-safe lane (iteration counts compare exactly up
    to 2**24; scale factors are the same literal on every rank)."""
    arr = np.asarray(values, dtype=np.float64).ravel()
    if jax.process_count() <= 1:
        return arr
    from jax.experimental import multihost_utils

    if timeout_seconds is None:
        timeout_seconds = _collective_timeout()
    rows = call_with_timeout(
        lambda: multihost_utils.process_allgather(arr),
        timeout_seconds,
        f"agree:{tag}",
    )
    rows = np.asarray(rows).reshape(jax.process_count(), arr.size)
    if not (rows == rows[0]).all():
        raise CoordinationError(tag, rows.tolist())
    return rows[0]


def _heartbeat_path(directory: str, rank: int) -> str:
    return _os.path.join(directory, f"rank{rank}.hb.json")


def write_heartbeat(
    directory: str,
    rank: int,
    pid: Optional[int] = None,
    host: Optional[str] = None,
    wall: Optional[float] = None,
    seq: int = 0,
) -> None:
    """Atomically (tmp + rename) write one rank's heartbeat record —
    a reader never sees a torn record, only the previous one."""
    rec = {
        "rank": int(rank),
        "pid": int(pid if pid is not None else _os.getpid()),
        "host": host or _socket.gethostname(),
        "wall": float(wall if wall is not None else _time.time()),
        "seq": int(seq),
    }
    tmp = _heartbeat_path(directory, rank) + f".tmp.{_os.getpid()}"
    with open(tmp, "w") as f:
        _json.dump(rec, f)
    _os.replace(tmp, _heartbeat_path(directory, rank))


class RankWatchdog:
    """Per-process rank-liveness watchdog.

    ``start()`` writes this rank's heartbeat immediately and launches a
    daemon thread that (a) rewrites it every ``interval_seconds`` and
    (b) checks every peer's record: dead pid (same host) or a stamp
    stale past ``timeout_seconds`` triggers ``on_failure`` once with a
    :class:`RankFailureError`. The default ``on_failure`` emits a
    ``rank:failure`` telemetry event, writes a
    ``rank_failure_p<rank>.json`` forensics report into ``report_dir``,
    flushes the telemetry sink and ``os._exit(EXIT_RANK_FAILURE)`` —
    correct even when the main thread is wedged inside a collective
    (tests pass a recording callback instead).

    Records whose wall stamp predates this watchdog's start are ignored
    (minus 1 s of slack): a restarted run reusing the same heartbeat
    directory must not insta-fail on the previous incarnation's corpses.
    """

    def __init__(
        self,
        directory: str,
        timeout_seconds: float,
        interval_seconds: Optional[float] = None,
        rank: Optional[int] = None,
        num_processes: Optional[int] = None,
        on_failure=None,
        report_dir: Optional[str] = None,
    ):
        self.directory = directory
        self.timeout = float(timeout_seconds)
        self.interval = (
            float(interval_seconds)
            if interval_seconds is not None
            else max(0.1, self.timeout / 4.0)
        )
        self.rank = jax.process_index() if rank is None else int(rank)
        self.num_processes = (
            jax.process_count() if num_processes is None
            else int(num_processes)
        )
        self.report_dir = report_dir
        self._on_failure = on_failure
        self.failure: Optional[RankFailureError] = None
        self._host = _socket.gethostname()
        self._stop = _threading.Event()
        self._thread: Optional[_threading.Thread] = None
        self._seq = 0
        self._t0 = None  # monotonic start
        self._wall0 = None  # wall-clock start (record freshness floor)
        self._reported = False

    # ------------------------------------------------------------------ #
    def start(self) -> "RankWatchdog":
        _os.makedirs(self.directory, exist_ok=True)
        self._t0 = _time.monotonic()
        self._wall0 = _time.time()
        self._beat()
        self._thread = _threading.Thread(
            target=self._loop, daemon=True,
            name=f"tpucfd-watchdog-r{self.rank}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0 * self.interval + 1.0)
            self._thread = None

    def _beat(self) -> None:
        self._seq += 1
        try:
            write_heartbeat(self.directory, self.rank, seq=self._seq)
        except OSError:
            pass  # a transiently unwritable dir must not kill the run

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._beat()
            err = self.check_peers()
            if err is not None:
                self.failure = err
                self._fire(err)
                return

    # ------------------------------------------------------------------ #
    def _check_peer(self, peer: int) -> Optional[str]:
        """Reason string when ``peer`` looks dead/stalled, else None."""
        path = _heartbeat_path(self.directory, peer)
        rec = None
        try:
            with open(path) as f:
                rec = _json.load(f)
        except (OSError, ValueError):
            rec = None  # absent (or unreadable): handled below
        if rec is not None and float(rec.get("wall", 0.0)) < (
            self._wall0 - 1.0
        ):
            rec = None  # previous incarnation's record: not evidence
        if rec is None:
            if _time.monotonic() - self._t0 > self.timeout:
                return (
                    "no heartbeat record within "
                    f"{self.timeout:g}s of watchdog start"
                )
            return None
        pid = rec.get("pid")
        if rec.get("host") == self._host and pid:
            try:
                _os.kill(int(pid), 0)
            except ProcessLookupError:
                return f"process (pid {pid}) is dead"
            except (PermissionError, OSError):
                pass  # alive but not ours to signal-probe
        age = _time.time() - float(rec.get("wall", 0.0))
        if age > self.timeout:
            return (
                f"heartbeat stale for {age:.1f}s "
                f"(timeout {self.timeout:g}s)"
            )
        return None

    def check_peers(self) -> Optional[RankFailureError]:
        """One sweep over every peer; the first dead/stalled one wins."""
        for peer in range(self.num_processes):
            if peer == self.rank:
                continue
            reason = self._check_peer(peer)
            if reason is not None:
                return RankFailureError(
                    peer, reason, detected_by=self.rank,
                    suspects=self.suspects(),
                )
        return None

    def await_verdict(self, grace: Optional[float] = None):
        """Poll the peers for up to ``grace`` seconds (default: the
        timeout plus two intervals) and return the
        :class:`RankFailureError` if one emerges, else ``None``.

        Classifies an exception that RACED the monitor: a gloo
        "connection reset" often reaches the main thread within
        milliseconds of a peer's death — before its heartbeat is stale
        and while its pid may still be an unreaped zombie. Waiting one
        staleness window settles the question either way."""
        if grace is None:
            grace = self.timeout + 2.0 * self.interval
        deadline = _time.monotonic() + grace
        while True:
            err = self.failure or self.check_peers()
            if err is not None:
                return err
            if _time.monotonic() >= deadline:
                return None
            _time.sleep(min(self.interval, 0.2))

    def suspects(self) -> list:
        """Non-raising peer sweep: ``[{rank, reason}, ...]`` for every
        peer currently failing its liveness checks."""
        out = []
        for peer in range(self.num_processes):
            if peer == self.rank:
                continue
            reason = self._check_peer(peer)
            if reason is not None:
                out.append({"rank": peer, "reason": reason})
        return out

    # ------------------------------------------------------------------ #
    def report(self, err: RankFailureError) -> None:
        """Structured forensics: one ``rank:failure`` telemetry event +
        a ``rank_failure_p<rank>.json`` report in ``report_dir``, then a
        sink flush — idempotent, shared by the monitor's abort path and
        the main thread's exception path."""
        if self._reported:
            return
        self._reported = True
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.event(
            "rank", "failure",
            rank=err.rank, reason=err.reason,
            detected_by=self.rank, exit_code=EXIT_RANK_FAILURE,
        )
        if self.report_dir:
            payload = {
                "failed_rank": err.rank,
                "reason": err.reason,
                "detected_by": self.rank,
                "suspects": err.suspects,
                "watchdog_timeout": self.timeout,
                "exit_code": EXIT_RANK_FAILURE,
                "wall_time": _time.time(),
                "resume": "--resume auto",
            }
            try:
                tmp = _os.path.join(
                    self.report_dir,
                    f"rank_failure_p{self.rank}.json.tmp",
                )
                with open(tmp, "w") as f:
                    _json.dump(payload, f, indent=2)
                _os.replace(tmp, tmp[: -len(".tmp")])
            except OSError:
                pass  # forensics must never mask the abort itself
        telemetry.get_sink().flush()

    def _fire(self, err: RankFailureError) -> None:
        if self._on_failure is not None:
            self._on_failure(err)
            return
        # Default: the main thread may be unreachable (wedged in a
        # gloo/ICI collective) — report, flush, and hard-exit with the
        # documented code so the survivor never hangs past the timeout.
        self.report(err)
        import sys as _sys

        print(f"watchdog: {err}; exiting {EXIT_RANK_FAILURE}",
              file=_sys.stderr, flush=True)
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.get_sink().close()
        _os._exit(EXIT_RANK_FAILURE)


@_contextlib.contextmanager
def watchdog_scope(watchdog: Optional[RankWatchdog]):
    """Run a block under an (optional) started + installed watchdog.

    On an exception inside the block, if the watchdog has (or now
    finds) a dead/stalled peer, the exception is converted to the
    structured :class:`RankFailureError` — a gloo "connection reset"
    racing the monitor thread classifies as the rank failure it is
    instead of a generic exit 1.
    """
    if watchdog is None:
        yield None
        return
    watchdog.start()
    install_watchdog(watchdog)
    try:
        yield watchdog
    except RankFailureError as exc:
        watchdog.report(exc)  # e.g. a timeout-wrapped barrier fired
        raise
    except Exception as exc:
        # wait up to one staleness window: the exception usually beats
        # the heartbeat evidence (and a SIGKILLed peer may still be an
        # unreaped zombie whose pid probes alive)
        err = watchdog.failure or watchdog.await_verdict()
        if err is not None:
            watchdog.report(err)
            raise err from exc
        raise
    finally:
        install_watchdog(None)
        watchdog.stop()
