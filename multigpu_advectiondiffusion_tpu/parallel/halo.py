"""Distributed halo exchange.

TPU-native replacement for the reference's five-stream MPI choreography
(``MultiGPU/Diffusion3d_Baseline/main.c:203-297``: pack kernel → DtH copy →
``MPI_Isend``/``Irecv`` → HtD copy → unpack kernel, per RK stage). Here the
whole exchange is two ``jax.lax.ppermute`` shifts per sharded axis inside
``shard_map`` — data moves HBM→ICI→HBM with XLA's async collective
scheduler providing the compute/communication overlap the reference
hand-builds with streams.

Two deliberate upgrades over the reference (SURVEY §2.1.5, §3.2):
  * the *state* ``u`` is exchanged before computing, not the RHS ``Lu``,
    which fixes the stale-``u`` z-halo defect of the multi-GPU Burgers;
  * any subset of axes may be decomposed (the reference supports only
    1-D slabs).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from multigpu_advectiondiffusion_tpu.core.bc import Boundary, boundary_halo, pad_axis
from multigpu_advectiondiffusion_tpu.ops.stencils import Padder, slice_axis
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    axis_extent,
)


def exchange_spec() -> dict:
    """Queryable exchange metadata (the ``stencil_spec()`` discipline
    applied to the halo layer): one exchange site is two ``ppermute``
    shifts per sharded axis, and every traced site records itself
    through the listed counters — what the collective-schedule
    verifier's dynamic cross-check (``analysis/collective_verify.
    halo_counter_profile``) reads back out of per-rank streams to
    assert every rank traced the same exchange sites."""
    return {
        "ppermute_shifts": 2,
        "counters": (
            "halo.exchanges_traced",
            "halo.bytes_per_execution",
        ),
    }


def remote_dma_spec() -> dict:
    """Queryable metadata of the IN-KERNEL halo exchange (the
    ``exchange_spec()`` discipline for the remote-DMA rung,
    ``ops/pallas/fused_slab_run._whole_run_dma_kernel``): the slab
    rung's dma mode replaces the ppermute site entirely — ghost rows
    move over ICI via ``pltpu.make_async_remote_copy`` from inside the
    Pallas program — so its traffic is recorded through these counters
    and the ``halo:in_kernel`` event instead of the ppermute pair. The
    collective-schedule verifier's dynamic cross-check
    (``analysis/collective_verify.halo_counter_profile``) reads BOTH
    specs so a dma-mode stream profiles rank-uniform without a stale
    ppermute expectation."""
    return {
        "kernel": "fused-whole-run-slab",
        "counters": ("halo.dma_bytes_per_execution",),
        "events": (("halo", "in_kernel"),),
    }


def record_remote_dma(kernel: str, plane_shape, itemsize: int,
                      window_rows: int, blocks: int,
                      mesh_axis: str) -> None:
    """Telemetry record of one in-kernel remote-DMA exchange *site*.

    Runs at TRACE time (the slab rung's ``_run_dma`` executes under
    ``jit``/``shard_map``), mirroring :func:`_record_exchange`:
    ``bytes`` is the ICI payload per compiled execution — two
    ``window_rows``-deep slabs of the padded trailing plane (the rows
    actually pushed), times ``blocks`` (one exchange per k-step block,
    the initial embed push included: ``ceil(num_iters / k)`` pushes per
    run call). The ``halo:in_kernel`` event carries the same facts so
    ``tpucfd-trace``'s phase breakdown attributes the comm to the
    in-kernel path instead of reading zero exchanged bytes."""
    from multigpu_advectiondiffusion_tpu import telemetry

    sink = telemetry.get_sink()
    if not sink.active:
        return
    plane = int(itemsize)
    for n in plane_shape:
        plane *= int(n)
    nbytes = 2 * int(window_rows) * plane * int(blocks)
    sink.counter(
        "halo.dma_bytes_per_execution", nbytes,
        axis=0, mesh_axis=mesh_axis, window_rows=int(window_rows),
        blocks=int(blocks),
    )
    sink.event(
        "halo", "in_kernel",
        kernel=kernel, axis=0, mesh_axis=mesh_axis,
        depth=int(window_rows), blocks=int(blocks),
        bytes_per_execution=nbytes,
    )


def exchange_ghosts(
    u: jnp.ndarray,
    axis: int,
    halo: int,
    mesh_axis: str,
    num_shards: int,
    bc: Boundary,
    repeats: int = 1,
    wire_dtype=None,
):
    """The two ``ppermute`` shifts of a halo exchange, returned as the
    ``(lo, hi)`` ghost slabs without concatenating onto ``u``.

    Building block for the overlapped interior/boundary schedule
    (:func:`ops.stencils.split_axis_apply`): keeping the ghosts as
    separate values lets XLA schedule the collectives concurrently with
    interior compute that does not depend on them — the role of the
    reference's boundary-first five-stream choreography
    (``MultiGPU/Diffusion3d_Baseline/main.c:203-297``).

    ``halo`` is the exchange *depth* — the communication-avoiding k-step
    schedule passes ``k * G`` here (one deep exchange per k-step block)
    while the per-step schedules pass the stencil halo. ``repeats`` is a
    telemetry-only hint: how many times the compiled program executes
    this trace site per run (e.g. the loop trip count when the exchange
    sits inside a ``fori_loop`` body), so ``halo.bytes_per_execution``
    reports true bytes moved instead of one trace-site's worth.

    ``wire_dtype`` (ISSUE 16, the bf16-storage rung): when set to a
    narrower dtype than ``u``, ONLY the exchanged ghost slabs are
    down-cast before the ``ppermute`` and up-cast on receipt — the
    interior never leaves ``u.dtype``. BC ghosts on global-edge shards
    take the same round trip so edge shards see the same declared
    storage rounding as interior shards. Byte counters report the wire
    dtype's (halved) payload.
    """
    n_local = u.shape[axis]
    if n_local < halo:
        raise ValueError(
            f"shard of {n_local} cells can't serve a halo of {halo} on axis {axis}"
        )
    wire = None if wire_dtype is None else jnp.dtype(wire_dtype)
    if wire == jnp.dtype(u.dtype):
        wire = None
    _record_exchange(u, axis, halo, mesh_axis, repeats, wire_dtype=wire)
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    bwd = [((i + 1) % num_shards, i) for i in range(num_shards)]
    # left halo <- left neighbor's rightmost cells; right halo <- right
    # neighbor's leftmost cells (tags 1/5 pair messaging in main.c:218,234).
    # named_scope: the two shifts appear as one labeled region per axis
    # in --trace captures, under the enclosing stepper span
    with jax.named_scope(f"tpucfd.halo_exchange_ax{axis}"):
        send_hi = slice_axis(u, axis, n_local - halo, n_local)
        send_lo = slice_axis(u, axis, 0, halo)
        if wire is not None:
            send_hi = send_hi.astype(wire)
            send_lo = send_lo.astype(wire)
        from_left = lax.ppermute(send_hi, mesh_axis, fwd)
        from_right = lax.ppermute(send_lo, mesh_axis, bwd)
        if bc.kind != "periodic":
            idx = lax.axis_index(mesh_axis)
            bc_left = boundary_halo(u, axis, halo, bc, "left")
            bc_right = boundary_halo(u, axis, halo, bc, "right")
            if wire is not None:
                bc_left = bc_left.astype(wire)
                bc_right = bc_right.astype(wire)
            from_left = jnp.where(idx == 0, bc_left, from_left)
            from_right = jnp.where(
                idx == num_shards - 1, bc_right, from_right
            )
        if wire is not None:
            from_left = from_left.astype(u.dtype)
            from_right = from_right.astype(u.dtype)
        return from_left, from_right


def _record_exchange(
    u, axis: int, halo: int, mesh_axis: str, repeats: int = 1,
    wire_dtype=None,
) -> None:
    """Telemetry record of one halo exchange *site*.

    Runs at TRACE time (``exchange_ghosts`` executes under ``jit``).
    ``bytes`` is the ICI/DCN payload of the site per compiled execution:
    two ``halo``-deep slabs (lo + hi) of the shard-local block, times
    ``repeats`` — the caller's static count of how often the site runs
    inside one execution (loop trip count for exchanges traced inside a
    ``fori_loop`` body, number of k-step blocks for the deep
    communication-avoiding schedule; 1 for straight-line sites). Sites
    in dynamic-trip loops (``while_loop`` run_to) cannot know their
    count and record ``repeats=1`` — the stream still carries the depth
    so a consumer can scale by the summary's step count."""
    from multigpu_advectiondiffusion_tpu import telemetry

    sink = telemetry.get_sink()
    if not sink.active:
        return
    slab = 1
    for ax, n in enumerate(u.shape):
        slab *= halo if ax == axis else int(n)
    # wire_dtype: the bf16-storage rung moves ghost slabs down-cast on
    # the wire — the payload is the wire dtype's itemsize, not the
    # resident block's (ISSUE 16)
    item = jnp.dtype(wire_dtype if wire_dtype is not None
                     else u.dtype).itemsize
    nbytes = 2 * slab * item
    sink.counter(
        "halo.exchanges_traced", 1, axis=axis, mesh_axis=mesh_axis
    )
    sink.counter(
        "halo.bytes_per_execution", int(repeats) * nbytes,
        axis=axis, mesh_axis=mesh_axis, halo=halo, repeats=int(repeats),
    )


def exchange_axis(
    u: jnp.ndarray,
    axis: int,
    halo: int,
    mesh_axis: str,
    num_shards: int,
    bc: Boundary,
    wire_dtype=None,
) -> jnp.ndarray:
    """Pad one axis of a shard-local block with neighbor (or BC) ghost cells.

    Must run inside ``shard_map`` with ``mesh_axis`` in scope. Uses cyclic
    permutes; for non-periodic axes the global-edge shards overwrite the
    wrapped block with BC ghosts (Dirichlet fill or edge replication).
    """
    from_left, from_right = exchange_ghosts(
        u, axis, halo, mesh_axis, num_shards, bc, wire_dtype=wire_dtype
    )
    return jnp.concatenate([from_left, u, from_right], axis=axis)


def make_padder(
    decomp: Decomposition,
    mesh_axis_sizes: Dict[str, int],
    bcs: Sequence[Boundary],
    wire_dtype=None,
) -> Padder:
    """Padder closure for use inside ``shard_map``: ppermute on sharded
    axes, plain BC padding on local axes. ``wire_dtype`` down-casts only
    the exchanged ghost slabs on the wire (see
    :func:`exchange_ghosts`)."""

    def padder(u: jnp.ndarray, axis: int, halo: int) -> jnp.ndarray:
        name = decomp.mesh_axis(axis)
        if name is None or axis_extent(mesh_axis_sizes, name) == 1:
            return pad_axis(u, axis, halo, bcs[axis])
        return exchange_axis(
            u, axis, halo, name, axis_extent(mesh_axis_sizes, name),
            bcs[axis], wire_dtype=wire_dtype,
        )

    return padder


def make_ghost_fn(
    decomp: Decomposition,
    mesh_axis_sizes: Dict[str, int],
    bcs: Sequence[Boundary],
    wire_dtype=None,
):
    """Ghost-slab closure for the overlapped schedule: returns
    ``(lo, hi)`` for sharded axes, ``None`` for local axes (whose ghosts
    are plain BC padding with nothing to overlap). ``wire_dtype``
    down-casts only the exchanged slabs on the wire (see
    :func:`exchange_ghosts`)."""

    def ghost_fn(u: jnp.ndarray, axis: int, halo: int):
        name = decomp.mesh_axis(axis)
        if name is None or axis_extent(mesh_axis_sizes, name) == 1:
            return None
        return exchange_ghosts(
            u, axis, halo, name, axis_extent(mesh_axis_sizes, name),
            bcs[axis], wire_dtype=wire_dtype,
        )

    return ghost_fn


def make_ghost_refresh(
    decomp: Decomposition,
    mesh_axis_sizes: Dict[str, int],
    bcs: Sequence[Boundary],
    halo: int,
    interior_local: Sequence[int],
    core_offsets: Sequence[int] | None = None,
):
    """Refresh the ghost slabs of a *persistent padded* buffer in place.

    The fused Pallas steppers keep the state in a padded layout whose
    ghost cells are written once and treated as frozen
    (:mod:`ops.pallas.fused_diffusion`). Under a mesh the ghosts on
    sharded axes are neighbor data and go stale after every RK stage —
    this closure re-runs the ``ppermute`` exchange on the padded buffer's
    core window and writes the fresh slabs back into the ghost rows
    (``lax.dynamic_update_slice_in_dim``, in-place under XLA). This is
    the per-stage ghost rewrite of the reference's MPI loop
    (``MultiGPU/Diffusion3d_Baseline/main.c:203-297``) applied to the
    *tuned* kernel's persistent buffer. Must run inside ``shard_map``.

    ``interior_local`` is the shard-local interior shape; axes whose mesh
    extent is 1 (or unsharded) keep their frozen BC ghosts untouched.
    ``core_offsets`` gives the interior origin in the padded layout per
    axis (default ``halo`` on every axis — steppers with alignment
    margins, e.g. the fused Burgers y axis, sit deeper).

    ``halo`` is the refresh *depth*: the per-step schedules pass the
    stepper's stencil halo, the communication-avoiding k-step schedule
    passes its deep ``k * G`` exchange depth (with ``core_offsets``
    sitting ``k * G`` in). The closure takes an optional ``repeats``
    telemetry hint (see :func:`exchange_ghosts`) so loop-resident
    refreshes report true bytes per compiled execution.
    """
    offs = (
        tuple(core_offsets)
        if core_offsets is not None
        else (halo,) * len(interior_local)
    )
    sharded = [
        (ax, decomp.mesh_axis(ax))
        for ax in range(len(interior_local))
        if decomp.mesh_axis(ax) is not None
        and axis_extent(mesh_axis_sizes, decomp.mesh_axis(ax)) > 1
    ]

    def refresh(P: jnp.ndarray, repeats: int = 1) -> jnp.ndarray:
        for ax, name in sharded:
            n_loc = interior_local[ax]
            off = offs[ax]
            core = slice_axis(P, ax, off, off + n_loc)
            lo, hi = exchange_ghosts(
                core, ax, halo, name, axis_extent(mesh_axis_sizes, name),
                bcs[ax], repeats=repeats,
            )
            P = lax.dynamic_update_slice_in_dim(P, lo, off - halo, axis=ax)
            P = lax.dynamic_update_slice_in_dim(P, hi, off + n_loc, axis=ax)
        return P

    return refresh


def axis_offsets(decomp: Decomposition, local_shape: Sequence[int]):
    """Global index offset of this shard's block, per array axis.

    Inside ``shard_map``: ``offset = axis_index * local_n``
    (the analog of ``k + rank*_Nz`` in ``Tools.c:192``).
    """
    offs = []
    for ax in range(len(local_shape)):
        name = decomp.mesh_axis(ax)
        if name is None:
            offs.append(0)
        else:
            offs.append(lax.axis_index(name) * local_shape[ax])
    return offs
