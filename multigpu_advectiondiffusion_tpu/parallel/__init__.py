from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
    shard_map,
)
from multigpu_advectiondiffusion_tpu.parallel.halo import exchange_axis, make_padder

__all__ = [
    "Decomposition",
    "make_mesh",
    "shard_map",
    "exchange_axis",
    "make_padder",
]
