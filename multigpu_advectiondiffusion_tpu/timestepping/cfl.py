"""Time-step selection.

* Diffusive stability bound — ``dt = 1/(2 K sum_i 1/dx_i^2) * safety``
  (``MultiGPU/Diffusion3d_Baseline/main.c:64``, ``heat3d.m:39``).
* Advective CFL — ``dt = CFL * dx / max|f'(u)|``
  (``LFWENO5FDM3d.m:71``). The CUDA ports hard-code ``max|u| = 1``
  (``MultiGPU/Burgers3d_Baseline/main.c:193``) — a known defect; here the
  global wave-speed reduction is restored and, in the sharded step, runs as
  a ``lax.pmax`` over the device mesh.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp


def diffusive_dt(diffusivity: float, spacing: Sequence[float], safety: float = 0.8):
    inv = sum(1.0 / (dx * dx) for dx in spacing)
    return safety / (2.0 * diffusivity * inv)


def max_wave_speed(
    u: jnp.ndarray,
    dflux: Callable[[jnp.ndarray], jnp.ndarray],
    reduce_max: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Global ``max |f'(u)|``; ``reduce_max`` adds the cross-device pmax."""
    local = jnp.max(jnp.abs(dflux(u)))
    return reduce_max(local) if reduce_max is not None else local


def dt_from_wave_speed(
    a: jnp.ndarray,
    spacing: Sequence[float],
    cfl: float,
    reduce_max=None,
    floor: float = 1e-12,
):
    """CFL dt from an already-computed local ``max|f'(u)|`` scalar — the
    consumer of the fused steppers' in-kernel wave-speed emission, which
    replaces the between-step full-array reduction (one whole HBM read
    per step). The ONE definition of the CFL formula:
    :func:`advective_dt` composes it, so the emit and read-back paths
    cannot desynchronize."""
    if reduce_max is not None:
        a = reduce_max(a)
    return cfl * min(spacing) / jnp.maximum(a, floor)


def advective_dt(
    u: jnp.ndarray,
    dflux,
    spacing: Sequence[float],
    cfl: float,
    reduce_max=None,
    floor: float = 1e-12,
):
    return dt_from_wave_speed(
        max_wave_speed(u, dflux, reduce_max), spacing, cfl, floor=floor
    )


def advection_diffusion_dt(
    velocity: Sequence[float],
    diffusivity,
    spacing: Sequence[float],
    cfl: float = 0.4,
    safety: float = 0.8,
    reaction=0.0,
):
    """Combined stability bound for the mixed advection–diffusion(–
    reaction) operator: the inverse rates ADD (harmonic combination),
    so a configuration that is individually safe on each term stays
    safe when the terms act together —

        1/dt = sum_i |a_i|/dx_i / cfl  +  2 K sum_i 1/dx_i^2 / safety
             + lambda / safety.

    ``diffusivity`` is the MAX of the (possibly spatially varying)
    coefficient field; a traced scalar (the batched ensemble engine's
    member-varying K) flows straight through. Pure-advection,
    pure-diffusion and reaction-free limits reduce to the classic
    per-term formulas above."""
    inv = 0.0
    adv = sum(abs(float(a)) / dx for a, dx in zip(velocity, spacing))
    if adv:
        inv = inv + adv / cfl
    inv = inv + (
        2.0 * diffusivity * sum(1.0 / (dx * dx) for dx in spacing)
    ) / safety
    if isinstance(reaction, (int, float)):
        # static rate: stay a python float so fixed-dt solvers bake a
        # compile-time constant (the fused kernels' SMEM dt source)
        if reaction > 0.0:
            inv = inv + float(reaction) / safety
    elif reaction is not None:
        # traced rate (member-varying ensemble operand)
        inv = inv + jnp.maximum(reaction, 0.0) / safety
    return 1.0 / inv
