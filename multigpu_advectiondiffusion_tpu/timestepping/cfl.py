"""Time-step selection.

* Diffusive stability bound — ``dt = 1/(2 K sum_i 1/dx_i^2) * safety``
  (``MultiGPU/Diffusion3d_Baseline/main.c:64``, ``heat3d.m:39``).
* Advective CFL — ``dt = CFL * dx / max|f'(u)|``
  (``LFWENO5FDM3d.m:71``). The CUDA ports hard-code ``max|u| = 1``
  (``MultiGPU/Burgers3d_Baseline/main.c:193``) — a known defect; here the
  global wave-speed reduction is restored and, in the sharded step, runs as
  a ``lax.pmax`` over the device mesh.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp


def diffusive_dt(diffusivity: float, spacing: Sequence[float], safety: float = 0.8):
    inv = sum(1.0 / (dx * dx) for dx in spacing)
    return safety / (2.0 * diffusivity * inv)


def max_wave_speed(
    u: jnp.ndarray,
    dflux: Callable[[jnp.ndarray], jnp.ndarray],
    reduce_max: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Global ``max |f'(u)|``; ``reduce_max`` adds the cross-device pmax."""
    local = jnp.max(jnp.abs(dflux(u)))
    return reduce_max(local) if reduce_max is not None else local


def dt_from_wave_speed(
    a: jnp.ndarray,
    spacing: Sequence[float],
    cfl: float,
    reduce_max=None,
    floor: float = 1e-12,
):
    """CFL dt from an already-computed local ``max|f'(u)|`` scalar — the
    consumer of the fused steppers' in-kernel wave-speed emission, which
    replaces the between-step full-array reduction (one whole HBM read
    per step). The ONE definition of the CFL formula:
    :func:`advective_dt` composes it, so the emit and read-back paths
    cannot desynchronize."""
    if reduce_max is not None:
        a = reduce_max(a)
    return cfl * min(spacing) / jnp.maximum(a, floor)


def advective_dt(
    u: jnp.ndarray,
    dflux,
    spacing: Sequence[float],
    cfl: float,
    reduce_max=None,
    floor: float = 1e-12,
):
    return dt_from_wave_speed(
        max_wave_speed(u, dflux, reduce_max), spacing, cfl, floor=floor
    )
