"""Time-step selection.

* Diffusive stability bound — ``dt = 1/(2 K sum_i 1/dx_i^2) * safety``
  (``MultiGPU/Diffusion3d_Baseline/main.c:64``, ``heat3d.m:39``).
* Advective CFL — ``dt = CFL * dx / max|f'(u)|``
  (``LFWENO5FDM3d.m:71``). The CUDA ports hard-code ``max|u| = 1``
  (``MultiGPU/Burgers3d_Baseline/main.c:193``) — a known defect; here the
  global wave-speed reduction is restored and, in the sharded step, runs as
  a ``lax.pmax`` over the device mesh.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp


def diffusive_dt(diffusivity: float, spacing: Sequence[float], safety: float = 0.8):
    inv = sum(1.0 / (dx * dx) for dx in spacing)
    return safety / (2.0 * diffusivity * inv)


def max_wave_speed(
    u: jnp.ndarray,
    dflux: Callable[[jnp.ndarray], jnp.ndarray],
    reduce_max: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Global ``max |f'(u)|``; ``reduce_max`` adds the cross-device pmax."""
    local = jnp.max(jnp.abs(dflux(u)))
    return reduce_max(local) if reduce_max is not None else local


def advective_dt(
    u: jnp.ndarray,
    dflux,
    spacing: Sequence[float],
    cfl: float,
    reduce_max=None,
    floor: float = 1e-12,
):
    a = max_wave_speed(u, dflux, reduce_max)
    return cfl * min(spacing) / jnp.maximum(a, floor)
