from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
    INTEGRATORS,
    euler,
    ssp_rk2,
    ssp_rk3,
)
from multigpu_advectiondiffusion_tpu.timestepping import cfl

__all__ = ["INTEGRATORS", "euler", "ssp_rk2", "ssp_rk3", "cfl"]
