"""Explicit strong-stability-preserving time integrators.

The reference uses the 3-stage Shu–Osher SSP-RK3 everywhere, spelled out
inline per stage (``Matlab_Prototipes/DiffusionNd/heat3d.m:50-62``;
``MultiGPU/Diffusion3d_Baseline/Kernels.cu:266-300`` ``Compute_RK``):

    u1 = u  + dt L(u)
    u2 = 3/4 u + 1/4 (u1 + dt L(u1))
    u  = 1/3 u + 2/3 (u2 + dt L(u2))

Here integrators are higher-order functions ``(rhs, u, dt, post) -> u`` so
one jitted step fuses all stages. ``post`` (boundary fix-up) is applied
after **every stage**, exactly as the reference re-imposes BCs per RK
stage (``heat3d.m:50-67``, ``heat2d_axisymmetric.m:56-79``) — a per-step
fix-up would leak stale boundary values into intermediate stages.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

Rhs = Callable[[jnp.ndarray], jnp.ndarray]
Post = Optional[Callable[[jnp.ndarray], jnp.ndarray]]


def _id(u):
    return u


def euler(rhs: Rhs, u: jnp.ndarray, dt, post: Post = None) -> jnp.ndarray:
    post = post or _id
    return post(u + dt * rhs(u))


def ssp_rk2(rhs: Rhs, u: jnp.ndarray, dt, post: Post = None) -> jnp.ndarray:
    post = post or _id
    u1 = post(u + dt * rhs(u))
    return post(0.5 * (u + u1 + dt * rhs(u1)))


def ssp_rk3(rhs: Rhs, u: jnp.ndarray, dt, post: Post = None) -> jnp.ndarray:
    post = post or _id
    u1 = post(u + dt * rhs(u))
    u2 = post(0.75 * u + 0.25 * (u1 + dt * rhs(u1)))
    return post((u + 2.0 * (u2 + dt * rhs(u2))) / 3.0)


INTEGRATORS = {"euler": euler, "ssp_rk2": ssp_rk2, "ssp_rk3": ssp_rk3}

# rhs evaluations per step, for MLUPS-style accounting
STAGES = {"euler": 1, "ssp_rk2": 2, "ssp_rk3": 3}
