from multigpu_advectiondiffusion_tpu.bench.matrix import main

if __name__ == "__main__":
    main()
