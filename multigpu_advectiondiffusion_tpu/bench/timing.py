"""Device-honest wall-clock timing for benchmarks.

On the tunneled TPU platform this container attaches (PJRT plugin
``axon``), ``Array.block_until_ready`` returns before the device work has
completed — measured directly: an 8192x8192 bf16 matmul "finishes" in
~70 us (an impossible 15.8 PFLOP/s), while forcing a device→host data
dependency yields a plausible ~34 TFLOP/s. The only trustworthy
synchronization point is therefore an actual host fetch; :func:`sync`
fetches a scalar reduction of the result, which (a) depends on every
element of every shard, and (b) is replicated, so it is addressable from
any process in multi-host runs.

The fetch and the reduction cost a fixed overhead per call, so
:func:`timed_run` measures a zero-iteration run of the same jitted
program (same shapes, same sync) and subtracts it. This mirrors the
reference's accounting, which reports *kernel* time with the HtD/DtH
transfer segments timed separately between MPI barriers
(``MultiGPU/Diffusion3d_Baseline/main.c:139-147,184-187,305-307``), so
the MLUPS numbers remain comparable to the ``Run.m`` baselines. If the
subtraction is in the noise (tiny --quick grids), the raw, unsubtracted
time is used instead — conservative, never inflating.
"""

from __future__ import annotations

import statistics
import time
from typing import Callable, NamedTuple

import jax.numpy as jnp


def sync(arr) -> None:
    """Force completion of ``arr``'s producing computation via a
    device→host fetch that depends on all elements of all shards."""
    float(jnp.sum(arr))


class TimedRun(NamedTuple):
    seconds: float  # best-of-reps net execution time
    warmup_seconds: float  # compile + first full execution + sync
    median_seconds: float  # median-of-reps net execution time
    spread: float  # (max - min) / median of the per-rep net times
    outliers: int = 0  # stalled reps discarded and re-measured
    # spread over ALL measured reps including later-discarded ones —
    # keeps the full dispersion evidence in the artifact (a genuinely
    # bimodal row shows raw_spread >> spread, a stall shows one fat
    # outlier); equal to ``spread`` when nothing was discarded
    raw_spread: float = 0.0


# A rep whose net time exceeds this multiple of the running median is a
# stall (tunnel hiccup, host preemption), not a measurement: with only
# ~5 reps a single stalled rep can land *in* the median. Driver-captured
# evidence: BENCH_r03 burgers2d spread 148x — one rep of a ~0.95 s
# window took minutes.
_OUTLIER_FACTOR = 3.0


def _timed(full: Callable, zero: Callable, reps: int) -> TimedRun:
    """Measure ``full()`` minus the fixed sync/dispatch overhead of
    ``zero()`` (the same jitted program at zero work), best- and
    median-of-``reps``. Stalled reps (> ``_OUTLIER_FACTOR`` x the
    running median of accepted reps) are discarded and re-measured, up
    to ``reps`` extra attempts; the count is reported so the artifact
    stays self-qualifying."""
    reps = max(1, reps)
    t0 = time.perf_counter()
    sync(full())  # compile + warm-up
    warmup = time.perf_counter() - t0
    sync(zero())

    bases, raws = [], []
    discarded = []  # stalled raw times, kept for raw_spread evidence
    outliers = 0
    budget = reps  # extra attempts for discarded reps
    while len(raws) < reps:
        t0 = time.perf_counter()
        sync(zero())
        base = time.perf_counter() - t0
        t0 = time.perf_counter()
        sync(full())
        raw = time.perf_counter() - t0
        if (
            len(raws) >= 1
            and budget > 0
            and raw > _OUTLIER_FACTOR * statistics.median(raws)
        ):
            outliers += 1
            budget -= 1
            discarded.append(raw)
            continue  # a stall, not a measurement — re-measure
        bases.append(base)
        raws.append(raw)
    base = min(bases)
    nets = [r - base for r in raws]
    # If the subtraction is within the observed jitter of the overhead
    # measurement itself (tiny --quick grids), publish the raw time
    # instead of a jitter-dominated rate — conservative, never inflating.
    noise = max(bases) - base
    raw_mode = min(nets) <= noise
    if raw_mode:
        nets = list(raws)
    # Retrospective guard: the running-median filter above cannot catch a
    # stall in the FIRST rep (nothing to compare against yet) — drop any
    # rep that still exceeds the factor against the full set's median.
    # loop-discarded stalls, converted once into the published units
    discarded = [d if raw_mode else d - base for d in discarded]
    med0 = statistics.median(nets)
    kept = [n for n in nets if n <= _OUTLIER_FACTOR * med0]
    if kept and len(kept) < len(nets):
        outliers += len(nets) - len(kept)
        discarded.extend(n for n in nets if n > _OUTLIER_FACTOR * med0)
        nets = kept
    best, med = min(nets), statistics.median(nets)
    spread = (max(nets) - min(nets)) / med if med > 0 else 0.0
    # pre-filter dispersion over every measured rep (kept + discarded),
    # in the same units as the published nets
    all_nets = nets + discarded
    raw_spread = (
        (max(all_nets) - min(all_nets)) / med if med > 0 else 0.0
    )
    return TimedRun(best, warmup, med, spread, outliers, raw_spread)


def timed_run(solver, state, iters: int, reps: int = 3) -> TimedRun:
    """Best/median-of-``reps`` net seconds for ``solver.run(state, iters)``."""
    return _timed(
        lambda: solver.run(state, iters).u,
        lambda: solver.run(state, 0).u,
        reps,
    )


class TimedAdvance(NamedTuple):
    timing: TimedRun
    steps: int  # steps the while-loop actually took to reach t_end


def timed_advance(solver, state, t_end: float, reps: int = 3) -> TimedAdvance:
    """Best/median-of-``reps`` net seconds for
    ``solver.advance_to(state, t_end)`` — the reference drivers' native
    ``while (t < tEnd)`` mode. The zero-work overhead run is the same
    jitted program asked to advance to ``state.t`` (zero loop trips)."""
    steps = int(solver.advance_to(state, t_end).it - state.it)
    t_start = float(state.t)
    timing = _timed(
        lambda: solver.advance_to(state, t_end).u,
        lambda: solver.advance_to(state, t_start).u,
        reps,
    )
    return TimedAdvance(timing, steps)
