"""Benchmark matrix — drivers mirroring the reference's published configs.

Each entry reproduces one row of BASELINE.md (the author's archived
``Run.m`` numbers) with the same grid/iteration workload, and records the
TPU result next to the reference GFLOPS/MLUPS. Replaces the reference's
pitched/texture/shared *memory* variants (no TPU meaning) with the
framework's kernel-strategy axis: pure-XLA vs Pallas (``impl`` field).

Run:  python -m multigpu_advectiondiffusion_tpu.bench [--name X] [--quick]
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

from multigpu_advectiondiffusion_tpu.bench.timing import timed_run

# name -> (reference MLUPS, reference source). Single source of truth
# (bench.py imports these). All values are STAGE-update rates
# (cells*iters*3/time) so they divide our stage-counting mlups() metric
# like-for-like; the single-GPU *diffusion* Run.m numbers omit the x3 RK
# factor in the reference's own GFLOPS (BASELINE.md footnote 1), so those
# rows are converted here (x3) rather than quoted raw.
BASELINES_MLUPS = {
    "diffusion2d": (2681.0, "SingleGPU/Diffusion2d_PitchedMem/Run.m:3-12"),
    "diffusion3d": (2782.0, "SingleGPU/Diffusion3d_Blocking/Run.m:3-12"),
    "diffusion3d_multigpu": (731.0, "MultiGPU/Diffusion3d_Baseline/Run.m:4-13"),
    # the reference number IS f64 (USE_FLOAT false) — this row is the
    # apples-to-apples precision comparison
    "diffusion3d_multigpu_f64": (
        731.0, "MultiGPU/Diffusion3d_Baseline/Run.m:4-13"
    ),
    "burgers3d_512": (879.8, "SingleGPU/Burgers3d_WENO5/Run.m:15-25"),
    "burgers3d_512_axis": (879.8, "SingleGPU/Burgers3d_WENO5/Run.m:15-25"),
    "burgers3d_512_xla": (879.8, "SingleGPU/Burgers3d_WENO5/Run.m:15-25"),
    # the reference's WENO7 exists only as MATLAB prototypes
    # (LFWENO7FDM{1,2,3}d.m) with no benchmark; its nearest published
    # config — the same 512^3 viscous workload at order 5 — anchors the
    # row so the (heavier) order-7 rate is read against a real number
    "burgers3d_512_weno7": (879.8, "SingleGPU/Burgers3d_WENO5/Run.m:15-25"),
    # 1601*986*35*1067*3/563.49 s
    "burgers3d_slab": (313.9, "SingleGPU/Burgers3d_WENO5/Run.m:3-13"),
    # 1000*1000*200*167*3/247.54 s
    "burgers3d_wide": (404.8, "SingleGPU/Burgers3d_WENO5/Run.m:27-37"),
    "burgers2d_multigpu": (15.5, "MultiGPU/Burgers2d_Baseline/Run.m:4-14"),
    # 2-D order 7 has the same MATLAB-only status as 3-D
    # (LFWENO7FDM2d.m, never benchmarked); anchored on the same 2-D
    # workload's published order-5 number
    "burgers2d_weno7": (15.5, "MultiGPU/Burgers2d_Baseline/Run.m:4-14"),
    "burgers3d_multigpu": (37.9, "MultiGPU/Burgers3d_Baseline/Run.m:4-14"),
    # The reference never shipped the title ADR workload (its name
    # notwithstanding) — no published number exists. These rows anchor
    # on the diffusion baselines for the same grid class so vs_baseline
    # reads "vs the nearest published reference rate", explicitly NOT
    # a same-physics comparison (ISSUE 15).
    "adr3d": (731.0,
              "anchor: MultiGPU/Diffusion3d_Baseline/Run.m:4-13 (no "
              "reference ADR exists; nearest published 3-D rate)"),
    "adr2d": (2681.0,
              "anchor: SingleGPU/Diffusion2d_PitchedMem/Run.m:3-12 (no "
              "reference ADR exists; nearest published 2-D rate)"),
}


@dataclasses.dataclass(frozen=True)
class BenchCase:
    name: str
    kind: str  # diffusion | burgers
    grid_xyz: Tuple[int, ...]
    iters: int
    quick_scale: int = 4  # divide grid/iters by this in --quick mode
    weno_order: int = 5
    fixed_dt: bool = True  # reference parity: CUDA drivers fix dt
    nu: float = 0.0  # single-GPU Burgers are viscous (main.cpp:56-59)
    # kernel-strategy rung (f32 only; other dtypes run XLA): "pallas"
    # engages the fused steppers, "pallas_axis" pins the per-axis slab
    # kernels, "xla" the shifted-slice stencils — the ladder axis that
    # replaces the reference's pitched/texture/shared variants.
    impl: str = "pallas"
    # per-case precision (the --dtype flag overrides it for every case);
    # "float64" rows quantify the TPU's emulated-f64 cost against the
    # reference's only precision (USE_FLOAT false, DiffusionMPICUDA.h:66)
    dtype: str = "float32"


CASES = [
    # reference grids rounded to TPU-friendly multiples where needed
    BenchCase("diffusion2d", "diffusion", (1024, 1024), 1000),
    BenchCase("diffusion3d", "diffusion", (208, 200, 200), 605),
    BenchCase("diffusion3d_multigpu", "diffusion", (400, 200, 208), 101),
    # the reference's only precision, on the same literal grid: measures
    # the emulated-f64 cost ratio on TPU (no native f64 VPU path)
    BenchCase("diffusion3d_multigpu_f64", "diffusion", (400, 200, 208), 31,
              dtype="float64"),
    BenchCase("burgers3d_512", "burgers", (512, 512, 512), 86, nu=1e-5),
    # explicit slower rungs of the same flagship config (the reference
    # benches its non-winning variants too, RunAll.m)
    BenchCase("burgers3d_512_axis", "burgers", (512, 512, 512), 21,
              impl="pallas_axis", nu=1e-5),
    BenchCase("burgers3d_512_xla", "burgers", (512, 512, 512), 21,
              impl="xla", nu=1e-5),
    # order-7 rung of the fused family (halo 4), same flagship workload
    BenchCase("burgers3d_512_weno7", "burgers", (512, 512, 512), 40,
              weno_order=7, nu=1e-5),
    # the other two published single-GPU viscous-Burgers workloads
    # (Run.m:3-13 / :27-37); literal grids, reduced iteration counts
    # (MLUPS is a rate — the reference ran 1067x3 / 167x3 stages)
    BenchCase("burgers3d_slab", "burgers", (1601, 986, 35), 60, nu=1e-5),
    BenchCase("burgers3d_wide", "burgers", (1000, 1000, 200), 60, nu=1e-5),
    BenchCase("burgers2d_multigpu", "burgers", (400, 408), 200),
    # 2-D order-7 rung (halo-4 whole-run stepper), same 2-D workload
    BenchCase("burgers2d_weno7", "burgers", (400, 408), 200, weno_order=7),
    BenchCase("burgers3d_multigpu", "burgers", (400, 400, 408), 267),
    # the title workload (ISSUE 15): variable-K advection–diffusion–
    # reaction; 3-D rides the fused per-stage rung, 2-D the generic
    BenchCase("adr3d", "adr", (208, 200, 200), 300),
    BenchCase("adr2d", "adr", (1024, 1024), 400, impl="xla"),
]


@dataclasses.dataclass(frozen=True)
class EnsembleBenchCase:
    """One batched-ensemble row (ISSUE 9): B members advanced by ONE
    vmapped dispatch, reported as MLUPS*members next to the looped
    single-run baseline (``vs_looped``)."""

    name: str
    kind: str  # diffusion | burgers
    grid_xyz: Tuple[int, ...]
    iters: int
    members: int
    quick_scale: int = 4
    impl: str = "pallas"
    nu: float = 0.0


ENSEMBLE_CASES = [
    EnsembleBenchCase("ensemble_diffusion3d_b8", "diffusion",
                      (128, 128, 64), 60, 8),
    EnsembleBenchCase("ensemble_diffusion3d_b64", "diffusion",
                      (128, 128, 64), 20, 64),
    EnsembleBenchCase("ensemble_burgers3d_b8", "burgers",
                      (64, 64, 64), 30, 8, nu=1e-5),
]


@dataclasses.dataclass(frozen=True)
class PrecisionBenchCase:
    """One error-vs-speed precision row (ISSUE 16): the SAME workload
    timed at native f32 and at ``precision='bf16'`` (bf16 storage /
    f32 compute), recording the bf16 rung's MLUPS next to the native
    rate AND both runs' solution error — a speedup the science cannot
    cash is a regression, so the row carries the evidence for the
    per-dtype gate (``out/precision_gate.sh`` /
    ``diagnostics/compare``) right next to the rate."""

    name: str
    kind: str  # diffusion | burgers | adr
    grid_xyz: Tuple[int, ...]
    iters: int
    quick_scale: int = 4
    impl: str = "pallas"
    weno_order: int = 5
    fixed_dt: bool = True  # bf16 Burgers requires fixed dt
    nu: float = 0.0


PRECISION_CASES = [
    PrecisionBenchCase("precision_diffusion3d", "diffusion",
                       (208, 200, 200), 151),
    # Burgers' bf16 rung is the whole-run slab (per-stage WENO has no
    # split-dtype machinery) — pinned so a silent per-stage fallback
    # cannot masquerade as the bf16 measurement
    PrecisionBenchCase("precision_burgers3d", "burgers",
                       (256, 256, 256), 40, impl="pallas_slab",
                       nu=1e-5),
    PrecisionBenchCase("precision_adr3d", "adr", (208, 200, 200), 100),
]


def run_precision_case(case: PrecisionBenchCase, quick: bool = False,
                       repeats: int = 3) -> dict:
    """Time one workload at native f32 AND at ``precision='bf16'``,
    and record both runs' error: the analytic L1/L2/Linf norms where
    the family has them (diffusion/ADR heat-kernel workloads), plus
    the bf16 trajectory's relative L2 distance from the native one
    (always available — Burgers has no analytic 3-D solution). The
    row's gated value is the bf16 ``mlups``; the error fields are the
    science evidence the precision gate reads."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models import registry
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    grid_xyz = case.grid_xyz
    iters = case.iters
    if quick:
        grid_xyz = tuple(max(16, g // case.quick_scale) for g in grid_xyz)
        iters = max(3, iters // case.quick_scale)
    grid = Grid.make(*grid_xyz, lengths=[10.0] * len(grid_xyz))
    spec = registry.get(case.kind)
    cfg32 = spec.bench_build(grid, "float32", case.impl, case)
    cfg16 = dataclasses.replace(cfg32, precision="bf16")

    rows = {}
    outs = {}
    for label, cfg in (("native", cfg32), ("bf16", cfg16)):
        solver = spec.solver_cls(cfg)
        state = solver.initial_state()
        timed = timed_run(solver, state, iters, reps=repeats)
        outs[label] = (solver, solver.run(state, iters))
        cells = 1
        for g in grid_xyz:
            cells *= g
        rows[label] = {
            "engaged": solver.engaged_path()["stepper"],
            "storage_dtype": str(solver.storage_dtype),
            "seconds": round(timed.seconds, 4),
            "spread": round(timed.spread, 4),
            "mlups": round(
                mlups(cells, iters, STAGES[cfg.integrator], timed.seconds),
                1,
            ),
        }

    s16, out16 = outs["bf16"]
    s32, out32 = outs["native"]
    ref = float(jnp.linalg.norm(out32.u.astype(jnp.float32).ravel()))
    dist = float(jnp.linalg.norm(
        (out16.u.astype(jnp.float32) - out32.u.astype(jnp.float32)).ravel()
    ))
    result = {
        "name": case.name,
        "grid": "x".join(map(str, grid_xyz)),
        "iters": iters,
        "dtype": "float32",
        "precision": "bf16",
        "storage_dtype": rows["bf16"]["storage_dtype"],
        "impl": case.impl,
        "engaged": rows["bf16"]["engaged"],
        "seconds": rows["bf16"]["seconds"],
        "spread": rows["bf16"]["spread"],
        "mlups": rows["bf16"]["mlups"],
        "native_engaged": rows["native"]["engaged"],
        "native_mlups": rows["native"]["mlups"],
        "native_seconds": rows["native"]["seconds"],
        "speedup_vs_native": (
            round(rows["native"]["seconds"] / rows["bf16"]["seconds"], 3)
            if rows["bf16"]["seconds"]
            else None
        ),
        # bf16 trajectory vs the native one, relative L2 — nonzero by
        # construction (storage rounding), gated by the precision
        # gate's per-dtype band, never by the MLUPS thresholds
        "vs_native_rel_l2": round(dist / ref, 8) if ref else None,
        "ensemble": 1,
        "quick": quick,
    }
    for label, (solver, out) in outs.items():
        if hasattr(solver, "error_norms"):
            try:
                norms = solver.error_norms(out)
            except ValueError:
                # workloads without a closed form (variable-K ADR,
                # Burgers) gate on vs_native_rel_l2 instead
                continue
            key = "error" if label == "bf16" else "native_error"
            result[f"{key}_l2"] = round(float(norms.l2), 10)
            result[f"{key}_linf"] = round(float(norms.linf), 10)
    return result


def run_ensemble_case(case: EnsembleBenchCase, quick: bool = False,
                      repeats: int = 3) -> dict:
    """Time one batched-ensemble case: B members in ONE vmapped
    dispatch, plus the looped single-run baseline on the same compiled
    single program. Value convention: ``mlups`` is MLUPS*members (the
    batch's aggregate stage-update rate), so the bench gate diffs it
    like every other row."""
    import statistics
    import time

    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.bench.timing import sync
    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models import registry
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.models.state import SolverState
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    grid_xyz = case.grid_xyz
    iters = case.iters
    if quick:
        grid_xyz = tuple(max(8, g // case.quick_scale) for g in grid_xyz)
        iters = max(2, iters // case.quick_scale)
    grid = Grid.make(*grid_xyz, lengths=[2.0] * len(grid_xyz))
    # family config via the registry's bench hook; the width-swept
    # gaussian IC is the ensemble rows' common member-varying workload
    spec = registry.get(case.kind)
    cls = spec.solver_cls
    cfg = dataclasses.replace(
        spec.bench_build(grid, "float32", case.impl, case),
        ic="gaussian",
    )
    members = [
        {"ic_params": (("width", 0.1 + 0.002 * i),)}
        for i in range(case.members)
    ]
    es = EnsembleSolver(cls, cfg, members)
    est = es.initial_state()

    def wall(fn):
        sync(fn())  # compile + warm-up, untimed
        times = []
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            sync(fn())
            times.append(time.perf_counter() - t0)
        med = statistics.median(times)
        return med, (max(times) - min(times)) / med if med > 0 else 0.0

    batched_s, spread = wall(lambda: es.run(est, iters).u)
    single = es.member_solver(0)

    def looped():
        return jnp.stack([
            single.run(
                SolverState(u=est.u[i], t=est.t[i], it=est.it[i]), iters
            ).u
            for i in range(case.members)
        ])

    looped_s, _ = wall(looped)
    engaged = es.engaged_path()
    cells = 1
    for g in grid_xyz:
        cells *= g
    rate = mlups(cells * case.members, iters, STAGES[cfg.integrator],
                 batched_s)
    return {
        "name": case.name,
        "grid": "x".join(map(str, grid_xyz)),
        "iters": iters,
        "dtype": "float32",
        "impl": case.impl,
        "ensemble": case.members,
        "engaged": engaged["stepper"],
        "seconds": round(batched_s, 4),
        "spread": round(spread, 4),
        "mlups": round(rate, 1),
        "looped_seconds": round(looped_s, 4),
        "vs_looped": round(looped_s / batched_s, 3) if batched_s else None,
        "tuned": engaged.get("tuned"),
        "quick": quick,
    }


def resolve_impl(case: BenchCase, dtype: str,
                 mesh_spec: Optional[str] = None) -> str:
    """Kernel strategy actually benchmarked: the Pallas rungs' DMA tiling
    is f32-calibrated, so non-f32 dtypes run XLA — EXCEPT 3-D diffusion
    f64, which rides the fused f32 kernels through the
    f64-storage/f32-compute convention (the solver's own eligibility
    gate; non-eligible configs still land on the generic path and the
    'engaged' field says so). Multichip f32 rows (``--mesh``, e.g. the
    burgers3d_multigpu / split-overlap cases) route ``pallas`` through
    ``auto`` so the measured tuner picks the rung and the
    communication-avoiding ``steps_per_exchange`` from its decision
    cache. One definition — the JSON 'impl' field and the constructed
    solver must never diverge."""
    if dtype == "float32":
        if mesh_spec and case.impl == "pallas":
            return "auto"
        return case.impl
    if dtype == "float64" and case.kind == "diffusion" and len(
        case.grid_xyz
    ) == 3:
        return case.impl
    return "xla"


def build_solver(case: BenchCase, dtype: str, grid_xyz, mesh_spec: Optional[str]):
    """Case -> solver, resolved through the plugin registry: the
    family's ``bench_build`` hook constructs the config, so a third
    model brings its own bench cases without touching this function
    (ISSUE 15)."""
    from multigpu_advectiondiffusion_tpu.cli.drivers import (
        decomposition_for,
        parse_mesh_spec,
    )
    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models import registry

    grid = Grid.make(*grid_xyz, lengths=[10.0] * len(grid_xyz))
    mesh, sizes = parse_mesh_spec(mesh_spec)
    decomp = decomposition_for(grid, sizes)
    impl = resolve_impl(case, dtype, mesh_spec)
    spec = registry.get(case.kind)
    cfg = spec.bench_build(grid, dtype, impl, case)
    return spec.solver_cls(cfg, mesh=mesh, decomp=decomp)


def run_case(
    case: BenchCase,
    dtype: Optional[str] = None,
    quick: bool = False,
    mesh_spec: Optional[str] = None,
    repeats: int = 3,
) -> dict:
    dtype = dtype or case.dtype
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import STAGES
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    grid_xyz = case.grid_xyz
    iters = case.iters
    if quick:
        grid_xyz = tuple(max(16, g // case.quick_scale) for g in grid_xyz)
        iters = max(3, iters // case.quick_scale)

    solver = build_solver(case, dtype, grid_xyz, mesh_spec)
    state = solver.initial_state()

    timed = timed_run(solver, state, iters, reps=repeats)
    best = timed.seconds
    engaged = solver.engaged_path()
    # warm-up = compile + one full execution of the benchmarked program
    compile_s = max(timed.warmup_seconds - best, 0.0)

    cells = 1
    for g in grid_xyz:
        cells *= g
    rate = mlups(cells, iters, STAGES[solver.cfg.integrator], best)
    base, src = BASELINES_MLUPS.get(case.name, (None, None))
    # roofline efficiency on the engaged rung's static bytes/FLOPs model
    from multigpu_advectiondiffusion_tpu.telemetry import costmodel

    cost = costmodel.summarize_run(solver, engaged["stepper"], iters, best)
    # measured introspection beside the modeled roofline: the compiled
    # executable's own XLA-reported per-step numbers (telemetry/xprof)
    from multigpu_advectiondiffusion_tpu.telemetry import xprof

    meas = xprof.measured_summary(solver, iters, best) or {}
    result = {
        "name": case.name,
        "grid": "x".join(map(str, grid_xyz)),
        "iters": iters,
        "dtype": dtype,
        "impl": resolve_impl(case, dtype, mesh_spec),
        # which stepper rung actually executed (fused-whole-run-slab /
        # fused-whole-run / fused-stage / ... / generic-xla) — a row
        # that silently fell off the fused ladder is visible in the
        # artifact, not just slow (bench.py's engagement guard is the
        # hard-failing counterpart for the headline rows)
        "engaged": engaged["stepper"],
        # comm-avoiding cadence in effect + tuner provenance (non-None
        # exactly when impl resolved through "auto")
        "steps_per_exchange": engaged.get("steps_per_exchange", 1),
        # halo transport actually engaged: collective ppermute or the
        # in-kernel remote-DMA ring (ISSUE 13)
        "exchange": engaged.get("exchange", "collective"),
        # storage-precision provenance (ISSUE 16): rows predating the
        # fields read as native/compute-dtype in bench/compare.py
        "precision": engaged.get("precision", "native"),
        "storage_dtype": engaged.get("storage_dtype", dtype),
        "tuned": engaged.get("tuned"),
        "seconds": round(best, 4),
        "compile_seconds": round(compile_s, 3),
        "mlups": round(rate, 1),
        "roofline_pct": (cost or {}).get("roofline_pct"),
        # measured XLA columns (coverage-checked, non-gating in
        # bench/compare.py): per-step flops/bytes the compiled
        # executable reports, and its peak-footprint estimate
        "xla_flops": meas.get("xla_flops_per_step"),
        "xla_bytes": meas.get("xla_bytes_per_step"),
        "peak_bytes": meas.get("peak_bytes"),
        # single-run rows carry the member count explicitly (older
        # rounds without the field read as 1 — bench/compare.py)
        "ensemble": 1,
        "quick": quick,
        "mesh": mesh_spec,
    }
    if engaged.get("degraded"):
        # a mid-measurement kernel-ladder downgrade (Mosaic failure ->
        # slower rung) is recorded in the artifact; bench.py's guard is
        # the hard-failing counterpart
        result["degraded"] = engaged["degraded"]
    if base and not quick:
        result["reference_mlups"] = base
        result["vs_reference"] = round(rate / base, 3)
        result["reference_source"] = src
    return result


def main(argv=None):
    import argparse

    from multigpu_advectiondiffusion_tpu.utils.platform_env import (
        honor_platform_env,
    )

    honor_platform_env()

    ap = argparse.ArgumentParser(prog="multigpu_advectiondiffusion_tpu.bench")
    ap.add_argument("--name", default=None,
                    help="run one case (default: all)")
    ap.add_argument("--dtype", default=None,
                    help="override every case's precision (default: "
                         "per-case, float32 unless the row says f64)")
    ap.add_argument("--quick", action="store_true",
                    help="shrunken grids for smoke-benching")
    ap.add_argument("--mesh", default=None, help="e.g. dz=4")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default=None, help="write JSON lines here")
    ap.add_argument("--compare", default=None, metavar="PRIOR",
                    help="regression gate: after the run, diff the "
                         "produced rows against a prior round's "
                         "artifact (bench/compare.py thresholds) and "
                         "exit nonzero on any regression")
    ap.add_argument("--tuning-cache", default=None, metavar="PATH",
                    help="tuning decision cache for the impl='auto' "
                         "multichip rows (default: $TPUCFD_TUNING_CACHE "
                         "or the user cache dir)")
    args = ap.parse_args(argv)

    # multichip rows dispatch through impl="auto": enable measurement so
    # a cache miss tunes (and persists) instead of falling back
    from multigpu_advectiondiffusion_tpu import tuning

    tuning.configure(cache_path=args.tuning_cache, enabled=True)

    cases = [c for c in CASES if args.name is None or c.name == args.name]
    ens_cases = [
        c for c in ENSEMBLE_CASES
        if args.name is None or c.name == args.name
    ]
    prec_cases = [
        c for c in PRECISION_CASES
        if args.name is None or c.name == args.name
    ]
    if not cases and not ens_cases and not prec_cases:
        raise SystemExit(
            f"no case {args.name!r}; have "
            f"{[c.name for c in CASES + ENSEMBLE_CASES + PRECISION_CASES]}"
        )
    from jax.experimental import enable_x64

    lines = []
    for case in cases:
        # x64 scoped per case (jax.experimental.enable_x64 — the
        # top-level alias was removed): a process-wide flip would poison
        # the f32 Pallas rows' Mosaic lowering with i64 constants. The
        # resolved dtype is passed down so the scope and the solver
        # can't diverge.
        dtype = args.dtype or case.dtype
        with enable_x64(dtype == "float64"):
            res = run_case(case, dtype=dtype, quick=args.quick,
                           mesh_spec=args.mesh, repeats=args.repeats)
        line = json.dumps(res)
        print(line, flush=True)
        lines.append(line)
    for case in ens_cases:
        # batched-ensemble rows (ISSUE 9): the ensemble engine declines
        # meshes, so these never take --mesh; f32 only
        res = run_ensemble_case(case, quick=args.quick,
                                repeats=args.repeats)
        line = json.dumps(res)
        print(line, flush=True)
        lines.append(line)
    for case in prec_cases:
        # error-vs-speed precision rows (ISSUE 16): f32-facing configs
        # (no x64 scoping), single-run only — never take --mesh
        res = run_precision_case(case, quick=args.quick,
                                 repeats=args.repeats)
        line = json.dumps(res)
        print(line, flush=True)
        lines.append(line)
    if args.out:
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        # atomic publish: bench/compare.py gates against this file —
        # it must never read a half-written round
        atomic_write_text(args.out, "\n".join(lines) + "\n")
    if args.compare:
        # measured regression gate: this run's rows against the prior
        # round, per-row noise thresholds, loud nonzero exit
        from multigpu_advectiondiffusion_tpu.bench import compare as cmp

        new_rows = {}
        for line in lines:
            row = json.loads(line)
            key = cmp.row_key(row)
            if key and cmp.row_value(row) is not None:
                new_rows[key] = row
        # --name may have subsetted the cases: gate only what ran (the
        # full-round coverage check lives in out/bench_gate.sh)
        old_rows = {
            k: v for k, v in cmp.load_rows(args.compare).items()
            if k in new_rows
        }
        result = cmp.compare(new_rows, old_rows)
        print(result.format_text(), flush=True)
        if not result.ok:
            raise SystemExit(1)


if __name__ == "__main__":
    main()
