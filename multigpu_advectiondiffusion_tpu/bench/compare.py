"""Bench-round regression gate: diff a fresh bench JSON against a
prior round with per-row noise thresholds; nonzero exit on regression.

The bench trajectory (BENCH_r01 -> r05, driver-captured) records a
rate per metric plus its OWN measurement-quality evidence (median-of-
reps ``spread``, discarded-stall ``outliers``). This module is the
consumer that was missing: the trend-tracking discipline of HipBone
(PAPERS: arXiv 2202.12477 — every optimization claim is a measured
delta against the previous round) as an executable gate instead of a
human eyeballing JSON.

Input formats (auto-detected): the ``bench.py`` / ``bench/matrix.py``
JSON-lines artifacts (one row per line, ``metric``+``value`` or
``name``+``mlups``), a JSON list of such rows, or the driver's wrapper
object whose ``tail`` embeds the JSONL (the BENCH_r0*.json layout; a
truncated first line is skipped, not fatal).

Threshold per row: ``max(rel_tol, min(spread_factor * max(spread_old,
spread_new), spread_cap))`` — a noisy row must move by more than its
own observed dispersion before the gate calls it a regression, but the
spread-derived slack is capped (:data:`DEFAULT_SPREAD_CAP`) so a round
with pathological measured spread cannot widen its own gate past the
point where a real 20% regression reads as noise. The measured
introspection columns (:data:`MEASURED_FIELDS` — ``xla_flops``/
``xla_bytes``/``peak_bytes``) are coverage-checked (a dropped column
prints a note) but never gate. Usage::

    python -m multigpu_advectiondiffusion_tpu.bench.compare NEW OLD
    python -m multigpu_advectiondiffusion_tpu.bench.compare NEW --floors

``--floors`` checks each row's ``vs_baseline`` against the BASELINE.md
floor (>= 1.0) instead of a prior round. Wrappers: ``out/bench_gate.sh``
(newest BENCH_r0*.json + injected-slowdown self-test) and
``bench/matrix.py --compare PRIOR``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

DEFAULT_REL_TOL = 0.05
DEFAULT_SPREAD_FACTOR = 2.0
# Ceiling on the spread-derived part of the threshold: a round whose
# measured spread is huge (a CPU-round artifact, a noisy shared host)
# must not widen its own gate past this — otherwise an injected 20%
# regression can hide inside 2 x spread and the bench gate's selftest
# stops being deterministic. 15% keeps the documented noisy-row
# semantics (a -12% move on a 15%-spread row is noise) while any drop
# beyond 15% always gates.
DEFAULT_SPREAD_CAP = 0.15

# Measured-introspection columns (telemetry/xprof via bench rows):
# coverage-checked — a row that HAD them and silently lost them gets a
# printed note — but never gating: they are measurement provenance, not
# throughput, and XLA's counts legitimately shift across compiler
# versions.
MEASURED_FIELDS = ("xla_flops", "xla_bytes", "peak_bytes")

# Batched-ensemble columns (ISSUE 9/11): same coverage-note discipline
# as MEASURED_FIELDS — ``ensemble`` (member count B), ``vs_looped``
# (batched-over-looped amortization ratio) and, since the mesh-scale
# round, ``member_sharding``/``devices`` (member-axis placement) are
# provenance, not gated throughput. Rows from rounds BEFORE the
# ensemble engine (BENCH_r01-r05) carry none of these;
# :func:`row_members`/:func:`row_member_sharding` read them as 1 and
# their absence is never a coverage regression.
ENSEMBLE_FIELDS = ("ensemble", "vs_looped", "member_sharding", "devices")

# Low-precision-storage columns (ISSUE 16): ``storage_dtype`` (the
# dtype the state occupies in HBM and on the halo wire) and
# ``precision`` (the dispatch knob that selected it) ride the
# ``precision_*`` bench rows. Same coverage-note discipline: a row
# that HAD them and silently lost them prints a note, never gates.
# Rows from rounds before this family (r01-r07) carry neither field
# and read as the compute dtype via :func:`row_storage_dtype`.
PRECISION_FIELDS = ("storage_dtype", "precision")

# Halo-transport column (ISSUE 13): ``exchange`` records which halo
# transport a sharded slab row ran — "collective" (XLA ppermute
# between compiled calls) or "dma" (in-kernel remote-DMA pushes, the
# whole-run program never leaving Pallas). Same coverage-note
# discipline: provenance, not gated throughput; rows from rounds
# before the dma rung carry no field and read as "collective".
SCHEDULE_FIELDS = ("exchange",)

# Request-serving columns (ISSUE 17, widened by ISSUE 18): the
# ``serving_*`` rows carry the coalesced server's latency percentiles
# (p50/p95/p99, re-sourced through the shared fixed-log-boundary
# histogram in telemetry/metrics.py — the same estimator the fleet's
# merged snapshots report), mean batch occupancy, the queue-depth
# watermark from the server's exported gauge, and the coalesced-over-
# sequential wall ratio beside the req/s headline. Same coverage-note
# discipline: provenance, not gated throughput; rows from rounds
# before the request server carry none of these, and rounds before
# the metrics layer lack p95_ms/max_queue_depth.
SERVING_FIELDS = ("p50_ms", "p95_ms", "p99_ms", "occupancy",
                  "max_queue_depth", "vs_sequential")


def row_family(key: Optional[str]) -> Optional[str]:
    """The solver family a metric/name belongs to, resolved through
    the plugin registry's name-prefix convention (``adr3d_mlups`` ->
    ``adr``); ``None`` for rows outside the family namespace (scaling
    composites like ``ensemble_*`` resolve through their embedded
    family name). Never raises — coverage notes must survive arbitrary
    artifacts."""
    if not key:
        return None
    try:
        from multigpu_advectiondiffusion_tpu.models import registry

        fam = registry.family_of_run_name(key)
        if fam is not None:
            return fam
        # composite rows: ensemble_<family>..., <family> embedded
        for name in registry.names():
            if name in key:
                return name
    except Exception:
        pass
    return None


def family_coverage(rows: Dict[str, dict]):
    """``{family: row_count}`` over a round's rows — the per-family
    coverage surface the gate's notes read."""
    out: Dict[str, int] = {}
    for key in rows:
        fam = row_family(key)
        if fam:
            out[fam] = out.get(fam, 0) + 1
    return out


def row_exchange(row: Optional[dict]) -> str:
    """A row's halo transport; rounds before ISSUE 13 read as the
    collective default — never a parse error, never a coverage
    regression."""
    if not row:
        return "collective"
    v = row.get("exchange")
    return str(v) if v else "collective"


def row_storage_dtype(row: Optional[dict]) -> str:
    """A row's HBM/wire storage dtype; rounds before ISSUE 16 carry no
    field and read as the row's compute dtype (``dtype`` when recorded,
    else the repo-wide float32 default) — never a parse error, never a
    coverage regression."""
    if not row:
        return "float32"
    v = row.get("storage_dtype") or row.get("dtype")
    return str(v) if v else "float32"


def parse_rows(text: str) -> List[dict]:
    """JSON-lines -> row dicts; unparseable lines (the truncated head
    of a driver ``tail``) are skipped."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            rows.append(obj)
    return rows


def row_key(row: dict) -> Optional[str]:
    return row.get("metric") or row.get("name")


def row_value(row: dict) -> Optional[float]:
    v = row.get("value", row.get("mlups"))
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def row_spread(row: dict) -> float:
    try:
        return float(row.get("spread") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def row_members(row: dict) -> int:
    """Ensemble member count of a row; rounds predating the batched
    engine (BENCH_r01-r05) have no ``ensemble`` field and read as 1 —
    never a parse error, never a coverage regression."""
    try:
        return max(1, int(row.get("ensemble") or 1))
    except (TypeError, ValueError):
        return 1


def row_member_sharding(row: dict) -> int:
    """Member-axis shard count of a row (how many devices the member
    axis was spread over); rounds predating the mesh-scale ensemble
    round read as 1 — never a parse error, never a coverage
    regression."""
    try:
        return max(1, int(row.get("member_sharding") or 1))
    except (TypeError, ValueError):
        return 1


def load_rows(path: str) -> Dict[str, dict]:
    """A bench artifact -> ``{metric: row}``, whatever the container."""
    with open(path) as f:
        text = f.read()
    rows: List[dict] = []
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, list):
        rows = [r for r in obj if isinstance(r, dict)]
    elif isinstance(obj, dict) and isinstance(obj.get("tail"), str):
        rows = parse_rows(obj["tail"])  # driver wrapper (BENCH_r0*.json)
    elif isinstance(obj, dict) and isinstance(obj.get("rows"), list):
        rows = [r for r in obj["rows"] if isinstance(r, dict)]
    elif isinstance(obj, dict) and row_key(obj):
        rows = [obj]
    else:
        rows = parse_rows(text)
    out: Dict[str, dict] = {}
    for row in rows:
        key = row_key(row)
        if key and row_value(row) is not None:
            out[key] = row  # later rows win (tail may repeat a metric)
    return out


@dataclasses.dataclass
class RowResult:
    metric: str
    status: str  # ok | regression | improved | added | missing
    new: Optional[float] = None
    old: Optional[float] = None
    ratio: Optional[float] = None
    threshold: Optional[float] = None

    def line(self) -> str:
        if self.status in ("added", "missing"):
            return f"  {self.status.upper():>10}  {self.metric}"
        arrow = {"regression": "REGRESSION", "improved": "improved",
                 "ok": "ok"}[self.status]
        return (
            f"  {arrow:>10}  {self.metric}: {self.old:.2f} -> "
            f"{self.new:.2f}  ({100 * (self.ratio - 1):+.1f}%, "
            f"threshold ±{100 * self.threshold:.1f}%)"
        )


@dataclasses.dataclass
class CompareResult:
    rows: List[RowResult]
    # non-gating coverage notes (e.g. a measured xla_* column that
    # disappeared between rounds) — printed, never failing
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def regressions(self) -> List[RowResult]:
        return [r for r in self.rows
                if r.status in ("regression", "missing")]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "rows": [dataclasses.asdict(r) for r in self.rows],
            "notes": list(self.notes),
        }

    def format_text(self) -> str:
        lines = ["bench compare:"]
        lines += [r.line() for r in self.rows]
        for note in self.notes:
            lines.append(f"        note  {note}")
        n_reg = len(self.regressions)
        lines.append(
            "bench compare: PASS"
            if self.ok
            else f"bench compare: FAIL ({n_reg} regression(s))"
        )
        return "\n".join(lines)


def compare(
    new_rows: Dict[str, dict],
    old_rows: Dict[str, dict],
    rel_tol: float = DEFAULT_REL_TOL,
    spread_factor: float = DEFAULT_SPREAD_FACTOR,
    spread_cap: float = DEFAULT_SPREAD_CAP,
) -> CompareResult:
    """Per-metric diff of two rounds. A metric present in the old round
    but absent from the new one is a ``missing`` failure (a silently
    dropped benchmark is a regression in coverage); a new metric is
    reported as ``added`` and never fails."""
    results: List[RowResult] = []
    notes: List[str] = []
    # per-FAMILY coverage notes (ISSUE 15): a whole solver family
    # vanishing (or shrinking) between rounds is surfaced by name even
    # when the per-metric missing failures are being read row by row —
    # future rounds cannot silently drop the ADR family the repo is
    # named after
    old_fams = family_coverage(old_rows)
    new_fams = family_coverage(new_rows)
    for fam in sorted(set(old_fams) - set(new_fams)):
        notes.append(
            f"model family {fam!r} had {old_fams[fam]} row(s) in the "
            "prior round and NONE in this one (family coverage "
            "dropped; the per-metric MISSING failures below gate it)"
        )
    for fam in sorted(set(old_fams) & set(new_fams)):
        if new_fams[fam] < old_fams[fam]:
            notes.append(
                f"model family {fam!r} coverage shrank: "
                f"{old_fams[fam]} -> {new_fams[fam]} row(s)"
            )
    for key in sorted(set(old_rows) | set(new_rows)):
        old = old_rows.get(key)
        new = new_rows.get(key)
        if old is None:
            results.append(RowResult(key, "added",
                                     new=row_value(new)))
            continue
        if new is None:
            results.append(RowResult(key, "missing",
                                     old=row_value(old)))
            continue
        for field in (MEASURED_FIELDS + ENSEMBLE_FIELDS
                      + SCHEDULE_FIELDS + PRECISION_FIELDS
                      + SERVING_FIELDS):
            if old.get(field) is not None and new.get(field) is None:
                notes.append(
                    f"{key}: measured column {field!r} dropped "
                    "(coverage note, non-gating)"
                )
        if row_exchange(old) != row_exchange(new):
            # the same metric measured over a different halo transport
            # is a different schedule: surfaced, non-gating (the rate
            # comparison stays — same physics, same work)
            notes.append(
                f"{key}: halo transport changed "
                f"{row_exchange(old)} -> {row_exchange(new)} "
                "(coverage note, non-gating)"
            )
        if row_storage_dtype(old) != row_storage_dtype(new):
            # the same metric measured at a different storage dtype is
            # a different bandwidth workload: surfaced, non-gating (the
            # precision_* row NAMES carry the dtype by convention, so
            # this only fires on drift)
            notes.append(
                f"{key}: storage dtype changed "
                f"{row_storage_dtype(old)} -> {row_storage_dtype(new)} "
                "(coverage note, non-gating)"
            )
        if row_members(old) != row_members(new):
            # a row measured at a different member count is a different
            # workload: flag it as a note (the metric NAME carries the
            # B by convention, so this only fires on drift)
            notes.append(
                f"{key}: ensemble member count changed "
                f"{row_members(old)} -> {row_members(new)} "
                "(coverage note, non-gating)"
            )
        if row_member_sharding(old) != row_member_sharding(new):
            # member-placement drift: the same B spread over a
            # different number of devices is a different machine
            # configuration — the rate comparison stays (same
            # workload), but the drift is surfaced
            notes.append(
                f"{key}: member placement changed "
                f"{row_member_sharding(old)}-way -> "
                f"{row_member_sharding(new)}-way member sharding "
                "(coverage note, non-gating)"
            )
        ov, nv = row_value(old), row_value(new)
        threshold = max(
            rel_tol,
            min(
                spread_factor * max(row_spread(old), row_spread(new)),
                spread_cap,
            ),
        )
        ratio = nv / ov if ov else float("inf")
        if ratio < 1.0 - threshold:
            status = "regression"
        elif ratio > 1.0 + threshold:
            status = "improved"
        else:
            status = "ok"
        results.append(RowResult(key, status, new=nv, old=ov,
                                 ratio=round(ratio, 4),
                                 threshold=round(threshold, 4)))
    return CompareResult(results, notes=notes)


def check_floors(new_rows: Dict[str, dict],
                 floor: float = 1.0) -> CompareResult:
    """BASELINE.md-floor mode: every row carrying a ``vs_baseline``
    ratio must sit at or above ``floor`` (the reference's own published
    rate). Rows without the field are skipped — not every metric has a
    published baseline."""
    results = []
    for key in sorted(new_rows):
        row = new_rows[key]
        vs = row.get("vs_baseline")
        if vs is None:
            continue
        vs = float(vs)
        status = "ok" if vs >= floor else "regression"
        results.append(RowResult(key, status, new=vs, old=floor,
                                 ratio=round(vs / floor, 4),
                                 threshold=0.0))
    return CompareResult(results)


def main(argv=None) -> None:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="multigpu_advectiondiffusion_tpu.bench.compare",
        description="bench-round regression gate (nonzero exit on "
                    "regression)",
    )
    ap.add_argument("new", help="fresh bench artifact (JSONL rows or "
                                "driver wrapper JSON)")
    ap.add_argument("old", nargs="?", default=None,
                    help="prior round to diff against (e.g. the newest "
                         "BENCH_r0*.json)")
    ap.add_argument("--floors", action="store_true",
                    help="check vs_baseline >= 1 (BASELINE.md floors) "
                         "instead of a prior round")
    ap.add_argument("--rel-tol", type=float, default=DEFAULT_REL_TOL,
                    help="minimum relative threshold per row "
                         f"(default {DEFAULT_REL_TOL})")
    ap.add_argument("--spread-factor", type=float,
                    default=DEFAULT_SPREAD_FACTOR,
                    help="multiple of a row's own measured spread the "
                         "threshold grows to on noisy rows "
                         f"(default {DEFAULT_SPREAD_FACTOR})")
    ap.add_argument("--spread-cap", type=float,
                    default=DEFAULT_SPREAD_CAP,
                    help="ceiling on the spread-derived threshold "
                         "slack, so a noisy round cannot widen its own "
                         f"gate (default {DEFAULT_SPREAD_CAP})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    args = ap.parse_args(argv)

    if args.floors == (args.old is not None):
        ap.error("provide exactly one of: a prior round, or --floors")
    new_rows = load_rows(args.new)
    if not new_rows:
        raise SystemExit(f"no bench rows found in {args.new}")
    if args.floors:
        result = check_floors(new_rows)
    else:
        old_rows = load_rows(args.old)
        if not old_rows:
            raise SystemExit(f"no bench rows found in {args.old}")
        result = compare(new_rows, old_rows, rel_tol=args.rel_tol,
                         spread_factor=args.spread_factor,
                         spread_cap=args.spread_cap)
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
    else:
        print(result.format_text())
    if not result.ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
