"""Turnkey multi-chip strong-scaling rows.

The reference's headline artifact is *measured two-GPU scaling* of the
MultiGPU baselines (``MultiGPU/Diffusion3d_Baseline/Run.m:4-13`` — 5.87
GFLOPS on 2 GPUs over z-slabs; ``Burgers3d_Baseline/Run.m:4-14``). This
module is the standing equivalent: :func:`scaling_rows` measures the
same published global grids sharded over ``dz = 2..N`` z-slabs whenever
the live topology has more than one device, and returns nothing on one
chip — so the first session on real multi-chip hardware produces
scaling numbers with zero new code (``bench.py`` calls it on every run).

Strong scaling, deliberately: the reference holds the global grid fixed
and splits it over ranks (``main.c:84-101``), so per-chip MLUPS directly
exposes the halo-exchange tax the split-overlap schedule is designed to
hide. Each row reports the aggregate rate, the per-chip rate, and the
halo schedule actually engaged (``engaged_path``), and divides
``vs_baseline`` by the reference's own 2-GPU number — the ``dz=2`` row
is the apples-to-apples comparison, higher ``dz`` rows chart scaling
the reference never published.
"""

from __future__ import annotations

from typing import Sequence

from multigpu_advectiondiffusion_tpu.bench.matrix import BASELINES_MLUPS
from multigpu_advectiondiffusion_tpu.bench.timing import timed_run
from multigpu_advectiondiffusion_tpu.utils.metrics import mlups


def candidate_counts(n_devices: int, nz: int) -> list:
    """Slab counts to measure: powers of two up to the device count,
    plus the full count itself (even or odd), each restricted to
    divisors of the global z extent (the reference's own divisibility
    rule, ``main.c:88``)."""
    out = []
    d = 2
    while d <= n_devices:
        if nz % d == 0:
            out.append(d)
        d *= 2
    if n_devices >= 2 and n_devices not in out and nz % n_devices == 0:
        out.append(n_devices)
    return out


def _configs(on_tpu: bool):
    """The two published MultiGPU 3-D workloads (matrix.py's z-rounded
    grids), shrunk on CPU where the fused kernels run interpreted."""
    from multigpu_advectiondiffusion_tpu import (
        BurgersConfig,
        DiffusionConfig,
        Grid,
    )

    if on_tpu:
        dgrid = Grid.make(400, 200, 208, lengths=(10.0, 5.0, 5.2))
        bgrid = Grid.make(400, 400, 408, lengths=2.0)
        diters, biters = 606, 60
    else:
        dgrid = Grid.make(16, 16, 24, lengths=2.0)
        bgrid = Grid.make(16, 16, 24, lengths=2.0)
        diters, biters = 4, 4
    # impl="auto": the multichip rows dispatch through the measured
    # tuner — rung AND steps_per_exchange (the comm-avoiding k-step
    # cadence) come from the persisted decision cache, measured on a
    # miss when tuning is enabled (bench.py enables it)
    return {
        "diffusion3d": (
            DiffusionConfig(grid=dgrid, dtype="float32", impl="auto",
                            overlap="split"),
            diters,
            BASELINES_MLUPS["diffusion3d_multigpu"][0],
        ),
        "burgers3d": (
            BurgersConfig(grid=bgrid, dtype="float32", adaptive_dt=False,
                          impl="auto", overlap="split"),
            biters,
            BASELINES_MLUPS["burgers3d_multigpu"][0],
        ),
    }


def scaling_rows(
    devices: Sequence | None = None,
    on_tpu: bool | None = None,
    models: Sequence[str] = ("diffusion3d", "burgers3d"),
    reps: int = 5,
) -> list:
    """Measure z-slab strong scaling on the live topology.

    Returns a list of JSON-ready row dicts (empty on a single device):
    ``metric`` = ``{model}_scale_dz{d}_mlups``, ``value`` (aggregate
    MLUPS over ``d`` chips), ``per_chip``, ``devices``, ``spread``,
    ``outliers``, ``raw_spread``, ``engaged`` (stepper + halo schedule
    in effect), and ``vs_baseline`` against the reference's published
    2-GPU rate for the same workload.
    """
    import jax

    from multigpu_advectiondiffusion_tpu.models import registry
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )

    devices = list(devices if devices is not None else jax.devices())
    if on_tpu is None:
        on_tpu = devices[0].platform != "cpu"
    rows = []
    if len(devices) < 2:
        return rows
    configs = _configs(on_tpu)
    for model in models:
        cfg, iters, baseline = configs[model]
        # run names resolve to solver families through the registry
        solver_cls = registry.solver_for_run_name(model)
        nz = cfg.grid.shape[0]
        for d in candidate_counts(len(devices), nz):
            mesh = make_mesh({"dz": d}, devices=devices[:d])
            solver = solver_cls(
                cfg, mesh=mesh, decomp=Decomposition.slab("dz")
            )
            engaged = solver.engaged_path("iters")
            timing = timed_run(solver, solver.initial_state(), iters,
                               reps=reps)
            stages = STAGES.get(cfg.integrator, 3)
            rate = mlups(cfg.grid.num_cells, iters, stages,
                         timing.median_seconds)
            rows.append(
                {
                    "metric": f"{model}_scale_dz{d}_mlups",
                    "value": round(rate, 2),
                    "unit": "MLUPS",
                    "vs_baseline": round(rate / baseline, 3),
                    "per_chip": round(rate / d, 2),
                    "devices": d,
                    "spread": round(timing.spread, 4),
                    "outliers": timing.outliers,
                    "raw_spread": round(timing.raw_spread, 4),
                    "engaged": (
                        engaged["stepper"]
                        + (
                            f"+{engaged['overlap']}"
                            if engaged.get("overlap")
                            else ""
                        )
                    ),
                    # the comm-avoiding cadence + where the decision
                    # came from (tuner cache/measurement/heuristic)
                    "steps_per_exchange": engaged.get(
                        "steps_per_exchange", 1
                    ),
                    # halo transport actually engaged (ISSUE 13)
                    "exchange": engaged.get("exchange", "collective"),
                    "tuned": engaged.get("tuned"),
                }
            )
    return rows


def exchange_head_to_head_rows(
    devices: Sequence | None = None,
    on_tpu: bool | None = None,
    models: Sequence[str] = ("diffusion3d", "burgers3d"),
    reps: int = 5,
) -> list:
    """The dma-vs-split-overlap halo-transport head-to-head
    (ISSUE 13): the same workload, same 2-way z-slab mesh (the
    reference's own 2-GPU artifact shape), pinned to the slab rung —
    once with the split-overlap XLA collective exchange, once with the
    in-kernel remote-DMA exchange. Metric pair
    ``{model}_dz2_halo_{split|dma}_mlups``.

    Engagement guard: the dma row must have ACTUALLY run the in-kernel
    transport — a silent degrade back to the collective exchange gets
    an ``engagement_error`` (bench.py fails the run on it). A loud
    decline (a backend with neither the Mosaic TPU target nor the CPU
    interpret simulator) is recorded as a ``declined`` row instead:
    unservable is a fact, not a regression.
    """
    import dataclasses

    import jax

    from multigpu_advectiondiffusion_tpu.models import registry
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )

    devices = list(devices if devices is not None else jax.devices())
    if on_tpu is None:
        on_tpu = devices[0].platform != "cpu"
    rows = []
    if len(devices) < 2:
        return rows
    configs = _configs(on_tpu)
    for model in models:
        cfg, iters, baseline = configs[model]
        if cfg.grid.shape[0] % 2:
            continue
        solver_cls = registry.solver_for_run_name(model)
        pair = (
            ("split", dataclasses.replace(
                cfg, impl="pallas_slab", overlap="split",
                exchange="collective",
            )),
            ("dma", dataclasses.replace(
                cfg, impl="pallas_slab", overlap="padded",
                exchange="dma",
            )),
        )
        for name, pcfg in pair:
            metric = f"{model}_dz2_halo_{name}_mlups"
            try:
                solver = solver_cls(
                    pcfg,
                    mesh=make_mesh({"dz": 2}, devices=devices[:2]),
                    decomp=Decomposition.slab("dz"),
                )
                engaged = solver.engaged_path("iters")
                timing = timed_run(
                    solver, solver.initial_state(), iters, reps=reps
                )
            except ValueError as exc:
                rows.append({
                    "metric": metric,
                    "declined": f"{exc}"[:200],
                })
                continue
            stages = STAGES.get(pcfg.integrator, 3)
            rate = mlups(pcfg.grid.num_cells, iters, stages,
                         timing.median_seconds)
            row = {
                "metric": metric,
                "value": round(rate, 2),
                "unit": "MLUPS",
                "vs_baseline": round(rate / baseline, 3),
                "per_chip": round(rate / 2, 2),
                "devices": 2,
                "spread": round(timing.spread, 4),
                "outliers": timing.outliers,
                "raw_spread": round(timing.raw_spread, 4),
                "engaged": (
                    engaged["stepper"]
                    + (f"+{engaged['overlap']}"
                       if engaged.get("overlap") else "")
                ),
                "steps_per_exchange": engaged.get(
                    "steps_per_exchange", 1
                ),
                "exchange": engaged.get("exchange", "collective"),
            }
            if name == "dma" and (
                engaged.get("exchange") != "dma"
                or engaged.get("degraded")
            ):
                row["engagement_error"] = {
                    "expected_exchange": "dma",
                    "engaged_exchange": engaged.get("exchange"),
                    "degraded": engaged.get("degraded"),
                }
            rows.append(row)
    return rows
