"""Advection–diffusion–reaction solver — the repo's title workload.

``u_t + div(a u) = div(K(x) grad u) + R(u)`` with

* constant advection velocity ``a`` (one value per physical axis),
  discretized either by the monotone first-order **upwind** flux (the
  fused Pallas rung's scheme, matched term-for-term on the generic
  rung) or by **WENO5** linear-advection via the existing
  Lax–Friedrichs flux machinery (``ops/weno.flux_divergence`` with
  ``ops/flux.linear`` — generic rung only);
* spatially varying diffusivity ``K(x) = K0 (1 + eps * prod_i
  cos(pi x̂_i))`` applied in the non-conservative form ``K(x) lap(u)``
  over the existing O4/O2 Laplacian taps (``eps = kappa_variation``,
  ``|eps| < 1`` keeps K positive; ``x̂ = g/(n-1) - 1/2`` in global cell
  indices — :func:`kappa_profile` is the ONE definition the fused
  kernel's in-kernel evaluation mirrors);
* linear-decay reaction ``R(u) = -lambda u`` (``reaction_rate``).

The family is a *plugin*: it implements the registration contract
(``stencil_spec`` / ``diagnostics_spec`` / ``ensemble_operands`` /
``cfl_rule``) and registers a :class:`~.registry.ModelSpec` at module
bottom — every generic subsystem (sharded dispatch, sentinel/rollback,
ensemble vmap, measured tuner, science gates, static verifiers, CLI,
bench) serves it with zero family-specific wiring. Reference-parity
walls follow the diffusion family's discipline (RHS zeroed on the
global boundary band, Dirichlet faces re-clamped) with *global*
indices, so sharded runs reproduce single-device runs to roundoff
(the advective fusion re-associates across program shapes, so the
match is ulp-level rather than bit-exact; tests pin the bound).

Analytic solution (constant coefficients, ``eps = 0``): the advecting,
decaying heat kernel ``u(x, t) = (t0/t)^{d/2} exp(-|x - a (t-t0)|^2 /
(4 K t)) exp(-lambda (t-t0))`` — translation by ``a t``, diffusive
spreading, exponential decay; the accuracy tests (tests/test_adr.py)
hold both rungs to it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.base import (
    LocalPhysics,
    SolverBase,
    StepContext,
)
from multigpu_advectiondiffusion_tpu.models.registry import (
    ModelSpec,
    register_model,
    resolve_bc,
)
from multigpu_advectiondiffusion_tpu.models.state import SolverState
from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
from multigpu_advectiondiffusion_tpu.ops.laplacian import (
    D2_STENCILS,
    laplacian,
)
from multigpu_advectiondiffusion_tpu.ops.stencils import (
    boundary_band_mask,
    face_mask,
    shifted,
)
from multigpu_advectiondiffusion_tpu.ops.weno import HALO, flux_divergence
from multigpu_advectiondiffusion_tpu.timestepping.cfl import (
    advection_diffusion_dt,
)
from multigpu_advectiondiffusion_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class ADRConfig:
    grid: Grid
    diffusivity: float = 1.0  # K0, the base (mean) diffusivity
    # advection velocity: a scalar (broadcast to every axis) or one
    # value per PHYSICAL axis in x [y [z]] order (the --n convention)
    velocity: object = 0.5
    # spatial variation amplitude eps of K(x) = K0 (1 + eps prod cos);
    # |eps| < 1 keeps the coefficient positive; 0 = constant K (the
    # analytic-solution case)
    kappa_variation: float = 0.0
    reaction_rate: float = 0.0  # lambda >= 0; R(u) = -lambda u
    # advective discretization: "upwind" (monotone first-order; the
    # fused rung's scheme) or "weno5" (LF-split linear advection via
    # the existing WENO machinery; generic rung only)
    advect: str = "upwind"
    order: int = 4  # diffusive Laplacian order (2 | 4)
    cfl: float = 0.4  # advective share of the combined dt bound
    safety: float = 0.8  # diffusive/reaction share of the dt bound
    integrator: str = "ssp_rk3"
    dtype: str = "float32"
    ic: object = "heat_kernel"
    ic_params: Tuple = ()
    bc: object = "dirichlet"
    t0: float = 0.1  # initial time of the analytic kernel
    reference_parity: bool = True
    boundary_band: int = 2  # frozen global band (diffusion discipline)
    impl: str = "xla"
    overlap: str = "padded"
    # accepted for config uniformity (the auto-tuner's decision replace
    # writes them); ADR serves the per-step collective cadence only —
    # the k-step/dma schedules live on the slab rung this family does
    # not ship
    steps_per_exchange: int = 1
    exchange: str = "collective"
    # storage precision rung (see DiffusionConfig): "native" or "bf16"
    # (f32 compute state stored/exchanged as bfloat16; ADR engages it
    # on the 3-D per-stage fused rung and the generic XLA path)
    precision: str = "native"

    def __post_init__(self):
        from multigpu_advectiondiffusion_tpu.ops import IMPLS

        if self.precision not in ("native", "bf16"):
            raise ValueError(
                f"unknown precision {self.precision!r}; "
                "'native' or 'bf16'"
            )
        if self.impl not in IMPLS:
            raise ValueError(
                f"unknown impl {self.impl!r}; ladder rungs: {IMPLS}"
            )
        if self.overlap not in ("padded", "split"):
            raise ValueError(f"unknown overlap {self.overlap!r}")
        if self.advect not in ("upwind", "weno5"):
            raise ValueError(
                f"unknown advect {self.advect!r}; 'upwind' or 'weno5'"
            )
        if self.order not in D2_STENCILS:
            raise ValueError(
                f"unknown diffusive order {self.order}; use "
                f"{sorted(D2_STENCILS)}"
            )
        if not -1.0 < float(self.kappa_variation) < 1.0:
            raise ValueError(
                "kappa_variation must satisfy |eps| < 1 (K(x) must "
                f"stay positive), got {self.kappa_variation!r}"
            )
        if float(self.reaction_rate) < 0.0:
            raise ValueError(
                "reaction_rate is a linear DECAY rate (lambda >= 0); "
                f"got {self.reaction_rate!r}"
            )
        if int(self.steps_per_exchange or 1) != 1:
            raise ValueError(
                "ADR serves the per-step exchange cadence only "
                "(steps_per_exchange=1): the k-step deep-halo schedule "
                "rides the slab rung, which this family does not ship"
            )
        if self.exchange != "collective":
            raise ValueError(
                "ADR serves the XLA collective halo exchange only: "
                "the in-kernel remote-DMA transport rides the slab "
                "rung, which this family does not ship"
            )
        if not isinstance(self.velocity, (int, float)):
            vel = tuple(self.velocity)
            if len(vel) != self.grid.ndim:
                raise ValueError(
                    f"velocity has {len(vel)} components for a "
                    f"{self.grid.ndim}-D grid (x [y [z]] order, or one "
                    "scalar broadcast to every axis)"
                )


def kappa_profile(shape_global, local_shape, offsets, eps: float, dtype):
    """The dimensionless K-variation profile ``1 + eps prod_i
    cos(pi x̂_i)`` on a (possibly shard-local) window, ``x̂ = g/(n-1) -
    1/2`` in GLOBAL cell indices — the single source the fused kernel's
    in-kernel evaluation (``ops/pallas/fused_adr._stage_kernel``)
    mirrors; tests hold the two together. ``None`` when ``eps == 0``
    (constant coefficient: scalar multiply, no field)."""
    if not eps:
        return None
    prof = None
    ndim = len(shape_global)
    for ax in range(ndim):
        g = jnp.arange(local_shape[ax], dtype=dtype) + offsets[ax]
        c = jnp.cos(math.pi * (g / (shape_global[ax] - 1) - 0.5))
        shp = [1] * ndim
        shp[ax] = -1
        c = jnp.reshape(c, shp)
        prof = c if prof is None else prof * c
    return (1.0 + eps * prof).astype(dtype)


class ADRSolver(SolverBase):
    cfg: ADRConfig

    def __init__(self, cfg: ADRConfig, mesh=None, decomp=None):
        super().__init__(cfg, mesh=mesh, decomp=decomp)
        cfg = self.cfg  # impl="auto" may have replaced it
        kmax = float(cfg.diffusivity) * (
            1.0 + abs(float(cfg.kappa_variation))
        )
        self.dt = float(
            advection_diffusion_dt(
                self._velocity_zyx(), kmax, cfg.grid.spacing,
                cfl=cfg.cfl, safety=cfg.safety,
                reaction=float(cfg.reaction_rate),
            )
        )

    # ------------------------------------------------------------------ #
    # Registration contract (models/registry.REQUIRED_SOLVER_CONTRACT)
    # ------------------------------------------------------------------ #
    def stencil_spec(self) -> dict:
        """Family stencil metadata: the per-stage radius is the MAX of
        the advective and diffusive tap reaches (upwind 1 / WENO5 3 vs
        O2 1 / O4 2) — what the tuner's fused ghost depth and the
        static halo verifier's ADR combos derive from."""
        cfg = self.cfg
        adv_r = 1 if cfg.advect == "upwind" else HALO[5]
        diff_r = D2_STENCILS[cfg.order][1]
        return {
            "family": "adr",
            "advective_radius": adv_r,
            "diffusive_radius": diff_r,
            "stage_radius": max(adv_r, diff_r),
        }

    def diagnostics_spec(self) -> dict:
        """Reaction-free ADR transports and spreads but creates no new
        extremum (monotone upwind flux; K(x) > 0), and nonnegative data
        stays nonnegative — register the max-principle AND positivity
        tolerance rules so a broken coefficient/flux surfaces as a
        ``phys:violation`` before the norm sentinel trips. With decay
        (lambda > 0) extrema shrink, so the rules stay valid; the
        analytic amplitude-decay meta is registered only for the
        constant-coefficient reaction-free heat-kernel workload whose
        log-log slope is exactly ``-d/2``."""
        from multigpu_advectiondiffusion_tpu.diagnostics import physics

        cfg = self.cfg
        spec = {"rules": [], "meta": {}}
        spec["rules"].append(physics.max_principle_rule())
        spec["rules"].append(physics.positivity_rule())
        if (
            cfg.ic == "heat_kernel"
            and not cfg.kappa_variation
            and not cfg.reaction_rate
        ):
            spec["meta"]["decay_rate_analytic"] = -self.grid.ndim / 2.0
        return spec

    def ensemble_operands(self) -> dict:
        """Member-varying scalars of the batched ensemble engine: the
        base diffusivity K0 and the decay rate lambda (both move the
        stability dt, recomputed in-trace per member)."""
        return {
            "diffusivity": float(self.cfg.diffusivity),
            "reaction_rate": float(self.cfg.reaction_rate),
        }

    def cfl_rule(self) -> dict:
        """Queryable time-step contract: the combined harmonic
        advective/diffusive/reaction bound
        (``timestepping.cfl.advection_diffusion_dt``)."""
        cfg = self.cfg
        return {
            "kind": "advection-diffusion-reaction",
            "dt": float(self.dt),
            "cfl": float(cfg.cfl),
            "safety": float(cfg.safety),
            "terms": {
                "advective": any(self._velocity_zyx()),
                "diffusive": True,
                "reaction": bool(cfg.reaction_rate),
            },
        }

    # ------------------------------------------------------------------ #
    # Config plumbing
    # ------------------------------------------------------------------ #
    def _velocity_zyx(self) -> Tuple[float, ...]:
        """Velocity per ARRAY axis (z, y, x order): config scalars
        broadcast, tuples arrive in physical x [y [z]] order and flip."""
        v = self.cfg.velocity
        if isinstance(v, (int, float)):
            return (float(v),) * self.grid.ndim
        return tuple(float(c) for c in reversed(tuple(v)))

    def _op_impl(self) -> str:
        """Per-op kernel strategy: Pallas flavors route the Laplacian
        through the per-axis kernels for f32
        (``SolverBase._pallas_f32_gate``); the advective sweep always
        runs XLA (the per-axis WENO kernels are Burgers-calibrated)."""
        from multigpu_advectiondiffusion_tpu.ops import op_impl as _norm

        self._op_fallback = None
        return self._pallas_f32_gate(_norm(self.cfg.impl))

    def ic_spec(self):
        """Thread t0/K0 into the heat-kernel IC so the initial state
        matches :meth:`exact_solution` at ``t = t0`` (the diffusion
        family's coupling, applied to the advecting kernel — at t0 the
        translation is zero, so the centered kernel is exact)."""
        name = self.cfg.ic
        if name == "heat_kernel":
            return name, {
                "t0": self.cfg.t0,
                "diffusivity": self.cfg.diffusivity,
            }
        return name, {}

    # ------------------------------------------------------------------ #
    # Shard-local physics
    # ------------------------------------------------------------------ #
    def build_local(self, ctx: StepContext, overrides=None) -> LocalPhysics:
        cfg = self.cfg
        grid = cfg.grid
        bcs = self.bcs
        spacing = grid.spacing
        vel = self._velocity_zyx()
        eps = float(cfg.kappa_variation)
        # ensemble mode: traced per-member K0/lambda enter as operands
        # (never closure constants); the stability dt re-derives from
        # them in-trace
        K0 = cfg.diffusivity
        lam = cfg.reaction_rate
        has_react = bool(cfg.reaction_rate)
        dt = self.dt
        if overrides and (
            "diffusivity" in overrides or "reaction_rate" in overrides
        ):
            if "diffusivity" in overrides:
                K0 = overrides["diffusivity"]
            if "reaction_rate" in overrides:
                lam = overrides["reaction_rate"]
                has_react = True
            dt = advection_diffusion_dt(
                vel, K0 * (1.0 + abs(eps)), spacing,
                cfl=cfg.cfl, safety=cfg.safety, reaction=lam,
            )

        ghost_fn = ctx.ghost_fn if cfg.overlap == "split" else None
        impl = self._op_impl()
        # the K-variation profile on this shard's window (global
        # indices via ctx.offsets; None = constant coefficient)
        prof = kappa_profile(
            ctx.global_shape, ctx.local_shape, ctx.offsets, eps,
            self.dtype,
        )

        def diffusive(u):
            lap = laplacian(
                u, spacing, diffusivity=1.0, order=cfg.order,
                padder=ctx.padder, impl=impl, ghost_fn=ghost_fn,
            )
            return K0 * lap if prof is None else (K0 * prof) * lap

        if cfg.advect == "weno5":
            fluxes = [
                flux_lib.linear(c=a) if a else None for a in vel
            ]

            def advective(u):
                acc = None
                for axis in range(u.ndim):
                    if fluxes[axis] is None:
                        continue
                    div = flux_divergence(
                        u, axis, spacing[axis], fluxes[axis],
                        order=5, variant="js",
                        padder=ctx.padder, ghost_fn=ghost_fn,
                    )
                    acc = div if acc is None else acc + div
                return acc

        else:

            def advective(u):
                acc = None
                for axis, a in enumerate(vel):
                    if a == 0.0:
                        continue
                    up = ctx.padder(u, axis, 1)
                    n = u.shape[axis]
                    lo = shifted(up, axis, 0, n)   # u_{i-1}
                    mid = shifted(up, axis, 1, n)  # u_i
                    hi = shifted(up, axis, 2, n)   # u_{i+1}
                    cp = max(a, 0.0) / spacing[axis]
                    cm = min(a, 0.0) / spacing[axis]
                    term = cp * (mid - lo) + cm * (hi - mid)
                    acc = term if acc is None else acc + term
                return acc

        walled_axes = [
            a for a, b in enumerate(bcs) if b.kind != "periodic"
        ]
        band = boundary_band_mask(
            ctx.local_shape, cfg.boundary_band, ctx.global_shape,
            ctx.offsets, axes=walled_axes,
        ) if cfg.reference_parity and walled_axes else None

        def rhs(u):
            out = diffusive(u)
            adv = advective(u)
            if adv is not None:
                out = out - adv
            if has_react:
                out = out - lam * u
            if band is not None:
                out = jnp.where(band, out, jnp.zeros_like(out))
            return out

        post = None
        if cfg.reference_parity and walled_axes:
            dir_axes = [
                a for a in walled_axes if bcs[a].kind == "dirichlet"
            ]
            clamps = [
                (
                    face_mask(ctx.local_shape, [a], ctx.global_shape,
                              ctx.offsets),
                    bcs[a].value,
                )
                for a in dir_axes
            ]
            if clamps:

                def post(u):
                    # Dirichlet walls re-imposed each step (the
                    # diffusion family's heat3d.m:65-67 discipline)
                    for faces, value in clamps:
                        u = jnp.where(
                            faces, jnp.asarray(value, u.dtype), u
                        )
                    return u

        return LocalPhysics(rhs=rhs, static_dt=dt, post=post)

    # ------------------------------------------------------------------ #
    # Fused per-stage Pallas fast path
    # ------------------------------------------------------------------ #
    def _fused_stepper(self, mode: str = "iters"):
        """The fused ADR SSP-RK3 per-stage stepper when eligible, else
        ``None`` (generic path). Eligibility mirrors the kernel's baked
        assumptions: 3-D cartesian, upwind advection, O4 diffusion,
        SSP-RK3, f32, uniform frozen Dirichlet walls. Under a mesh the
        stages run shard-local with the per-stage ppermute ghost
        refresh; ADR ships no whole-step/slab/split-overlap variants —
        those pins decline loudly here and the generic rung serves
        them."""
        cfg = self.cfg
        from multigpu_advectiondiffusion_tpu.ops import is_fused_impl
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R

        self._fused_fallback = None
        if not is_fused_impl(cfg.impl):
            return self._decline(
                f"impl={cfg.impl!r} does not request fusion"
            )
        if cfg.impl in ("pallas_step", "pallas_slab"):
            return self._decline(
                "ADR ships a per-stage fused rung only (no whole-step/"
                "slab variant)"
            )
        if self.grid.ndim != 3:
            return self._decline("fused ADR kernel is 3-D only")
        if cfg.advect != "upwind":
            return self._decline(
                "fused ADR bakes the monotone upwind advective flux; "
                "WENO5 advection rides the generic rung"
            )
        if cfg.order != 4:
            return self._decline("fused ADR bakes the O4 diffusive taps")
        if cfg.integrator != "ssp_rk3":
            return self._decline("fused kernels bake in SSP-RK3")
        if self.dtype != jnp.float32:
            return self._decline("fused ADR kernel is float32-only")
        if not cfg.reference_parity or cfg.boundary_band < 1:
            return self._decline(
                "fused walls need reference_parity with "
                "boundary_band >= 1"
            )
        bcs = self.bcs
        if not all(b.kind == "dirichlet" for b in bcs) or not all(
            b.value == bcs[0].value for b in bcs
        ):
            return self._decline(
                "fused walls need uniform Dirichlet BCs on every axis"
            )
        lshape = (
            self.grid.shape
            if self.mesh is None
            else self.decomp.local_shape(self.mesh, self.grid.shape)
        )
        if self.mesh is not None:
            if self._split_overlap_requested():
                return self._decline(
                    "fused ADR runs the serialized per-stage ghost "
                    "refresh; overlap='split' rides the generic rung"
                )
            if any(lshape[ax] < R for ax, _ in self.decomp.axes):
                return self._decline(
                    f"a sharded axis is thinner than the O4 halo ({R})"
                )
        if "fused" not in self._cache:
            from multigpu_advectiondiffusion_tpu.ops.pallas.fused_adr import (  # noqa: E501
                FusedADRStepper,
            )

            # precision='bf16' (ISSUE 16): kernel/HBM buffers at bf16,
            # taps/RK in f32 via the kernel's compute_dtype upcast,
            # f32 facing state restored at extract
            kernel_dtype = (
                jnp.dtype(jnp.bfloat16)
                if self._precision_mode() == "bf16"
                else self.dtype
            )
            kwargs = {}
            if self.mesh is not None:
                kwargs["global_shape"] = self.grid.shape
            if jnp.dtype(kernel_dtype) != jnp.dtype(self.dtype):
                kwargs["storage_dtype"] = self.dtype
            self._cache["fused"] = FusedADRStepper(
                lshape,
                kernel_dtype,
                self.grid.spacing,
                cfg.diffusivity,
                self._velocity_zyx(),
                cfg.reaction_rate,
                self.dt,
                cfg.boundary_band,
                bcs[0].value,
                kappa_variation=cfg.kappa_variation,
                **kwargs,
            )
        return self._cache["fused"]

    # ------------------------------------------------------------------ #
    # Analytic solution (constant coefficients)
    # ------------------------------------------------------------------ #
    def exact_solution(self, t: float) -> jnp.ndarray:
        """The advecting, decaying heat kernel (module docstring).
        Defined only for constant coefficients (``kappa_variation ==
        0``) — the variable-K workload is validated by the max-
        principle/positivity diagnostics and rung cross-checks
        instead."""
        cfg = self.cfg
        if cfg.kappa_variation:
            raise ValueError(
                "no closed-form solution with spatially varying K"
            )
        d = cfg.diffusivity
        vel = self._velocity_zyx()
        tau = t - cfg.t0
        ndim = cfg.grid.ndim
        r2 = None
        for ax in range(ndim):
            c = cfg.grid.coords(ax, self.dtype) - vel[ax] * tau
            shp = [1] * ndim
            shp[ax] = -1
            term = jnp.reshape(c * c, shp)
            r2 = term if r2 is None else r2 + term
        amp = (cfg.t0 / t) ** (ndim / 2.0) * math.exp(
            -float(cfg.reaction_rate) * tau
        )
        return (amp * jnp.exp(-r2 / (4.0 * d * t))).astype(self.dtype)

    def error_norms(self, state: SolverState, t: float | None = None):
        t_val = float(state.t) if t is None else t
        return metrics.error_norms(
            state.u, self.exact_solution(t_val), self.cfg.grid.spacing
        )


# --------------------------------------------------------------------- #
# Registration: the family as a declarative plugin descriptor
# --------------------------------------------------------------------- #
def _cli_configure(p, ndim):
    p.add_argument("--K", type=float, default=1.0,
                   help="base diffusivity K0 of K(x)")
    p.add_argument("--velocity", type=float, nargs="+", default=[0.5],
                   help="advection velocity: one value (broadcast) or "
                        "one per physical axis (x [y [z]])")
    p.add_argument("--kappa-variation", type=float, default=0.0,
                   metavar="EPS",
                   help="spatial variation amplitude of K(x) = K0 (1 + "
                        "EPS prod cos(pi x̂)); |EPS| < 1 (0 = constant)")
    p.add_argument("--reaction", type=float, default=0.0,
                   metavar="LAMBDA",
                   help="linear decay rate; R(u) = -LAMBDA u")
    p.add_argument("--advect", default="upwind",
                   choices=["upwind", "weno5"],
                   help="advective flux: monotone upwind (fused-rung "
                        "eligible) or WENO5 linear advection (generic)")
    p.add_argument("--order", type=int, default=4, choices=[2, 4],
                   help="diffusive Laplacian order")
    p.add_argument("--cfl", type=float, default=0.4)
    p.add_argument("--t0", type=float, default=0.1)


def _cli_build(args, grid, ndim):
    vel = list(args.velocity)
    if len(vel) not in (1, ndim):
        raise ValueError(
            f"--velocity wants 1 or {ndim} values for a {ndim}-D grid, "
            f"got {len(vel)}"
        )
    velocity = vel[0] if len(vel) == 1 else tuple(vel)
    return ADRConfig(
        grid=grid,
        diffusivity=args.K,
        velocity=velocity,
        kappa_variation=args.kappa_variation,
        reaction_rate=args.reaction,
        advect=args.advect,
        order=args.order,
        cfl=args.cfl,
        integrator=args.integrator,
        dtype=args.dtype,
        ic=args.ic or "heat_kernel",
        bc=resolve_bc(args, "dirichlet"),
        t0=args.t0,
        impl=args.impl,
        overlap=args.overlap,
        steps_per_exchange=args.steps_per_exchange,
        exchange=args.exchange,
        precision=getattr(args, "precision", "native"),
    )


def _stage_radius(cfg) -> int:
    """Fused per-stage stencil radius (the tuner's ghost depth is 3h):
    the fused ADR kernel shares the Pallas O4 layout (R = 2)."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R

    return R


def _key_extras(cfg):
    return [
        f"advect={cfg.advect}",
        f"order={cfg.order}",
        f"kvar={bool(cfg.kappa_variation)}",
        f"react={bool(cfg.reaction_rate)}",
    ]


def _cost_kwargs(cfg):
    return {
        "order": cfg.order,
        "advect": cfg.advect,
        "reaction": bool(cfg.reaction_rate),
        "variable_k": bool(cfg.kappa_variation),
    }


def _bench_build(grid, dtype, impl, case):
    # the bench rows exercise the full family: variable K, advection
    # on every axis, decay — the title workload, not a diffusion alias
    return ADRConfig(
        grid=grid, dtype=dtype, impl=impl, velocity=0.5,
        kappa_variation=0.2, reaction_rate=0.25, ic="heat_kernel",
    )


register_model(ModelSpec(
    name="adr",
    config_cls=ADRConfig,
    solver_cls=ADRSolver,
    description="advection–diffusion–reaction with spatially varying "
                "K(x) — the title workload",
    check_error=True,
    sweep_aliases={"K": "diffusivity", "lambda": "reaction_rate"},
    cli_configure=_cli_configure,
    cli_build=_cli_build,
    stage_radius=_stage_radius,
    key_extras=_key_extras,
    cost_kwargs=_cost_kwargs,
    bench_build=_bench_build,
))
