"""Batched ensemble execution engine (ROADMAP item 1, ISSUE 9).

The reference runs one binary per configuration and archives each
timing by hand (``Run.m`` comments); a parameter sweep is N serialized
processes, each paying compile, dispatch and HBM streaming alone. Here
the sweep is ONE batched launch: :class:`EnsembleSolver` builds a
``(B, *grid)`` initial state from per-member overrides (initial
conditions and/or the solver's member-varying scalars — diffusivity K,
CFL, Burgers Riemann states via ICs) and advances all B members per
dispatch through ``SolverBase.run_ensemble`` / ``advance_to_ensemble``:

* uniform-physics ensembles (IC sweeps) ``vmap`` the fused per-stage
  stepper — bit-exact against the looped single runs
  (tests/test_ensemble.py);
* scalar sweeps ride the generic stepper with the member scalars as
  batched operands (never closure constants);
* uniform-physics ensembles additionally fold B into the slab
  whole-run rung's Pallas grid (``fused_slab_run.run_batched``: a
  leading member grid axis — one program advances the whole batch);
* a device mesh composes through a ``members`` axis (the TPU-pod
  batched-simulation shape of arXiv 2108.11076): members-sharded-only
  meshes (``make_mesh({'members': P})``) run one batched program per
  device, members x z-slab meshes (``{'members': P, 'dz': Q}``) vmap
  the shard-local stepper with the existing halo exchange running per
  spatial subgroup — one dispatch serves B x P users. Remaining
  declines (spatial-only meshes, k > 1 deep-halo cadence, slab pins
  over spatial subgroups) raise loudly with their reason.

Divergence stays member-attributed: the sentinel reduces per member
(``resilience/sentinel.make_ensemble_probe``), so one blown-up member
raises :class:`~..resilience.errors.EnsembleMemberDivergedError`
naming its index while the others' results remain valid.

Pairs with the persistent AOT executable cache
(``tuning/aot_cache.py``): a repeat of the same batched request loads
the compiled executable from disk instead of recompiling.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from multigpu_advectiondiffusion_tpu.models.state import EnsembleState
from multigpu_advectiondiffusion_tpu.resilience.errors import (
    EnsembleMemberDivergedError,
)

# member-override keys that rebuild the member's INITIAL STATE (via a
# per-member config) but do not enter the batched step as operands
_IC_KEYS = ("ic", "ic_params", "t0")


def parse_sweep_spec(spec: str, members: int) -> tuple:
    """``'NAME=a:b'`` (linear sweep) or ``'NAME=v1,v2,...'`` (explicit,
    one value per member) -> ``(name, [B floats])`` — the CLI
    ``--sweep`` grammar."""
    name, sep, body = spec.partition("=")
    name = name.strip()
    if not sep or not name or not body:
        raise ValueError(
            f"--sweep wants NAME=a:b or NAME=v1,v2,...; got {spec!r}"
        )
    if ":" in body:
        lo, _, hi = body.partition(":")
        values = np.linspace(float(lo), float(hi), members)
        return name, [float(v) for v in values]
    values = [float(v) for v in body.split(",")]
    if len(values) != members:
        raise ValueError(
            f"--sweep {name}: {len(values)} values for {members} members"
        )
    return name, values


class EnsembleSolver:
    """Front end over one template solver: build the batched state,
    dispatch the batched programs, thread per-member summaries out.

    ``members`` is either an int B (B identical members — the pure
    amortization case) or a sequence of per-member override dicts whose
    keys are the solver's :meth:`~..models.base.SolverBase.
    ensemble_operands` names (member-varying scalars) and/or the IC
    keys ``ic``/``ic_params``/``t0`` (member-varying initial states,
    e.g. Burgers Riemann ``left``/``right`` sweeps via
    ``ic_params``)."""

    def __init__(self, solver_cls, cfg, members, mesh=None, decomp=None):
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            MEMBER_AXIS,
            axis_extent,
            member_extent,
        )

        spatial_decomp = None
        if mesh is not None:
            sizes = dict(mesh.shape)
            if MEMBER_AXIS not in sizes:
                raise ValueError(
                    "an ensemble mesh composes through a 'members' "
                    "axis (members, not shards, are the batched "
                    "parallel dimension) — e.g. make_mesh({'members': "
                    "8}) or make_mesh({'members': 4, 'dz': 2}); a "
                    "purely spatial mesh shards one member's grid"
                )
            spatial_decomp = decomp
            if spatial_decomp is not None and MEMBER_AXIS in (
                spatial_decomp.mesh_axis_names()
            ):
                raise ValueError(
                    "the 'members' mesh axis may not shard a grid "
                    "axis — member sharding is halo-free by "
                    "construction (it partitions the batched state's "
                    "leading member axis only)"
                )
        elif decomp is not None:
            raise ValueError("a decomposition needs a mesh")
        if isinstance(members, int):
            if members < 1:
                raise ValueError("an ensemble needs at least one member")
            members = [{} for _ in range(members)]
        self._overrides = [dict(m) for m in members]
        self.members = len(self._overrides)
        mext = member_extent(mesh)
        if self.members % mext:
            raise ValueError(
                f"{self.members} members do not tile the {mext}-way "
                "member axis — B must be a multiple of the member-"
                "sharding extent"
            )
        if cfg.impl == "auto":
            # measured dispatch, keyed BY the ensemble dimension AND
            # the mesh layout: the tuner MEASURES the batched candidate
            # space at the actual B (generic vmap / fused-stage vmap /
            # B-folded slab, under this mesh) instead of keying a
            # single-run proxy by ens=B — tuning/autotuner.autotune
            from multigpu_advectiondiffusion_tpu import tuning

            decision = tuning.resolve(
                solver_cls, cfg, mesh, spatial_decomp,
                ensemble=self.members,
            )
            self._tuned = decision
            cfg = dataclasses.replace(cfg, impl=decision["impl"])
        else:
            self._tuned = None
        self.solver_cls = solver_cls
        self.cfg = cfg
        self.mesh = mesh
        self._spatial_decomp = spatial_decomp
        # the template every member shares: spatially sharded only when
        # a spatial subgroup actually decomposes the grid (extent > 1) —
        # its shard-local program then runs per member under the vmap
        spatial = spatial_decomp is not None and any(
            axis_extent(dict(mesh.shape), nm) > 1
            for _, nm in spatial_decomp.axes
        )
        self.solver = solver_cls(
            cfg,
            mesh=mesh if spatial else None,
            decomp=spatial_decomp if spatial else None,
        )
        if mesh is not None:
            self.solver.arm_ensemble_mesh(
                mesh, spatial_decomp if spatial else None
            )
        supported = set(self.solver.ensemble_operands())
        for i, ov in enumerate(self._overrides):
            unknown = sorted(set(ov) - supported - set(_IC_KEYS))
            if unknown:
                raise ValueError(
                    f"member {i}: override(s) {unknown} are neither "
                    f"member-varying operands ({sorted(supported)}) nor "
                    f"IC keys {list(_IC_KEYS)} — structure-changing "
                    "knobs (impl, weno_order, grid, ...) cannot vary "
                    "inside one batched executable"
                )
        # construction-time loud gate (mesh/slab-pin/k>1/operand names)
        self.solver._ensemble_gate(
            tuple(k for ov in self._overrides for k in ov
                  if k in supported)
        )
        self._probe = None
        self._probe_parts = None
        self._baseline = None

    # ------------------------------------------------------------------ #
    # State + operands
    # ------------------------------------------------------------------ #
    def member_cfg(self, i: int):
        """Member ``i``'s effective config (template + its overrides) —
        used for per-member initial states and summaries; execution
        itself stays on the ONE batched program."""
        ov = {
            k: v for k, v in self._overrides[i].items()
            if k in {f.name for f in dataclasses.fields(self.cfg)}
        }
        if "ic_params" in ov and not isinstance(ov["ic_params"], tuple):
            ov["ic_params"] = tuple(
                (k, v) for k, v in dict(ov["ic_params"]).items()
            )
        return dataclasses.replace(self.cfg, **ov) if ov else self.cfg

    def member_solver(self, i: int):
        """A throwaway single-member solver for member ``i`` (initial
        states, analytic solutions, looped-baseline benches) — never
        the execution path."""
        return self.solver_cls(self.member_cfg(i))

    def initial_state(self) -> EnsembleState:
        states = [
            self.member_solver(i).initial_state()
            for i in range(self.members)
        ]
        est = EnsembleState.stack(states)
        if self.mesh is not None:
            # place the batched state on the ensemble sharding: member
            # axis over 'members', grid axes over the spatial subgroup
            import jax
            from jax.sharding import NamedSharding

            uspec, mspec = self.solver._ensemble_specs()
            est = EnsembleState(
                u=jax.device_put(est.u, NamedSharding(self.mesh, uspec)),
                t=jax.device_put(est.t, NamedSharding(self.mesh, mspec)),
                it=jax.device_put(
                    est.it, NamedSharding(self.mesh, mspec)
                ),
            )
        self.arm(est)
        return est

    def operands(self) -> Optional[dict]:
        """``{name: [B values]}`` for every member-varying scalar where
        any member differs from the template default; ``None`` when the
        physics is uniform (the fused-eligible case)."""
        defaults = self.solver.ensemble_operands()
        out = {}
        for name, default in defaults.items():
            col = [
                float(ov.get(name, default)) for ov in self._overrides
            ]
            if any(v != float(default) for v in col):
                out[name] = col
        return out or None

    # ------------------------------------------------------------------ #
    # Execution
    # ------------------------------------------------------------------ #
    def run(self, estate: EnsembleState, num_iters: int,
            donate: bool = False) -> EnsembleState:
        return self.solver.run_ensemble(
            estate, num_iters, operands=self.operands(), donate=donate,
        )

    def advance_to(self, estate: EnsembleState, t_end: float,
                   max_steps: Optional[int] = None,
                   donate: bool = False) -> EnsembleState:
        """``donate=True`` consumes ``estate`` (its ``u`` buffer is
        donated into the dispatch and deleted after — ISSUE 19); use
        the returned state only."""
        return self.solver.advance_to_ensemble(
            estate, t_end, operands=self.operands(),
            max_steps=max_steps, donate=donate,
        )

    def prewarm(self, max_steps: Optional[int] = None,
                donate: bool = False, per_member_te: bool = True):
        """Speculative AOT prewarm of the :meth:`advance_to`
        executable — deserializes a stored blob, never compiles cold.
        Returns the solver's prewarm status string (or ``None`` when
        the AOT path is unavailable)."""
        ops = self.operands() or {}
        return self.solver.prewarm_advance_to_ensemble(
            self.members, operand_names=tuple(sorted(ops)),
            max_steps=max_steps, donate=donate,
            per_member_te=per_member_te,
        )

    def engaged_path(self) -> dict:
        """Batched-dispatch provenance: the inner stepper the vmap
        wraps, the member count, and (``impl='auto'``) the tuner
        decision — the bench rows' engagement-guard surface."""
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            member_extent,
        )

        last = getattr(self.solver, "_ensemble_last", None) or {}
        out = {
            "impl": getattr(self.solver, "_requested_impl", self.cfg.impl),
            "stepper": last.get("stepper", "ensemble-vmap[unrun]"),
            "ensemble": self.members,
            "operands": last.get("operands", []),
            "fallback": getattr(self.solver, "_fused_fallback", None),
            # mesh placement provenance: a batched row that silently
            # fell back to one device is visible (and bench-guarded)
            "devices": last.get(
                "devices",
                1 if self.mesh is None else int(self.mesh.devices.size),
            ),
            "member_sharding": last.get(
                "member_sharding", member_extent(self.mesh)
            ),
            "mesh": last.get(
                "mesh", self.solver._ensemble_mesh_token()
            ),
        }
        if self._tuned is not None:
            out["tuned"] = {
                k: self._tuned.get(k)
                for k in ("source", "impl", "mlups", "key")
                if k in self._tuned
            }
        return out

    # ------------------------------------------------------------------ #
    # Per-member health + summaries
    # ------------------------------------------------------------------ #
    def _get_probe_parts(self):
        if self._probe_parts is None:
            from multigpu_advectiondiffusion_tpu.resilience.sentinel import (
                make_ensemble_probe_parts,
            )

            self._probe_parts = make_ensemble_probe_parts(self.solver)
        return self._probe_parts

    def _get_probe(self):
        if self._probe is None:
            launch, collect = self._get_probe_parts()
            self._probe = lambda estate: collect(launch(estate))
        return self._probe

    def probe_launch(self, estate: EnsembleState):
        """Enqueue the per-member health reduction on-device WITHOUT
        blocking (JAX async dispatch). The pipelined server calls this
        right after a slice dispatch — before the slice's output buffer
        is donated into the next slice — and judges the result later
        via :meth:`check_health_launched`."""
        return self._get_probe_parts()[0](estate)

    def arm(self, estate: EnsembleState) -> None:
        """Record the per-member healthy baseline (mass integrals and
        norms) the drift reports and the growth bound read against."""
        stats = self._get_probe()(estate)
        bad = [
            i for i, m in enumerate(stats["max_abs"])
            if not np.isfinite(m)
        ]
        if bad:
            raise EnsembleMemberDivergedError(
                int(np.max(np.asarray(estate.it))),
                float(np.max(np.asarray(estate.t))),
                bad, [stats["max_abs"][i] for i in bad],
                reason="non-finite initial state",
            )
        self._baseline = stats

    def _judge_stats(self, stats: dict, step: int, t: float,
                     growth: float) -> dict:
        """The divergence verdict over collected probe stats — shared
        by the blocking and launched health checks."""
        norms = stats["max_abs"]
        bad, why = [], None
        for i, m in enumerate(norms):
            if not np.isfinite(m):
                bad.append(i)
                why = "non-finite field"
        if not bad and self._baseline is not None:
            for i, m in enumerate(norms):
                bound = growth * max(1.0, self._baseline["max_abs"][i])
                if m > bound:
                    bad.append(i)
                    why = f"norm grew past the growth bound ({growth:g})"
        if bad:
            raise EnsembleMemberDivergedError(
                int(step), float(t),
                bad, [norms[i] for i in bad], reason=why,
            )
        return stats

    def check_health(self, estate: EnsembleState,
                     growth: float = 1e3) -> dict:
        """Per-member divergence check: non-finite members (or members
        whose norm grew past ``growth * max(1, |u0|)``) raise
        :class:`EnsembleMemberDivergedError` naming their indices —
        the rest of the batch stays valid. Returns the per-member
        stats dict on health."""
        stats = self._get_probe()(estate)
        return self._judge_stats(
            stats,
            step=int(np.max(np.asarray(estate.it))),
            t=float(np.max(np.asarray(estate.t))),
            growth=growth,
        )

    def check_health_launched(self, launched, step: int = 0,
                              t: float = 0.0,
                              growth: float = 1e3) -> dict:
        """:meth:`check_health` against a :meth:`probe_launch` handle:
        blocks only on the tiny per-member stat arrays — never on the
        full state, which may already be donated into a later slice."""
        stats = self._get_probe_parts()[1](launched)
        return self._judge_stats(stats, step=step, t=t, growth=growth)

    def member_summaries(self, estate: EnsembleState) -> list:
        """One dict per member (max|u|, min/max, l2, mass, mass drift
        vs the armed baseline, final t/it, its overrides) — the batched
        run's answer to the reference's per-run PrintSummary."""
        stats = self._get_probe()(estate)
        t = np.asarray(estate.t)
        it = np.asarray(estate.it)
        out = []
        for i in range(self.members):
            row = {
                "member": i,
                "t": float(t[i]),
                "it": int(it[i]),
                "max_abs": stats["max_abs"][i],
                "min": stats["min"][i],
                "max": stats["max"][i],
                "l2": stats["l2"][i],
                "mass": stats["mass"][i],
            }
            if self._baseline is not None:
                m0 = self._baseline["mass"][i]
                row["mass_drift"] = (row["mass"] - m0) / max(
                    abs(m0), 1e-30
                )
            if self._overrides[i]:
                row["overrides"] = {
                    k: v for k, v in self._overrides[i].items()
                }
            out.append(row)
        return out
