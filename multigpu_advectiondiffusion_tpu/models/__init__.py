from multigpu_advectiondiffusion_tpu.models.state import (
    EnsembleState,
    SolverState,
)
from multigpu_advectiondiffusion_tpu.models.diffusion import (
    DiffusionConfig,
    DiffusionSolver,
)
from multigpu_advectiondiffusion_tpu.models.burgers import BurgersConfig, BurgersSolver
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver

__all__ = [
    "SolverState",
    "EnsembleState",
    "EnsembleSolver",
    "DiffusionConfig",
    "DiffusionSolver",
    "BurgersConfig",
    "BurgersSolver",
]
