"""Solver families, resolved through the plugin registry.

The config/solver classes exported here are DERIVED from
``models/registry.py`` — adding a family means registering a
:class:`~.registry.ModelSpec` in its module, never editing this file
(ISSUE 15 satellite: no more hard-coded model import lists).
"""

from multigpu_advectiondiffusion_tpu.models import registry
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver
from multigpu_advectiondiffusion_tpu.models.state import (
    EnsembleState,
    SolverState,
)

__all__ = ["SolverState", "EnsembleState", "EnsembleSolver", "registry"]

for _spec in registry.specs():
    for _cls in (_spec.config_cls, _spec.solver_cls):
        globals()[_cls.__name__] = _cls
        __all__.append(_cls.__name__)
del _spec, _cls
