from multigpu_advectiondiffusion_tpu.models.state import SolverState
from multigpu_advectiondiffusion_tpu.models.diffusion import (
    DiffusionConfig,
    DiffusionSolver,
)
from multigpu_advectiondiffusion_tpu.models.burgers import BurgersConfig, BurgersSolver

__all__ = [
    "SolverState",
    "DiffusionConfig",
    "DiffusionSolver",
    "BurgersConfig",
    "BurgersSolver",
]
