"""Heat / diffusion equation solver: ``u_t = K lap(u) + S(u)``.

TPU-native re-design of the reference's diffusion family:

* 1/2/3-D, 2nd- or 4th-order Laplacian, SSP-RK3
  (``Matlab_Prototipes/DiffusionNd/heat{1,2,3}d.m``,
  ``SingleGPU/Diffusion{2,3}d*``, ``MultiGPU/Diffusion{2,3}d_Baseline``).
* Axisymmetric r-y variant (``heat2d_axisymmetric.m``) via
  ``geometry="axisymmetric"``.

Reference-parity behavior (on by default): the Laplacian is zeroed on the
2-cell boundary band (``Laplace3d.m:21``) and Dirichlet faces are
re-clamped after every step (``heat3d.m:65-67``) — both applied with
*global* indices, so a sharded run reproduces the single-device solution
bit-for-bit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.base import (
    LocalPhysics,
    SolverBase,
    StepContext,
)
from multigpu_advectiondiffusion_tpu.models.state import SolverState
from multigpu_advectiondiffusion_tpu.ops.axisym import (
    axis_mask,
    axisymmetric_laplacian,
    inverse_radius,
)
from multigpu_advectiondiffusion_tpu.ops.laplacian import laplacian
from multigpu_advectiondiffusion_tpu.ops.stencils import (
    boundary_band_mask,
    face_mask,
)
from multigpu_advectiondiffusion_tpu.timestepping.cfl import diffusive_dt
from multigpu_advectiondiffusion_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    grid: Grid
    diffusivity: float = 1.0  # K, "heat conduction" arg (main.c:38)
    order: int = 4
    integrator: str = "ssp_rk3"
    dtype: str = "float32"
    safety: float = 0.8  # dt stability factor (main.c:64: 0.8; MATLAB: 0.9)
    ic: object = "heat_kernel"
    ic_params: Tuple = ()
    bc: object = "dirichlet"
    t0: float = 0.1  # initial time of the analytic Gaussian (heat3d.m:15)
    reference_parity: bool = True
    boundary_band: int = 2  # width of the skipped band (Laplace3d.m:21)
    source: Optional[Callable] = None  # S(u) hook (heat3d.m:26-30)
    geometry: str = "cartesian"  # or "axisymmetric" (2-D r-y)
    # kernel strategy: "xla" | "pallas" (per-stage fused fast path) |
    # "pallas_step" (whole-step temporal-blocking variant — a measured-
    # slower ladder rung kept selectable for benchmarking)
    impl: str = "xla"
    # sharded halo schedule: "padded" (exchange -> concat -> stencil) or
    # "split" (interior computed concurrently with the in-flight ghost
    # collectives, boundary bands patched after — the reference's
    # boundary-first stream choreography as dataflow, main.c:203-260)
    overlap: str = "padded"
    # communication-avoiding exchange cadence: exchange a k*G-deep halo
    # once per k steps (redundant ghost recompute in between) instead of
    # G-deep every step. 1 = per-step (reference MPI cadence); > 1 rides
    # the sharded slab rung only and is validated at dispatch like the
    # impl ladder. impl="auto" lets the measured tuner pick it.
    steps_per_exchange: int = 1
    # halo-exchange transport: "collective" (XLA ppermute between
    # compiled calls — every schedule above) or "dma" (the sharded
    # whole-run slab rung pushes its ghost rows to the ±z neighbors
    # from INSIDE the Pallas program via remote DMA and never returns
    # to XLA between steps; z-slab meshes, TPU backend or the CPU
    # interpret simulator). Validated like the impl ladder; "auto"
    # impl lets the measured tuner pick it.
    exchange: str = "collective"
    # storage precision rung: "native" (state stored at dtype) or
    # "bf16" (f32 compute state stored/exchanged as bfloat16 — half the
    # HBM and halo bytes, Kahan-compensated generic loop; requires
    # dtype='float32'; validated in SolverBase._validate_precision)
    precision: str = "native"

    def __post_init__(self):
        from multigpu_advectiondiffusion_tpu.ops import IMPLS

        if self.precision not in ("native", "bf16"):
            raise ValueError(
                f"unknown precision {self.precision!r}; "
                "'native' or 'bf16'"
            )
        if self.geometry not in ("cartesian", "axisymmetric"):
            raise ValueError(f"unknown geometry {self.geometry!r}")
        if self.overlap not in ("padded", "split"):
            raise ValueError(f"unknown overlap {self.overlap!r}")
        if self.impl not in IMPLS:
            raise ValueError(
                f"unknown impl {self.impl!r}; ladder rungs: {IMPLS}"
            )
        if not isinstance(self.steps_per_exchange, int) or (
            self.steps_per_exchange < 1
        ):
            raise ValueError(
                "steps_per_exchange must be an int >= 1, got "
                f"{self.steps_per_exchange!r}"
            )
        if self.exchange not in ("collective", "dma"):
            raise ValueError(
                f"unknown exchange {self.exchange!r}; "
                "'collective' or 'dma'"
            )
        if self.geometry == "axisymmetric" and self.grid.ndim != 2:
            raise ValueError("axisymmetric geometry requires a 2-D (y, r) grid")


class DiffusionSolver(SolverBase):
    cfg: DiffusionConfig

    def __init__(self, cfg: DiffusionConfig, mesh=None, decomp=None):
        super().__init__(cfg, mesh=mesh, decomp=decomp)
        self.dt = diffusive_dt(cfg.diffusivity, cfg.grid.spacing, cfg.safety)

    def _op_impl(self) -> str:
        """Per-op kernel strategy: Pallas flavors map to the per-axis
        kernels for f32 only (``SolverBase._pallas_f32_gate``)."""
        from multigpu_advectiondiffusion_tpu.ops import op_impl as _norm

        self._op_fallback = None
        return self._pallas_f32_gate(_norm(self.cfg.impl))

    def ic_spec(self):
        """Thread the config's diffusivity/t0 into the analytic ICs so the
        initial state always matches :meth:`exact_solution` at ``t = t0``
        (the MATLAB drivers couple these by construction, heat3d.m:33-36)."""
        name = self.cfg.ic
        if name == "heat_kernel" and self.cfg.geometry == "axisymmetric":
            name = "heat_kernel_radial"
        if name in ("heat_kernel", "heat_kernel_radial"):
            return name, {"t0": self.cfg.t0, "diffusivity": self.cfg.diffusivity}
        return name, {}

    def stencil_spec(self) -> dict:
        """Family stencil metadata (registration contract): the
        diffusive tap radius of the configured Laplacian order — what
        the tuner's fused ghost depth and the halo verifier's family
        combos derive from."""
        from multigpu_advectiondiffusion_tpu.ops.laplacian import (
            D2_STENCILS,
        )

        r = D2_STENCILS[self.cfg.order][1]
        return {
            "family": "diffusion",
            "diffusive_radius": r,
            "stage_radius": r,
        }

    def cfl_rule(self) -> dict:
        """Queryable time-step contract (registration contract): the
        diffusive stability bound ``safety / (2 K sum 1/dx^2)``
        computed at construction."""
        return {
            "kind": "diffusive",
            "dt": float(self.dt),
            "safety": float(self.cfg.safety),
        }

    def diagnostics_spec(self) -> dict:
        """In-situ diagnostics contract (``diagnostics/physics.py``):

        * pure diffusion (no source) on a Cartesian grid satisfies the
          discrete maximum principle — register the tolerance rule so a
          new extremum (over-steep dt, broken stencil coefficient)
          surfaces as a ``phys:violation`` before the norm sentinel
          ever trips;
        * the heat-kernel workload's amplitude decays at the analytic
          rate ``-d/2`` in ``log max u`` vs ``log t`` — recorded as
          ``decay_rate_analytic`` so the measured fit
          (``gaussian_decay_fit``; trace-report "physics" section)
          reads against it."""
        from multigpu_advectiondiffusion_tpu.diagnostics import physics

        spec = {"rules": [], "meta": {}}
        if self.cfg.source is None and self.cfg.geometry == "cartesian":
            spec["rules"].append(physics.max_principle_rule())
        if self.cfg.ic == "heat_kernel" and self.cfg.geometry == "cartesian":
            spec["meta"]["decay_rate_analytic"] = -self.grid.ndim / 2.0
        return spec

    def ensemble_operands(self) -> dict:
        """Member-varying scalars the batched ensemble engine may pass
        as traced operands: the diffusivity K (which also moves the
        stability dt, recomputed in-trace per member)."""
        return {"diffusivity": float(self.cfg.diffusivity)}

    def build_local(self, ctx: StepContext, overrides=None) -> LocalPhysics:
        cfg = self.cfg
        grid = cfg.grid
        bcs = self.bcs
        # ensemble mode: a traced per-member K enters as an operand
        # (closure constants cannot vary along the vmapped member axis);
        # the stability dt is re-derived from it in-trace
        K = cfg.diffusivity
        dt = self.dt
        if overrides and "diffusivity" in overrides:
            K = overrides["diffusivity"]
            dt = diffusive_dt(K, grid.spacing, cfg.safety)

        if cfg.geometry == "axisymmetric":
            r = grid.coords(1, self.dtype)
            inv_r_local = inverse_radius(r)
            on_axis_local = axis_mask(r)
            # slice the local window when the r axis is sharded
            if ctx.local_shape[1] != ctx.global_shape[1]:
                inv_r_local = jax.lax.dynamic_slice_in_dim(
                    inv_r_local, ctx.offsets[1], ctx.local_shape[1]
                )
                if on_axis_local is not None:
                    on_axis_local = jax.lax.dynamic_slice_in_dim(
                        on_axis_local, ctx.offsets[1], ctx.local_shape[1]
                    )

            def operator(u):
                return axisymmetric_laplacian(
                    u,
                    grid.spacing,
                    inv_r_local,
                    diffusivity=K,
                    padder=ctx.padder,
                    on_axis=on_axis_local,
                )

        else:

            ghost_fn = ctx.ghost_fn if cfg.overlap == "split" else None

            impl = self._op_impl()
            if impl == "pallas" and overrides and "diffusivity" in overrides:
                # ensemble operand mode: the per-axis Pallas kernels bake
                # their coefficients as compile-time constants and reject
                # a traced per-member K (captured-constant error) — the
                # batched generic path runs the XLA stencils instead,
                # recorded like every other per-op fallback
                self._op_fallback = (
                    "member-varying diffusivity is a traced operand; "
                    "per-axis Pallas kernels bake constants — XLA runs"
                )
                impl = "xla"

            def operator(u):
                # a list keeps traced per-member K indexable per axis
                return laplacian(
                    u,
                    grid.spacing,
                    diffusivity=[K] * grid.ndim,
                    order=cfg.order,
                    padder=ctx.padder,
                    impl=impl,
                    ghost_fn=ghost_fn,
                )

        walled_axes = [a for a, b in enumerate(bcs) if b.kind != "periodic"]
        band = boundary_band_mask(
            ctx.local_shape, cfg.boundary_band, ctx.global_shape, ctx.offsets,
            axes=walled_axes,
        ) if cfg.reference_parity and walled_axes else None

        def rhs(u):
            lu = operator(u)
            if cfg.source is not None:
                lu = lu + cfg.source(u)
            if band is not None:
                lu = jnp.where(band, lu, jnp.zeros_like(lu))
            return lu

        post = None
        if cfg.reference_parity and walled_axes:
            dir_axes = [a for a in walled_axes if bcs[a].kind == "dirichlet"]
            edge_axes = [a for a in walled_axes if bcs[a].kind == "edge"]
            clamps = [
                (
                    face_mask(ctx.local_shape, [a], ctx.global_shape, ctx.offsets),
                    bcs[a].value,
                )
                for a in dir_axes
            ]

            def post(u):
                # Dirichlet walls re-imposed each step (heat3d.m:65-67).
                for faces, value in clamps:
                    u = jnp.where(faces, jnp.asarray(value, u.dtype), u)
                # Zero-gradient walls: the frozen band copies the first
                # evolving row (heat2d_axisymmetric.m:64-66 u(1,:)=u(3,:)).
                for a in edge_axes:
                    n_loc, n_glob = ctx.local_shape[a], ctx.global_shape[a]
                    gidx = jnp.arange(n_loc) + ctx.offsets[a]
                    tgt = jnp.clip(gidx, cfg.boundary_band,
                                   n_glob - 1 - cfg.boundary_band)
                    # local index of the source row, clipped into this shard
                    lidx = jnp.clip(tgt - ctx.offsets[a], 0, n_loc - 1)
                    u = jnp.take(u, lidx, axis=a)
                return u

        return LocalPhysics(rhs=rhs, static_dt=dt, post=post)

    # ------------------------------------------------------------------ #
    # Fully-fused Pallas fast path (single-chip or shard-local under a
    # mesh; reference-parity walls)
    # ------------------------------------------------------------------ #
    def _fused_stepper(self, mode: str = "iters"):
        """The fused SSP-RK3 stepper when this config is eligible, else
        ``None`` (generic path). Eligibility mirrors the assumptions the
        kernel bakes in: frozen Dirichlet ghosts/boundary band, static dt,
        2-D/3-D cartesian O4, f32 (f64 states ride the f32 kernels
        through the f64-storage/f32-compute convention, 3-D only). Under
        a mesh the per-stage kernels (3-D z-slab grid; 2-D whole-shard)
        run shard-local — ghosts ppermute-refreshed between stages, the
        tuned kernel under MPI
        (``MultiGPU/Diffusion3d_Baseline/main.c:189-303``,
        ``Diffusion2d_Baseline/main.c:189-280``); the whole-step and
        whole-run variants stay single-chip (their temporal blocking
        crosses the points where ghosts must refresh).

        3-D ``impl='pallas'`` prefers the slab-pipelined whole-run
        stepper (``fused-whole-run-slab``) where its VMEM/profitability
        model says the one-HBM-round-trip-per-step schedule wins; it
        declines cleanly to the per-stage ``fused-stage`` path
        otherwise. ``impl='pallas_slab'`` pins the slab stepper (modulo
        hard VMEM fit), ``'pallas_stage'`` pins per-stage. ``mode``:
        the slab stepper has no ``run_to``, so the ``"t_end"`` selection
        (advance_to) always takes per-stage."""
        cfg = self.cfg
        bcs = self.bcs
        from multigpu_advectiondiffusion_tpu.ops import is_fused_impl

        lshape = (
            self.grid.shape
            if self.mesh is None
            else self.decomp.local_shape(self.mesh, self.grid.shape)
        )
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R

        self._fused_fallback = None
        if not is_fused_impl(cfg.impl):
            return self._decline(f"impl={cfg.impl!r} does not request fusion")
        if cfg.geometry != "cartesian":
            return self._decline("fused kernels are cartesian-only")
        if cfg.order != 4:
            return self._decline("fused kernels bake in the O4 Laplacian")
        if cfg.integrator != "ssp_rk3":
            return self._decline("fused kernels bake in SSP-RK3")
        if cfg.source is not None:
            return self._decline("source-term hook needs the generic path")
        if not cfg.reference_parity or cfg.boundary_band < 1:
            # kernel's face clamp lives inside the non-interior branch;
            # band 0 would let faces evolve
            return self._decline(
                "fused walls need reference_parity with boundary_band >= 1"
            )
        if self.grid.ndim not in (2, 3):
            return self._decline("fused diffusion kernels are 2-D/3-D only")
        # f64 states run the f32 kernels with f64 storage at the run
        # boundary (Mosaic has no f64 vector path; accuracy is f32 —
        # PARITY.md). Kernel buffers are f32 either way.
        f64_storage = self.dtype == jnp.dtype("float64")
        # precision='bf16' is the same convention pointed the other way:
        # facing/extract dtype stays f32, kernel/HBM buffers (and every
        # ghost-refresh wire) are bf16 — taps still evaluate in f32 via
        # the kernels' compute_dtype upcast (ISSUE 16)
        bf16_store = self._precision_mode() == "bf16"
        if bf16_store:
            if self.grid.ndim != 3:
                return self._decline(
                    "precision='bf16' fused kernels are 3-D only "
                    "(2-D whole-run/whole-shard variants lack the "
                    "split-dtype machinery)"
                )
            if cfg.impl == "pallas_step":
                return self._decline(
                    "precision='bf16' has no whole-step rung; use the "
                    "per-stage or slab stepper"
                )
        if self.dtype == jnp.bfloat16:
            # bf16-storage/f32-compute rung: HBM bytes halved (the
            # ref-grid row is HBM-roof-bound) — 3-D per-stage only.
            # Measured 1.6x the f32 rate BUT accuracy-rejected for
            # stability-dt workloads (updates round away; PARITY.md) —
            # an explicit opt-in, never a silent default.
            if self.grid.ndim != 3 or cfg.impl == "pallas_step":
                return self._decline(
                    "bf16 storage exists only for the 3-D per-stage stepper"
                )
        elif f64_storage:
            if (
                self.grid.ndim != 3
                or cfg.impl == "pallas_step"
                or self.mesh is not None
            ):
                return self._decline(
                    "f64 storage rides the 3-D fused steppers, "
                    "single-chip only"
                )
        elif self.dtype != jnp.float32:
            return self._decline("fused kernels are float32/bf16-storage only")
        if not all(b.kind == "dirichlet" for b in bcs) or not all(
            b.value == bcs[0].value for b in bcs
        ):
            return self._decline(
                "fused walls need uniform Dirichlet BCs on every axis"
            )
        if self.mesh is not None:
            if cfg.impl == "pallas_step":
                return self._decline(
                    "whole-step temporal blocking crosses ghost-refresh "
                    "points; single-chip only"
                )
            # every sharded axis must serve the stencil halo from its core
            if any(lshape[ax] < R for ax, _ in self.decomp.axes):
                return self._decline(
                    f"a sharded axis is thinner than the O4 halo ({R})"
                )
        if f64_storage:
            kernel_dtype = jnp.float32
        elif bf16_store:
            kernel_dtype = jnp.dtype(jnp.bfloat16)
        else:
            kernel_dtype = self.dtype
        slab = self._select_slab(mode, lshape, kernel_dtype, f64_storage)
        if slab is not None:
            return slab
        if "fused" not in self._cache:
            if self.grid.ndim == 3 and cfg.impl == "pallas_step":
                from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (  # noqa: E501
                    StepFusedDiffusionStepper as cls,
                )
            elif self.grid.ndim == 3:
                from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (  # noqa: E501
                    FusedDiffusionStepper as cls,
                )
            elif self.mesh is None:
                from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (  # noqa: E501
                    FusedDiffusion2DStepper as cls,
                )

                if not cls.supported(self.grid.shape, self.dtype):
                    return self._decline(
                        "2-D grid exceeds the whole-run VMEM budget"
                    )
            else:
                # the 2-D tuned kernel under the mesh: per-stage
                # whole-shard kernels with ppermute ghost refresh between
                # stages (MultiGPU/Diffusion2d_Baseline/main.c:189-280)
                from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (  # noqa: E501
                    ShardedFusedDiffusion2DStepper as cls,
                )

                if not cls.supported(lshape, self.dtype):
                    return self._decline(
                        "2-D shard exceeds the per-stage VMEM budget"
                    )
            kwargs = {}
            if self.mesh is not None:
                # both the 3-D z-slab and 2-D whole-shard per-stage
                # steppers implement the three-call split-overlap
                # schedule (they decline it themselves off-design)
                kwargs["global_shape"] = self.grid.shape
                kwargs["overlap_split"] = self._split_overlap_requested()
            if jnp.dtype(kernel_dtype) != jnp.dtype(self.dtype):
                # split-dtype storage, both directions: f64-facing on
                # f32 kernels, and f32-facing on bf16 kernels
                kwargs["storage_dtype"] = self.dtype
            self._cache["fused"] = cls(
                lshape,
                kernel_dtype,
                self.grid.spacing,
                [cfg.diffusivity] * self.grid.ndim,
                self.dt,
                cfg.boundary_band,
                bcs[0].value,
                **kwargs,
            )
        return self._cache["fused"]

    def _select_slab(self, mode, lshape, kernel_dtype, f64_storage):
        """The slab-pipelined whole-run stepper when this config should
        engage it (the top rung of the 3-D ladder), else ``None`` and
        the caller falls through to the per-stage selection. The
        VMEM-budget block sizing and the traffic-vs-recompute
        profitability model live in ``fused_slab_run``.
        ``steps_per_exchange > 1`` pins the slab rung (the k-step
        schedule lives nowhere else) and turns every decline below into
        a hard error instead of a silent per-stage fallback."""
        cfg = self.cfg
        k = int(getattr(cfg, "steps_per_exchange", 1) or 1)
        dma = self._exchange_mode() == "dma"
        pinned = cfg.impl == "pallas_slab" or k > 1 or dma

        def decline(reason):
            if dma:
                raise ValueError(
                    f"exchange='dma' needs the sharded slab rung: "
                    f"{reason}"
                )
            if k > 1:
                raise ValueError(
                    f"steps_per_exchange={k} needs the sharded slab "
                    f"rung: {reason}"
                )
            return None

        if self.grid.ndim != 3 or cfg.impl not in ("pallas", "pallas_slab"):
            return None  # k > 1 / dma on these configs: rejected at __init__
        if mode == "t_end":
            # no run_to: advance_to keeps the per-stage path
            return decline("the slab stepper has no run_to (use --iters)")
        if self.dtype == jnp.bfloat16:
            return decline("bf16 storage rides the per-stage stepper")
        from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
            SlabRunDiffusionStepper as slab_cls,
        )

        if self.mesh is not None:
            # whole-run temporal blocking crosses ghost refreshes: under
            # a mesh the slab stepper runs per-step calls with a k*G-deep
            # z exchange per k steps — z-slab decompositions only, and a
            # measured-unknown tradeoff vs per-stage, so it engages only
            # when pinned (impl='pallas_slab', steps_per_exchange > 1,
            # or a tuner decision routed through either)
            if not pinned:
                return None
            if any(ax != 0 for ax in self._sharded_axes()):
                return decline("z-slab decompositions only")
            if lshape[0] < k * slab_cls.halo:
                return decline(
                    f"local z extent {lshape[0]} cannot serve the "
                    f"{k * slab_cls.halo}-deep exchange"
                )
            if dma and not self._dma_backend_ok():
                import jax as _jax

                return decline(
                    "in-kernel remote DMA needs the TPU backend (or "
                    "the CPU interpret simulator); backend="
                    f"{_jax.default_backend()!r}"
                )
        if not slab_cls.supported(
            lshape, kernel_dtype, sharded=self.mesh is not None
        ):
            return decline("local shape exceeds the slab VMEM budget")
        if not pinned and not slab_cls.profitable(
            lshape, kernel_dtype, sharded=self.mesh is not None
        ):
            return None
        if "fused_slab" not in self._cache:
            kwargs = {}
            if self.mesh is not None:
                kwargs["global_shape"] = self.grid.shape
                kwargs["overlap_split"] = (
                    not dma and self._split_overlap_requested()
                )
                if k > 1:
                    kwargs["steps_per_exchange"] = k
                if dma:
                    kwargs.update(self._dma_stepper_kwargs())
            if jnp.dtype(kernel_dtype) != jnp.dtype(self.dtype):
                kwargs["storage_dtype"] = self.dtype
            self._cache["fused_slab"] = slab_cls(
                lshape,
                kernel_dtype,
                self.grid.spacing,
                [cfg.diffusivity] * self.grid.ndim,
                self.dt,
                cfg.boundary_band,
                self.bcs[0].value,
                **kwargs,
            )
        return self._cache["fused_slab"]

    # ------------------------------------------------------------------ #
    # Analytic solution support (heat3d.m:36; heat2d_axisymmetric.m:39)
    # ------------------------------------------------------------------ #
    def exact_solution(self, t: float) -> jnp.ndarray:
        cfg = self.cfg
        d = cfg.diffusivity
        r2 = cfg.grid.radius_sq(self.dtype)
        if cfg.geometry == "axisymmetric":
            r = cfg.grid.coords(1, self.dtype)
            amp = (cfg.t0 / t) ** 1.0
            return (amp * jnp.exp(-(r[None, :] ** 2) / (4.0 * d * t))) * jnp.ones(
                cfg.grid.shape, self.dtype
            )
        power = cfg.grid.ndim / 2.0
        return ((cfg.t0 / t) ** power * jnp.exp(-r2 / (4.0 * d * t))).astype(
            self.dtype
        )

    def error_norms(self, state: SolverState, t: float | None = None):
        t_val = float(state.t) if t is None else t
        return metrics.error_norms(
            state.u, self.exact_solution(t_val), self.cfg.grid.spacing
        )

    # ------------------------------------------------------------------ #
    # MATLAB-exact accuracy-test loop (diffusion3dTest.m:43-70)
    # ------------------------------------------------------------------ #
    def advance_reference(self, state: SolverState, t_end: float) -> SolverState:
        """Reproduce the reference test loop *exactly*, including its two
        quirks (``diffusion3dTest.m:41-70``): the Dirichlet clamp is
        applied once per step (after stage 3, not per stage), and the RK
        update of the final step uses the untrimmed dt — only afterwards
        is dt trimmed and time advanced, so the state integrates slightly
        past ``t_end``. Needed to hit the frozen norms in
        ``TestingAccuracy.log``."""
        from jax import lax

        def block(u, t, te):
            def cond(c):
                return c[1] < te

            def body(c):
                u, t, dt = c
                phys = self.build_local(self._context(u))
                u = self.integrator(phys.rhs, u, dt.astype(u.dtype), None)
                if phys.post is not None:
                    u = phys.post(u)
                dt = jnp.where(t + dt > te, te - t, dt)
                return (u, t + dt, dt)

            dt0 = jnp.asarray(self.dt, dtype=t.dtype)
            u, t, _ = lax.while_loop(cond, body, (u, t, dt0))
            return u, t

        # t_end is a traced operand — one compilation serves the whole
        # grid-refinement sweep (the convergence CLI calls this per nc)
        f = self._compiled("advref", lambda: self._wrap(block, 1, 2))
        u, t = f(state.u, state.t, jnp.asarray(t_end, state.t.dtype))
        return SolverState(u=u, t=t, it=state.it)


# --------------------------------------------------------------------- #
# Registration: the family as a declarative plugin descriptor
# (models/registry.py; the CLI, tuner, cost model, bench matrix and
# static verifiers resolve the family through this spec)
# --------------------------------------------------------------------- #
def _cli_configure(p, ndim, axisym: bool = False):
    p.add_argument("--K", type=float, default=0.27 if axisym else 1.0,
                   help="diffusivity (main.c arg 1)")
    p.add_argument("--order", type=int, default=4, choices=[2, 4])
    p.add_argument("--t0", type=float, default=1.0 if axisym else 0.1)


def _cli_build(args, grid, ndim, geometry: str = "cartesian"):
    from multigpu_advectiondiffusion_tpu.models.registry import resolve_bc

    return DiffusionConfig(
        grid=grid,
        diffusivity=args.K,
        order=args.order,
        integrator=args.integrator,
        dtype=args.dtype,
        ic=args.ic or "heat_kernel",
        bc=resolve_bc(args, "dirichlet" if geometry == "cartesian"
                      else ("edge", "dirichlet")),
        t0=args.t0,
        geometry=geometry,
        impl=args.impl,
        overlap=args.overlap,
        steps_per_exchange=args.steps_per_exchange,
        exchange=args.exchange,
        precision=getattr(args, "precision", "native"),
    )


def _stage_radius(cfg) -> int:
    """Fused per-stage stencil radius (tuner ghost depth = 3h): the
    Pallas O4 layout radius, regardless of the generic path's order."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R

    return R


def _key_extras(cfg):
    return [
        f"order={getattr(cfg, 'order', 4)}",
        f"geom={getattr(cfg, 'geometry', 'cartesian')}",
    ]


def _cost_kwargs(cfg):
    return {"order": getattr(cfg, "order", 4)}


def _bench_build(grid, dtype, impl, case):
    return DiffusionConfig(
        grid=grid, diffusivity=1.0, dtype=dtype, impl=impl
    )


from multigpu_advectiondiffusion_tpu.models.registry import (  # noqa: E402
    ModelSpec,
    register_model,
)

register_model(ModelSpec(
    name="diffusion",
    config_cls=DiffusionConfig,
    solver_cls=DiffusionSolver,
    description="heat/diffusion equation u_t = K lap(u) + S(u)",
    check_error=True,
    sweep_aliases={"K": "diffusivity"},
    cli_configure=_cli_configure,
    cli_build=_cli_build,
    stage_radius=_stage_radius,
    key_extras=_key_extras,
    cost_kwargs=_cost_kwargs,
    bench_build=_bench_build,
))
