"""Burgers / scalar conservation-law solver:
``u_t + sum_axis d f(u)/dx_axis = nu lap(u)``.

TPU-native re-design of the reference's WENO family:

* Inviscid 1/2/3-D with WENO5-JS (``Matlab_Prototipes/InviscidBurgersNd/
  LFWENO5FDM{1,2,3}d.m``, ``MultiGPU/Burgers{2,3}d_Baseline``),
  WENO5-Z (``SingleGPU/Burgers3d_WENO5_SharedMem``) and WENO7
  (``LFWENO7FDM*``).
* Viscous option ``nu > 0`` with the 4th-order Laplacian — the single-GPU
  Burgers variants are viscous with ``nu = 1e-5``
  (``SingleGPU/Burgers3d_WENO5/main.cpp:56-59,147``).
* Selectable flux: burgers / linear / buckley (``LFWENO5FDM3d.m:30-40``).

Adaptive dt ``CFL dx / max|f'(u)|`` (``LFWENO5FDM3d.m:71``) is the default,
with the global reduction running as ``lax.pmax`` over the device mesh.
``adaptive_dt=False`` reproduces the CUDA drivers' hard-coded unit wave
speed (``MultiGPU/Burgers3d_Baseline/main.c:193`` — a documented defect
kept available only for benchmark parity).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.base import (
    LocalPhysics,
    SolverBase,
    StepContext,
)
from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
from multigpu_advectiondiffusion_tpu.ops.laplacian import laplacian
from multigpu_advectiondiffusion_tpu.ops.weno import flux_divergence
from multigpu_advectiondiffusion_tpu.timestepping.cfl import advective_dt


@dataclasses.dataclass(frozen=True)
class BurgersConfig:
    grid: Grid
    flux: str = "burgers"
    flux_params: Tuple = ()
    weno_order: int = 5
    weno_variant: str = "js"
    cfl: float = 0.4  # LFWENO5FDM3d.m:25
    nu: float = 0.0  # viscosity; 1e-5 in SingleGPU Burgers (main.cpp:56)
    laplacian_order: int = 4
    adaptive_dt: bool = True
    integrator: str = "ssp_rk3"
    dtype: str = "float32"
    ic: object = "gaussian"
    ic_params: Tuple = ()
    bc: object = "edge"
    t0: float = 0.0
    # kernel strategy: "xla" | "pallas"; other pallas flavors (e.g. the
    # CLI-global "pallas_step") are accepted and map to the per-axis
    # pallas kernels (Burgers has no whole-step variant)
    impl: str = "xla"
    # sharded halo schedule: "padded" | "split" (see DiffusionConfig)
    overlap: str = "padded"
    # communication-avoiding exchange cadence (see DiffusionConfig):
    # k*G-deep exchange once per k steps on the sharded slab rung;
    # impl="auto" lets the measured tuner pick it
    steps_per_exchange: int = 1
    # halo-exchange transport (see DiffusionConfig): "collective" (XLA
    # ppermute between compiled calls) or "dma" (in-kernel remote-DMA
    # pushes on the sharded whole-run slab rung)
    exchange: str = "collective"
    # storage precision rung (see DiffusionConfig): "native" or "bf16"
    # (f32 compute state stored/exchanged as bfloat16; Burgers engages
    # it on the fixed-dt 3-D slab rung and the generic XLA path)
    precision: str = "native"

    def __post_init__(self):
        from multigpu_advectiondiffusion_tpu.ops import IMPLS

        if self.precision not in ("native", "bf16"):
            raise ValueError(
                f"unknown precision {self.precision!r}; "
                "'native' or 'bf16'"
            )
        if self.overlap not in ("padded", "split"):
            raise ValueError(f"unknown overlap {self.overlap!r}")
        if self.impl not in IMPLS:
            raise ValueError(
                f"unknown impl {self.impl!r}; ladder rungs: {IMPLS}"
            )
        if not isinstance(self.steps_per_exchange, int) or (
            self.steps_per_exchange < 1
        ):
            raise ValueError(
                "steps_per_exchange must be an int >= 1, got "
                f"{self.steps_per_exchange!r}"
            )
        if self.exchange not in ("collective", "dma"):
            raise ValueError(
                f"unknown exchange {self.exchange!r}; "
                "'collective' or 'dma'"
            )


class BurgersSolver(SolverBase):
    cfg: BurgersConfig

    def __init__(self, cfg: BurgersConfig, mesh=None, decomp=None):
        super().__init__(cfg, mesh=mesh, decomp=decomp)
        self.flux = flux_lib.get(cfg.flux, **dict(cfg.flux_params))
        # the CUDA-parity fixed step (Burgers3d_Baseline/main.c:193), or
        # None in adaptive mode — the single definition every consumer
        # (generic path, fused stepper, bench t_end rows) reads
        self.dt = None if cfg.adaptive_dt else cfg.cfl * min(cfg.grid.spacing)

    def _op_impl(self) -> str:
        """Per-op kernel strategy for this config. Pallas flavors map to
        the per-axis kernels, with two XLA exceptions (both reported via
        ``engaged_path``): non-f32 dtypes
        (``SolverBase._pallas_f32_gate``), and WENO7 under
        ``impl="pallas"`` (the per-axis WENO7 kernel measures ~2x slower
        than XLA at 512^3, PARITY.md ladder; "pallas" promises
        best-available — pin the rung with ``impl="pallas_axis"``)."""
        from multigpu_advectiondiffusion_tpu.ops import op_impl as _norm

        self._op_fallback = None
        impl = self._pallas_f32_gate(_norm(self.cfg.impl))
        if (
            impl == "pallas"
            and self.cfg.weno_order == 7
            and self.cfg.impl != "pallas_axis"
        ):
            self._op_fallback = (
                "per-axis WENO7 measured slower than XLA; pin with "
                "impl='pallas_axis'"
            )
            return "xla"
        return impl

    def stencil_spec(self) -> dict:
        """Family stencil metadata (registration contract): the WENO
        reconstruction radius of the configured order (the viscous O4
        Laplacian's radius 2 never exceeds it)."""
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        r = HALO[self.cfg.weno_order]
        return {
            "family": "burgers",
            "advective_radius": r,
            "diffusive_radius": 2 if self.cfg.nu else 0,
            "stage_radius": r,
        }

    def cfl_rule(self) -> dict:
        """Queryable time-step contract (registration contract): the
        advective CFL bound ``cfl dx / max|f'(u)|`` — adaptive (global
        wave-speed reduction per step) or the CUDA-parity fixed step."""
        return {
            "kind": "advective",
            "cfl": float(self.cfg.cfl),
            "adaptive": bool(self.cfg.adaptive_dt),
            "dt": None if self.dt is None else float(self.dt),
        }

    def diagnostics_spec(self) -> dict:
        """In-situ diagnostics contract: WENO on the convex Burgers flux
        is essentially non-oscillatory — total variation is bounded by
        the initial data's, so the TV-monotonicity tolerance rule
        (``diagnostics/physics.py``) catches spurious oscillation (a
        flux-split sign error, a broken smoothness weight) that leaves
        smooth-case convergence order intact."""
        from multigpu_advectiondiffusion_tpu.diagnostics import physics

        spec = {"rules": [], "meta": {}}
        if self.cfg.flux == "burgers":
            spec["rules"].append(physics.tv_monotone_rule())
        return spec

    def ensemble_operands(self) -> dict:
        """Member-varying scalars the batched ensemble engine may pass
        as traced operands: the CFL number (fixed-dt members get
        ``cfl * min(dx)`` re-derived in-trace; adaptive members scale
        their wave-speed dt). Riemann-state sweeps vary through
        per-member initial conditions, not operands."""
        return {"cfl": float(self.cfg.cfl)}

    def build_local(self, ctx: StepContext, overrides=None) -> LocalPhysics:
        cfg = self.cfg
        spacing = cfg.grid.spacing
        fx = self.flux
        # ensemble mode: a traced per-member CFL enters as an operand
        cfl = cfg.cfl
        fixed_dt = self.dt
        if overrides and "cfl" in overrides:
            cfl = overrides["cfl"]
            if not cfg.adaptive_dt:
                fixed_dt = cfl * min(spacing)

        ghost_fn = ctx.ghost_fn if cfg.overlap == "split" else None
        # Burgers has no whole-step variant; any pallas flavor (e.g. the
        # CLI's global --impl pallas_step) maps to the per-axis kernels.
        impl = self._op_impl()

        def rhs(u):
            acc = None
            for axis in range(u.ndim):
                div = flux_divergence(
                    u,
                    axis,
                    spacing[axis],
                    fx,
                    order=cfg.weno_order,
                    variant=cfg.weno_variant,
                    padder=ctx.padder,
                    impl=impl,
                    ghost_fn=ghost_fn,
                )
                acc = div if acc is None else acc + div
            out = -acc
            if cfg.nu:
                out = out + laplacian(
                    u,
                    spacing,
                    diffusivity=cfg.nu,
                    order=cfg.laplacian_order,
                    padder=ctx.padder,
                    impl=impl,
                    ghost_fn=ghost_fn,
                )
            return out

        if cfg.adaptive_dt:
            dt_fn = lambda u: advective_dt(  # noqa: E731
                u, fx.df, spacing, cfl, reduce_max=ctx.reduce_max
            )
            return LocalPhysics(rhs=rhs, dt_fn=dt_fn)
        # CUDA-parity fixed dt: CFL * dx / 1.0 (Burgers3d_Baseline/main.c:193)
        return LocalPhysics(rhs=rhs, static_dt=fixed_dt)

    # ------------------------------------------------------------------ #
    # Fully-fused Pallas fast path (single chip, fixed dt, edge BCs)
    # ------------------------------------------------------------------ #
    def _fused_stepper(self, mode: str = "iters"):
        """The fused SSP-RK3 stepper when this config is eligible, else
        ``None``. Eligibility mirrors the kernels' assumptions: 2-D/3-D
        cartesian WENO5-JS/Z or WENO7-JS, edge ghosts, f32. The 3-D per-stage kernel
        serves every dt mode and world: adaptive dt rides a runtime SMEM
        scalar (global ``max|f'(u)|`` reduction between steps), and under
        a mesh the kernel runs shard-local with ppermute ghost refresh
        between stages (the tuned kernel under MPI,
        ``MultiGPU/Burgers3d_Baseline/main.c:189-317``; x-sharded
        meshes switch to the stored-x-ghost layout, PARITY.md). In 2-D
        the single-chip path is the whole-run VMEM stepper (adaptive dt
        via an in-core reduction per step); under a mesh the per-stage
        whole-shard kernels take over with the same ghost-refresh
        choreography (``MultiGPU/Burgers2d_Baseline/main.c:186+``).

        3-D *fixed-dt* ``impl='pallas'`` prefers the slab-pipelined
        whole-run stepper where its model says it wins (the WENO stages
        are VPU-bound, so the redundant-recompute tax usually loses at
        depth — the model mostly keeps the per-stage path on large
        grids); ``impl='pallas_slab'`` pins it, ``'pallas_stage'`` pins
        per-stage. Adaptive dt needs a between-step global reduction the
        whole-run grid cannot host, and ``mode="t_end"`` needs run_to —
        both keep the per-stage stepper."""
        import jax.numpy as jnp

        from multigpu_advectiondiffusion_tpu.ops import is_fused_impl

        cfg = self.cfg
        self._fused_fallback = None
        if not is_fused_impl(cfg.impl):
            return self._decline(f"impl={cfg.impl!r} does not request fusion")
        if self.grid.ndim not in (2, 3):
            return self._decline("fused WENO kernels are 2-D/3-D only")
        fused_orders = {(5, "js"), (5, "z"), (7, "js")}
        if (cfg.weno_order, cfg.weno_variant) not in fused_orders:
            return self._decline(
                "fused kernels implement WENO5-JS/Z and WENO7-JS only"
            )
        if cfg.integrator != "ssp_rk3":
            return self._decline("fused kernels bake in SSP-RK3")
        if cfg.nu != 0.0 and cfg.laplacian_order != 4:
            return self._decline("fused viscous term is the O4 Laplacian")
        if self.dtype != jnp.float32:
            return self._decline("fused kernels are float32-only")
        # precision='bf16' (ISSUE 16): Burgers' only fused bf16 rung is
        # the whole-run slab stepper (its step_fn wraps the f32 WENO
        # stages around a bf16-resident grid). The per-stage kernel
        # computes in the buffer dtype with adaptive-dt SMEM machinery —
        # no split-dtype path — so anything that can't ride the slab
        # declines loudly to the compensated generic XLA rung.
        bf16_store = self._precision_mode() == "bf16"
        if bf16_store and self.grid.ndim != 3:
            return self._decline(
                "precision='bf16' Burgers rides the 3-D slab stepper "
                "(or the generic path); 2-D has no split-dtype rung"
            )
        if bf16_store and cfg.adaptive_dt:
            return self._decline(
                "precision='bf16' Burgers needs --fixed-dt: the "
                "adaptive-dt per-stage stepper has no split-dtype "
                "machinery"
            )
        if not all(b.kind == "edge" for b in self.bcs):
            return self._decline("fused ghost discipline needs edge BCs")
        lshape = (
            self.grid.shape
            if self.mesh is None
            else self.decomp.local_shape(self.mesh, self.grid.shape)
        )
        if self.grid.ndim == 3:
            from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (  # noqa: E501
                FusedBurgersStepper as cls,
            )
            from multigpu_advectiondiffusion_tpu.ops.weno import HALO

            halo = HALO[cfg.weno_order]
            # every sharded axis must serve the stencil halo from its core
            if self.mesh is not None and any(
                lshape[ax] < halo for ax, _ in self.decomp.axes
            ):
                return self._decline(
                    f"a sharded axis is thinner than the WENO{cfg.weno_order}"
                    f" halo ({halo})"
                )
            # an x-sharded mesh switches the stepper to the stored-x-ghost
            # layout (interior at lane offset halo) so the ppermute
            # refresh has real ghost lanes to rewrite — the lane-aligned
            # default stores none (fused_burgers._x_widths; priced in
            # PARITY.md). y-rounding is incompatible only with a
            # y-sharded axis (dead columns would be exchanged as
            # neighbor ghosts). _sharded_axes filters out extent-1 mesh
            # axes, which exchange nothing and trip neither gate.
            sharded_axes = self._sharded_axes()
            x_sharded = 2 in sharded_axes
            y_sharded = 1 in sharded_axes
            if not cls.supported(lshape, self.dtype, y_sharded=y_sharded,
                                 order=cfg.weno_order, x_sharded=x_sharded):
                return self._decline(
                    "no viable VMEM block tiling for this local shape"
                )
        elif self.mesh is None:
            from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (  # noqa: E501
                FusedBurgers2DStepper as cls,
            )
            if not cls.supported(lshape, self.dtype,
                                 order=cfg.weno_order):
                return self._decline(
                    "2-D grid exceeds the whole-run VMEM budget"
                )
        else:
            # the 2-D tuned kernel under the mesh: per-stage whole-shard
            # kernels with ppermute ghost refresh between stages
            # (MultiGPU/Burgers2d_Baseline/main.c:186+)
            from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (  # noqa: E501
                ShardedFusedBurgers2DStepper as cls,
            )
            from multigpu_advectiondiffusion_tpu.ops.weno import HALO

            halo = HALO[cfg.weno_order]
            if any(
                lshape[ax] < halo for ax, _ in self.decomp.axes
            ):
                return self._decline(
                    f"a sharded axis is thinner than the WENO"
                    f"{cfg.weno_order} halo ({halo})"
                )
            if not cls.supported(lshape, self.dtype,
                                 order=cfg.weno_order):
                return self._decline(
                    "2-D shard exceeds the per-stage VMEM budget"
                )
        slab = self._select_slab(mode, lshape)
        if slab is not None:
            return slab
        if bf16_store:
            return self._decline(
                "precision='bf16' Burgers engages only the slab "
                "whole-run rung (per-stage WENO has no split-dtype "
                "machinery); the slab declined for this config"
            )
        if "fused" not in self._cache:
            spacing = self.grid.spacing
            kwargs = {}
            if self.grid.ndim == 3:
                kwargs["order"] = cfg.weno_order
                if self.mesh is not None:
                    kwargs["global_shape"] = self.grid.shape
                    kwargs["y_sharded"] = y_sharded
                    kwargs["x_sharded"] = x_sharded
                    kwargs["overlap_split"] = self._split_overlap_requested()
                if cfg.adaptive_dt:
                    from multigpu_advectiondiffusion_tpu.timestepping.cfl import (  # noqa: E501
                        dt_from_wave_speed,
                        max_wave_speed,
                    )

                    reduce = self.mesh_reduce_max()
                    kwargs["dt_fn"] = lambda u: advective_dt(
                        u, self.flux.df, spacing, cfg.cfl, reduce_max=reduce
                    )
                    # in-kernel emitted max: the final stage folds
                    # max|f'(u_next)| so the CFL for the next step needs
                    # no HBM re-read; wave_fn seeds the first step
                    # (local max — dt_from_max applies the pmax)
                    kwargs["dt_from_max"] = lambda m: dt_from_wave_speed(
                        m, spacing, cfg.cfl, reduce_max=reduce
                    )
                    kwargs["wave_fn"] = lambda u: max_wave_speed(
                        u, self.flux.df
                    )
                else:
                    kwargs["dt"] = self.dt
                self._cache["fused"] = cls(
                    lshape, self.dtype, spacing, self.flux,
                    cfg.weno_variant, cfg.nu, **kwargs,
                )
            else:
                kwargs["order"] = cfg.weno_order
                if self.mesh is not None:
                    kwargs["global_shape"] = self.grid.shape
                    kwargs["overlap_split"] = self._split_overlap_requested()
                if cfg.adaptive_dt:
                    if self.mesh is not None:
                        # interior-view reduction + lax.pmax between steps
                        reduce = self.mesh_reduce_max()
                        kwargs["dt_fn"] = lambda u: advective_dt(
                            u, self.flux.df, spacing, cfg.cfl,
                            reduce_max=reduce,
                        )
                    else:
                        # in-core reduction on the padded state: ghost/
                        # slack cells are edge replicas, so the full-array
                        # max equals the interior max (whole_run_adaptive)
                        kwargs["dt_fn"] = lambda u: advective_dt(
                            u, self.flux.df, spacing, cfg.cfl
                        )
                else:
                    kwargs["dt"] = self.dt
                self._cache["fused"] = cls(
                    lshape, self.dtype, spacing, self.flux,
                    cfg.weno_variant, cfg.nu, **kwargs,
                )
        return self._cache["fused"]

    def _select_slab(self, mode, lshape):
        """The slab-pipelined whole-run stepper when this fixed-dt 3-D
        config should engage it, else ``None`` (per-stage selection
        proceeds). Shared eligibility (orders, BCs, dtype, halo checks)
        has already passed when this runs. ``steps_per_exchange > 1``
        pins the slab rung (the k-step communication-avoiding schedule
        lives nowhere else) and turns every decline below into a hard
        error instead of a silent per-stage fallback."""
        import jax.numpy as jnp

        cfg = self.cfg
        k = int(getattr(cfg, "steps_per_exchange", 1) or 1)
        dma = self._exchange_mode() == "dma"
        # precision='bf16': the slab rung is Burgers' only fused bf16
        # path, so bf16 skips the profitability model (engage where
        # supported; declines stay soft and fall to the generic rung)
        bf16_store = self._precision_mode() == "bf16"
        kernel_dtype = (
            jnp.dtype(jnp.bfloat16) if bf16_store else self.dtype
        )
        pinned = cfg.impl == "pallas_slab" or k > 1 or dma or bf16_store

        def decline(reason):
            if dma:
                raise ValueError(
                    f"exchange='dma' needs the sharded slab rung: "
                    f"{reason}"
                )
            if k > 1:
                raise ValueError(
                    f"steps_per_exchange={k} needs the sharded slab "
                    f"rung: {reason}"
                )
            return None

        if self.grid.ndim != 3 or cfg.impl not in ("pallas", "pallas_slab"):
            return None  # k > 1 / dma on these configs: rejected at __init__
        if mode == "t_end":
            return decline("the slab stepper has no run_to (use --iters)")
        if cfg.adaptive_dt:
            # adaptive dt needs the between-step global reduction only
            # the per-stage loop hosts
            return decline("adaptive dt rides the per-stage stepper")
        from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
            SlabRunBurgersStepper as slab_cls,
        )
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        G = 3 * HALO[cfg.weno_order]
        if self.mesh is not None:
            if not pinned:
                return None
            if any(ax != 0 for ax in self._sharded_axes()):
                return decline("z-slab decompositions only")
            if dma and not self._dma_backend_ok():
                import jax as _jax

                return decline(
                    "in-kernel remote DMA needs the TPU backend (or "
                    "the CPU interpret simulator); backend="
                    f"{_jax.default_backend()!r}"
                )
        if not slab_cls.supported(lshape, kernel_dtype,
                                  order=cfg.weno_order):
            return decline("local shape exceeds the slab VMEM budget")
        if not pinned and not slab_cls.profitable(
            lshape, kernel_dtype, order=cfg.weno_order
        ):
            return None
        if self.mesh is not None and lshape[0] < k * G:
            # shard too thin to serve the k*G-deep exchange
            return decline(
                f"local z extent {lshape[0]} cannot serve the "
                f"{k * G}-deep exchange"
            )
        if "fused_slab" not in self._cache:
            kwargs = {"order": cfg.weno_order}
            if self.mesh is not None:
                kwargs["global_shape"] = self.grid.shape
                kwargs["overlap_split"] = (
                    not dma and self._split_overlap_requested()
                )
                if k > 1:
                    kwargs["steps_per_exchange"] = k
                if dma:
                    kwargs.update(self._dma_stepper_kwargs())
            if jnp.dtype(kernel_dtype) != jnp.dtype(self.dtype):
                kwargs["storage_dtype"] = self.dtype
            self._cache["fused_slab"] = slab_cls(
                lshape, kernel_dtype, self.grid.spacing, self.flux,
                cfg.weno_variant, cfg.nu, dt=self.dt, **kwargs,
            )
        return self._cache["fused_slab"]


# --------------------------------------------------------------------- #
# Registration: the family as a declarative plugin descriptor
# (models/registry.py; the CLI, tuner, cost model, bench matrix and
# static verifiers resolve the family through this spec)
# --------------------------------------------------------------------- #
def _cli_configure(p, ndim):
    p.add_argument("--flux", default="burgers",
                   choices=["burgers", "linear", "buckley"])
    p.add_argument("--weno-order", type=int, default=5, choices=[5, 7])
    p.add_argument("--weno-variant", default="js", choices=["js", "z"])
    p.add_argument("--cfl", type=float, default=0.4)
    p.add_argument("--nu", type=float, default=0.0,
                   help="viscosity (1e-5 in SingleGPU Burgers)")
    p.add_argument("--fixed-dt", action="store_true",
                   help="reference-parity dt = CFL*dx (hard-coded "
                        "max|u|=1, Burgers3d_Baseline/main.c:193)")


def _cli_build(args, grid, ndim):
    from multigpu_advectiondiffusion_tpu.models.registry import resolve_bc

    return BurgersConfig(
        grid=grid,
        flux=args.flux,
        weno_order=args.weno_order,
        weno_variant=args.weno_variant,
        cfl=args.cfl,
        nu=args.nu,
        adaptive_dt=not args.fixed_dt,
        integrator=args.integrator,
        dtype=args.dtype,
        ic=args.ic or "gaussian",
        bc=resolve_bc(args, "edge"),
        impl=args.impl,
        overlap=args.overlap,
        steps_per_exchange=args.steps_per_exchange,
        exchange=args.exchange,
        precision=getattr(args, "precision", "native"),
    )


def _stage_radius(cfg) -> int:
    """Fused per-stage stencil radius (tuner ghost depth = 3h): the
    WENO reconstruction halo of the configured order."""
    from multigpu_advectiondiffusion_tpu.ops.weno import HALO

    return HALO[getattr(cfg, "weno_order", 5)]


def _key_extras(cfg):
    return [
        f"weno={cfg.weno_order}-{cfg.weno_variant}",
        f"adaptive={bool(cfg.adaptive_dt)}",
        f"viscous={bool(getattr(cfg, 'nu', 0.0))}",
    ]


def _cost_kwargs(cfg):
    return {
        "weno_order": getattr(cfg, "weno_order", 5),
        "viscous": bool(getattr(cfg, "nu", 0.0)),
    }


def _bench_build(grid, dtype, impl, case):
    return BurgersConfig(
        grid=grid,
        weno_order=getattr(case, "weno_order", 5),
        cfl=0.4,
        adaptive_dt=not getattr(case, "fixed_dt", True),
        nu=getattr(case, "nu", 0.0),
        dtype=dtype,
        ic="gaussian",
        impl=impl,
    )


from multigpu_advectiondiffusion_tpu.models.registry import (  # noqa: E402
    ModelSpec,
    register_model,
)

register_model(ModelSpec(
    name="burgers",
    config_cls=BurgersConfig,
    solver_cls=BurgersSolver,
    description="scalar conservation law u_t + div f(u) = nu lap(u), "
                "WENO5/7 + Lax–Friedrichs",
    check_error=False,
    cli_configure=_cli_configure,
    cli_build=_cli_build,
    stage_radius=_stage_radius,
    key_extras=_key_extras,
    cost_kwargs=_cost_kwargs,
    bench_build=_bench_build,
))
