"""Shared solver machinery: one step definition, two execution worlds.

Each concrete solver implements :meth:`build_local` — the shard-local
physics (RHS, dt rule, post-step fix-up) expressed against a
:class:`StepContext`. The base class then runs that same definition either

* single-device: plain ``jit``, ghost cells from BC padding; or
* sharded: ``jit(shard_map(...))`` over a ``jax.sharding.Mesh``, ghost
  cells from ``ppermute`` halo exchanges, reductions via ``lax.pmax``.

This replaces the reference's split between the SingleGPU drivers and the
MPI drivers (``SingleGPU/*/main.cpp`` vs ``MultiGPU/*/main.c``), which
duplicate the whole time loop to add communication. The entire time loop
(``lax.fori_loop`` / ``lax.while_loop``) lives *inside* one jit — and, when
sharded, inside one ``shard_map`` — so XLA sees the full program and can
overlap halo collectives with interior compute (the reference builds this
overlap by hand with five CUDA streams, ``main.c:189-303``).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.core.dtypes import canonicalize
from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.state import (
    EnsembleState,
    SolverState,
)
from multigpu_advectiondiffusion_tpu.ops.stencils import Padder
from multigpu_advectiondiffusion_tpu.ops.stencils import slice_axis
from multigpu_advectiondiffusion_tpu.parallel.halo import (
    axis_offsets,
    exchange_ghosts,
    make_ghost_fn,
    make_ghost_refresh,
    make_padder,
)
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    axis_extent,
    reduce_axis_names,
    shard_map,
)
from multigpu_advectiondiffusion_tpu.timestepping.integrators import INTEGRATORS
from multigpu_advectiondiffusion_tpu.utils.ic import initial_condition


def _consume_donated(*arrays) -> None:
    """Enforce donation semantics on EVERY backend (ISSUE 19).

    After a donated dispatch the input state is dead: XLA:TPU/GPU alias
    its buffer into the output (the in-place HBM update donation buys),
    but XLA:CPU implements no donation and would silently keep the
    input alive — a reuse-after-donate bug would then pass the CPU
    tier-1 suite and corrupt data on the accelerator. The dispatch
    layer therefore deletes the donated operands itself, so ANY later
    use raises jax's loud "Array has been deleted" RuntimeError
    identically on every backend. PJRT defers the actual free until
    in-flight computations drop their usage holds, so deleting right
    after the (async) dispatch is safe."""
    for arr in arrays:
        delete = getattr(arr, "delete", None)
        if delete is None:
            continue  # tracer/numpy operand: nothing to consume
        is_deleted = getattr(arr, "is_deleted", None)
        if is_deleted is not None and is_deleted():
            continue
        delete()


@dataclasses.dataclass
class StepContext:
    """What the shard-local physics may depend on."""

    padder: Padder
    offsets: Sequence  # global index offset of this block, per axis
    local_shape: Tuple[int, ...]
    global_shape: Tuple[int, ...]
    reduce_max: Callable[[jnp.ndarray], jnp.ndarray]
    # (lo, hi) ghost slabs for sharded axes (None per-axis when local;
    # None entirely when unsharded) — enables the overlapped
    # interior/boundary schedule (ops.stencils.split_axis_apply)
    ghost_fn: Optional[Callable] = None


@dataclasses.dataclass
class LocalPhysics:
    """Product of :meth:`SolverBase.build_local`."""

    rhs: Callable[[jnp.ndarray], jnp.ndarray]
    dt_fn: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None  # None -> static
    static_dt: Optional[float] = None
    post: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None


class SolverBase:
    def __init__(self, cfg, mesh=None, decomp: Decomposition | None = None):
        self.cfg = cfg
        self.mesh = mesh
        self.decomp = decomp
        if mesh is not None and decomp is None:
            self.decomp = Decomposition.slab(tuple(mesh.shape)[0])
        if mesh is not None:
            self.decomp.validate(mesh, cfg.grid.shape)
        self.dtype = canonicalize(cfg.dtype)
        self._cache = {}
        # kernel-ladder degradation bookkeeping: the impl the user asked
        # for (engaged_path reports it even after a downgrade swapped
        # cfg.impl) and the downgrade events themselves
        self._requested_impl = getattr(cfg, "impl", "xla")
        self._degrade_events = []
        # measured introspection (telemetry/xprof.py): one ExecRecord
        # per compiled executable, appended at first call — survives
        # _cache.clear() (records are history, not dispatch state)
        self._xla_records = []
        self._tuned = None
        if self._requested_impl == "auto":
            # measured dispatch: the tuner resolves (rung, k) per
            # (solver, shape, dtype, mesh, backend) key from its
            # persisted decision cache, measuring candidates on a miss
            # when tuning is enabled (tuning.configure / --tune); the
            # concrete rung replaces cfg.impl before any dispatch runs
            from multigpu_advectiondiffusion_tpu import tuning

            decision = tuning.resolve(type(self), cfg, mesh, self.decomp)
            self._tuned = decision
            self.cfg = cfg = dataclasses.replace(
                cfg,
                impl=decision["impl"],
                steps_per_exchange=decision.get("steps_per_exchange", 1),
                exchange=decision.get("exchange", "collective"),
            )
        self._validate_steps_per_exchange()
        self._validate_exchange()
        self._validate_precision()

    def _validate_steps_per_exchange(self) -> None:
        """Gate the communication-avoiding chunk knob the way impl
        strings are gated (``ops.IMPLS``): a config that cannot honor
        ``steps_per_exchange > 1`` fails at construction instead of
        silently running the per-step exchange cadence. Deeper
        eligibility (VMEM fit, dtype, adaptive dt, shard thickness) is
        enforced at dispatch by ``_select_slab``, which raises rather
        than declines when k > 1."""
        k = int(getattr(self.cfg, "steps_per_exchange", 1) or 1)
        if k == 1:
            return
        if self.grid.ndim != 3:
            raise ValueError(
                "steps_per_exchange > 1 rides the 3-D slab stepper only"
            )
        if self.mesh is None:
            raise ValueError(
                "steps_per_exchange > 1 needs a device mesh — it trades "
                "deeper halo exchanges for fewer of them"
            )
        if any(ax != 0 for ax in self._sharded_axes()):
            raise ValueError(
                "steps_per_exchange > 1 serves z-slab decompositions only"
            )
        if self.cfg.impl not in ("pallas", "pallas_slab"):
            raise ValueError(
                f"steps_per_exchange={k} needs the sharded slab rung "
                f"(impl='pallas'/'pallas_slab'/'auto'), not "
                f"impl={self.cfg.impl!r}"
            )

    def _exchange_mode(self) -> str:
        return str(getattr(self.cfg, "exchange", "collective")
                   or "collective")

    def _validate_exchange(self) -> None:
        """Gate the halo-exchange transport knob the way impl strings
        and ``steps_per_exchange`` are gated: a config that cannot
        honor ``exchange='dma'`` (the in-kernel remote-DMA whole-run
        rung) fails at construction instead of silently running the
        XLA collective cadence. Backend/VMEM eligibility is enforced
        at dispatch by ``_select_slab``, which raises rather than
        declines when dma is requested."""
        if self._exchange_mode() != "dma":
            return
        if self.grid.ndim != 3:
            raise ValueError(
                "exchange='dma' rides the 3-D sharded slab rung only"
            )
        if self.mesh is None:
            raise ValueError(
                "exchange='dma' pushes ghost rows between z neighbors "
                "— it needs a device mesh (an unsharded run has no "
                "neighbor to push to)"
            )
        if any(ax != 0 for ax in self._sharded_axes()):
            raise ValueError(
                "exchange='dma' serves z-slab decompositions only"
            )
        if self.cfg.impl not in ("pallas", "pallas_slab"):
            raise ValueError(
                "exchange='dma' needs the sharded slab rung "
                "(impl='pallas'/'pallas_slab'/'auto'), not "
                f"impl={self.cfg.impl!r}"
            )
        if getattr(self.cfg, "overlap", None) == "split":
            raise ValueError(
                "exchange='dma' replaces the XLA exchange entirely — "
                "the split-overlap schedule does not compose with it "
                "(drop overlap='split')"
            )
        name = self.decomp.mesh_axis(0)
        if not isinstance(name, str):
            raise ValueError(
                "exchange='dma' cannot ride a compound (multihost) "
                "mesh axis — remote DMA moves over ICI, not DCN"
            )
        if len(dict(self.mesh.shape)) != 1:
            raise ValueError(
                "exchange='dma' serves single-axis z-slab meshes: the "
                "remote-DMA ring addresses logical device ids along "
                "ONE mesh axis"
            )
        if jax.process_count() > 1:
            raise ValueError(
                "exchange='dma' is single-process (ICI) only — "
                "multihost z layouts keep the collective exchange"
            )

    def _precision_mode(self) -> str:
        return str(getattr(self.cfg, "precision", "native") or "native")

    def _validate_precision(self) -> None:
        """Gate the low-precision-storage knob the way impl strings and
        ``exchange`` are gated: a config that cannot honor
        ``precision='bf16'`` (the bf16-storage / f32-compute bandwidth
        rung, ISSUE 16) fails at construction instead of silently
        running native storage. The rung stores the run-resident state
        (HBM buffers, halo/remote-DMA wires) in bfloat16 while every
        stencil tap and RK stage computes in float32; the generic-XLA
        loop additionally carries a bf16 compensation term (hi/lo
        split) so long-horizon error stays bounded
        (``core.dtypes.bf16_carry_enabled``)."""
        from multigpu_advectiondiffusion_tpu.core.dtypes import (
            bf16_carry_enabled,
        )

        mode = self._precision_mode()
        if mode == "native":
            self._bf16_carry = False
            return
        if mode != "bf16":
            raise ValueError(
                f"unknown precision {mode!r}; use 'native' or 'bf16'"
            )
        if self.dtype == jnp.bfloat16:
            raise ValueError(
                "precision='bf16' with dtype='bfloat16' is redundant — "
                "the knob downcasts a float32 compute state to bf16 "
                "storage; the all-bf16 compute experiment remains the "
                "separate dtype='bfloat16' opt-in"
            )
        if self.dtype != jnp.float32:
            raise ValueError(
                "precision='bf16' stores a float32 compute state in "
                f"bfloat16; cfg.dtype must be float32, got {self.dtype}"
            )
        self._bf16_carry = bf16_carry_enabled()
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.event(
            "precision", "engage",
            storage_dtype="bfloat16", compute_dtype="float32",
            carry=bool(self._bf16_carry),
        )

    @property
    def storage_dtype(self):
        """The dtype the run-resident state occupies in HBM and on
        every halo/remote-DMA wire under the engaged configuration —
        the itemsize the cost model prices HBM passes with and the
        tuner/AOT keys fingerprint. Equals :attr:`dtype` except under
        ``precision='bf16'``."""
        if self._precision_mode() == "bf16":
            return jnp.dtype(jnp.bfloat16)
        return self.dtype

    # -- bf16-storage generic-loop plumbing (precision='bf16') -------- #
    def _bf16_pack(self, u):
        """Facing f32 state -> the loop-resident bf16 representation:
        ``(hi,)`` (plain downcast) or ``(hi, lo)`` with the Kahan-style
        compensation term ``lo = bf16(u - f32(hi))`` when the carry is
        armed. ``bf16(u) == hi`` exactly, so a wire transfer of the
        reconstructed state truncated to bf16 transmits precisely
        ``hi`` — the carry never doubles halo bytes."""
        hi = u.astype(jnp.bfloat16)
        if not self._bf16_carry:
            return (hi,)
        lo = (u - hi.astype(u.dtype)).astype(jnp.bfloat16)
        return (hi, lo)

    def _bf16_unpack(self, packed):
        """Inverse of :meth:`_bf16_pack`: reconstruct the f32 compute
        state from the stored representation (``f32(hi) [+ f32(lo)]``).
        Without the carry, small-dt increments round away entirely at
        the bf16 ulp — the stall the compensation exists to prevent
        (tests/test_precision.py proves both directions)."""
        u = packed[0].astype(self.dtype)
        if len(packed) > 1:
            u = u + packed[1].astype(self.dtype)
        return u

    @staticmethod
    def _dma_backend_ok() -> bool:
        """Whether this process can execute the in-kernel remote-DMA
        program: the Mosaic TPU target, or the CPU backend's interpret
        simulator (which models the remote copies — the tier-1 test
        surface). GPU has neither."""
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
            interpret_mode,
        )

        return jax.default_backend() == "tpu" or interpret_mode()

    def _dma_stepper_kwargs(self) -> dict:
        """Constructor kwargs arming a slab stepper's in-kernel
        exchange: the (validated, single, string) z mesh axis and its
        shard count."""
        sizes = dict(self.mesh.shape)
        name = self.decomp.mesh_axis(0)
        return {
            "exchange": "dma",
            "mesh_axis": name,
            "num_shards": axis_extent(sizes, name),
        }

    # ------------------------------------------------------------------ #
    # To be provided by subclasses
    # ------------------------------------------------------------------ #
    def build_local(self, ctx: StepContext, overrides=None) -> LocalPhysics:
        """Shard-local physics. ``overrides`` (ensemble mode only) maps
        member-varying scalar names — the keys of
        :meth:`ensemble_operands` — to *traced* 0-d values that must
        enter the step as operands, not closure constants: the batched
        dispatch vmaps one compiled program over the member axis, so a
        per-member diffusivity/CFL arrives here as a traced scalar."""
        raise NotImplementedError

    def ensemble_operands(self) -> dict:
        """The member-varying scalar contract of the batched ensemble
        engine: ``{name: default}`` for every scalar
        :meth:`build_local` can take as a traced override. The base
        class supports none — ensembles of such solvers vary initial
        conditions only."""
        return {}

    def diagnostics_spec(self) -> dict:
        """Per-solver in-situ physics-diagnostics contract
        (``diagnostics/physics.py``). Optional keys:

        * ``observables`` — extra :class:`~.diagnostics.physics.
          Observable` entries fused into the sentinel's jitted probe
          beyond the standard suite (budgets/TV/spectral tail);
        * ``rules`` — :class:`~.diagnostics.physics.ViolationRule`
          tolerance checks of the probed stats against the run-initial
          baseline (max-principle, TV-monotonicity, ...);
        * ``meta`` — fields riding every ``phys:diag`` event (e.g. the
          analytic decay rate the trace analyzer fits against).

        The base class registers nothing: every solver still gets the
        standard suite; overrides add what their physics guarantees."""
        return {}

    def stencil_spec(self) -> dict:
        """Family-level stencil metadata — part of the solver-plugin
        registration contract (``models/registry.
        REQUIRED_SOLVER_CONTRACT``; the steppers' per-instance
        ``stencil_spec`` remains the halo verifier's per-rung source).
        Expected keys: ``stage_radius`` (the max of the advective and
        diffusive tap reaches) plus per-term radii. The base class
        declares nothing — REGISTERED solvers must override (enforced
        at ``register_model`` and by the ``registry-completeness``
        lint rule); ad-hoc unregistered subclasses may ignore it."""
        return {}

    def cfl_rule(self) -> dict:
        """Queryable time-step contract — part of the registration
        contract: what rule produced this solver's dt (``kind`` plus
        ``dt``/``cfl``/``safety`` as applicable). Base declares
        nothing; registered solvers must override (same enforcement as
        :meth:`stencil_spec`)."""
        return {}

    # ------------------------------------------------------------------ #
    # Config plumbing
    # ------------------------------------------------------------------ #
    @property
    def grid(self) -> Grid:
        return self.cfg.grid

    @property
    def bcs(self) -> Tuple[Boundary, ...]:
        spec = self.cfg.bc
        if isinstance(spec, (list, tuple)):
            out = tuple(Boundary.parse(s) for s in spec)
            if len(out) != self.grid.ndim:
                raise ValueError("per-axis bc list rank mismatch")
            return out
        return (Boundary.parse(spec),) * self.grid.ndim

    @property
    def integrator(self):
        return INTEGRATORS[self.cfg.integrator]

    def sharding(self):
        if self.mesh is None:
            return None
        return self.decomp.sharding(self.mesh, self.grid.ndim)

    def mesh_reduce_max(self):
        """Cross-device max reduction for this solver's mesh (identity
        when unsharded / all extents 1). Must run inside ``shard_map``.
        The pmax axis-name set comes from the ONE
        ``parallel.mesh.reduce_axis_names`` source — the generic step,
        the fused steppers' adaptive dt AND the static sharding pass
        (``analysis/collective_verify``) must agree exactly."""
        if self.mesh is None:
            return None
        names = reduce_axis_names(self.decomp, self.mesh.shape)
        if not names:
            return None
        return lambda x: lax.pmax(x, names)

    def mesh_reduce_sum(self):
        """Cross-device sum reduction over the same axis-name set as
        :meth:`mesh_reduce_max` (the physics probe's mass/L2 integrals
        must span exactly the shards the divergence probe spans). Must
        run inside ``shard_map``; ``None`` when unsharded."""
        if self.mesh is None:
            return None
        names = reduce_axis_names(self.decomp, self.mesh.shape)
        if not names:
            return None
        return lambda x: lax.psum(x, names)

    # ------------------------------------------------------------------ #
    # State creation
    # ------------------------------------------------------------------ #
    def ic_spec(self):
        """IC name and default params; subclasses override to thread config
        (e.g. diffusivity/t0) into parameterized ICs."""
        return self.cfg.ic, {}

    def initial_state(self, t: float | None = None) -> SolverState:
        name, defaults = self.ic_spec()
        params = {**defaults, **dict(self.cfg.ic_params)}
        u0 = initial_condition(name, self.grid, dtype=self.dtype, **params)
        if self.mesh is not None:
            sharding = self.sharding()
            if jax.process_count() > 1:
                # multi-process: device_put onto a global sharding runs a
                # consistency collective some backends (CPU) can't host —
                # assemble the global array from each process's
                # addressable shards instead (the IC is computed globally
                # on every host, as the reference computes its IC on
                # every rank, main.c:112-130)
                u0 = jax.make_array_from_callback(
                    u0.shape, sharding, lambda idx: u0[idx]
                )
            else:
                u0 = jax.device_put(u0, sharding)
        t0 = t if t is not None else getattr(self.cfg, "t0", 0.0)
        return SolverState.create(u0, t=t0)

    # ------------------------------------------------------------------ #
    # Shard-local step assembly
    # ------------------------------------------------------------------ #
    def _context(self, u: jnp.ndarray) -> StepContext:
        gshape = self.grid.shape
        if self.mesh is None:
            return StepContext(
                padder=lambda x, axis, halo: pad_axis(x, axis, halo, self.bcs[axis]),
                offsets=[0] * self.grid.ndim,
                local_shape=gshape,
                global_shape=gshape,
                reduce_max=lambda x: x,
            )
        sizes = dict(self.mesh.shape)
        reduce = self.mesh_reduce_max()
        lshape = self.decomp.local_shape(self.mesh, gshape)
        # precision='bf16': ghost slabs cross the wire at the declared
        # storage dtype (half the bytes); the interior stays f32
        wire = (
            jnp.bfloat16 if self._precision_mode() == "bf16" else None
        )
        return StepContext(
            padder=make_padder(self.decomp, sizes, self.bcs,
                               wire_dtype=wire),
            offsets=axis_offsets(self.decomp, lshape),
            local_shape=lshape,
            global_shape=gshape,
            reduce_max=reduce if reduce is not None else (lambda x: x),
            ghost_fn=make_ghost_fn(self.decomp, sizes, self.bcs,
                                   wire_dtype=wire),
        )

    def _local_step(self, u, t, t_end=None, overrides=None):
        """One time step on a (possibly shard-local) block.
        ``overrides`` threads member-varying traced scalars into
        :meth:`build_local` (ensemble dispatch only)."""
        # named_scope: the generic step shows up as one labeled region
        # in --trace captures, matching the fused steppers' spans
        with jax.named_scope("tpucfd.step"):
            phys = self.build_local(self._context(u), overrides=overrides)
            dt = phys.dt_fn(u) if phys.dt_fn is not None else phys.static_dt
            if t_end is not None:
                dt = jnp.minimum(dt, t_end - t)
            dt = jnp.asarray(dt, dtype=t.dtype)
            u = self.integrator(phys.rhs, u, dt.astype(u.dtype), phys.post)
            return u, t + dt

    # ------------------------------------------------------------------ #
    # Execution: wrap a (u, t) -> (u, t) block program for this world
    # ------------------------------------------------------------------ #
    def _wrap(self, fn, n_out_scalars: int = 1, n_in_scalars: int = 1,
              check: bool | None = None):
        """Jit a block program ``(u, *scalars) -> (u, *scalars)``;
        sharded, the field follows the decomposition spec and scalars
        are replicated.

        The replication/vma checker stays on except for Pallas-flavored
        configs (whose ``pallas_call`` outputs carry no vma typing) and
        blocks that force ``check=False`` — jax ships no replication
        rule for ``lax.while_loop``, so the generic ``advance_to`` loop
        cannot be checked on any impl."""
        from multigpu_advectiondiffusion_tpu.ops import is_pallas_impl

        # opt-in checkify sanitizer (--checkify, analysis/sanitizer.py):
        # the block program compiles with NaN/div0/OOB checks discharged
        # in, and a trip surfaces as SanitizerError through the
        # supervisor's existing rollback path. Single-device only —
        # shard_map carries no checkify rule, so a meshed config fails
        # loudly here (pin semantics) instead of silently unchecked.
        from multigpu_advectiondiffusion_tpu.analysis import sanitizer

        if sanitizer.enabled():
            if self.mesh is not None:
                raise ValueError(
                    "--checkify instruments single-device programs; "
                    "shard_map carries no checkify rule — run unsharded "
                    "or drop --checkify"
                )
            return sanitizer.checked_jit(fn)
        if self.mesh is None:
            return jax.jit(fn)
        if check is None:
            check = not is_pallas_impl(getattr(self.cfg, "impl", ""))
        spec = self.decomp.partition_spec(self.grid.ndim)
        return jax.jit(
            shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(spec,) + (P(),) * n_in_scalars,
                out_specs=(spec,) + (P(),) * n_out_scalars,
                check=check,
            )
        )

    def _compiled(self, key, builder, steps=None, donate=False):
        """One dispatch-cache entry per program. ``steps`` is the
        iteration count the program bakes in (None for data-dependent
        trip counts, e.g. the t_end while_loop) — threaded to the
        measured-introspection layer so the executable's XLA-reported
        bytes/FLOPs read against the per-step cost model. ``donate``
        marks a program compiled with its state operand donated (ISSUE
        19) — a DIFFERENT executable than the undonated build, so the
        bit separates the local cache entry and rides the AOT key."""
        if donate:
            key = (*key, "donated") if isinstance(key, tuple) else (
                key, "donated"
            )
        if key not in self._cache:
            from multigpu_advectiondiffusion_tpu import telemetry
            from multigpu_advectiondiffusion_tpu.telemetry import xprof

            sink = telemetry.get_sink()
            if sink.active:
                # rung-selection record: one event per program the
                # dispatch layer builds (the compile itself happens at
                # first call, inside the caller's span — where the
                # xprof wrapper captures the executable's cost/memory
                # analyses and compile seconds as an xla:cost event)
                sink.event(
                    "dispatch", "build",
                    key=str(key),
                    impl=getattr(self.cfg, "impl", "xla"),
                    requested_impl=self._requested_impl,
                )
            # persistent AOT executable cache (tuning/aot_cache.py):
            # when enabled, the introspection wrapper resolves this key
            # against the on-disk store before paying lower+compile
            from multigpu_advectiondiffusion_tpu.tuning import aot_cache

            aot_key = None
            if aot_cache.enabled():
                aot_key = aot_cache.dispatch_key(self, key, steps=steps,
                                                 donate=donate)
            self._cache[key] = xprof.wrap_dispatch(
                builder(), solver=self, key=str(key), steps=steps,
                aot_key=aot_key, donated=donate,
            )
        return self._cache[key]

    def _dispatch_span(self, op: str, mode: str = "iters", **fields):
        """Context labeling one public driver call with the engaged
        rung: a ``jax.profiler.TraceAnnotation`` (so ``--trace``
        captures show ``tpucfd.run[fused-whole-run-slab]``-style spans
        over the whole rung hierarchy) plus, when a telemetry sink is
        installed, a structured ``solver.<op>`` span carrying the
        engaged stepper/impl/overlap."""
        from multigpu_advectiondiffusion_tpu import telemetry
        from multigpu_advectiondiffusion_tpu.utils.profiling import annotate

        eng = self.engaged_path(mode=mode)
        stack = contextlib.ExitStack()
        stack.enter_context(annotate(f"tpucfd.{op}[{eng['stepper']}]"))
        sink = telemetry.get_sink()
        if sink.active:
            stack.enter_context(
                sink.span(
                    f"solver.{op}",
                    stepper=eng["stepper"],
                    impl=eng["impl"],
                    overlap=eng.get("overlap"),
                    **fields,
                )
            )
        return stack

    # ------------------------------------------------------------------ #
    # Graceful kernel-ladder degradation
    # ------------------------------------------------------------------ #
    def _with_ladder(self, call, mode: str = "iters"):
        """Execute ``call()`` (a public driver's body), falling down the
        kernel ladder on a Pallas/Mosaic compile or launch failure at
        dispatch: ``pallas_slab -> pallas_stage -> xla``.

        Only ``impl='pallas'`` (the best-*available* promise) degrades;
        an explicit rung pin (``pallas_slab``/``pallas_stage``/...) fails
        loudly — the user asked for that kernel, not a slower answer.
        Failures surfacing asynchronously after dispatch (a launch fault
        found at a later sync) propagate to the caller; the ladder
        guards the dispatch/compile point, where Mosaic rejections
        actually appear."""
        while True:
            try:
                return call()
            except Exception as exc:  # noqa: BLE001 — classifier filters
                if not self._degrade_after(exc, mode):
                    raise

    def _degrade_after(self, exc, mode: str) -> bool:
        """Record a downgrade and retarget ``cfg.impl`` one rung down;
        True if the caller should retry. The classifier keeps this
        narrow: only kernel-infrastructure failures under an auto
        (``impl='pallas'``) config degrade."""
        from multigpu_advectiondiffusion_tpu.resilience.errors import (
            is_kernel_failure,
        )

        if not is_kernel_failure(exc):
            return False
        if self._exchange_mode() == "dma":
            # the in-kernel remote-DMA rung has its own ladder: a
            # Mosaic rejection of the dma program degrades to the
            # split-overlap XLA exchange on the SAME rung/cadence —
            # same physics, same k-schedule, comm back between
            # compiled calls — rather than failing the run
            ev = {
                "from": "fused-whole-run-slab[dma]",
                "to": "fused-whole-run-slab[split]",
                "reason": f"{type(exc).__name__}: {exc}"[:300],
            }
            self._degrade_events.append(ev)
            from multigpu_advectiondiffusion_tpu import telemetry

            telemetry.event(
                "ladder", "degrade",
                **{"from": ev["from"], "to": ev["to"],
                   "reason": ev["reason"]},
            )
            self.cfg = dataclasses.replace(
                self.cfg, exchange="collective", overlap="split"
            )
            self._cache.clear()
            return True
        if self._requested_impl != "pallas":
            return False
        if int(getattr(self.cfg, "steps_per_exchange", 1) or 1) > 1:
            # the k-step schedule exists only on the slab rung: falling
            # down the ladder would silently drop the requested exchange
            # cadence — fail loudly instead (pin semantics)
            return False
        engaged = self.engaged_path(mode=mode)["stepper"]
        if engaged in ("generic-xla", "per-axis-pallas") and getattr(
            self.cfg, "impl", "xla"
        ) == "xla":
            return False  # already at the bottom of the ladder
        nxt = (
            "pallas_stage"
            if engaged == "fused-whole-run-slab"
            else "xla"
        )
        ev = {
            "from": engaged,
            "to": nxt,
            "reason": f"{type(exc).__name__}: {exc}"[:300],
        }
        self._degrade_events.append(ev)
        from multigpu_advectiondiffusion_tpu import telemetry

        # the downgrade is an attributable event, not just a summary
        # footnote: the stream shows WHEN the ladder fell and under what
        # error, ordered against the chunks around it
        telemetry.event(
            "ladder", "degrade",
            **{"from": ev["from"], "to": ev["to"], "reason": ev["reason"]},
        )
        self.cfg = dataclasses.replace(self.cfg, impl=nxt)
        self._cache.clear()
        return True

    # ------------------------------------------------------------------ #
    # Public drivers
    # ------------------------------------------------------------------ #
    def step(self, state: SolverState) -> SolverState:
        def call():
            with self._dispatch_span("step"):
                f = self._compiled(
                    "step", lambda: self._wrap(self._local_step), steps=1
                )
                u, t = f(state.u, state.t)
                return SolverState(u=u, t=t, it=state.it + 1)

        return self._with_ladder(call)

    def _fused_stepper(self, mode: str = "iters"):
        """Solver-specific fully-fused fast path, or ``None`` (generic).
        Overridden by solvers that have a fused Pallas stepper.

        ``mode`` mirrors the execution dispatch: the whole-run slab
        stepper has no ``run_to`` (its grid bakes the step count), so
        ``advance_to`` asks for the ``"t_end"`` selection and gets the
        per-stage stepper instead of a dead-end slab instance."""
        del mode
        return None

    def _decline(self, reason: str):
        """Record why the fused fast path was declined (read by
        :meth:`engaged_path`) and return ``None`` for the caller to
        propagate. Solvers call this at every eligibility exit.

        ``steps_per_exchange > 1`` turns every decline into a hard
        error: the k-step communication-avoiding schedule exists only on
        the sharded slab rung, so a config that falls off the fused
        ladder cannot honor the requested exchange cadence — pin
        semantics, like an undispatachable explicit rung pin."""
        self._fused_fallback = reason
        if int(getattr(self.cfg, "steps_per_exchange", 1) or 1) > 1:
            raise ValueError(
                "steps_per_exchange > 1 needs the sharded slab rung; "
                f"this config declined fusion: {reason}"
            )
        if self._exchange_mode() == "dma":
            raise ValueError(
                "exchange='dma' needs the sharded slab rung; "
                f"this config declined fusion: {reason}"
            )
        return None

    def _pallas_f32_gate(self, impl: str) -> str:
        """Route non-f32 dtypes off the per-axis Pallas kernels: they
        are f32-calibrated and Mosaic has no f64 vector path — a TPU
        run would fail in the compiler rather than fall back. The ONE
        definition both solvers' ``_op_impl`` use; the reason lands in
        :meth:`engaged_path`."""
        import jax.numpy as jnp

        if impl == "pallas" and self.dtype != jnp.float32:
            self._op_fallback = (
                "per-axis Pallas kernels are float32-only; XLA runs"
            )
            return "xla"
        return impl

    def engaged_path(self, mode: str = "iters") -> dict:
        """Which kernel strategy actually executes for this config.

        The reference's ``PrintSummary`` tells the user what ran
        (``MultiGPU/Diffusion3d_Baseline/Tools.c:255-269``); without this
        a ``--impl pallas`` config that fails fused eligibility would
        silently benchmark the generic path. Keys: ``impl`` (requested),
        ``stepper`` (what executes: ``fused-stage`` / ``fused-whole-run``
        / ``fused-step`` / ``per-axis-pallas`` / ``generic-xla``),
        ``overlap`` (sharded halo schedule actually in effect),
        ``fallback`` (reason the fused stepper was declined, or None),
        and — when the kernel ladder degraded after a Mosaic/Pallas
        dispatch failure — ``degraded``, the downgrade event list
        (from/to rung + failure text); absent on healthy runs.

        ``mode`` mirrors the execution dispatch: ``"t_end"`` engages the
        fused stepper only when it has ``run_to`` (``advance_to``'s extra
        requirement) — the whole-run/whole-step classes don't, and their
        t_end runs use the generic loop.
        """
        from multigpu_advectiondiffusion_tpu.ops import (
            is_fused_impl,
            is_pallas_impl,
        )

        impl = getattr(self, "_requested_impl", None) or getattr(
            self.cfg, "impl", "xla"
        )
        fused = self._fused_stepper(mode="t_end" if mode == "t_end" else "iters")
        if fused is not None and mode == "t_end" and not hasattr(
            fused, "run_to"
        ):
            self._fused_fallback = (
                f"{fused.engaged_label} stepper has no run_to; "
                "t_end mode runs the generic loop"
            )
            fused = None
        if fused is not None:
            overlap = None
            exchange = getattr(fused, "exchange", "collective")
            if getattr(fused, "sharded", False):
                if exchange == "dma":
                    # the whole-run program exchanges in-kernel: there
                    # is no XLA-level halo schedule to overlap
                    overlap = "in-kernel"
                elif getattr(fused, "overlap_split", False):
                    overlap = "split"
                else:
                    overlap = "serialized-refresh"
            out = {
                "impl": impl,
                "stepper": fused.engaged_label,
                "overlap": overlap,
                # comm-avoiding chunk length actually in effect (1 =
                # the per-step exchange cadence)
                "steps_per_exchange": int(
                    getattr(fused, "steps_per_exchange", 1)
                ),
                # halo-exchange transport actually engaged
                "exchange": exchange,
                # HBM-resident dtype of the engaged stepper's buffers
                # (f64 facing states live as f32 in-kernel; bf16 under
                # precision='bf16')
                "storage_dtype": str(
                    jnp.dtype(getattr(fused, "dtype", self.dtype))
                ),
                "precision": self._precision_mode(),
                "fallback": None,
            }
            if self._tuned is not None:
                out["tuned"] = self._tuned_summary()
            if self._degrade_events:
                out["degraded"] = list(self._degrade_events)
            return out
        # honor solver-level per-op dispatch rules (e.g. Burgers keeps
        # XLA for WENO7 under impl="pallas" — measured faster)
        op = (
            self._op_impl()
            if hasattr(self, "_op_impl")
            else ("pallas" if is_pallas_impl(impl) else "xla")
        )
        stepper = "per-axis-pallas" if op == "pallas" else "generic-xla"
        fallback = None
        if is_fused_impl(impl):
            fallback = getattr(
                self, "_fused_fallback", None
            ) or "config not fused-eligible"
            op_reason = getattr(self, "_op_fallback", None)
            if op_reason:
                fallback += "; " + op_reason
        elif is_pallas_impl(impl) and op == "xla":
            # explicit per-axis rung requested but undispatchable
            fallback = getattr(self, "_op_fallback", None)
        overlap = (
            getattr(self.cfg, "overlap", None)
            if self.mesh is not None
            else None
        )
        out = {
            "impl": impl,
            "stepper": stepper,
            "overlap": overlap,
            "steps_per_exchange": int(
                getattr(self.cfg, "steps_per_exchange", 1) or 1
            ),
            "exchange": self._exchange_mode(),
            "storage_dtype": str(jnp.dtype(self.storage_dtype)),
            "precision": self._precision_mode(),
            "fallback": fallback,
        }
        if self._tuned is not None:
            out["tuned"] = self._tuned_summary()
        if self._degrade_events:
            out["degraded"] = list(self._degrade_events)
        return out

    def _tuned_summary(self) -> dict:
        """Compact tuner provenance for engaged_path/bench rows: where
        the decision came from and what it measured — enough to audit a
        published rate without re-opening the cache file."""
        d = self._tuned or {}
        return {
            k: d.get(k)
            for k in ("source", "impl", "steps_per_exchange", "exchange",
                      "mlups", "key")
            if k in d
        }

    def _sharded_axes(self):
        """Array axes that are *actually* decomposed: listed in the
        decomposition AND backed by a mesh extent > 1. The single
        definition of "sharded" for every eligibility gate — extent-1
        axes exchange no ghosts and must never trip layout/rounding
        gates (axis_extent, not sizes.get: compound (tuple) mesh-axis
        entries — the multihost z layout ('dz_dcn', 'dz_ici') — are
        never keys of mesh.shape and would silently read as extent 1).
        """
        if self.mesh is None:
            return []
        sizes = dict(self.mesh.shape)
        return [
            ax for ax, name in self.decomp.axes
            if axis_extent(sizes, name) > 1
        ]

    def _split_overlap_requested(self) -> bool:
        """``overlap='split'`` with a decomposition the fused steppers'
        three-call overlapped schedule serves: the leading (z) axis
        sharded, and — 3-D only — optionally y and/or x as well (pencil/
        block meshes: the z halo rides the overlapped exchanged-slab
        schedule, the other sharded axes a serialized per-stage
        refresh). Single definition for every solver's eligibility."""
        if self.mesh is None or getattr(self.cfg, "overlap", None) != "split":
            return False
        sharded = self._sharded_axes()
        if sharded == [0]:
            return True
        return self.grid.ndim == 3 and bool(sharded) and sharded[0] == 0

    def _fused_sharded_ctx(self, fused):
        """``(refresh, offsets_fn, exch)`` for running a fused stepper
        shard-local inside ``shard_map``: ghosts ppermute-refreshed after
        every RK stage, global wall masks fed this shard's offsets (the
        reference runs its tuned kernel under MPI the same way,
        ``MultiGPU/Diffusion3d_Baseline/main.c:189-303``). All ``None``
        when unsharded. ``offsets_fn``/``exch`` must be called inside
        ``shard_map`` (they read ``lax.axis_index``/``ppermute``).

        When the stepper runs the split-overlap schedule
        (``fused.overlap_split``), ``exch`` replaces ``refresh``: it
        returns the ``(lo, hi)`` exchanged z-slabs of the padded
        buffer's core, which the stage's edge calls consume as separate
        operands — so XLA schedules the interior call concurrently with
        the ppermute instead of serializing on a buffer rewrite.

        Both closures exchange at the stepper's ``exchange_depth``
        (the stencil halo per stage/step, or ``k * G`` for the
        communication-avoiding k-step slab schedule) and take an
        optional ``repeats`` telemetry hint (see
        ``parallel.halo.exchange_ghosts``)."""
        if self.mesh is None or not fused.sharded:
            return None, None, None
        sizes = dict(self.mesh.shape)
        depth = int(getattr(fused, "exchange_depth", fused.halo))

        def offsets_fn():
            return jnp.stack(
                [
                    jnp.asarray(o, jnp.int32)
                    for o in axis_offsets(self.decomp, fused.interior_shape)
                ]
            )

        if getattr(fused, "exchange", "collective") == "dma":
            # in-kernel remote-DMA exchange: the stepper's whole-run
            # program moves its own ghost rows over ICI — no ppermute
            # refresh/exch closures exist at the XLA level
            return None, offsets_fn, None

        if getattr(fused, "overlap_split", False):
            name = self.decomp.mesh_axis(0)
            nsh = axis_extent(sizes, name)
            offs = getattr(
                fused, "core_offsets",
                (fused.halo,) * len(fused.interior_shape),
            )
            off = offs[0]
            lz = fused.interior_shape[0]

            def exch(P, repeats: int = 1):
                core = slice_axis(P, 0, off, off + lz)
                return exchange_ghosts(
                    core, 0, depth, name, nsh, self.bcs[0],
                    repeats=repeats,
                )

            # Pencil meshes: the non-z sharded axes keep the serialized
            # per-stage buffer refresh — only the z halo rides the
            # overlapped exchanged-slab schedule (the stages' y-ghost
            # reads come from the buffer, so each stage's composed
            # output is refreshed before the next consumes it).
            others = {
                ax: nm
                for ax, nm in self.decomp.axes
                if ax != 0 and axis_extent(sizes, nm) > 1
            }
            refresh = None
            if others:
                refresh = make_ghost_refresh(
                    Decomposition.of(others), sizes, self.bcs, fused.halo,
                    fused.interior_shape,
                    core_offsets=getattr(fused, "core_offsets", None),
                )
            return refresh, offsets_fn, exch

        refresh = make_ghost_refresh(
            self.decomp, sizes, self.bcs, depth, fused.interior_shape,
            core_offsets=getattr(fused, "core_offsets", None),
        )
        return refresh, offsets_fn, None

    def run(self, state: SolverState, num_iters: int) -> SolverState:
        """Fixed-count loop (the CUDA drivers' ``max_iters`` mode,
        ``MultiGPU/Diffusion3d_Baseline/main.c:189``). A Mosaic/Pallas
        failure at dispatch under ``impl='pallas'`` retries one kernel-
        ladder rung down (:meth:`_with_ladder`)."""
        def call():
            with self._dispatch_span("run", iters=int(num_iters)):
                return self._run_impl(state, num_iters)

        return self._with_ladder(call)

    def _run_impl(self, state: SolverState, num_iters: int) -> SolverState:
        fused = self._fused_stepper()
        if fused is not None:
            refresh, offsets_fn, exch = self._fused_sharded_ctx(fused)

            def block(u, t):
                # kwargs only when sharded — the 2-D whole-run steppers
                # are single-chip and take none of these
                kw = {}
                if refresh is not None:
                    kw["refresh"] = refresh
                if exch is not None:
                    kw["exch"] = exch
                if offsets_fn is not None:
                    kw["offsets"] = offsets_fn()
                return fused.run(u, t, num_iters, **kw)

            f = self._compiled(
                ("fused_run", num_iters), lambda: self._wrap(block),
                steps=int(num_iters),
            )
            u, t = f(state.u, state.t)
            return SolverState(u=u, t=t, it=state.it + num_iters)

        if self._precision_mode() == "bf16":
            # bf16-storage generic rung: the loop-resident state is the
            # packed bf16 representation (hi, or hi+compensation lo) —
            # the facing/public state stays f32; every step
            # reconstructs f32, marches, re-splits. With the carry the
            # loop carries 2+2 bytes/cell (f32 traffic parity — the win
            # is the halo wire and the carry-free fused rungs); without
            # it, 2 bytes/cell at bf16 rounding error.
            def block(u, t):
                def body(i, c):
                    u2, t2 = self._local_step(
                        self._bf16_unpack(c[:-1]), c[-1]
                    )
                    return self._bf16_pack(u2) + (t2,)

                out = lax.fori_loop(
                    0, num_iters, body, self._bf16_pack(u) + (t,)
                )
                return self._bf16_unpack(out[:-1]), out[-1]
        else:
            def block(u, t):
                return lax.fori_loop(
                    0, num_iters, lambda i, c: self._local_step(*c), (u, t)
                )

        f = self._compiled(("run", num_iters), lambda: self._wrap(block),
                           steps=int(num_iters))
        u, t = f(state.u, state.t)
        return SolverState(u=u, t=t, it=state.it + num_iters)

    def advance_to(self, state: SolverState, t_end: float) -> SolverState:
        """March until ``t_end`` with the last step trimmed to land exactly
        (the corrected version of the MATLAB drivers' loop, heat3d.m:48-77).

        ``t_end`` is a traced operand: one compilation serves every end
        time, so parameter sweeps do not recompile per value.

        When the config is fused-eligible and the stepper has a
        ``run_to`` (the 3-D fused Burgers), this mode runs at the fused
        stepper's speed — the reference Burgers drivers' *only* execution
        mode is ``while (t < tEnd)`` over the tuned kernels
        (``MultiGPU/Burgers3d_Baseline/main.c:190-317``)."""
        def call():
            with self._dispatch_span("advance_to", mode="t_end",
                                     t_end=float(t_end)):
                return self._advance_impl(state, t_end)

        return self._with_ladder(call, mode="t_end")

    def _advance_impl(self, state: SolverState, t_end: float) -> SolverState:
        fused = self._fused_stepper(mode="t_end")
        if fused is not None and hasattr(fused, "run_to"):
            refresh, offsets_fn, exch = self._fused_sharded_ctx(fused)

            def fblock(u, t, te):
                offs = offsets_fn() if offsets_fn is not None else None
                return fused.run_to(u, t, te, refresh=refresh, offsets=offs,
                                    exch=exch)

            f = self._compiled("fused_adv", lambda: self._wrap(fblock, 2, 2))
            u, t, steps = f(
                state.u, state.t, jnp.asarray(t_end, state.t.dtype)
            )
            return SolverState(u=u, t=t, it=state.it + steps)

        if self._precision_mode() == "bf16":
            # bf16-storage generic rung, t_end mode: same packed loop
            # state as _run_impl's fori body (the arity n is static —
            # 1 without the compensation carry, 2 with it)
            def block(u, t, te):
                eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))
                n = len(self._bf16_pack(u))

                def cond(c):
                    return c[n] < te - eps

                def body(c):
                    u2, t2 = self._local_step(
                        self._bf16_unpack(c[:n]), c[n], t_end=te
                    )
                    return self._bf16_pack(u2) + (t2, c[n + 1] + 1)

                out = lax.while_loop(
                    cond, body,
                    self._bf16_pack(u) + (t, jnp.zeros((), jnp.int32)),
                )
                return self._bf16_unpack(out[:n]), out[n], out[n + 1]
        else:
            def block(u, t, te):
                eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))

                def cond(c):
                    return c[1] < te - eps

                def body(c):
                    u, t, it = c
                    u, t = self._local_step(u, t, t_end=te)
                    return (u, t, it + 1)

                return lax.while_loop(cond, body,
                                      (u, t, jnp.zeros((), jnp.int32)))

        # check=False: no vma/replication rule exists for while_loop
        f = self._compiled("adv", lambda: self._wrap(block, 2, 2,
                                                     check=False))
        u, t, steps = f(
            state.u, state.t, jnp.asarray(t_end, state.t.dtype)
        )
        return SolverState(u=u, t=t, it=state.it + steps)

    # ------------------------------------------------------------------ #
    # Ensemble (leading-member-axis) execution — one compiled
    # executable advances B independent members per dispatch,
    # amortizing compile, dispatch and HBM streaming across the batch
    # (ROADMAP item 1; front end in models/ensemble.py)
    # ------------------------------------------------------------------ #
    def _ensemble_gate(self, operand_names=()) -> None:
        """Loud eligibility gate for batched dispatch — mirror of the
        impl/steps_per_exchange construction gates: a config the
        ensemble engine cannot serve fails here instead of silently
        running something else. Since the mesh-scale round (ROADMAP
        item 1) device meshes and the slab rung are ADMITTED: a mesh
        composes through a ``members`` axis (members-sharded, optionally
        x a z-slab spatial subgroup) and uniform-physics ensembles fold
        B into the slab rung's grid; the declines left below are the
        genuinely unservable configs, each with its reason."""
        emesh = getattr(self, "_ensemble_mesh", None)
        if self.mesh is not None and emesh is None:
            raise ValueError(
                "a purely spatial device mesh shards ONE member's grid; "
                "ensembles compose with a mesh through a 'members' axis "
                "— build via EnsembleSolver(..., mesh=make_mesh("
                "{'members': P}) or {'members': P, 'dz': Q})"
            )
        if int(getattr(self.cfg, "steps_per_exchange", 1) or 1) > 1:
            raise ValueError(
                "steps_per_exchange > 1 rides the spatially sharded "
                "slab rung, whose k-step deep-halo schedule does not "
                "fold a member axis — run ensembles at the per-step "
                "exchange cadence"
            )
        if self._exchange_mode() == "dma":
            raise ValueError(
                "exchange='dma' rides the spatially sharded slab "
                "rung, whose in-kernel remote-DMA ring does not fold "
                "a member axis — the batched ensemble engine keeps "
                "the collective exchange"
            )
        if self._precision_mode() == "bf16":
            raise ValueError(
                "precision='bf16' is a single-run rung: neither the "
                "vmapped fused stepper nor the B-folded slab grid "
                "threads the bf16 storage split (and its compensation "
                "carry) through the member axis — run ensembles at "
                "native precision"
            )
        if getattr(self.cfg, "impl", "xla") == "pallas_slab":
            if self.mesh is not None:
                raise ValueError(
                    "the B-folded slab grid serves unsharded-spatial "
                    "instances only (members-only meshes run one fold "
                    "per device); a spatial z-slab x slab-rung ensemble "
                    "remains unservable — its per-step ghost refresh "
                    "cannot cross the member fold"
                )
            if operand_names:
                raise ValueError(
                    "the B-folded slab grid bakes uniform physics "
                    "(fixed dt, closure coefficients); member-varying "
                    f"operand(s) {sorted(operand_names)} ride the "
                    "generic rung — drop the impl='pallas_slab' pin"
                )
        supported = set(self.ensemble_operands())
        unknown = sorted(set(operand_names) - supported)
        if unknown:
            raise ValueError(
                f"{type(self).__name__} has no member-varying operand(s) "
                f"{unknown}; supported: {sorted(supported) or 'none'}"
            )

    def _ensemble_fused(self):
        """The fused stepper the batched dispatch may ride, or ``None``
        (generic vmapped loop). Two fused shapes are served: the
        per-stage rung under ``jax.vmap``, and — new this round — the
        whole-run slab rung with B FOLDED into its Pallas grid
        (``fused_slab_run.run_batched``: a leading member grid axis,
        one program for the whole batched run). Spatially sharded fused
        steppers decline (their ghost refresh does not fold a member
        axis); the 2-D whole-run steppers' in-core padding stays
        unproven under batching."""
        fused = self._fused_stepper(mode="iters")
        if fused is None:
            return None
        if getattr(fused, "sharded", False):
            return self._decline(
                "spatially sharded fused steppers decline the member "
                "axis (ghost refresh cannot cross the fold); the "
                "generic rung serves members x spatial meshes"
            )
        if fused.engaged_label in ("fused-stage", "fused-whole-run-slab"):
            return fused
        return self._decline(
            f"ensemble batching serves the fused-stage (vmap) and "
            f"whole-run-slab (B-fold) rungs; {fused.engaged_label} "
            f"declines batching"
        )

    # -- ensemble mesh plumbing (set by models/ensemble.EnsembleSolver:
    # the full device mesh whose 'members' axis shards the batched
    # state's leading axis; None = single-device batching) ----------- #
    _ensemble_mesh = None
    _ensemble_spatial = None  # spatial Decomposition (grid axes only)

    def arm_ensemble_mesh(self, mesh, spatial_decomp) -> None:
        """Attach the members(-x-spatial) mesh the batched dispatch
        wraps its programs over. ``spatial_decomp`` maps GRID axes to
        the mesh's non-member axes (None = members-only sharding); the
        member axis itself is halo-free by construction and never
        appears in it (verified statically by
        ``analysis/halo_verify.verify_member_mesh``)."""
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            MEMBER_AXIS,
        )

        if mesh is not None and MEMBER_AXIS not in dict(mesh.shape):
            raise ValueError(
                "an ensemble mesh needs a 'members' axis"
            )
        self._ensemble_mesh = mesh
        self._ensemble_spatial = spatial_decomp

    def _ensemble_specs(self):
        """``(state_spec, member_spec)`` PartitionSpecs of the batched
        ``(B, *grid)`` state and the per-member ``(B,)`` scalars under
        the armed ensemble mesh (``None`` when unmeshed)."""
        if self._ensemble_mesh is None:
            return None
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            MEMBER_AXIS,
        )

        ndim = self.grid.ndim
        spatial = [None] * ndim
        if self._ensemble_spatial is not None:
            mapping = self._ensemble_spatial.mapping
            spatial = [mapping.get(ax) for ax in range(ndim)]
        return P(MEMBER_AXIS, *spatial), P(MEMBER_AXIS)

    def _ensemble_wrap(self, fn, n_in_scalars: int, n_out_scalars: int,
                       n_in_global: int = 0, donate: bool = False):
        """Jit a batched block ``(us, *member_scalars, *globals) ->
        (us, *member_scalars)``. Under the armed ensemble mesh the
        block runs inside ``shard_map``: the state follows
        ``(members, *spatial)``, per-member operands follow the member
        axis, trailing globals (t_end) replicate. ``check=False``
        throughout — the bodies host vmapped while/fori loops and
        Pallas calls, neither of which carries vma typing.

        ``donate`` (ISSUE 19) donates the batched state operand
        (argument 0): XLA aliases the input ``(B, *grid)`` buffer into
        the output, so the slice march updates HBM in place instead of
        holding two copies of the ensemble state per dispatch. Input
        and output state share one PartitionSpec, so the alias is
        always layout-compatible. Backends without donation support
        (XLA:CPU) ignore the hint — the dispatch layer's
        :func:`_consume_donated` makes the semantics uniform anyway."""
        if donate:
            import warnings

            # XLA:CPU implements no donation and warns per dispatch;
            # semantics stay uniform via _consume_donated, so the
            # warning is noise on the tier-1 path
            warnings.filterwarnings(
                "ignore",
                message="Some donated buffers were not usable",
            )
        kwargs = {"donate_argnums": (0,)} if donate else {}
        specs = self._ensemble_specs()
        if specs is None:
            return jax.jit(fn, **kwargs)
        uspec, mspec = specs
        return jax.jit(
            shard_map(
                fn,
                mesh=self._ensemble_mesh,
                in_specs=(uspec,) + (mspec,) * n_in_scalars
                + (P(),) * n_in_global,
                out_specs=(uspec,) + (mspec,) * n_out_scalars,
                check=False,
            ),
            **kwargs,
        )

    def _ensemble_mesh_token(self):
        """Dispatch-cache/AOT key component naming the ensemble mesh
        layout (device placement changes the compiled executable)."""
        emesh = self._ensemble_mesh
        if emesh is None:
            return None
        return ",".join(f"{n}:{s}" for n, s in emesh.shape.items())

    def _ensemble_pack(self, operands, members: int):
        """Normalize ``{name: (B,)-array}`` member-varying operands to
        ``(names, (B, P) matrix)`` in a deterministic column order; an
        empty/None dict packs to a zero-width matrix (uniform physics,
        fused-eligible)."""
        if not operands:
            return (), jnp.zeros((members, 0), jnp.float32)
        names = tuple(sorted(operands))
        self._ensemble_gate(names)
        cols = []
        for n in names:
            col = jnp.asarray(operands[n], jnp.float32).reshape(-1)
            if col.shape[0] != members:
                raise ValueError(
                    f"operand {n!r} has {col.shape[0]} values for "
                    f"{members} members"
                )
            cols.append(col)
        return names, jnp.stack(cols, axis=1)

    def _ensemble_record(self, members, stepper, mode, names):
        """Record + emit the dispatch facts (``ensemble:dispatch``
        events; ``engaged`` provenance for bench rows and the CLI
        summary — the reference's PrintSummary discipline applied to
        the batched engine). Carries the mesh placement since the
        mesh-scale round: ``devices`` (total devices the dispatch
        spans) and ``member_sharding`` (member-axis shard count), so a
        batched row that silently fell back to one device is visible
        in the stream (and failed by the bench engagement guard)."""
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            member_extent,
        )

        emesh = self._ensemble_mesh
        devices = 1 if emesh is None else int(emesh.devices.size)
        msh = member_extent(emesh)
        self._ensemble_last = {
            "members": int(members),
            "stepper": stepper,
            "mode": mode,
            "operands": list(names),
            "devices": devices,
            "member_sharding": msh,
            "mesh": self._ensemble_mesh_token(),
        }
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.event(
            "ensemble", "dispatch",
            members=int(members), stepper=stepper, mode=mode,
            operands=list(names), devices=devices, member_sharding=msh,
            mesh=self._ensemble_mesh_token(),
        )

    def run_ensemble(self, estate: EnsembleState, num_iters: int,
                     operands=None, donate: bool = False) -> EnsembleState:
        """Advance every member ``num_iters`` steps in ONE dispatch.

        Uniform-physics ensembles (no ``operands``) ``vmap`` the fused
        per-stage stepper where the config engages it — bit-exact
        against the looped single runs (tests/test_ensemble.py);
        member-varying scalars (``{name: (B,) values}`` for the names
        in :meth:`ensemble_operands`) ride the generic stepper with the
        scalars as batched operands.

        ``donate=True`` donates ``estate.u`` into the dispatch (in-place
        HBM update, no second ``(B,*grid)`` buffer) and CONSUMES it:
        ``estate`` must not be touched after this returns — any reuse
        raises loudly on every backend (:func:`_consume_donated`)."""
        B = estate.members
        names, ops = self._ensemble_pack(operands, B)
        self._ensemble_gate(names)
        fused = self._ensemble_fused() if not names else None
        mtok = self._ensemble_mesh_token()
        slab_fold = (
            fused is not None
            and fused.engaged_label == "fused-whole-run-slab"
        )
        if slab_fold:
            label = "ensemble-fold[fused-whole-run-slab]"
        elif fused is not None:
            label = f"ensemble-vmap[{fused.engaged_label}]"
        else:
            label = "ensemble-vmap[generic-xla]"
        self._ensemble_record(B, label, "iters", names)
        with self._dispatch_span("run_ensemble", mode="t_end",
                                 iters=int(num_iters), members=B):
            if slab_fold:
                # B folded into the slab rung's Pallas grid: ONE
                # whole-run program per device advances its members
                # (under a members-only mesh each device runs the fold
                # over its own member shard)
                def block(us, ts):
                    return fused.run_batched(us, ts, num_iters)

                f = self._compiled(
                    ("ens_slab_run", num_iters, B, mtok),
                    lambda: self._ensemble_wrap(block, 1, 1,
                                                donate=donate),
                    steps=int(num_iters), donate=donate,
                )
                u, t = f(estate.u, estate.t)
                if donate:
                    _consume_donated(estate.u)
                return EnsembleState(u=u, t=t, it=estate.it + num_iters)

            if fused is not None:
                def block(us, ts):
                    return jax.vmap(
                        lambda u, t: fused.run(u, t, num_iters)
                    )(us, ts)

                f = self._compiled(
                    ("ens_fused_run", num_iters, B, mtok),
                    lambda: self._ensemble_wrap(block, 1, 1,
                                                donate=donate),
                    steps=int(num_iters), donate=donate,
                )
                u, t = f(estate.u, estate.t)
                if donate:
                    _consume_donated(estate.u)
                return EnsembleState(u=u, t=t, it=estate.it + num_iters)

            def member(u, t, p):
                ov = {n: p[i] for i, n in enumerate(names)} or None

                def body(i, c):
                    return self._local_step(c[0], c[1], overrides=ov)

                return lax.fori_loop(0, num_iters, body, (u, t))

            def block(us, ts, ps):
                return jax.vmap(member)(us, ts, ps)

            f = self._compiled(
                ("ens_run", num_iters, B, names, mtok),
                lambda: self._ensemble_wrap(block, 2, 1, donate=donate),
                steps=int(num_iters), donate=donate,
            )
            u, t = f(estate.u, estate.t, ops)
            if donate:
                _consume_donated(estate.u)
            return EnsembleState(u=u, t=t, it=estate.it + num_iters)

    def _ensemble_advance_block(self, names, max_steps,
                                per_member_te: bool):
        """The ``advance_to_ensemble`` batched program, as a function —
        shared VERBATIM between the real dispatch and
        :meth:`prewarm_advance_to_ensemble` so a prewarmed executable
        is bit-identical to (and cache-keyed the same as) the one the
        live call would build."""
        def member(u, t, p, te):
            ov = {n: p[i] for i, n in enumerate(names)} or None
            eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))

            if max_steps is not None:
                def fbody(i, c):
                    u, t, it = c
                    u2, t2 = self._local_step(u, t, t_end=te,
                                              overrides=ov)
                    live = t < te - eps
                    return (
                        jnp.where(live, u2, u),
                        jnp.where(live, t2, t),
                        it + live.astype(jnp.int32),
                    )

                return lax.fori_loop(
                    0, int(max_steps), fbody,
                    (u, t, jnp.zeros((), jnp.int32)),
                )

            def cond(c):
                return c[1] < te - eps

            def body(c):
                u, t, it = c
                u, t = self._local_step(u, t, t_end=te, overrides=ov)
                return (u, t, it + 1)

            return lax.while_loop(
                cond, body, (u, t, jnp.zeros((), jnp.int32))
            )

        if per_member_te:
            # te rides the member axis like t/operands do: the vmap
            # batches it, the ensemble mesh shards it with mspec
            def block(us, ts, ps, tes):
                return jax.vmap(member, in_axes=(0, 0, 0, 0))(
                    us, ts, ps, tes
                )
        else:
            def block(us, ts, ps, te):
                return jax.vmap(member, in_axes=(0, 0, 0, None))(
                    us, ts, ps, te
                )
        return block

    def advance_to_ensemble(self, estate: EnsembleState, t_end: float,
                            operands=None,
                            max_steps: int | None = None,
                            donate: bool = False) -> EnsembleState:
        """March every member to ``t_end`` in one dispatch (vmapped
        while-loop; finished members freeze while stragglers — e.g.
        smaller member dt — keep stepping). Generic rung only: the
        fused ``run_to`` loops host their own scalar plumbing that the
        member axis does not fold.

        ``max_steps`` switches the data-dependent ``while_loop`` for a
        bounded ``fori_loop`` whose finished members freeze via masked
        updates — semantically identical when ``max_steps`` covers the
        longest member trajectory, and REVERSE-MODE DIFFERENTIABLE
        (``jax.grad`` through a dynamic-trip ``while_loop`` is
        undefined): the gradient-based inverse-problem path
        (``examples/inverse_diffusivity.py``) differentiates through
        this dispatch with respect to the member operands.

        ``t_end`` may be a scalar (every member marches to the same
        horizon) or a ``(B,)`` sequence — the request-serving shape
        (service/server.py): coalesced requests asking different
        horizons ride ONE dispatch, each member freezing at its own
        ``te``. The scalar path keeps its original compiled key; the
        per-member path compiles a variant with ``te`` as a batched
        member scalar.

        ``donate=True`` donates ``estate.u`` into the dispatch (ISSUE
        19): XLA updates the ensemble state in place instead of holding
        a second ``(B, *grid)`` buffer, and the input ``estate`` is
        CONSUMED — touching ``estate.u`` afterwards is a loud
        ``RuntimeError`` on every backend."""
        import numpy as _np

        B = estate.members
        names, ops = self._ensemble_pack(operands, B)
        self._ensemble_gate(names)
        mtok = self._ensemble_mesh_token()
        te_host = _np.asarray(t_end, dtype=_np.float64)
        per_member_te = te_host.ndim > 0
        if per_member_te and te_host.reshape(-1).shape[0] != B:
            raise ValueError(
                f"t_end has {te_host.reshape(-1).shape[0]} values for "
                f"{B} members — pass a scalar or one horizon per member"
            )
        self._ensemble_record(B, "ensemble-vmap[generic-xla]", "t_end",
                              names)
        with self._dispatch_span("advance_to_ensemble", mode="t_end",
                                 t_end=float(_np.max(te_host)),
                                 members=B):
            block = self._ensemble_advance_block(names, max_steps,
                                                 per_member_te)
            if per_member_te:
                f = self._compiled(
                    ("ens_adv", B, names, mtok, max_steps, "vte"),
                    lambda: self._ensemble_wrap(block, 3, 2,
                                                donate=donate),
                    donate=donate,
                )
                u, t, steps = f(
                    estate.u, estate.t, ops,
                    jnp.asarray(te_host.reshape(-1), estate.t.dtype),
                )
                if donate:
                    _consume_donated(estate.u)
                return EnsembleState(u=u, t=t, it=estate.it + steps)

            f = self._compiled(
                ("ens_adv", B, names, mtok, max_steps),
                lambda: self._ensemble_wrap(block, 2, 2, n_in_global=1,
                                            donate=donate),
                donate=donate,
            )
            u, t, steps = f(
                estate.u, estate.t, ops,
                jnp.asarray(t_end, estate.t.dtype),
            )
            if donate:
                _consume_donated(estate.u)
            return EnsembleState(u=u, t=t, it=estate.it + steps)

    def prewarm_advance_to_ensemble(self, members: int,
                                    operand_names=(),
                                    max_steps: int | None = None,
                                    donate: bool = False,
                                    per_member_te: bool = True):
        """Speculative AOT prewarm (ISSUE 19): resolve the
        ``advance_to_ensemble`` executable for ``(members,
        operand_names, max_steps, donate)`` from the persistent AOT
        store WITHOUT concrete operands and WITHOUT ever compiling —
        ``jax.ShapeDtypeStruct`` avals fingerprint identically to the
        concrete arrays the live call will pass, so a deserialized hit
        is the executable the next batch dispatches.

        Returns ``"hit"`` (deserialized and resident), ``"resident"``
        (already compiled/loaded in this process), ``"miss"`` (no
        store entry — the live call will pay the compile), or ``None``
        (prewarm unavailable: xprof/AOT cache disabled). Never
        compiles cold, never raises on a cache problem.

        The block builder is :meth:`_ensemble_advance_block` — the
        SAME function the live dispatch uses — so even on a miss the
        jit function parked in the dispatch cache is exactly the one
        the live call would have built."""
        B = int(members)
        names = tuple(sorted(operand_names)) if operand_names else ()
        self._ensemble_gate(names)
        mtok = self._ensemble_mesh_token()
        block = self._ensemble_advance_block(names, max_steps,
                                             per_member_te)
        if per_member_te:
            f = self._compiled(
                ("ens_adv", B, names, mtok, max_steps, "vte"),
                lambda: self._ensemble_wrap(block, 3, 2,
                                            donate=donate),
                donate=donate,
            )
        else:
            f = self._compiled(
                ("ens_adv", B, names, mtok, max_steps),
                lambda: self._ensemble_wrap(block, 2, 2, n_in_global=1,
                                            donate=donate),
                donate=donate,
            )
        prewarm = getattr(f, "prewarm", None)
        if prewarm is None:
            return None  # introspection wrapper absent: no AOT path
        rdt = (jnp.float64 if self.dtype == jnp.dtype(jnp.float64)
               else jnp.float32)
        te_shape = (B,) if per_member_te else ()
        shaped = (
            jax.ShapeDtypeStruct((B, *self.grid.shape), self.dtype),
            jax.ShapeDtypeStruct((B,), rdt),
            jax.ShapeDtypeStruct((B, len(names)), jnp.float32),
            jax.ShapeDtypeStruct(te_shape, rdt),
        )
        return prewarm(shaped)
