"""Solver-plugin registry: a PDE family is a declarative descriptor.

After fourteen rounds every subsystem — the dispatch ladder, the
measured tuner, the supervisor, telemetry, diagnostics, the static
halo/collective verifiers, the ensemble engine, the scheduler — was
wired through exactly two hard-coded models. The PALABOS multi-GPU port
(PAPERS.md, arXiv 2506.09242) and the TPU CFD framework paper (arXiv
2108.11076) both land on the same architecture: once halo exchange,
stepping and sharding are a reusable skeleton, new physics is a
kernel-sized plugin. This module is that step: a solver family
registers ONE :class:`ModelSpec` naming its config/solver classes plus
the hooks every generic subsystem needs —

* the CLI (``cli/__main__.py``) builds its ``<name>{1,2,3}d``
  subcommands and resolves ``--model NAME`` from the registry;
* the measured tuner (``tuning/autotuner.py``) derives its cache-key
  extras and fused ghost depth from ``key_extras``/``stage_radius``;
* the cost model (``telemetry/costmodel.py``) resolves the family kind
  and per-step FLOP kwargs through ``spec_for_config``/``cost_kwargs``;
* the bench matrix (``bench/matrix.py``) constructs case configs via
  ``bench_build``; ``bench/scaling.py`` resolves run names via
  :func:`solver_for_run_name`;
* the static halo verifier (``analysis/halo_verify.py``) iterates
  registered family names — a registered family with no combo battery
  is a coverage FAILURE, not a silent gap.

The *registration contract* finishes what PR 8–11 started: the
queryable per-solver methods those rounds introduced ad hoc are now
REQUIRED of every registered solver class — declared in the class's own
body, enforced twice:

=====================  ==================================================
``stencil_spec()``     family stencil metadata: per-stage radius = the
                       max of the advective and diffusive tap reaches
                       (feeds the tuner's fused ghost depth and the
                       static halo verifier)
``diagnostics_spec()`` in-situ physics observables/rules/meta fused
                       into the sentinel's jitted probe (PR 8)
``ensemble_operands()`` member-varying traced scalars of the batched
                       ensemble engine (PR 9)
``cfl_rule()``         the family's time-step rule, queryable (kind,
                       dt/cfl/safety) — what a checkpoint resumes under
=====================  ==================================================

once at :func:`register_model` (runtime — a half-wired plugin fails at
import, before any dispatch), and once statically by the
``registry-completeness`` lint rule (``analysis/rules.py``,
``tpucfd-check``/``out/lint_gate.sh``), which proves the declaration in
the registering module's AST without executing it.

Built-in families self-register at the bottom of their modules
(``models/diffusion.py``, ``models/burgers.py``, ``models/adr.py`` —
the title workload); :func:`_ensure_builtins` imports them lazily so
``import registry`` alone never drags jax in a direction the caller
didn't ask for, and so registration order cannot depend on which model
a user imported first.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Mapping, Optional, Tuple

#: contract methods every registered solver class must DECLARE in its
#: own body (not merely inherit): the ad-hoc queryable methods of
#: PR 8–11 promoted to the registration contract. Checked at
#: register_model() time AND statically by the registry-completeness
#: lint rule (analysis/rules.py).
REQUIRED_SOLVER_CONTRACT = (
    "stencil_spec",
    "diagnostics_spec",
    "ensemble_operands",
    "cfl_rule",
)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One solver family's declarative descriptor.

    ``cli_configure(parser, ndim, **extra)`` adds the family's flags to
    a generated ``<name><ndim>d`` subcommand; ``cli_build(args, grid,
    ndim, **extra)`` turns parsed args into the family config (the ONE
    place CLI flags meet the config dataclass, so ``--model`` and the
    subcommands cannot diverge). ``stage_radius(cfg)`` is the fused
    per-stage stencil radius h (the tuner's ghost depth is ``3h``);
    ``key_extras(cfg)`` the family-specific tuning-cache key parts;
    ``cost_kwargs(cfg)`` the kwargs ``telemetry.costmodel.step_cost``
    prices the family with; ``bench_build(grid, dtype, impl, case)``
    the bench-matrix config constructor."""

    name: str
    config_cls: type
    solver_cls: type
    description: str
    kind: Optional[str] = None  # cost-model family key; defaults to name
    cli_dims: Tuple[int, ...] = (1, 2, 3)
    check_error: bool = False  # solver has an analytic error_norms
    sweep_aliases: Mapping[str, str] = dataclasses.field(
        default_factory=dict
    )
    cli_configure: Optional[Callable] = None
    cli_build: Optional[Callable] = None
    stage_radius: Optional[Callable] = None
    key_extras: Optional[Callable] = None
    cost_kwargs: Optional[Callable] = None
    bench_build: Optional[Callable] = None

    @property
    def family_kind(self) -> str:
        return self.kind or self.name


_REGISTRY: Dict[str, ModelSpec] = {}
_BUILTINS_LOADED = False


def register_model(spec: ModelSpec) -> ModelSpec:
    """Register one family. The registration contract is enforced HERE
    (the runtime half; the ``registry-completeness`` lint rule is the
    static half): a solver class missing any required contract method
    in its own body fails at import, not at dispatch."""
    missing = [
        m for m in REQUIRED_SOLVER_CONTRACT
        if m not in vars(spec.solver_cls)
    ]
    if missing:
        raise ValueError(
            f"solver {spec.solver_cls.__name__} cannot register as "
            f"{spec.name!r}: contract method(s) {missing} are not "
            "declared in the class body (REQUIRED_SOLVER_CONTRACT — "
            "a half-wired plugin must fail at registration, not at "
            "dispatch)"
        )
    if spec.name in _REGISTRY and _REGISTRY[spec.name] is not spec:
        existing = _REGISTRY[spec.name]
        if (
            existing.solver_cls.__name__ != spec.solver_cls.__name__
            or existing.config_cls.__name__ != spec.config_cls.__name__
        ):
            raise ValueError(
                f"model name {spec.name!r} already registered for "
                f"{existing.solver_cls.__name__}"
            )
    _REGISTRY[spec.name] = spec
    return spec


def _ensure_builtins() -> None:
    """Import the built-in family modules (idempotent): each registers
    itself at its module bottom, so lookups see the same registry no
    matter which model was imported first."""
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from multigpu_advectiondiffusion_tpu.models import (  # noqa: F401
        adr,
        burgers,
        diffusion,
    )


def names() -> Tuple[str, ...]:
    """Registered family names, registration order."""
    _ensure_builtins()
    return tuple(_REGISTRY)


def specs() -> Tuple[ModelSpec, ...]:
    _ensure_builtins()
    return tuple(_REGISTRY.values())


def get(name: str) -> ModelSpec:
    _ensure_builtins()
    spec = _REGISTRY.get(name)
    if spec is None:
        raise KeyError(
            f"unknown model {name!r}; registered models: {list(_REGISTRY)}"
        )
    return spec


def spec_for_config(cfg) -> Optional[ModelSpec]:
    """The spec whose config class ``cfg`` is an instance of (exact
    class first, then subclasses); ``None`` for unregistered configs —
    callers keep their duck-typed fallbacks for ad-hoc test doubles."""
    _ensure_builtins()
    cls = type(cfg)
    for spec in _REGISTRY.values():
        if spec.config_cls is cls:
            return spec
    for spec in _REGISTRY.values():
        try:
            if isinstance(cfg, spec.config_cls):
                return spec
        except TypeError:
            continue
    return None


def family_of_run_name(run_name: str) -> Optional[str]:
    """Longest registered family name prefixing ``run_name`` (bench
    metrics and CLI run names follow the ``<family><ndim>d...``
    convention) — the replacement for the scattered
    ``name.startswith("diffusion")`` literals."""
    _ensure_builtins()
    best = None
    for name in _REGISTRY:
        if run_name.startswith(name) and (
            best is None or len(name) > len(best)
        ):
            best = name
    return best


def solver_for_run_name(run_name: str) -> type:
    fam = family_of_run_name(run_name)
    if fam is None:
        raise KeyError(
            f"run name {run_name!r} matches no registered model family "
            f"({list(_REGISTRY)})"
        )
    return _REGISTRY[fam].solver_cls


def resolve_bc(args, default):
    """Shared CLI ``--bc`` resolution (one value or one per axis,
    reversed to array order) — lives registry-side so model modules'
    ``cli_build`` hooks can use it without importing the CLI package
    (which imports them)."""
    bc = getattr(args, "bc", None)
    if not bc:
        return default
    return bc[0] if len(bc) == 1 else tuple(reversed(bc))
