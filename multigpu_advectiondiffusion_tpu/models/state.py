"""Solver state carried through jitted time loops."""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class SolverState(NamedTuple):
    """The evolving solution plus simulated time and iteration count.

    A pytree, so it flows through ``jit`` / ``lax`` loops / ``shard_map``
    unchanged. ``t`` and ``it`` are 0-d arrays (replicated across shards).
    """

    u: jnp.ndarray
    t: jnp.ndarray
    it: jnp.ndarray

    @staticmethod
    def create(u: jnp.ndarray, t: float = 0.0) -> "SolverState":
        rdt = jnp.float64 if u.dtype == jnp.float64 else jnp.float32
        return SolverState(
            u=u, t=jnp.asarray(t, dtype=rdt), it=jnp.asarray(0, dtype=jnp.int32)
        )


class EnsembleState(NamedTuple):
    """A batch of B independent solver states advanced by ONE dispatch.

    The member axis leads every field: ``u`` is ``(B, *grid.shape)``,
    ``t`` and ``it`` are ``(B,)`` — members may sit at different
    simulated times (member-varying dt) and, in ``advance_to`` mode,
    different step counts. A pytree like :class:`SolverState`, so the
    batched programs flow through ``jit``/``vmap``/``lax`` loops
    unchanged.
    """

    u: jnp.ndarray   # (B, *grid.shape)
    t: jnp.ndarray   # (B,)
    it: jnp.ndarray  # (B,) int32

    @property
    def members(self) -> int:
        return int(self.u.shape[0])

    @staticmethod
    def stack(states) -> "EnsembleState":
        """Batch B single-member states into one ensemble state."""
        states = list(states)
        if not states:
            raise ValueError("an ensemble needs at least one member")
        return EnsembleState(
            u=jnp.stack([s.u for s in states]),
            t=jnp.stack([jnp.asarray(s.t) for s in states]),
            it=jnp.stack(
                [jnp.asarray(s.it, dtype=jnp.int32) for s in states]
            ),
        )

    def member(self, i: int) -> SolverState:
        """Member ``i`` as a plain :class:`SolverState` view."""
        return SolverState(u=self.u[i], t=self.t[i], it=self.it[i])
