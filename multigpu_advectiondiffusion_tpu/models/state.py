"""Solver state carried through jitted time loops."""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class SolverState(NamedTuple):
    """The evolving solution plus simulated time and iteration count.

    A pytree, so it flows through ``jit`` / ``lax`` loops / ``shard_map``
    unchanged. ``t`` and ``it`` are 0-d arrays (replicated across shards).
    """

    u: jnp.ndarray
    t: jnp.ndarray
    it: jnp.ndarray

    @staticmethod
    def create(u: jnp.ndarray, t: float = 0.0) -> "SolverState":
        rdt = jnp.float64 if u.dtype == jnp.float64 else jnp.float32
        return SolverState(
            u=u, t=jnp.asarray(t, dtype=rdt), it=jnp.asarray(0, dtype=jnp.int32)
        )
