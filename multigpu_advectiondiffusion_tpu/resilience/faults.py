"""Fault-injection harness driving ``tests/test_resilience.py``.

Every fault this framework claims to survive is injectable on a CPU-only
rig, so the recovery paths are tier-1-testable without hardware or an
actual preemption:

* :func:`nan_at_step` — poison the state with a NaN once the run crosses
  a global iteration (a transient numerical blow-up);
* :func:`mosaic_failure` — make fused-stepper dispatch raise a
  :class:`SimulatedMosaicError` whose message carries the real markers,
  exercising the kernel-ladder degradation exactly where a Mosaic
  compile/launch failure would surface;
* :func:`corrupt_checkpoint` / :func:`truncate_checkpoint` — bit-flip or
  tear a checkpoint file so CRC verification must catch it;
* :func:`send_signal` — deliver a real SIGTERM/SIGINT to a process (the
  scheduler-preemption stand-in).
"""

from __future__ import annotations

import contextlib
import os
import signal as _signal
from typing import Optional

from multigpu_advectiondiffusion_tpu.resilience.errors import (
    SimulatedMosaicError,
)


@contextlib.contextmanager
def nan_at_step(solver, step: int, once: bool = True):
    """Within the context, the first state ``solver`` produces at or
    after global iteration ``step`` gets one NaN cell (at the block
    center). ``once=True`` models a transient fault — after a rollback
    the same injection does not re-fire; ``once=False`` a persistent
    one, which must exhaust the supervisor's retries."""
    import jax.numpy as jnp

    orig = (solver.run, solver.step, solver.advance_to)
    fired = {"count": 0}

    def poison(out):
        if (once and fired["count"]) or int(out.it) < step:
            return out
        fired["count"] += 1
        idx = tuple(s // 2 for s in out.u.shape)
        return type(out)(
            u=out.u.at[idx].set(jnp.nan), t=out.t, it=out.it
        )

    solver.run = lambda st, n: poison(orig[0](st, n))
    solver.step = lambda st: poison(orig[1](st))
    solver.advance_to = lambda st, te: poison(orig[2](st, te))
    try:
        yield fired
    finally:
        solver.run, solver.step, solver.advance_to = orig


def _stepper_classes():
    """engaged_label -> fused stepper classes, imported lazily (the
    Pallas modules are heavyweight and the harness must import clean on
    rigs without them)."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (
        ShardedFusedBurgers2DStepper,
        ShardedFusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        FusedBurgersStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (
        FusedBurgers2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
        FusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (
        FusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (  # noqa: E501
        StepFusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunBurgersStepper,
        SlabRunDiffusionStepper,
    )

    return {
        "fused-whole-run-slab": (
            SlabRunDiffusionStepper, SlabRunBurgersStepper,
        ),
        "fused-stage": (
            FusedDiffusionStepper, FusedBurgersStepper,
            ShardedFusedDiffusion2DStepper, ShardedFusedBurgers2DStepper,
        ),
        "fused-whole-run": (
            FusedDiffusion2DStepper, FusedBurgers2DStepper,
        ),
        "fused-step": (StepFusedDiffusionStepper,),
    }


@contextlib.contextmanager
def mosaic_failure(rungs=None, detail: str = "fault injection"):
    """Within the context, dispatching any fused stepper whose
    ``engaged_label`` is in ``rungs`` (default: every fused rung) raises
    :class:`SimulatedMosaicError` — from ``run``/``run_to``, i.e. inside
    the jit trace, exactly where a real Mosaic rejection surfaces. The
    generic XLA path is untouched, so auto configs degrade and complete
    while pinned configs fail loudly."""
    classes = _stepper_classes()
    if rungs is None:
        rungs = tuple(classes)
    saved = []

    def _raiser(label):
        def run(self, *a, **kw):
            del a, kw
            raise SimulatedMosaicError(f"{detail} [{label}]")
        return run

    try:
        for label in rungs:
            for cls in classes[label]:
                for meth in ("run", "run_to"):
                    if hasattr(cls, meth):
                        saved.append((cls, meth, getattr(cls, meth)))
                        setattr(cls, meth, _raiser(label))
        yield
    finally:
        for cls, meth, fn in saved:
            setattr(cls, meth, fn)


def corrupt_checkpoint(path: str, nbytes: int = 8,
                       offset: Optional[int] = None) -> None:
    """Flip ``nbytes`` payload bytes in a ``.ckpt`` file (default: right
    after the 64-byte header) so the stored CRC32 no longer matches. For
    a ``.ckptd`` directory pass one of its shard files."""
    with open(path, "r+b") as f:
        f.seek(64 if offset is None else offset)
        data = f.read(nbytes)
        if not data:
            raise ValueError(f"nothing to corrupt at offset in {path}")
        f.seek(-len(data), os.SEEK_CUR)
        f.write(bytes(b ^ 0xFF for b in data))


def truncate_checkpoint(path: str, keep_bytes: int = 48) -> None:
    """Tear a checkpoint mid-write: keep only the first ``keep_bytes``
    (48 < the 64-byte header tears even the header)."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


def send_signal(pid: Optional[int] = None, signum=_signal.SIGTERM) -> None:
    """Deliver a real signal (default SIGTERM to this process) — the
    scheduler-preemption stand-in for in-process tests; subprocess tests
    use ``Popen.send_signal`` directly."""
    os.kill(os.getpid() if pid is None else pid, signum)
