"""Fault-injection harness driving ``tests/test_resilience.py``.

Every fault this framework claims to survive is injectable on a CPU-only
rig, so the recovery paths are tier-1-testable without hardware or an
actual preemption:

* :func:`nan_at_step` — poison the state with a NaN once the run crosses
  a global iteration (a transient numerical blow-up);
* :func:`mosaic_failure` — make fused-stepper dispatch raise a
  :class:`SimulatedMosaicError` whose message carries the real markers,
  exercising the kernel-ladder degradation exactly where a Mosaic
  compile/launch failure would surface;
* :func:`corrupt_checkpoint` / :func:`truncate_checkpoint` — bit-flip or
  tear a checkpoint file so CRC verification must catch it;
* :func:`send_signal` — deliver a real SIGTERM/SIGINT to a process (the
  scheduler-preemption stand-in).

Chaos harness (the distributed faults the watchdog + coordinated
recovery layer claims to survive, driven by ``tests/test_chaos.py``,
marker ``chaos``):

* :func:`kill_rank` — SIGKILL a rank's OS process: no cleanup runs, its
  collectives never complete (a dead host/preempted VM);
* :func:`stall_rank` — SIGSTOP a rank: the pid stays alive but its
  heartbeat goes stale and peers' collectives wedge (a livelocked or
  swapping rank — the failure MPI turns into an indefinite hang);
* :func:`sdc_at_step` — perturb ONE of the SDC guard's duplicate step
  executions so the bit-exact comparison must flag it;
* :func:`torn_ckptd_write` — tear a sharded ``.ckptd`` checkpoint the
  way a mid-write crash would (COMMIT removed, shard file missing,
  manifest gap/overlap), so the resume scan must skip it.
"""

from __future__ import annotations

import contextlib
import os
import signal as _signal
from typing import Optional

from multigpu_advectiondiffusion_tpu.resilience.errors import (
    SimulatedMosaicError,
)


@contextlib.contextmanager
def nan_at_step(solver, step: int, once: bool = True):
    """Within the context, the first state ``solver`` produces at or
    after global iteration ``step`` gets one NaN cell (at the block
    center). ``once=True`` models a transient fault — after a rollback
    the same injection does not re-fire; ``once=False`` a persistent
    one, which must exhaust the supervisor's retries."""
    import jax.numpy as jnp

    orig = (solver.run, solver.step, solver.advance_to)
    fired = {"count": 0}

    def poison(out):
        if (once and fired["count"]) or int(out.it) < step:
            return out
        fired["count"] += 1
        idx = tuple(s // 2 for s in out.u.shape)
        return type(out)(
            u=out.u.at[idx].set(jnp.nan), t=out.t, it=out.it
        )

    solver.run = lambda st, n: poison(orig[0](st, n))
    solver.step = lambda st: poison(orig[1](st))
    solver.advance_to = lambda st, te: poison(orig[2](st, te))
    try:
        yield fired
    finally:
        solver.run, solver.step, solver.advance_to = orig


def _stepper_classes():
    """engaged_label -> fused stepper classes, imported lazily (the
    Pallas modules are heavyweight and the harness must import clean on
    rigs without them)."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (
        ShardedFusedBurgers2DStepper,
        ShardedFusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        FusedBurgersStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (
        FusedBurgers2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
        FusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (
        FusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (  # noqa: E501
        StepFusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunBurgersStepper,
        SlabRunDiffusionStepper,
    )

    return {
        "fused-whole-run-slab": (
            SlabRunDiffusionStepper, SlabRunBurgersStepper,
        ),
        "fused-stage": (
            FusedDiffusionStepper, FusedBurgersStepper,
            ShardedFusedDiffusion2DStepper, ShardedFusedBurgers2DStepper,
        ),
        "fused-whole-run": (
            FusedDiffusion2DStepper, FusedBurgers2DStepper,
        ),
        "fused-step": (StepFusedDiffusionStepper,),
    }


@contextlib.contextmanager
def mosaic_failure(rungs=None, detail: str = "fault injection"):
    """Within the context, dispatching any fused stepper whose
    ``engaged_label`` is in ``rungs`` (default: every fused rung) raises
    :class:`SimulatedMosaicError` — from ``run``/``run_to``, i.e. inside
    the jit trace, exactly where a real Mosaic rejection surfaces. The
    generic XLA path is untouched, so auto configs degrade and complete
    while pinned configs fail loudly."""
    classes = _stepper_classes()
    if rungs is None:
        rungs = tuple(classes)
    saved = []

    def _raiser(label):
        def run(self, *a, **kw):
            del a, kw
            raise SimulatedMosaicError(f"{detail} [{label}]")
        return run

    try:
        for label in rungs:
            for cls in classes[label]:
                for meth in ("run", "run_to"):
                    if hasattr(cls, meth):
                        saved.append((cls, meth, getattr(cls, meth)))
                        setattr(cls, meth, _raiser(label))
        yield
    finally:
        for cls, meth, fn in saved:
            setattr(cls, meth, fn)


def corrupt_checkpoint(path: str, nbytes: int = 8,
                       offset: Optional[int] = None) -> None:
    """Flip ``nbytes`` payload bytes in a ``.ckpt`` file (default: right
    after the 64-byte header) so the stored CRC32 no longer matches. For
    a ``.ckptd`` directory pass one of its shard files."""
    with open(path, "r+b") as f:
        f.seek(64 if offset is None else offset)
        data = f.read(nbytes)
        if not data:
            raise ValueError(f"nothing to corrupt at offset in {path}")
        f.seek(-len(data), os.SEEK_CUR)
        f.write(bytes(b ^ 0xFF for b in data))


def truncate_checkpoint(path: str, keep_bytes: int = 48) -> None:
    """Tear a checkpoint mid-write: keep only the first ``keep_bytes``
    (48 < the 64-byte header tears even the header)."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


def send_signal(pid: Optional[int] = None, signum=_signal.SIGTERM) -> None:
    """Deliver a real signal (default SIGTERM to this process) — the
    scheduler-preemption stand-in for in-process tests; subprocess tests
    use ``Popen.send_signal`` directly."""
    os.kill(os.getpid() if pid is None else pid, signum)


# --------------------------------------------------------------------- #
# Chaos harness: distributed / torn-write faults
# --------------------------------------------------------------------- #
def _pid(proc) -> int:
    """Accept a pid or anything with a ``.pid`` (subprocess.Popen)."""
    return int(getattr(proc, "pid", proc))


def kill_rank(proc) -> None:
    """SIGKILL a rank's OS process. Nothing runs on the victim — no
    signal handlers, no atexit, no final checkpoint — and every
    collective its peers are in (or enter) can never complete: the
    fault the rank-liveness watchdog exists to bound."""
    os.kill(_pid(proc), _signal.SIGKILL)


def kill_server_mid_batch(proc, root: str, timeout: float = 60.0,
                          poll: float = 0.02) -> int:
    """SIGKILL the request server (``service/server.py``) once it is
    provably MID-BATCH: wait for a ``serve:slice`` event in the
    server's telemetry stream — one bounded ``advance_to_ensemble``
    slice committed, members checkpointed, more marching to do — then
    deliver SIGKILL. Returns the number of slice events observed at
    kill time; raises ``TimeoutError`` if the server never reaches a
    slice boundary (it may have died first — check the process).

    This is the chaos fixture of the zero-lost-request claim: the kill
    lands after journal records exist for in-flight requests but
    before they are done, so only a correct replay-and-resume restart
    can answer every request exactly once."""
    import time as _time

    events = os.path.join(root, "serve_events.jsonl")
    pid = _pid(proc)
    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        slices = 0
        try:
            with open(events) as f:
                for line in f:
                    if '"serve"' in line and '"slice"' in line:
                        slices += 1
        except OSError:
            slices = 0
        if slices:
            os.kill(pid, _signal.SIGKILL)
            return slices
        poll_fn = getattr(proc, "poll", None)
        if callable(poll_fn) and poll_fn() is not None:
            raise TimeoutError(
                "server exited before reaching a slice boundary "
                f"(rc={poll_fn()})"
            )
        _time.sleep(poll)
    raise TimeoutError(
        f"no serve:slice event in {events} within {timeout}s"
    )


def stall_rank(proc):
    """SIGSTOP a rank's OS process (pid stays alive, heartbeat goes
    stale — the wedged-not-dead failure). Returns a ``resume()``
    callable delivering SIGCONT; tolerate the victim having been killed
    meanwhile."""
    pid = _pid(proc)
    os.kill(pid, _signal.SIGSTOP)

    def resume():
        try:
            os.kill(pid, _signal.SIGCONT)
        except ProcessLookupError:
            pass

    return resume


@contextlib.contextmanager
def sdc_at_step(solver, step: int, once: bool = True,
                magnitude: float = 1e-3):
    """Within the context, ``solver.step`` calls whose output crosses
    global iteration ``step`` get one cell perturbed by ``magnitude`` —
    since the SDC guard executes the step TWICE and compares bit-exact,
    a corrupted execution models a hardware flake the guard must flag.
    ``once=True`` corrupts exactly the first such call (a transient
    flake: after rollback the guard re-checks clean and recovery
    completes); ``once=False`` corrupts every OTHER call (a flaky ALU:
    each duplicate pair keeps mismatching, which must exhaust the
    supervisor's retry budget — corrupting EVERY call would be
    undetectable by replay, both executions agreeing on the same wrong
    bits). The supervisor's chunked ``run`` calls are untouched, so the
    trajectory itself stays clean.
    """
    import jax.numpy as jnp

    orig = solver.step
    fired = {"count": 0}

    def wrapped(st):
        out = orig(st)
        if int(out.it) < step:
            return out
        fired["count"] += 1
        if once and fired["count"] > 1:
            return out
        if not once and fired["count"] % 2 == 0:
            return out
        idx = tuple(s // 2 for s in out.u.shape)
        bump = jnp.asarray(magnitude, out.u.dtype)
        return type(out)(
            u=out.u.at[idx].add(bump), t=out.t, it=out.it
        )

    solver.step = wrapped
    try:
        yield fired
    finally:
        solver.step = orig


@contextlib.contextmanager
def disk_full(targets=("checkpoint", "journal"), times: Optional[int] = None):
    """Within the context, the named durable-write paths raise
    ``OSError(ENOSPC)`` — the disk-full fault the scheduler must
    degrade under instead of dying (ISSUE 14 satellite):

    * ``'checkpoint'`` — ``utils/io.save_checkpoint`` and
      ``save_checkpoint_sharded`` (a job's checkpoint write fails; the
      scheduler classifies the attempt ``disk_full``, retries once,
      then marks the job failed with forensics);
    * ``'journal'`` — the scheduler journal's raw write
      (``service/journal.Journal._write``; the journal must park the
      record, mark itself degraded, and heal in order once the disk
      frees up).

    ``times=N`` fires only the first N writes (the freed-disk
    recovery case); ``None`` fires for the context's whole extent.
    Yields the fired-count dict like the other injectors."""
    import errno

    from multigpu_advectiondiffusion_tpu.utils import io as io_mod

    fired = {"count": 0}

    def _should_fire() -> bool:
        if times is not None and fired["count"] >= times:
            return False
        fired["count"] += 1
        return True

    saved = []

    def _patch(owner, name):
        orig = getattr(owner, name)
        saved.append((owner, name, orig))

        def inner(*a, **kw):
            if _should_fire():
                raise OSError(
                    errno.ENOSPC, "No space left on device (injected)"
                )
            return orig(*a, **kw)

        setattr(owner, name, inner)

    try:
        if "checkpoint" in targets:
            _patch(io_mod, "save_checkpoint")
            _patch(io_mod, "save_checkpoint_sharded")
        if "journal" in targets:
            from multigpu_advectiondiffusion_tpu.service.journal import (
                Journal,
            )

            _patch(Journal, "_write")
        yield fired
    finally:
        for owner, name, fn in saved:
            setattr(owner, name, fn)


@contextlib.contextmanager
def stall_dispatch(seconds: float, operand: Optional[str] = None,
                   value: Optional[float] = None,
                   times: Optional[int] = None):
    """Within the context, ``EnsembleSolver.advance_to`` calls sleep
    ``seconds`` of wall time before dispatching — the hung-dispatch
    fault (a wedged device, a pathological compile, a collective that
    never completes) the request server's slice-budget watchdog must
    bound (ISSUE 20).

    With ``operand``/``value`` set, only batches CARRYING a member
    whose override ``operand`` is within 1e-9 of ``value`` stall — the
    poison-member case: the watchdog's bisection must isolate exactly
    that member and let every cohort without it march clean.
    ``times=N`` fires only the first N stalls (a transient wedge);
    ``None`` stalls for the context's whole extent. Yields the
    fired-count dict like the other injectors."""
    import time as _time

    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    fired = {"count": 0}
    orig = EnsembleSolver.advance_to

    def _carries_poison(solver) -> bool:
        if operand is None:
            return True
        for ov in getattr(solver, "_overrides", []):
            try:
                if abs(float(ov.get(operand)) - float(value)) < 1e-9:
                    return True
            except (TypeError, ValueError):
                continue
        return False

    def stalled(self, *a, **kw):
        if _carries_poison(self) and (
            times is None or fired["count"] < times
        ):
            fired["count"] += 1
            _time.sleep(seconds)
        return orig(self, *a, **kw)

    EnsembleSolver.advance_to = stalled
    try:
        yield fired
    finally:
        EnsembleSolver.advance_to = orig


def torn_ckptd_write(directory: str, mode: str = "uncommitted") -> None:
    """Tear a sharded ``.ckptd`` checkpoint directory the way a
    mid-write crash (or bit-rot) would, so the verification/resume path
    must refuse it:

    * ``'uncommitted'`` — remove the COMMIT marker (the write never
      finished);
    * ``'missing_shard'`` — delete one shard file out from under the
      manifest;
    * ``'manifest_gap'`` — shrink one manifest entry's extent: cells of
      the global array are covered by no shard;
    * ``'manifest_overlap'`` — grow one manifest entry's extent into
      its neighbor (or out of bounds): two shards claim the same cells.
    """
    import glob
    import json

    if mode == "uncommitted":
        os.remove(os.path.join(directory, "COMMIT"))
        return
    if mode == "missing_shard":
        shards = sorted(glob.glob(os.path.join(directory, "shard_*.ckpt")))
        if not shards:
            raise ValueError(f"no shard files to remove in {directory}")
        os.remove(shards[-1])
        return
    if mode in ("manifest_gap", "manifest_overlap"):
        mpaths = sorted(
            glob.glob(os.path.join(directory, "manifest_p*.json"))
        )
        if not mpaths:
            raise ValueError(f"no process manifests in {directory}")
        with open(mpaths[0]) as f:
            m = json.load(f)
        entry = min(m["shards"], key=lambda e: tuple(e["start"]))
        delta = -1 if mode == "manifest_gap" else 1
        if entry["shape"][0] + delta <= 0:
            raise ValueError("shard too small to tear along axis 0")
        entry["shape"] = [entry["shape"][0] + delta] + entry["shape"][1:]
        # this IS the fault: the torn-checkpoint injector deliberately
        # rewrites a manifest in place to simulate the corruption the
        # atomic-write discipline prevents
        # tpucfd-check: allow[raw-artifact-write] — deliberate torn write
        with open(mpaths[0], "w") as f:
            json.dump(m, f)
        return
    raise ValueError(f"unknown torn-checkpoint mode {mode!r}")
