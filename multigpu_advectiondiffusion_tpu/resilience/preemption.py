"""Preemption-safe exit: SIGTERM/SIGINT -> final checkpoint -> exit 75.

Cluster schedulers announce preemption by signal. The guard converts the
first SIGTERM/SIGINT into a flag the chunked run loops poll between
device calls; the CLI then writes a final atomic checkpoint plus a
``preempt.json`` manifest and exits with :data:`EXIT_PREEMPTED` (75,
``EX_TEMPFAIL`` — "try again later", i.e. resume with ``--resume
auto``). A second signal restores the default handler and re-raises it,
so a wedged run can still be killed.
"""

from __future__ import annotations

import os
import signal

#: Documented CLI exit code for a preempted-but-checkpointed run
#: (os.EX_TEMPFAIL: rerun the same command with ``--resume auto``).
EXIT_PREEMPTED = 75


class PreemptionExit(SystemExit):
    """Raised by the run driver after the final checkpoint landed;
    carries :data:`EXIT_PREEMPTED` so the CLI process exits with the
    documented code."""

    def __init__(self, signum: int, checkpoint: str | None):
        self.signum = signum
        self.checkpoint = checkpoint
        super().__init__(EXIT_PREEMPTED)


class PreemptionGuard:
    """Context manager installing latch-style SIGTERM/SIGINT handlers.

    ``should_stop`` turns True at the first signal; handlers are
    restored on exit. Only the main thread of the main interpreter may
    install signal handlers — anywhere else (or under a test harness
    that already owns the signals) the guard degrades to an inert
    always-False flag rather than failing the run.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._old = {}
        self.signum = None
        self.active = False

    @property
    def should_stop(self) -> bool:
        return self.signum is not None

    def _handler(self, signum, frame):
        del frame
        if self.signum is not None:
            # second signal: stop politely waiting — die the default way
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self.signum = signum

    def __enter__(self):
        try:
            for s in self._signals:
                self._old[s] = signal.signal(s, self._handler)
            self.active = True
        except ValueError:
            # not the main thread: handlers cannot install (the first
            # signal.signal call raises before any handler changed) —
            # degrade to an inert always-False flag
            self._old = {}
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old = {}
        self.active = False
        return False
