"""Structured resilience errors + the kernel-failure classifier.

Kept dependency-free (no jax import at module level) so the dispatch
layer (``models/base.py``) can import it without cycles.
"""

from __future__ import annotations


class SolverDivergedError(RuntimeError):
    """The divergence sentinel found a non-finite field or a norm past
    the growth bound. Carries the structured facts a supervisor needs to
    roll back and retry: the global step, the simulated time, and the
    offending max-norm."""

    def __init__(self, step: int, t: float, norm: float,
                 reason: str = "non-finite field"):
        self.step = int(step)
        self.t = float(t)
        self.norm = float(norm)
        self.reason = reason
        super().__init__(
            f"solver diverged at step {self.step} (t={self.t:.6g}): "
            f"{reason} (max|u| = {self.norm:.6g})"
        )


class SimulatedMosaicError(RuntimeError):
    """Fault-injection stand-in for a Mosaic compile/launch failure.

    The message carries the same markers the classifier keys on, so the
    dispatch layer's ladder degradation treats it exactly like the real
    thing (``resilience/faults.py`` raises it from a stepper's dispatch
    point)."""

    def __init__(self, detail: str = "injected fault"):
        super().__init__(
            f"Mosaic failed to compile the Pallas kernel: {detail}"
        )


# Substrings (lowercased) identifying a Pallas/Mosaic compile or launch
# failure in an exception's type name or message. Deliberately narrow:
# a generic numerical error must NOT be retried on a slower rung — only
# kernel-infrastructure failures are recoverable by changing kernels.
_KERNEL_FAILURE_MARKERS = (
    "mosaic",
    "pallas",
    "tpu_custom_call",
    "vmem",  # scoped-VMEM / VMEM-limit compile rejections
    "xla tpu compile",
)


def is_kernel_failure(exc: BaseException) -> bool:
    """Whether ``exc`` looks like a Pallas/Mosaic compile or launch
    failure that a lower kernel-ladder rung could avoid."""
    if isinstance(exc, SolverDivergedError):
        return False  # physics, not kernels — handled by the supervisor
    if isinstance(exc, (KeyboardInterrupt, SystemExit, MemoryError)):
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in _KERNEL_FAILURE_MARKERS)
