"""Structured resilience errors + the kernel-failure classifier.

Kept dependency-free (no jax import at module level) so the dispatch
layer (``models/base.py``) can import it without cycles.
"""

from __future__ import annotations


class SolverDivergedError(RuntimeError):
    """The divergence sentinel found a non-finite field or a norm past
    the growth bound. Carries the structured facts a supervisor needs to
    roll back and retry: the global step, the simulated time, and the
    offending max-norm."""

    def __init__(self, step: int, t: float, norm: float,
                 reason: str = "non-finite field"):
        self.step = int(step)
        self.t = float(t)
        self.norm = float(norm)
        self.reason = reason
        super().__init__(
            f"solver diverged at step {self.step} (t={self.t:.6g}): "
            f"{reason} (max|u| = {self.norm:.6g})"
        )


class EnsembleMemberDivergedError(SolverDivergedError):
    """One (or more) members of a batched ensemble run diverged.

    The ensemble sentinel reduces PER MEMBER — one member's NaN or
    norm blow-up must name its index instead of poisoning the whole
    batch's verdict. Carries ``members`` (offending indices) and
    ``norms`` (their max-norms); ``norm`` is the worst one, so the
    error still quacks like a :class:`SolverDivergedError` for
    existing handlers."""

    def __init__(self, step: int, t: float, members, norms,
                 reason: str = "non-finite field"):
        self.members = [int(m) for m in members]
        self.member_norms = [float(n) for n in norms]
        worst = max(
            (n for n in self.member_norms), default=float("nan")
        )
        super().__init__(
            step, t, worst,
            reason=(
                f"{reason} in ensemble member(s) "
                f"{self.members} of the batch"
            ),
        )


class SDCDetectedError(SolverDivergedError):
    """The silent-data-corruption guard re-executed one step from a
    probed state and the two executions disagreed bit-for-bit on a
    deterministic rung — a hardware/memory flake, not physics. Subclasses
    :class:`SolverDivergedError` so the supervisor's existing rollback
    path recovers it (without a dt backoff: the time step is not the
    problem); if retries run out, the CLI maps it to :data:`EXIT_SDC`."""

    def __init__(self, step: int, t: float, mismatched_cells: int = 0):
        self.mismatched_cells = int(mismatched_cells)
        self.step = int(step)
        self.t = float(t)
        self.norm = float("nan")
        self.reason = (
            "silent data corruption: duplicate executions of one step "
            f"differ in {self.mismatched_cells} cell(s)"
        )
        RuntimeError.__init__(
            self,
            f"SDC detected at step {self.step} (t={self.t:.6g}): "
            f"{self.mismatched_cells} cell(s) differ between bit-exact "
            "duplicate executions",
        )


class PhysicsViolationError(SolverDivergedError):
    """A tolerance-guarded physics invariant broke (maximum-principle
    breach, total-variation growth — ``diagnostics/physics.py``) while
    the field was still finite and inside the norm bound.

    Raised only under the opt-in ``--diag-strict`` escalation; it
    subclasses :class:`SolverDivergedError` so the supervisor's
    existing rollback-and-retry path recovers it WITH the dt backoff
    (a broken invariant under WENO/RK3 usually means the step outran
    the resolution — exactly what the backoff schedule treats)."""

    def __init__(self, step: int, t: float, norm: float,
                 violations=()):
        self.violations = list(violations)
        what = "; ".join(
            v.get("message", v.get("rule", "?")) for v in self.violations
        ) or "physics invariant violated"
        super().__init__(step, t, norm, reason=f"physics violation: {what}")


class SanitizerError(SolverDivergedError):
    """The checkify sanitizer (``analysis/sanitizer.py``, the
    ``--checkify`` mode) caught a NaN / division-by-zero / OOB index
    *inside* an instrumented stepper — at the offending primitive, one
    chunk earlier than the divergence sentinel's norm probe would
    notice the fallout, and named (checkify's message carries the
    primitive and source line).

    Subclasses :class:`SolverDivergedError` so the supervisor's
    existing rollback + dt-backoff path recovers it unchanged — the
    second oracle the fault-injection suite reads. ``step``/``t`` are
    unknown at the dispatch wrapper (-1/nan) unless the catcher fills
    them in."""

    def __init__(self, message: str, step: int = -1,
                 t: float = float("nan")):
        self.checkify_message = str(message)
        super().__init__(
            step, t, float("nan"), reason=f"checkify: {message}"
        )


#: Documented CLI exit code when a peer rank died or stalled past the
#: watchdog timeout: the survivor aborts instead of hanging in a
#: collective forever. Restart the job (on the surviving topology if a
#: host is gone) with ``--resume auto``.
EXIT_RANK_FAILURE = 76

#: Documented CLI exit code when the silent-data-corruption guard
#: detected a duplicate-execution mismatch and the rollback budget ran
#: out — the hardware (or memory) is flaking faster than recovery can
#: absorb; the run directory still holds the last committed checkpoint.
EXIT_SDC = 77


class RankFailureError(RuntimeError):
    """A peer process of a multi-process run is dead or wedged.

    Raised by the rank-liveness watchdog (``parallel/multihost.py``)
    when a peer's heartbeat record goes stale, its pid dies, or a
    timeout-wrapped collective never completes. Carries the offending
    rank (``None`` when the watchdog cannot attribute the failure to a
    single peer) so the survivor's exit report names who to blame; the
    CLI maps it to :data:`EXIT_RANK_FAILURE`.
    """

    def __init__(self, rank, reason: str, detected_by=None, suspects=()):
        self.rank = None if rank is None else int(rank)
        self.reason = reason
        self.detected_by = None if detected_by is None else int(detected_by)
        self.suspects = list(suspects)
        who = f"rank {self.rank}" if self.rank is not None else "a peer rank"
        super().__init__(f"{who} failed: {reason}")


class CoordinationError(RuntimeError):
    """Cross-rank agreement on a rollback/checkpoint decision failed:
    the ranks proposed different values — a control-flow desync that
    must abort loudly instead of letting ranks continue from different
    checkpoints (the torn-recovery failure mode coordinated rollback
    exists to rule out)."""

    def __init__(self, tag: str, per_rank_values):
        self.tag = tag
        self.per_rank_values = per_rank_values
        super().__init__(
            f"cross-rank agreement {tag!r} failed: ranks proposed "
            f"different values {per_rank_values}"
        )


class SimulatedMosaicError(RuntimeError):
    """Fault-injection stand-in for a Mosaic compile/launch failure.

    The message carries the same markers the classifier keys on, so the
    dispatch layer's ladder degradation treats it exactly like the real
    thing (``resilience/faults.py`` raises it from a stepper's dispatch
    point)."""

    def __init__(self, detail: str = "injected fault"):
        super().__init__(
            f"Mosaic failed to compile the Pallas kernel: {detail}"
        )


# Substrings (lowercased) identifying a Pallas/Mosaic compile or launch
# failure in an exception's type name or message. Deliberately narrow:
# a generic numerical error must NOT be retried on a slower rung — only
# kernel-infrastructure failures are recoverable by changing kernels.
_KERNEL_FAILURE_MARKERS = (
    "mosaic",
    "pallas",
    "tpu_custom_call",
    "vmem",  # scoped-VMEM / VMEM-limit compile rejections
    "xla tpu compile",
)


def is_kernel_failure(exc: BaseException) -> bool:
    """Whether ``exc`` looks like a Pallas/Mosaic compile or launch
    failure that a lower kernel-ladder rung could avoid."""
    if isinstance(exc, SolverDivergedError):
        return False  # physics, not kernels — handled by the supervisor
    if isinstance(exc, (RankFailureError, CoordinationError)):
        return False  # a dead/desynced peer: no rung change can help
    if isinstance(exc, (KeyboardInterrupt, SystemExit, MemoryError)):
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in _KERNEL_FAILURE_MARKERS)
