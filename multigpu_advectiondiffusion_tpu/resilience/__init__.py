"""Resilient run supervision: divergence sentinels, rollback-and-retry,
graceful kernel-ladder degradation, preemption-safe exit.

The reference's multi-GPU runs are fire-and-forget: a NaN blow-up, a
killed rank, or a failed kernel launch loses the whole run (SURVEY §2.1 —
there is no restart, no health check, no fault path anywhere in
``MultiGPU/*/main.c``). Long-running TPU CFD frameworks treat fault
handling as part of the solver (Wang et al., arXiv:2108.11076; PALABOS,
arXiv:2506.09242); this subsystem does the same for this framework:

* :mod:`~.sentinel` — jitted, mesh-aware health probes (all-finite +
  norm-growth bound via the solvers' own ``mesh_reduce_max`` machinery)
  sampled between fused-run calls, raising a structured
  :class:`SolverDivergedError` without breaking the whole-run rungs;
* :mod:`~.supervisor` — :func:`supervise_run` wraps ``run``/``advance_to``
  with periodic checkpointing and, on divergence, rolls back to the last
  good state and retries under a reduced-dt/CFL backoff schedule;
* :mod:`~.recovery` — ``--resume auto``: newest CRC-valid checkpoint in a
  directory, corrupt/truncated ones skipped with a report;
* :mod:`~.preemption` — SIGTERM/SIGINT trigger a final atomic checkpoint
  + manifest and a documented exit code (:data:`EXIT_PREEMPTED`);
* :mod:`~.faults` — the fault-injection harness driving
  ``tests/test_resilience.py`` (NaN-at-step-N, simulated Mosaic failure,
  checkpoint truncation/corruption, simulated SIGTERM) and the chaos
  harness driving ``tests/test_chaos.py`` (``kill_rank``/``stall_rank``
  against real OS processes, ``sdc_at_step``, ``torn_ckptd_write``).

The DISTRIBUTED fault-tolerance layer (ISSUE 5) lives across this
package and ``parallel/multihost.py``: a rank-liveness watchdog
(heartbeat records + timeout-wrapped collectives, structured
:class:`RankFailureError` + exit code :data:`EXIT_RANK_FAILURE` instead
of an MPI-style indefinite hang), coordinated cross-rank
rollback/checkpoint agreement (asserted via ``multihost.agree``,
:class:`CoordinationError` on desync), COMMIT-marker torn-write defense
for ``.ckptd`` directories with elastic resharded resume, and an opt-in
silent-data-corruption guard at sentinel cadence
(:class:`SDCDetectedError`, exit code :data:`EXIT_SDC` when
unrecoverable).

Graceful kernel-ladder degradation itself lives at the dispatch layer
(``models/base.py``): under ``impl='pallas'`` (best-available) a
Pallas/Mosaic compile or launch failure falls down the ladder
``pallas_slab -> pallas_stage -> xla`` with the downgrade recorded in
``engaged_path()['degraded']``; explicit rung pins still fail loudly.
"""

from multigpu_advectiondiffusion_tpu.resilience.errors import (
    EXIT_RANK_FAILURE,
    EXIT_SDC,
    CoordinationError,
    RankFailureError,
    SDCDetectedError,
    SimulatedMosaicError,
    SolverDivergedError,
    is_kernel_failure,
)
from multigpu_advectiondiffusion_tpu.resilience.preemption import (
    EXIT_PREEMPTED,
    PreemptionExit,
    PreemptionGuard,
)
from multigpu_advectiondiffusion_tpu.resilience.recovery import (
    find_latest_checkpoint,
)
from multigpu_advectiondiffusion_tpu.resilience.sentinel import (
    DivergenceSentinel,
    make_health_probe,
)
from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
    SupervisorReport,
    scale_dt,
    supervise_run,
)

__all__ = [
    "EXIT_PREEMPTED",
    "EXIT_RANK_FAILURE",
    "EXIT_SDC",
    "CoordinationError",
    "DivergenceSentinel",
    "PreemptionExit",
    "PreemptionGuard",
    "RankFailureError",
    "SDCDetectedError",
    "SimulatedMosaicError",
    "SolverDivergedError",
    "SupervisorReport",
    "find_latest_checkpoint",
    "is_kernel_failure",
    "make_health_probe",
    "scale_dt",
    "supervise_run",
]
