"""Divergence sentinels: jitted, mesh-aware health probes.

A probe is one tiny jitted program — ``max|u|`` reduced across the
device mesh through the solver's own ``mesh_reduce_max`` machinery (the
same pmax axis-name set the fused steppers' adaptive dt uses) — sampled
*between* fused-run calls. The whole-run slab rung therefore keeps its
one-Pallas-program-per-chunk shape; the sentinel costs one extra
O(cells) reduction per cadence, not a change of stepper (cost measured
in PARITY.md "Failure modes & resilience").

The probe maps non-finite cells to ``+inf`` before reducing (XLA's
reduce-max combiner does not reliably propagate NaN, notably across
shard boundaries), so a single NaN/Inf cell anywhere in the global
field makes the replicated probe value ``+inf`` on every process —
all-finite and norm-growth checks ride one scalar.
"""

from __future__ import annotations

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.resilience.errors import (
    SolverDivergedError,
)


def make_health_probe(solver):
    """``state -> float max|u|`` as one jitted (and, under a mesh,
    shard_mapped) call; the reduction is replicated so every process
    reads the same scalar."""
    reduce = solver.mesh_reduce_max() if solver.mesh is not None else None

    def block(u, m0):
        del m0
        a = jnp.abs(u).astype(jnp.float32)
        # NaN -> +inf BEFORE reducing: XLA's reduce-max combiner does
        # not reliably propagate NaN (observed dropped across shard
        # boundaries on CPU), while max(+inf, x) = +inf always — so one
        # non-finite cell anywhere makes the replicated probe +inf
        a = jnp.where(jnp.isnan(a), jnp.inf, a)
        m = jnp.max(a)
        if reduce is not None:
            m = reduce(m)
        return u, m

    f = solver._wrap(block)

    def probe(state) -> float:
        _, m = f(state.u, jnp.zeros((), jnp.float32))
        return float(m)

    return probe


class DivergenceSentinel:
    """All-finite + norm-growth health check against a solver's state.

    ``growth`` bounds ``max|u|`` at ``growth * max(1, max|u0|)`` — both
    model families are max-norm non-increasing (diffusion decays, the
    WENO Burgers schemes are essentially non-oscillatory), so real
    growth past a generous factor means the integration left physics.
    """

    def __init__(self, solver, growth: float = 1e3):
        self._probe = make_health_probe(solver)
        self.growth = float(growth)
        self.bound = None

    def arm(self, state) -> float:
        """Record the healthy baseline norm (call once on the initial
        state; re-arm after a rollback changes the reference)."""
        norm0 = self._probe(state)
        if not jnp.isfinite(norm0):
            raise SolverDivergedError(
                int(state.it), float(state.t), norm0,
                reason="non-finite initial state",
            )
        self.bound = self.growth * max(1.0, norm0)
        return norm0

    def check(self, state) -> float:
        """One probe; raises :class:`SolverDivergedError` on a
        non-finite field or a norm past the growth bound."""
        norm = self._probe(state)
        if not jnp.isfinite(norm):
            raise SolverDivergedError(
                int(state.it), float(state.t), norm,
                reason="non-finite field",
            )
        if self.bound is not None and norm > self.bound:
            raise SolverDivergedError(
                int(state.it), float(state.t), norm,
                reason=f"norm grew past {self.bound:.6g} "
                       f"(growth bound {self.growth:g})",
            )
        return norm
