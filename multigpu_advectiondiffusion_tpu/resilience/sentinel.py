"""Divergence sentinels: jitted, mesh-aware health probes.

A probe is one tiny jitted program — ``max|u|`` reduced across the
device mesh through the solver's own ``mesh_reduce_max`` machinery (the
same pmax axis-name set the fused steppers' adaptive dt uses) — sampled
*between* fused-run calls. The whole-run slab rung therefore keeps its
one-Pallas-program-per-chunk shape; the sentinel costs one extra
O(cells) reduction per cadence, not a change of stepper (cost measured
in PARITY.md "Failure modes & resilience").

The probe maps non-finite cells to ``+inf`` before reducing (XLA's
reduce-max combiner does not reliably propagate NaN, notably across
shard boundaries), so a single NaN/Inf cell anywhere in the global
field makes the replicated probe value ``+inf`` on every process —
all-finite and norm-growth checks ride one scalar.

The same jitted program also carries the *physics* probe the telemetry
stream consumes (one fused reduction pass, no extra dispatch): min/max
of ``u``, the L2 norm and the mass integral ``vol * sum(u)`` — both
model families conserve/decay mass, so the mass-integral drift against
the armed baseline is the cheapest global correctness signal a long
run can stream (``physics`` events; drift line in ``RunSummary``).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.resilience.errors import (
    SolverDivergedError,
)


def make_health_probe(solver, diagnostics: bool = False):
    """``state -> dict`` of replicated global scalars as one jitted
    (and, under a mesh, shard_mapped) call: ``max_abs`` (non-finite
    mapped to +inf), ``min``, ``max``, ``l2`` and ``mass`` (both
    volume-weighted, matching ``utils.metrics`` conventions).

    ``diagnostics=True`` fuses the solver's physics-observable suite
    (``diagnostics/physics.py`` — conservation budgets, total
    variation, the spectral high-wavenumber tail, per-solver extras)
    into the SAME jitted block: the extra scalars ride the probe's
    existing field pass and the two stacked mesh reductions (one psum
    vector, one pmax vector), so the whole suite costs at most one
    extra HBM read and ZERO additional compiled programs — the
    compile-count proof lives in ``tests/test_diagnostics.py``. The
    returned probe exposes ``probe.traces`` (trace-time counter) and
    ``probe.observable_keys`` for that proof."""
    reduce_max = (
        solver.mesh_reduce_max() if solver.mesh is not None else None
    )
    reduce_sum = (
        solver.mesh_reduce_sum() if solver.mesh is not None else None
    )
    vol = math.prod(solver.grid.spacing)
    observables = []
    if diagnostics:
        from multigpu_advectiondiffusion_tpu.diagnostics import physics

        observables = physics.observables_for(solver)
    sum_keys = [k for ob in observables if ob.reduction == "sum"
                for k in ob.keys]
    max_keys = [k for ob in observables if ob.reduction == "max"
                for k in ob.keys]
    traces = {"count": 0}

    def block(u, z):
        del z
        traces["count"] += 1  # python side-effect: counts TRACES only
        a = jnp.abs(u).astype(jnp.float32)
        # NaN -> +inf BEFORE reducing: XLA's reduce-max combiner does
        # not reliably propagate NaN (observed dropped across shard
        # boundaries on CPU), while max(+inf, x) = +inf always — so one
        # non-finite cell anywhere makes the replicated probe +inf
        a = jnp.where(jnp.isnan(a), jnp.inf, a)
        uf = u.astype(jnp.float32)
        maxes = [jnp.max(a), jnp.max(uf), jnp.max(-uf)]
        sums = [jnp.sum(uf), jnp.sum(uf * uf)]
        for ob in observables:
            vals = ob.local(uf)
            dst = sums if ob.reduction == "sum" else maxes
            for i in range(len(ob.keys)):
                dst.append(vals[i])
        sv = jnp.stack(sums)
        mv = jnp.stack(maxes)
        if reduce_max is not None:
            mv = reduce_max(mv)
        if reduce_sum is not None:
            sv = reduce_sum(sv)
        return u, jnp.concatenate([mv, sv])

    f = solver._wrap(block)

    def probe(state) -> dict:
        nm = 3 + len(max_keys)
        _, v = f(state.u, jnp.zeros((1,), jnp.float32))
        vals = [float(x) for x in v]
        m, umax, neg_umin = vals[0], vals[1], vals[2]
        s, s2 = vals[nm], vals[nm + 1]
        stats = {
            "max_abs": m,
            "min": -neg_umin,
            "max": umax,
            "l2": math.sqrt(max(vol * s2, 0.0)) if math.isfinite(s2) else s2,
            "mass": vol * s,
        }
        if observables:
            raw = dict(zip(max_keys, vals[3:nm]))
            raw.update(zip(sum_keys, vals[nm + 2:]))
            for ob in observables:
                stats.update(ob.finalize_raw(solver, raw))
        return stats

    probe.traces = traces
    probe.observable_keys = tuple(
        k for ob in observables for k in ob.output_keys
    )
    return probe


def make_ensemble_probe_parts(solver):
    """The ensemble probe split at its device/host seam:
    ``(launch, collect)``. ``launch(estate)`` enqueues the jitted
    vmapped reduction and returns DEVICE arrays without blocking (JAX
    async dispatch); ``collect(launched)`` pulls the tiny per-member
    stats to host floats. The pipelined server (ISSUE 19) launches at
    dispatch time — before the state buffer is donated into the next
    slice — and collects at retirement, so the health check never
    needs a live ``u`` and never stalls the pipeline."""
    import jax

    vol = math.prod(solver.grid.spacing)

    def one(u):
        a = jnp.abs(u).astype(jnp.float32)
        a = jnp.where(jnp.isnan(a), jnp.inf, a)
        uf = u.astype(jnp.float32)
        return (
            jnp.max(a), jnp.min(uf), jnp.max(uf),
            jnp.sum(uf * uf), jnp.sum(uf),
        )

    f = jax.jit(jax.vmap(one))

    def launch(estate):
        return f(estate.u)

    def collect(launched) -> dict:
        m, umin, umax, s2, s = (
            list(map(float, v)) for v in launched
        )
        return {
            "max_abs": m,
            "min": umin,
            "max": umax,
            "l2": [
                math.sqrt(max(vol * x, 0.0)) if math.isfinite(x) else x
                for x in s2
            ],
            "mass": [vol * x for x in s],
        }

    return launch, collect


def make_ensemble_probe(solver):
    """Per-member health/physics probe for batched ensemble states:
    ``EnsembleState -> {key: (B,) list}`` of ``max_abs`` (non-finite
    mapped to +inf, like the single-run probe), ``min``, ``max``,
    ``l2`` and ``mass`` — ONE jitted vmapped reduction pass, reduced
    along each member's own axes only, so one diverging member reports
    its index instead of poisoning the batch (the member analog of the
    mesh-aware probe above). Ensemble runs are single-device per
    member, so no mesh reduction applies."""
    launch, collect = make_ensemble_probe_parts(solver)

    def probe(estate) -> dict:
        return collect(launch(estate))

    return probe


def duplicate_step_check(solver, state):
    """Silent-data-corruption probe: execute ONE step twice from the
    same ``state`` and compare the results bit-for-bit.

    On a deterministic rung (every rung of this framework: the step
    functions are pure jitted programs with no RNG and a fixed
    reduction order per compiled executable) two executions of the same
    compiled step on the same operands must agree exactly; any
    mismatch is a hardware/memory flake — the silent corruption that
    otherwise propagates into every later state and checkpoint.
    Sharded-safe: the elementwise inequality reduces over the global
    array, so every process sees the same replicated verdict (the
    comparison itself is the cheap part — the cost is the two extra
    steps, paid only at the opt-in cadence).

    Returns ``(ok, mismatched_cells)``.
    """
    import jax.numpy as jnp

    a = solver.step(state)
    b = solver.step(state)
    mismatched = int(jnp.sum(a.u != b.u))
    return mismatched == 0, mismatched


class DivergenceSentinel:
    """All-finite + norm-growth health check against a solver's state.

    ``growth`` bounds ``max|u|`` at ``growth * max(1, max|u0|)`` — both
    model families are max-norm non-increasing (diffusion decays, the
    WENO Burgers schemes are essentially non-oscillatory), so real
    growth past a generous factor means the integration left physics.

    Every probe also refreshes :attr:`stats` — the physics scalars of
    the last checked state (min/max/l2/mass plus ``mass_drift``, the
    relative drift of the mass integral against the armed baseline) —
    which the supervisor streams as ``physics`` telemetry events.

    ``diagnostics=True`` arms the full in-situ physics suite
    (``diagnostics/physics.py``) inside the SAME jitted probe: every
    checked state's stats then carry the fused observables
    (conservation budgets, TV, spectral tail, per-solver extras), the
    run-initial :attr:`baseline` is recorded once on first arm (the
    reference the tolerance rules and drift reports read against — a
    rollback re-arm does not move it, like ``mass0``), and
    :meth:`check_violations` evaluates the solver's tolerance rules
    (max-principle, TV-monotonicity) against it.
    """

    def __init__(self, solver, growth: float = 1e3,
                 diagnostics: bool = False):
        self._probe = make_health_probe(solver, diagnostics=diagnostics)
        self.growth = float(growth)
        self.bound = None
        self.mass0 = None
        self.stats = None
        self.diagnostics = bool(diagnostics)
        self.baseline = None
        self.rules = []
        self.meta = {}
        if diagnostics:
            from multigpu_advectiondiffusion_tpu.diagnostics import physics

            self.rules = physics.rules_for(solver)
            self.meta = physics.meta_for(solver)

    def _stats_with_drift(self, stats: dict) -> dict:
        if self.mass0 is not None:
            stats["mass_drift"] = (stats["mass"] - self.mass0) / max(
                abs(self.mass0), 1e-30
            )
        self.stats = stats
        return stats

    def arm(self, state) -> float:
        """Record the healthy baseline norm and mass integral (call once
        on the initial state; re-arm after a rollback changes the
        reference)."""
        stats = self._probe(state)
        norm0 = stats["max_abs"]
        if not jnp.isfinite(norm0):
            raise SolverDivergedError(
                int(state.it), float(state.t), norm0,
                reason="non-finite initial state",
            )
        self.bound = self.growth * max(1.0, norm0)
        # the baseline survives re-arming after a rollback ONLY if unset:
        # drift is always reported against the run's initial state
        if self.mass0 is None:
            self.mass0 = stats["mass"]
        if self.baseline is None:
            self.baseline = dict(stats)
        self._stats_with_drift(stats)
        return norm0

    def check_violations(self, stats=None):
        """Evaluate the solver's tolerance rules against the run-initial
        baseline (empty list when clean, diagnostics off, or not yet
        armed). Host-side only — the scalars were already paid for by
        the fused probe."""
        if not self.rules or self.baseline is None:
            return []
        from multigpu_advectiondiffusion_tpu.diagnostics import physics

        return physics.check_violations(
            self.rules, stats if stats is not None else (self.stats or {}),
            self.baseline,
        )

    def check(self, state) -> float:
        """One probe; raises :class:`SolverDivergedError` on a
        non-finite field or a norm past the growth bound."""
        stats = self._probe(state)
        norm = stats["max_abs"]
        if not jnp.isfinite(norm):
            raise SolverDivergedError(
                int(state.it), float(state.t), norm,
                reason="non-finite field",
            )
        self._stats_with_drift(stats)
        if self.bound is not None and norm > self.bound:
            raise SolverDivergedError(
                int(state.it), float(state.t), norm,
                reason=f"norm grew past {self.bound:.6g} "
                       f"(growth bound {self.growth:g})",
            )
        return norm
