"""``--resume auto``: newest CRC-valid checkpoint in a run directory.

Selection rules (documented in README "Failure modes & resilience"):

1. candidates are rotation-managed names —
   ``checkpoint_<iteration>.{ckpt,npz,ckptd}`` with a purely numeric
   iteration stem (the same filter ``rotate_checkpoints`` applies, so a
   user file like ``checkpoint_best.ckpt`` is never auto-selected);
2. newest first by iteration number (name order == write order);
3. the first candidate that passes full integrity verification wins —
   header parse, payload CRC32, and for ``.ckptd`` directories the
   COMMIT marker, the manifest's exact tiling of the global index
   space (no gaps, no overlaps) plus every shard's CRC;
4. corrupt/truncated/uncommitted candidates are reported to stderr and
   skipped — the exact behavior a preempted (or SIGKILLed) run needs
   when it died mid-write: a ``.ckptd`` directory torn before its
   COMMIT landed is named in the report and never selected.

Returns ``None`` when nothing valid exists — the caller starts from the
initial condition.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from multigpu_advectiondiffusion_tpu.utils import io as io_utils

_CKPT_SUFFIXES = (".ckpt", ".npz", ".ckptd")


def _iteration(name: str, prefix: str) -> Optional[int]:
    stem = name[len(prefix):].rsplit(".", 1)[0]
    return int(stem) if stem.isdigit() else None


def scan_checkpoints(directory: str, prefix: str = "checkpoint_"):
    """Rotation-managed checkpoint names in ``directory``, newest first
    (by iteration number, then name — same ordering the rotator uses)."""
    if not os.path.isdir(directory):
        return []
    names = [
        name
        for name in os.listdir(directory)
        if name.startswith(prefix)
        and name.endswith(_CKPT_SUFFIXES)
        and _iteration(name, prefix) is not None
    ]
    names.sort(key=lambda n: (_iteration(n, prefix), n), reverse=True)
    return names


def find_latest_checkpoint(
    directory: str, prefix: str = "checkpoint_", report=None
) -> Optional[str]:
    """Path of the newest checkpoint in ``directory`` that passes CRC
    verification, or ``None``. ``report`` (default: stderr print)
    receives one message per skipped corrupt candidate."""
    if report is None:
        def report(msg):
            print(msg, file=sys.stderr)

    for name in scan_checkpoints(directory, prefix):
        path = os.path.join(directory, name)
        try:
            io_utils.verify_checkpoint(path)
        except (IOError, OSError, ValueError) as err:
            report(
                f"--resume auto: skipping corrupt checkpoint {path}: {err}"
            )
            continue
        return path
    return None
