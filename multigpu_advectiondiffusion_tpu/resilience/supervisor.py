"""Supervised run loop: chunked execution with divergence sentinels,
rollback-and-retry under a dt/CFL backoff schedule, periodic
checkpointing and preemption-aware early exit.

The loop wraps the solvers' own ``run``/``advance_to`` drivers in
cadence-sized chunks, so every chunk still executes at the engaged
rung's full speed (the whole-run slab stepper runs one Pallas program
per chunk); the supervisor adds one health probe per cadence and a
host-side copy of the last known-good state for rollback.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from multigpu_advectiondiffusion_tpu import telemetry
from multigpu_advectiondiffusion_tpu.resilience.errors import (
    SDCDetectedError,
    SolverDivergedError,
)
from multigpu_advectiondiffusion_tpu.resilience.sentinel import (
    DivergenceSentinel,
    duplicate_step_check,
)

#: declared agree-tag namespace of the supervised loop (queryable
#: collective metadata, aggregated by ``parallel.multihost.
#: collective_spec``): every coordinated decision this module asserts
#: across ranks uses exactly one of these tags, and the static
#: collective-schedule verifier holds the call sites to this list in
#: both directions — a new ``_agree(...)`` tag must be declared here
#: or ``tpucfd-check`` fails the tree
AGREE_TAGS = ("checkpoint", "rollback")


@dataclasses.dataclass
class SupervisorReport:
    """What happened while supervising — lands in ``RunSummary``."""

    sentinel_every: int = 0
    probes: int = 0
    retries: int = 0
    events: List[dict] = dataclasses.field(default_factory=list)
    preempted: bool = False
    final_norm: Optional[float] = None
    # silent-data-corruption guard (opt-in, probe cadence): checks run,
    # detections caught — a detection also lands in ``events`` with the
    # rollback it triggered
    sdc_every: int = 0
    sdc_checks: int = 0
    sdc_detects: int = 0
    # True when rollback/checkpoint decisions were asserted identical
    # across ranks (multi-process runs)
    coordinated: bool = False
    # physics-probe facts of the LAST probe (chunk cadence): relative
    # mass-integral drift vs the armed initial state, plus the full
    # min/max/L2/mass scalars — the drift line in RunSummary.print_block
    mass_drift: Optional[float] = None
    physics: Optional[dict] = None
    # in-situ physics-diagnostics record (diag_every > 0): the fused
    # observable suite's per-probe trajectory, the armed baseline, every
    # tolerance-rule violation, and the per-solver meta (analytic decay
    # rate etc.) — the science gate (diagnostics/compare.py) diffs the
    # trajectory between rounds
    diag_every: int = 0
    diagnostics: Optional[dict] = None
    # step-time record of the live watch (telemetry/live.py): chunk
    # count, robust median, outliers, histogram — the wall-clock health
    # the resilience stack otherwise only sees after a failure
    perf: Optional[dict] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def scale_dt(solver, factor: float) -> str:
    """Back off the solver's time step by ``factor``: the fixed ``dt``
    when the solver has one, else the CFL number of an adaptive-dt
    config. Compiled programs and fused-stepper instances bake dt in, so
    the solver's cache is dropped — the next chunk recompiles at the
    reduced step. Returns a description of what changed."""
    if getattr(solver, "dt", None) is not None:
        solver.dt = float(solver.dt) * factor
        what = f"dt -> {solver.dt:.6g}"
    elif hasattr(solver.cfg, "cfl"):
        solver.cfg = dataclasses.replace(
            solver.cfg, cfl=float(solver.cfg.cfl) * factor
        )
        what = f"cfl -> {solver.cfg.cfl:.6g}"
    else:
        raise ValueError(
            "solver exposes neither a fixed dt nor a cfl to back off"
        )
    solver._cache.clear()
    return what


def supervise_run(
    solver,
    state,
    iters: Optional[int] = None,
    t_end: Optional[float] = None,
    sentinel_every: int = 0,
    growth: float = 1e3,
    max_retries: int = 3,
    dt_backoff: float = 0.5,
    checkpoint_every: int = 0,
    save_checkpoint: Optional[Callable] = None,
    should_stop: Optional[Callable[[], bool]] = None,
    sdc_every: int = 0,
    coordinated: Optional[bool] = None,
    progress: Optional[Callable[[dict], None]] = None,
    diag_every: int = 0,
    diag_strict: bool = False,
    snapshot_every: int = 0,
    save_snapshot: Optional[Callable] = None,
):
    """Run to ``iters`` steps or simulated time ``t_end`` under
    supervision; returns ``(final_state, SupervisorReport)``.

    * every ``sentinel_every`` steps the health probe runs; a non-finite
      field or norm-growth violation raises
      :class:`SolverDivergedError`, the loop rolls the state back to the
      last good checkpoint and retries with dt (or CFL) scaled by
      ``dt_backoff`` — at most ``max_retries`` times, every event
      recorded in the report;
    * every ``checkpoint_every`` steps ``save_checkpoint(state)`` runs
      (disk persistence is the caller's policy) and the in-memory
      rollback point advances;
    * ``should_stop()`` (the preemption guard) is consulted between
      chunks; a True ends the loop early with ``report.preempted``.

    ``iters`` mode executes exactly ``iters`` steps regardless of
    backoffs (the reference drivers' fixed-count mode); ``t_end`` mode
    lands on the same simulated time whatever dt the backoff schedule
    settled on — the mode to use when a retried run must reproduce the
    un-faulted answer.

    ``sdc_every`` > 0 arms the opt-in silent-data-corruption guard:
    every ``sdc_every``-th sentinel probe re-executes one step from the
    probed state and compares bit-exact
    (:func:`~.sentinel.duplicate_step_check`); a mismatch emits an
    ``sdc:detect`` telemetry event and recovers through the same
    rollback path as a divergence — but WITHOUT the dt backoff (the
    time step is not the problem), so a recovered run reproduces the
    un-faulted trajectory bit-for-bit.

    Every completed chunk emits a ``progress`` telemetry event (step
    rate, MLUPS, ETA, last mass drift), samples a ``mem:watermark``
    device-memory event (:mod:`telemetry.xprof` — backend memory stats
    or the live-arrays census; the running peak lands in
    ``RunSummary.memory``), and feeds the rolling step-time
    watch (:mod:`telemetry.live`): a chunk whose per-step wall time
    breaches the robust median+MAD threshold emits ``perf:outlier`` —
    the live fingerprint of preemption stalls, SDC re-execution and
    thermal jitter. ``progress`` (a callable) additionally receives
    each event's fields — the CLI's ``--progress`` status line. The
    final step-time histogram lands in ``report.perf`` and as one
    ``perf:histogram`` event. Chunk wall time is host-observed between
    chunk boundaries; checkpoint-write seconds are excluded (the probe
    is not — it is part of the cadence being watched).

    ``coordinated`` (default: auto — on whenever ``jax.process_count()
    > 1``) makes every rollback and checkpoint decision an explicit
    cross-rank agreement (:func:`parallel.multihost.agree`): all ranks
    assert the same rollback target, retry count and backoff factor
    before acting, and the same checkpoint iteration before writing —
    a desync raises :class:`CoordinationError` loudly instead of ranks
    silently recovering to different states.

    ``diag_every`` > 0 arms the in-situ physics-diagnostics suite
    (``diagnostics/physics.py``) INSIDE the sentinel's one jitted probe
    (no second compiled program): every ``diag_every``-th sentinel
    probe emits a ``phys:diag`` event carrying the fused observables
    (conservation budgets, total variation, spectral tail, per-solver
    extras), appends the point to ``report.diagnostics['trajectory']``
    (what the science gate diffs between rounds), and evaluates the
    solver's tolerance rules against the run-initial baseline — each
    breach is a ``phys:violation`` event. ``diag_strict`` escalates a
    breach into :class:`PhysicsViolationError`, recovered through the
    SAME rollback + dt-backoff path as a divergence.

    ``snapshot_every`` > 0 (with ``save_snapshot``) streams a field
    snapshot at that step cadence from the chunk boundaries —
    ``save_snapshot(state)`` is the caller's writer (the CLI threads
    the downsampled, rotation-capped async streamer of
    ``utils/io.SnapshotStreamer``); snapshot seconds are excluded from
    the watched chunk time like checkpoint writes.
    """
    if (iters is None) == (t_end is None):
        raise ValueError("provide exactly one of iters/t_end")
    if sdc_every and not sentinel_every:
        raise ValueError(
            "the SDC guard rides the sentinel cadence: sdc_every needs "
            "sentinel_every > 0"
        )
    if diag_every and not sentinel_every:
        raise ValueError(
            "the diagnostics suite rides the sentinel's jitted probe: "
            "diag_every needs sentinel_every > 0"
        )
    if snapshot_every and save_snapshot is None:
        raise ValueError("snapshot_every > 0 needs a save_snapshot writer")
    import jax

    coordinate = (
        jax.process_count() > 1 if coordinated is None else bool(coordinated)
    )
    report = SupervisorReport(
        sentinel_every=int(sentinel_every),
        sdc_every=int(sdc_every),
        coordinated=coordinate,
        diag_every=int(diag_every),
    )

    from multigpu_advectiondiffusion_tpu.telemetry import xprof
    from multigpu_advectiondiffusion_tpu.telemetry.live import (
        StepTimeWatch,
        emit_histogram,
    )
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )

    watch = StepTimeWatch()
    _cells = getattr(solver.grid, "num_cells", 0)
    _stages = STAGES.get(getattr(solver.cfg, "integrator", ""), 3)
    # per-chunk checkpoint-write seconds, excluded from the watched
    # chunk time (disk latency is not step-time jitter)
    _chunk_io = [0.0]

    def _progress(nxt, chunk_steps: int, chunk_seconds: float) -> None:
        chunk_seconds -= _chunk_io[0]
        _chunk_io[0] = 0.0
        if chunk_steps <= 0 or chunk_seconds <= 0:
            return
        watch.observe(chunk_steps, chunk_seconds, step=int(nxt.it))
        # chunk-cadence device-memory watermark (mem:watermark):
        # device-reported where the backend provides memory_stats(),
        # live-arrays census otherwise — the run-level peak lands in
        # RunSummary.memory
        xprof.sample_watermark(step=int(nxt.it))
        per_step = watch.median() or (chunk_seconds / chunk_steps)
        steps_done = int(nxt.it) - start_it
        if iters is not None:
            eta = max(0, int(iters) - steps_done) * per_step
        else:
            # t_end mode: remaining simulated time over the measured
            # per-step pace (dt from this chunk's actual advance)
            dt_chunk = (float(nxt.t) - t_prev[0]) / chunk_steps
            eta = (
                max(0.0, float(t_end) - float(nxt.t)) / dt_chunk * per_step
                if dt_chunk > 0 else None
            )
        t_prev[0] = float(nxt.t)
        fields = {
            "step": int(nxt.it),
            "steps_done": steps_done,
            "steps_total": int(iters) if iters is not None else None,
            "time": float(nxt.t),
            "t_end": float(t_end) if t_end is not None else None,
            "step_seconds": round(chunk_seconds / chunk_steps, 6),
            "rate_steps_per_s": round(chunk_steps / chunk_seconds, 3),
            "mlups": (
                round(_cells * _stages * chunk_steps
                      / chunk_seconds / 1e6, 3)
                if _cells else None
            ),
            "eta_seconds": round(eta, 3) if eta is not None else None,
            "mass_drift": report.mass_drift,
            "retries": report.retries,
            "outliers": watch.outliers,
        }
        telemetry.event("progress", "chunk", **fields)
        if progress is not None:
            p = dict(fields)
            p["t"] = p.pop("time")  # the sink reserves "t" for itself
            progress(p)

    t_prev = [float(state.t)]

    def _finish(final_state):
        if watch.chunks:
            report.perf = emit_histogram(watch)
        return final_state, report

    def _agree(tag: str, *values):
        """Assert every rank proposes the same decision (no-op in
        single-process runs); the agreement itself becomes an event."""
        if not coordinate:
            return
        from multigpu_advectiondiffusion_tpu.parallel import multihost

        multihost.agree(tag, values)
        telemetry.event(
            "resilience", "agree", tag=tag,
            values=[float(v) for v in values],
        )
    sentinel = None
    if sentinel_every:
        sentinel = DivergenceSentinel(
            solver, growth=growth, diagnostics=diag_every > 0
        )
        norm0 = sentinel.arm(state)
        if diag_every:
            report.diagnostics = {
                "observables": list(sentinel._probe.observable_keys),
                "rules": [r.name for r in sentinel.rules],
                "strict": bool(diag_strict),
                "meta": dict(sentinel.meta),
                "baseline": dict(sentinel.baseline or {}),
                "trajectory": [],
                "violations": [],
            }
        # every supervised run opens with one resilience event: the
        # armed sentinel's cadence/bound baseline (healthy runs are
        # attributable too, not only failing ones)
        telemetry.event(
            "resilience", "sentinel_armed",
            cadence=int(sentinel_every), growth=float(growth),
            norm0=norm0, mass0=sentinel.mass0,
            max_retries=int(max_retries), dt_backoff=float(dt_backoff),
        )

    last_good = state
    start_it = int(state.it)
    last_ckpt_it = start_it
    last_snap_it = start_it

    def _after_chunk(nxt, probe_due: bool):
        """Sentinel + checkpoint bookkeeping; returns the accepted state
        or raises SolverDivergedError for the retry handler."""
        nonlocal last_good, last_ckpt_it, last_snap_it
        if sentinel is not None and probe_due:
            report.probes += 1
            report.final_norm = sentinel.check(nxt)
            stats = sentinel.stats or {}
            report.physics = dict(stats)
            report.mass_drift = stats.get("mass_drift")
            # chunk-cadence physics stream, piggybacked on the jitted
            # probe the divergence check already paid for
            telemetry.event(
                "physics", "probe",
                step=int(nxt.it), time=float(nxt.t), **stats,
            )
            if diag_every and report.probes % diag_every == 0:
                # the fused diagnostic suite: same probe, richer stats —
                # the trajectory point is what the science gate diffs
                point = {"step": int(nxt.it), "time": float(nxt.t)}
                point.update(
                    (k, v) for k, v in stats.items()
                    if isinstance(v, (int, float))
                )
                report.diagnostics["trajectory"].append(point)
                telemetry.event(
                    "phys", "diag",
                    step=int(nxt.it), time=float(nxt.t),
                    **sentinel.meta, **stats,
                )
                violations = sentinel.check_violations(stats)
                for v in violations:
                    rec = {
                        "step": int(nxt.it), "time": float(nxt.t), **v,
                    }
                    report.diagnostics["violations"].append(rec)
                    telemetry.event(
                        "phys", "violation",
                        step=int(nxt.it), time=float(nxt.t),
                        rule=v["rule"], message=v["message"],
                        tolerance=v["tolerance"],
                    )
                if violations and diag_strict:
                    from multigpu_advectiondiffusion_tpu.resilience.errors import (  # noqa: E501
                        PhysicsViolationError,
                    )

                    raise PhysicsViolationError(
                        int(nxt.it), float(nxt.t),
                        stats.get("max_abs", float("nan")),
                        violations=violations,
                    )
            if sdc_every and report.probes % sdc_every == 0:
                # opt-in SDC guard: one step re-executed twice from the
                # probed state, compared bit-exact; runs BEFORE the
                # rollback point advances so a detection recovers to
                # the last state that passed it
                report.sdc_checks += 1
                ok, mismatched = duplicate_step_check(solver, nxt)
                if not ok:
                    report.sdc_detects += 1
                    telemetry.event(
                        "sdc", "detect",
                        step=int(nxt.it), time=float(nxt.t),
                        mismatched_cells=mismatched,
                    )
                    raise SDCDetectedError(
                        int(nxt.it), float(nxt.t),
                        mismatched_cells=mismatched,
                    )
        if checkpoint_every and (
            int(nxt.it) - last_ckpt_it >= checkpoint_every
        ):
            # coordinated commit: every rank asserts the same
            # checkpoint iteration before any shard byte is written
            _agree("checkpoint", int(nxt.it))
            if save_checkpoint is not None:
                io_t0 = time.monotonic()
                save_checkpoint(nxt)
                _chunk_io[0] += time.monotonic() - io_t0
            last_ckpt_it = int(nxt.it)
            last_good = nxt
        elif sentinel is not None and probe_due and not checkpoint_every:
            # no checkpoint cadence: every probed-good state is the
            # rollback point (in-memory checkpointing)
            last_good = nxt
        if snapshot_every and (
            int(nxt.it) - last_snap_it >= snapshot_every
        ):
            # field-snapshot streaming at cadence; disk seconds join
            # the checkpoint-I/O exclusion (not step-time jitter)
            io_t0 = time.monotonic()
            save_snapshot(nxt)
            _chunk_io[0] += time.monotonic() - io_t0
            last_snap_it = int(nxt.it)
        return nxt

    def _locate(err: SolverDivergedError, at) -> SolverDivergedError:
        """A SanitizerError (checkify trip) carries no step/t — they
        are unknown at the dispatch wrapper. Pin it to the chunk's
        starting state so the rollback event is attributable."""
        if getattr(err, "step", 0) < 0:
            err.step = int(at.it)
            err.t = float(at.t)
            err.args = (
                f"solver diverged at step {err.step} "
                f"(t={err.t:.6g}): {err.reason} "
                f"(max|u| = {err.norm:.6g})",
            )
        return err

    def _recover(err: SolverDivergedError):
        nonlocal last_good
        report.retries += 1
        if report.retries > max_retries:
            telemetry.event(
                "resilience", "retries_exhausted",
                step=err.step, time=err.t, retries=report.retries - 1,
                reason=err.reason,
            )
            raise err
        sdc = isinstance(err, SDCDetectedError)
        if sdc:
            # corruption, not stiffness: recompute from the rollback
            # point at the SAME dt — the retried trajectory reproduces
            # the un-faulted one bit-for-bit
            action = "recompute (dt unchanged)"
        else:
            action = scale_dt(solver, dt_backoff)
        # coordinated rollback: all ranks assert the same rollback
        # target, retry count and backoff factor before continuing
        _agree(
            "rollback", report.retries, err.step, int(last_good.it),
            0.0 if sdc else dt_backoff,
        )
        ev = {
            "step": err.step,
            "t": err.t,
            "norm": err.norm,
            "reason": err.reason,
            "rollback_to_it": int(last_good.it),
            "action": action,
        }
        report.events.append(ev)
        # "time" (not "t"): the sink's own key "t" is the event timestamp
        telemetry.event(
            "resilience", "rollback", retry=report.retries,
            step=ev["step"], time=ev["t"], norm=ev["norm"],
            reason=ev["reason"], rollback_to_it=ev["rollback_to_it"],
            action=ev["action"],
        )
        if sentinel is not None:
            sentinel.arm(last_good)
        return last_good

    cadences = [
        c for c in (sentinel_every, checkpoint_every, snapshot_every) if c
    ]
    if iters is not None:
        target_it = start_it + int(iters)
        chunk = min(cadences) if cadences else int(iters)
        while int(state.it) < target_it:
            if should_stop is not None and should_stop():
                report.preempted = True
                telemetry.event(
                    "resilience", "preempt", step=int(state.it),
                    time=float(state.t),
                )
                break
            n = min(chunk, target_it - int(state.it))
            prev_it = int(state.it)
            c0 = time.monotonic()
            try:
                nxt = solver.run(state, n)
                done = int(nxt.it) - start_it
                probe_due = bool(sentinel_every) and (
                    done % sentinel_every == 0 or int(nxt.it) >= target_it
                )
                state = _after_chunk(nxt, probe_due=probe_due)
                _progress(
                    nxt, int(nxt.it) - prev_it, time.monotonic() - c0
                )
            except SolverDivergedError as err:
                state = _recover(_locate(err, state))
                _chunk_io[0] = 0.0
        return _finish(state)

    import jax.numpy as jnp

    te = float(t_end)
    # termination tolerance at the STATE's time resolution: state.t is
    # often f32, and an eps below its ulp would spin this (host-side)
    # loop forever on the final sub-ulp residual the trimmed device
    # loop cannot represent
    res = (
        float(jnp.finfo(state.t.dtype).eps)
        if jnp.issubdtype(state.t.dtype, jnp.floating)
        else 0.0
    )
    eps = max(1e-12, 4.0 * res) * max(1.0, abs(te))
    dt_est = getattr(solver, "dt", None)
    while float(state.t) < te - eps:
        if should_stop is not None and should_stop():
            report.preempted = True
            telemetry.event(
                "resilience", "preempt", step=int(state.it),
                time=float(state.t),
            )
            break
        if dt_est is None:
            # adaptive dt with no estimate yet: one step calibrates the
            # probe window (its cost is one generic step)
            try:
                nxt = solver.step(state)
                dt_est = max(float(nxt.t) - float(state.t), 0.0) or None
                state = _after_chunk(nxt, probe_due=bool(sentinel_every))
            except SolverDivergedError as err:
                state = _recover(_locate(err, state))
                dt_est = None
            continue
        if sentinel_every:
            tk = min(float(state.t) + sentinel_every * float(dt_est), te)
        else:
            tk = te
        prev_it = int(state.it)
        c0 = time.monotonic()
        try:
            nxt = solver.advance_to(state, tk)
            steps = int(nxt.it) - int(state.it)
            if steps > 0:
                dt_est = (float(nxt.t) - float(state.t)) / steps
            state = _after_chunk(nxt, probe_due=bool(sentinel_every))
            _progress(nxt, int(nxt.it) - prev_it, time.monotonic() - c0)
            if steps == 0 and tk >= te:
                # the device loop can no longer advance toward te (the
                # remainder is below the time dtype's resolution): done
                break
        except SolverDivergedError as err:
            state = _recover(_locate(err, state))
            dt_est = getattr(solver, "dt", None)
            _chunk_io[0] = 0.0
    return _finish(state)
