"""Floating-point policy.

The reference compiles for a single ``REAL`` selected at build time
(``MultiGPU/Diffusion3d_Baseline/DiffusionMPICUDA.h:66-73``, default double).
On TPU float64 is software-emulated, so the policy here is: float32 by
default (fast path on MXU/VPU), float64 opt-in for accuracy studies (needs
``jax.config.jax_enable_x64``), bfloat16 available for experiments.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_ALIASES = {
    "f32": jnp.float32,
    "float32": jnp.float32,
    "single": jnp.float32,
    "f64": jnp.float64,
    "float64": jnp.float64,
    "double": jnp.float64,
    "bf16": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
}


def bf16_carry_enabled() -> bool:
    """Whether the bf16-storage rung carries the Kahan compensation term.

    On by default: the generic XLA path of ``precision='bf16'`` keeps a
    bf16 ``lo`` carry next to the bf16 ``hi`` state so small per-step
    increments that round away at bf16 still accumulate (ISSUE 16).
    ``TPUCFD_BF16_NO_CARRY=1`` disables it — the knob exists for the
    science-gate selftest (``out/precision_gate.sh --selftest``), which
    proves the uncompensated rung FAILS the per-dtype tolerance bands.
    """
    import os

    return os.environ.get("TPUCFD_BF16_NO_CARRY", "").lower() not in (
        "1", "true", "yes",
    )


def canonicalize(dtype) -> jnp.dtype:
    """Resolve a user-facing dtype spec to a concrete jnp dtype."""
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _ALIASES:
            raise ValueError(f"unknown dtype {dtype!r}; use one of {sorted(_ALIASES)}")
        dt = _ALIASES[key]
    else:
        dt = jnp.dtype(dtype).type
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        raise ValueError(
            "float64 requested but jax_enable_x64 is off; "
            "set JAX_ENABLE_X64=1 or jax.config.update('jax_enable_x64', True)"
        )
    return jnp.dtype(dt)
