"""Structured node-centered grids.

The reference builds its grids inline in every driver with the convention
``dx = L/(Nx-1)`` over symmetric domains (e.g.
``MultiGPU/Diffusion3d_Baseline/main.c:61-63``,
``Matlab_Prototipes/DiffusionNd/heat3d.m:17-23``,
``Matlab_Prototipes/InviscidBurgersNd/LFWENO5FDM3d.m:52-55``). Here the grid
is a first-class object shared by every solver.

Array-axis convention: fields are stored C-order with **x innermost**, i.e.
a 3-D field has shape ``(nz, ny, nx)``. On TPU this places the x sweep along
vector lanes and matches the reference's flat index ``o = i + nx*j + nx*ny*k``
(``MultiGPU/Diffusion3d_Baseline/Tools.c:110``), so ``u.ravel()`` reproduces
the reference's binary file layout.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence, Tuple

import jax.numpy as jnp

# Axis names in array order for each dimensionality.
_AXIS_NAMES = {1: ("x",), 2: ("y", "x"), 3: ("z", "y", "x")}


@dataclasses.dataclass(frozen=True)
class Grid:
    """A uniform node-centered grid.

    Attributes:
      shape: number of nodes per array axis, e.g. ``(nz, ny, nx)``.
      bounds: ``(lo, hi)`` physical bounds per array axis.
    """

    shape: Tuple[int, ...]
    bounds: Tuple[Tuple[float, float], ...]

    def __post_init__(self):
        if len(self.shape) != len(self.bounds):
            raise ValueError(
                f"shape {self.shape} and bounds {self.bounds} rank mismatch"
            )
        if not 1 <= len(self.shape) <= 3:
            raise ValueError("only 1-D/2-D/3-D grids are supported")
        for n in self.shape:
            if n < 2:
                raise ValueError(f"need at least 2 nodes per axis, got {self.shape}")

    # ------------------------------------------------------------------ #
    # Constructors
    # ------------------------------------------------------------------ #
    @staticmethod
    def make(
        nx: int,
        ny: int | None = None,
        nz: int | None = None,
        lengths: Sequence[float] | float | None = None,
        bounds: Sequence[Tuple[float, float]] | None = None,
    ) -> "Grid":
        """Build a grid from physical-order sizes ``nx, ny, nz``.

        ``lengths`` are physical-order extents ``(L, W, H)``; the domain is
        centered at the origin (matches ``heat3d.m:23`` meshgrid from ``-L/2``
        to ``L/2``). Alternatively pass explicit physical-order ``bounds``.
        """
        sizes = [n for n in (nx, ny, nz) if n is not None]
        ndim = len(sizes)
        if bounds is None:
            if lengths is None:
                lengths = [2.0] * ndim
            if isinstance(lengths, (int, float)):
                lengths = [float(lengths)] * ndim
            if len(lengths) != ndim:
                raise ValueError("lengths rank mismatch")
            bounds = [(-L / 2.0, L / 2.0) for L in lengths]
        if len(bounds) != ndim:
            raise ValueError("bounds rank mismatch")
        # physical order (x, y, z) -> array order (z, y, x)
        shape = tuple(reversed(sizes))
        bnds = tuple(tuple(map(float, b)) for b in reversed(bounds))
        return Grid(shape=shape, bounds=bnds)

    @staticmethod
    def make_periodic(
        nx: int,
        ny: int | None = None,
        nz: int | None = None,
        lengths: Sequence[float] | float | None = None,
        origin: float = 0.0,
    ) -> "Grid":
        """Grid for periodic axes: nodes at ``origin + i*L/n`` for
        ``i = 0..n-1`` so that ``n * dx`` equals the physical period ``L``
        (the two endpoint nodes of :meth:`make` would alias under wrap
        padding)."""
        sizes = [n for n in (nx, ny, nz) if n is not None]
        ndim = len(sizes)
        if lengths is None:
            lengths = [1.0] * ndim
        if isinstance(lengths, (int, float)):
            lengths = [float(lengths)] * ndim
        bounds = [
            (origin, origin + L * (n - 1) / n) for n, L in zip(sizes, lengths)
        ]
        return Grid.make(*sizes, bounds=bounds)

    # ------------------------------------------------------------------ #
    # Geometry
    # ------------------------------------------------------------------ #
    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return _AXIS_NAMES[self.ndim]

    @property
    def spacing(self) -> Tuple[float, ...]:
        """Node spacing per array axis, ``dx = (hi-lo)/(n-1)``."""
        return tuple(
            (hi - lo) / (n - 1) for n, (lo, hi) in zip(self.shape, self.bounds)
        )

    @property
    def num_cells(self) -> int:
        return math.prod(self.shape)

    @property
    def cell_volume(self) -> float:
        return math.prod(self.spacing)

    def axis_index(self, name: str) -> int:
        return self.axis_names.index(name)

    def coords(self, axis: int, dtype=jnp.float32) -> jnp.ndarray:
        lo, hi = self.bounds[axis]
        return jnp.linspace(lo, hi, self.shape[axis], dtype=dtype)

    def meshgrid(self, dtype=jnp.float32):
        """Coordinate arrays in array order, each of shape ``self.shape``."""
        axes = [self.coords(a, dtype) for a in range(self.ndim)]
        return jnp.meshgrid(*axes, indexing="ij")

    def radius_sq(self, dtype=jnp.float32) -> jnp.ndarray:
        """``x^2 + y^2 + z^2`` about the domain center."""
        r2 = jnp.zeros(self.shape, dtype=dtype)
        for axis in range(self.ndim):
            lo, hi = self.bounds[axis]
            c = self.coords(axis, dtype) - 0.5 * (lo + hi)
            shp = [1] * self.ndim
            shp[axis] = self.shape[axis]
            r2 = r2 + jnp.reshape(c * c, shp)
        return r2

    # Physical-order accessors -- convenience for reference-style drivers.
    @property
    def shape_xyz(self) -> Tuple[int, ...]:
        return tuple(reversed(self.shape))

    @property
    def spacing_xyz(self) -> Tuple[float, ...]:
        return tuple(reversed(self.spacing))
