"""Boundary conditions and halo padding.

The reference has no explicit BC layer: the MATLAB heat solvers re-impose
Dirichlet walls after every step (``heat3d.m:65-67``), the CUDA Laplacians
simply skip a 2-cell boundary band (``Laplace3d.m:21``,
``SingleGPU/Diffusion3d_baselineCode/kernels.cu``), and the WENO residuals
replicate edge values into ghost cells (``WENO5resAdv_X.m:53``). Here BCs are
explicit per-axis objects feeding one halo-padding primitive that is reused
verbatim (via ppermute fix-up) at sharded-domain global edges.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

_KINDS = ("dirichlet", "edge", "periodic")


@dataclasses.dataclass(frozen=True)
class Boundary:
    """Per-axis boundary condition (same on both faces of the axis).

    kind:
      * ``dirichlet`` — ghost cells hold ``value`` (reference heat walls).
      * ``edge``      — ghost cells replicate the face value; zero-gradient
                        outflow (reference WENO ghost cells).
      * ``periodic``  — wrap-around.
    """

    kind: str = "dirichlet"
    value: float = 0.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown boundary kind {self.kind!r}; use {_KINDS}")

    @staticmethod
    def parse(spec) -> "Boundary":
        if isinstance(spec, Boundary):
            return spec
        if isinstance(spec, str):
            return Boundary(kind=spec)
        raise TypeError(f"cannot interpret boundary spec {spec!r}")


def pad_axis(u: jnp.ndarray, axis: int, halo: int, bc: Boundary) -> jnp.ndarray:
    """Pad ``u`` with ``halo`` ghost cells on both ends of one axis."""
    if halo == 0:
        return u
    pw = [(0, 0)] * u.ndim
    pw[axis] = (halo, halo)
    if bc.kind == "periodic":
        return jnp.pad(u, pw, mode="wrap")
    if bc.kind == "edge":
        return jnp.pad(u, pw, mode="edge")
    return jnp.pad(u, pw, mode="constant", constant_values=bc.value)


def pad_all(u: jnp.ndarray, halo: int, bcs) -> jnp.ndarray:
    """Pad every axis with its BC ghost cells in as few copies as possible.

    Sequential per-axis :func:`pad_axis` calls cost one full-array copy
    each; when all axes share one BC kind (the common case — the reference
    always uses a single global BC) this collapses to a single ``jnp.pad``,
    one copy total. Ghost corners get mode-consistent values; stencil
    operators never read them (13-point cross, ``Laplace3d.m:22-25``).
    """
    if halo == 0:
        return u
    same_kind = all(bc.kind == bcs[0].kind for bc in bcs)
    same_value = all(bc.value == bcs[0].value for bc in bcs)
    if same_kind and (bcs[0].kind != "dirichlet" or same_value):
        pw = [(halo, halo)] * u.ndim
        kind = bcs[0].kind
        if kind == "periodic":
            return jnp.pad(u, pw, mode="wrap")
        if kind == "edge":
            return jnp.pad(u, pw, mode="edge")
        return jnp.pad(u, pw, mode="constant", constant_values=bcs[0].value)
    for axis in range(u.ndim):
        u = pad_axis(u, axis, halo, bcs[axis])
    return u


def boundary_halo(
    u: jnp.ndarray, axis: int, halo: int, bc: Boundary, side: str
) -> jnp.ndarray:
    """The ghost block a *global* domain edge would receive (no wrap).

    Used by the distributed halo exchange to overwrite the cyclic
    ``ppermute`` result on edge shards for non-periodic axes.
    """
    if bc.kind == "periodic":
        raise ValueError("periodic axes take their halo from the ppermute")
    n = u.shape[axis]
    if bc.kind == "edge":
        idx = 0 if side == "left" else n - 1
        face = jnp.take(u, jnp.array([idx]), axis=axis)
        return jnp.repeat(face, halo, axis=axis)
    shape = list(u.shape)
    shape[axis] = halo
    return jnp.full(shape, bc.value, dtype=u.dtype)
