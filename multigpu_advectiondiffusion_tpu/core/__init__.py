from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.core import dtypes

__all__ = ["Grid", "Boundary", "pad_axis", "dtypes"]
