"""Seeded violation fixtures: one (bad, good) source pair per lint
rule, embedded as strings.

Consumed by ``tpucfd-check --selftest`` and ``tests/test_analysis.py``:
every rule must TRIP on its seeded ``bad`` fixture and stay silent on
the ``good`` twin — the proof that a green lint gate means "checked and
clean", not "checker broke". (These are string constants: the AST
engine never sees them as code when linting this package.)
"""

from __future__ import annotations

RULE_FIXTURES = {
    "raw-artifact-write": {
        "bad": (
            "import json\n"
            "\n"
            "def save_report(path, obj):\n"
            "    with open(path, 'w') as f:\n"
            "        json.dump(obj, f)\n"
        ),
        "good": (
            "import json\n"
            "import os\n"
            "import tempfile\n"
            "\n"
            "def save_report(path, obj):\n"
            "    fd, tmp = tempfile.mkstemp(dir='.')\n"
            "    with os.fdopen(fd, 'w') as f:\n"
            "        json.dump(obj, f)\n"
            "    os.replace(tmp, path)\n"
        ),
    },
    "unregistered-emission": {
        "bad": (
            "def emit(sink):\n"
            "    sink.event('totally_unknown_kind', 'x', foo=1)\n"
            "    sink.counter('no.such.counter', 1)\n"
        ),
        "good": (
            "def emit(sink):\n"
            "    sink.event('dispatch', 'build', key='k', impl='xla')\n"
            "    sink.counter('halo.exchanges_traced', 1)\n"
        ),
    },
    "host-sync-in-traced": {
        "bad": (
            "from jax import lax\n"
            "\n"
            "def advance(u, n):\n"
            "    def body(i, c):\n"
            "        return c + c.item()\n"
            "    return lax.fori_loop(0, n, body, u)\n"
        ),
        "good": (
            "from jax import lax\n"
            "\n"
            "def advance(u, n):\n"
            "    def body(i, c):\n"
            "        return c + 1.0\n"
            "    out = lax.fori_loop(0, n, body, u)\n"
            "    return float(out.item())  # host side: after the loop\n"
        ),
    },
    "rank-divergent-collective": {
        "bad": (
            "import jax\n"
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n"
            "\n"
            "def commit(path):\n"
            "    if jax.process_index() == 0:\n"
            "        multihost.barrier(f'commit:{path}')\n"
        ),
        "good": (
            "import jax\n"
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n"
            "\n"
            "def commit(path):\n"
            "    multihost.barrier(f'commit:{path}')\n"
            "    if jax.process_index() == 0:\n"
            "        print('committed', path)\n"
        ),
    },
    "rank-divergent-effect": {
        "bad": (
            "import jax\n"
            "import json\n"
            "import os\n"
            "\n"
            "def publish(path, obj):\n"
            "    is_coord = jax.process_index() == 0\n"
            "    if is_coord:\n"
            "        with open(path + '.tmp', 'w') as f:\n"
            "            json.dump(obj, f)\n"
            "        os.replace(path + '.tmp', path)\n"
        ),
        "good": (
            "import jax\n"
            "import json\n"
            "import os\n"
            "\n"
            "def publish(path, obj):\n"
            "    with open(path + '.tmp', 'w') as f:\n"
            "        json.dump(obj, f)\n"
            "    os.replace(path + '.tmp', path)\n"
            "    if jax.process_index() == 0:\n"
            "        print('published', path)\n"
        ),
    },
    "registry-completeness": {
        "bad": (
            "from multigpu_advectiondiffusion_tpu.models.registry "
            "import ModelSpec, register_model\n"
            "\n"
            "class ToyConfig:\n"
            "    pass\n"
            "\n"
            "class ToySolver:\n"
            "    def stencil_spec(self):\n"
            "        return {'stage_radius': 1}\n"
            "\n"
            "    def diagnostics_spec(self):\n"
            "        return {}\n"
            "\n"
            "register_model(ModelSpec(\n"
            "    name='toy', config_cls=ToyConfig,\n"
            "    solver_cls=ToySolver, description='half-wired',\n"
            "))\n"
        ),
        "good": (
            "from multigpu_advectiondiffusion_tpu.models.registry "
            "import ModelSpec, register_model\n"
            "\n"
            "class ToyConfig:\n"
            "    pass\n"
            "\n"
            "class ToySolver:\n"
            "    def stencil_spec(self):\n"
            "        return {'stage_radius': 1}\n"
            "\n"
            "    def diagnostics_spec(self):\n"
            "        return {}\n"
            "\n"
            "    def ensemble_operands(self):\n"
            "        return {}\n"
            "\n"
            "    def cfl_rule(self):\n"
            "        return {'kind': 'static', 'dt': 1e-3}\n"
            "\n"
            "register_model(ModelSpec(\n"
            "    name='toy', config_cls=ToyConfig,\n"
            "    solver_cls=ToySolver, description='fully wired',\n"
            "))\n"
        ),
    },
    "closure-constant": {
        "bad": (
            "class Solver:\n"
            "    def build_local(self, ctx, overrides=None):\n"
            "        cfg = self.cfg\n"
            "        K = cfg.diffusivity\n"
            "        if overrides and 'diffusivity' in overrides:\n"
            "            K = overrides['diffusivity']\n"
            "\n"
            "        def rhs(u):\n"
            "            return u * cfg.diffusivity\n"
            "        return rhs\n"
        ),
        "good": (
            "class Solver:\n"
            "    def build_local(self, ctx, overrides=None):\n"
            "        cfg = self.cfg\n"
            "        K = cfg.diffusivity\n"
            "        if overrides and 'diffusivity' in overrides:\n"
            "            K = overrides['diffusivity']\n"
            "\n"
            "        def rhs(u):\n"
            "            return u * K\n"
            "        return rhs\n"
        ),
    },
}
