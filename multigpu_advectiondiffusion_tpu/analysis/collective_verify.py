"""Collective-schedule & SPMD consistency verifier — the distributed
analogue of :mod:`halo_verify`.

The reference's MPI layer discovers a mismatched send/recv or a
rank-divergent barrier by HANGING at runtime; PR 5's watchdog bounds
the hang, but nothing *proves* the collective schedule sound before a
multi-chip session burns hardware time. Three passes, mirroring the
MUST/ISP class of MPI verification tools (PARITY "Static analysis"):

1. **Static schedule extraction + rank-uniformity.** An AST walk over
   the package finds every collective call site — ``multihost.barrier``
   / ``agree`` tags, ``exchange_ghosts``/``ppermute`` halo shifts,
   ``pmax``/``psum`` mesh reductions, ``process_allgather``,
   ``shard_map`` entries — records the rank-guard context of each
   (``process_index()``-derived conditions, propagated through names
   like ``is_coord``), and proves: no collective sits under
   rank-dependent control flow (the deadlock class — one rank enters
   the barrier, its peer never will), no two branches of a
   rank-dependent ``if`` carry different collective schedules
   (divergent join), every ``barrier``/``agree`` tag is unique per
   call site (a shadowed tag makes two distinct rendezvous points
   indistinguishable to the watchdog AND to this verifier's dynamic
   cross-check), every tag namespace matches the issuing module's
   declared metadata (``utils/io.CKPTD_BARRIER_TAGS``,
   ``resilience/supervisor.AGREE_TAGS`` — the ``stencil_spec()``
   discipline applied to collectives), and every barrier/agree site is
   reachable from a public entry point (dead rendezvous code would
   silently escape the dynamic cross-check). Failures name
   file/line/tag/guard.

2. **Sharding-spec pass.** A registry of mesh layouts the CLI/dispatch
   admits (:func:`default_sharding_cases` — slab/pencil/block,
   multi-host compound axes, member(-x-spatial) ensemble meshes) is
   proven against :class:`~..parallel.mesh.Decomposition` arithmetic:
   every ``PartitionSpec`` axis exists in the constructed mesh, no
   mesh axis shards two grid axes, the ``ppermute`` axis-name set
   equals the ``pmax``/``psum`` reduction set (both derived from the
   ONE :func:`~..parallel.mesh.reduce_axis_names` source), sharded
   extents divide the grid, and the member-axis rules (members never
   in a spatial spec; the B-fold never spatially sharded) generalized
   here from ``halo_verify.verify_member_mesh`` (which now delegates).

3. **Dynamic cross-check.** :func:`static_schedule` compiles the
   extracted sites into an alphabet of tag templates plus ordered
   chains (straight-line same-guard sequences, e.g. the three
   ``ckptd-*`` checkpoint-commit barriers); :func:`verify_trace`
   asserts a measured per-rank collective sequence (the existing
   telemetry stream's ``sync:barrier`` / ``resilience:agree`` events
   and ``halo.*`` counters — no new instrumentation) is a
   linearization of that schedule: every measured tag maps to a static
   site, every rank measured the SAME sequence, and every chain's
   members appear in chain order per concrete tag instance. The
   2-proc chaos test (``tests/test_chaos.py``) drives this against
   real processes, so the verifier cannot drift from the code it
   models.

Suppression: intentionally rank-divergent sites carry the audited
``# tpucfd-check: allow[<rule>]`` pragma (on the site or its guard
line) with a comment stating why they are safe — see the lint rules
``rank-divergent-collective`` / ``rank-divergent-effect`` in
:mod:`rules`, which share this module's taint analysis.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from multigpu_advectiondiffusion_tpu.analysis.framework import (
    ParsedModule,
    iter_modules,
)

# --------------------------------------------------------------------- #
# Rank-taint analysis (shared with the lint rules)
# --------------------------------------------------------------------- #
#: call names whose value is rank-dependent: control flow tested on
#: them diverges between processes
RANK_SOURCES = {"process_index", "is_coordinator"}

#: collective entry points, by terminal call name -> collective kind.
#: Entering any of these under rank-divergent control flow is the MPI
#: deadlock class: one rank arrives at the rendezvous, its peer never
#: will (or, for ppermute/psum inside shard_map, silent corruption).
COLLECTIVE_CALLS = {
    "barrier": "barrier",
    "sync_global_devices": "barrier",
    "agree": "agree",
    "_agree": "agree",
    "process_allgather": "allgather",
    "all_gather": "allgather",
    "ppermute": "ppermute",
    "exchange_ghosts": "ppermute",
    "exchange_axis": "ppermute",
    "pmax": "reduce",
    "psum": "reduce",
    "shard_map": "shard_map",
    # in-kernel ICI exchange (ops/pallas/fused_slab_run, ISSUE 13):
    # a remote DMA is a rendezvous too — a rank-divergent start is the
    # same deadlock class as a rank-guarded barrier (and the interpret
    # simulator's discharge rule literally requires lockstep SPMD
    # issue), so the sites are extracted and held to the same
    # rank-uniformity proofs. The dma rung REPLACES the ppermute site:
    # its declared metadata rides halo.remote_dma_spec(), aggregated
    # in multihost.collective_spec()['remote_dma'] and drift-guarded
    # both directions like the barrier/agree tag namespaces.
    "make_async_remote_copy": "remote_dma",
}

#: entry points the interprocedural reachability walk starts from: the
#: CLI drivers, the supervised loop, the checkpoint-commit protocol,
#: the dispatch surface and the distributed bring-up
ENTRY_POINTS = (
    "main",
    "run_solver",
    "run_ensemble_solver",
    "supervise_run",
    "run",
    "run_to",
    "step",
    "advance_to",
    "run_ensemble",
    "advance_to_ensemble",
    "save_checkpoint_sharded",
    "initialize",
)


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _fixpoint_taint(root: ast.AST, base: Set[str]) -> Set[str]:
    """Propagate rank taint through plain-name assignments inside
    ``root`` to a fixpoint, starting from ``base``."""
    tainted = set(base)

    def expr_tainted(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if (
                isinstance(n, ast.Call)
                and _terminal_name(n.func) in RANK_SOURCES
            ):
                return True
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in tainted
            ):
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(root):
            targets: List[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in tainted:
                    tainted.add(t.id)
                    changed = True
    return tainted


class RankTaint:
    """Per-scope rank-taint lookup: names whose value derives from
    ``process_index()`` / ``is_coordinator()`` (``is_coord = jax.
    process_index() == 0``; ``pid = jax.process_index()``), propagated
    through assignments to a fixpoint WITHIN each outermost function
    (closures over a tainted outer local — the ``_write_checkpoint``
    pattern — see it; an unrelated function reusing the same variable
    name does not). Plain names only — attribute targets
    (``self.rank``) are out of scope by design (instance state is
    constructor policy, not control flow the schedule walks)."""

    def __init__(self, mod: ParsedModule):
        self._mod = mod
        self._outer: Dict[ast.AST, ast.AST] = {}
        tops: List[ast.AST] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._outermost(node) is node:
                    tops.append(node)
        module_base = self._module_level_taint(mod, tops)
        self._by_fn: Dict[ast.AST, Set[str]] = {
            top: _fixpoint_taint(top, module_base) for top in tops
        }
        self._module = module_base

    def _outermost(self, node: ast.AST) -> Optional[ast.AST]:
        if node in self._outer:
            return self._outer[node]
        fn = None
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = cur
            cur = self._mod.parent(cur)
        self._outer[node] = fn
        return fn

    @staticmethod
    def _module_level_taint(mod: ParsedModule,
                            tops: Sequence[ast.AST]) -> Set[str]:
        # a pruned copy of the tree without any function bodies: only
        # genuinely module-scoped assignments seed every function
        del tops

        class _Prune(ast.NodeTransformer):
            def visit_FunctionDef(self, node):
                return None

            visit_AsyncFunctionDef = visit_FunctionDef

        pruned = _Prune().visit(
            ast.parse(mod.source, filename=mod.path)
        )
        return _fixpoint_taint(pruned, set())

    def names_for(self, node: ast.AST) -> Set[str]:
        outer = self._outermost(node)
        if outer is None:
            return self._module
        return self._by_fn.get(outer, self._module)


def tainted_names(mod: ParsedModule) -> RankTaint:
    """Build the per-scope rank-taint lookup for one module (the name
    is historical: consumers pass the result to :func:`rank_guards`,
    which resolves the right scope per node)."""
    return RankTaint(mod)


def _expr_rank_dependent(test: ast.AST, tainted: Set[str]) -> bool:
    for n in ast.walk(test):
        if (
            isinstance(n, ast.Call)
            and _terminal_name(n.func) in RANK_SOURCES
        ):
            return True
        if (
            isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and n.id in tainted
        ):
            return True
    return False


def rank_guards(
    mod: ParsedModule, node: ast.AST, taint: RankTaint
) -> List[Tuple[int, str]]:
    """``[(lineno, guard_source), ...]`` for every enclosing
    ``if``/``while``/ternary whose test is rank-dependent and whose
    body (not test) contains ``node`` — the control-flow contexts under
    which this node executes on some ranks but not others."""
    names = taint.names_for(node)
    out: List[Tuple[int, str]] = []
    child: ast.AST = node
    cur = mod.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.While, ast.IfExp)):
            if child is not cur.test and _expr_rank_dependent(
                cur.test, names
            ):
                out.append((cur.lineno, ast.unparse(cur.test)))
        child, cur = cur, mod.parent(cur)
    return out


# --------------------------------------------------------------------- #
# Collective-site extraction
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class CollectiveSite:
    """One statically extracted collective call site."""

    kind: str  # barrier | agree | allgather | ppermute | reduce | shard_map
    tag: Optional[str]  # literal/f-string template ('*' wildcards); None = dynamic
    path: str
    line: int
    function: str  # innermost enclosing function name ('<module>' at top level)
    guards: Tuple[str, ...]  # ALL enclosing conditional tests (source text)

    def __str__(self) -> str:
        t = self.tag if self.tag is not None else "<dynamic>"
        return f"{self.path}:{self.line}: {self.kind}[{t}]"


def _tag_template(node: Optional[ast.AST]) -> Optional[str]:
    """Literal tag -> itself; f-string -> template with ``*`` for every
    interpolation (``f"ckptd-begin:{d}"`` -> ``ckptd-begin:*``); string
    concatenation of a literal prefix -> ``prefix*``; else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _tag_template(node.left)
        if left is not None and not left.endswith("*"):
            return left + "*"
    return None


def _all_guards(mod: ParsedModule, node: ast.AST) -> Tuple[str, ...]:
    out = []
    child: ast.AST = node
    cur = mod.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.While, ast.IfExp)):
            if child is not cur.test:
                out.append(ast.unparse(cur.test))
        child, cur = cur, mod.parent(cur)
    return tuple(reversed(out))


def _enclosing_function_name(mod: ParsedModule, node: ast.AST) -> str:
    fn = mod.enclosing_function(node)
    return fn.name if fn is not None else "<module>"


def extract_sites(mod: ParsedModule) -> List[CollectiveSite]:
    """Every collective call site in one module, with tag template and
    guard context. The *definitions* of the wrappers themselves
    (``multihost.barrier`` calling ``sync_global_devices``) extract
    like any other site — their dynamic tags are simply untracked."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        kind = COLLECTIVE_CALLS.get(name or "")
        if kind is None:
            continue
        tag = None
        if kind in ("barrier", "agree") and node.args:
            tag = _tag_template(node.args[0])
        out.append(
            CollectiveSite(
                kind=kind,
                tag=tag,
                path=mod.relpath,
                line=node.lineno,
                function=_enclosing_function_name(mod, node),
                guards=_all_guards(mod, node),
            )
        )
    return out


# --------------------------------------------------------------------- #
# Violations + report
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class CollectiveViolation:
    """One broken collective/SPMD invariant, named precisely."""

    rule: str
    path: str  # module path, or the sharding-case name
    line: int
    site: str  # tag / axis / spec being complained about
    message: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.site}: "
            f"{self.message}"
        )


@dataclasses.dataclass
class CollectiveReport:
    sites: List[CollectiveSite] = dataclasses.field(default_factory=list)
    violations: List[CollectiveViolation] = dataclasses.field(
        default_factory=list
    )
    cases_proven: List[str] = dataclasses.field(default_factory=list)
    chains: int = 0
    reachable_functions: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations


# --------------------------------------------------------------------- #
# Sharding-spec pass (registry-driven; halo_verify.verify_member_mesh
# delegates here)
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class ShardingCase:
    """One mesh layout the CLI/dispatch admits, as static data (no
    devices, no Mesh object — pure axis arithmetic)."""

    name: str
    mesh_axes: Dict[str, int]
    spatial: Dict[int, object]  # grid axis -> mesh axis name / tuple
    ndim: int = 3
    member: bool = False  # an ensemble mesh (members axis required)
    global_shape: Optional[Tuple[int, ...]] = None


def mesh_layout_violations(
    name: str,
    mesh_axes: Dict[str, int],
    spatial: Dict[int, object],
    ndim: Optional[int] = None,
    member: bool = True,
    global_shape: Optional[Sequence[int]] = None,
) -> List[Tuple[Optional[int], str, object, object]]:
    """The ONE registry-driven mesh-layout checker: returns
    ``(axis, what, expected, actual)`` rows (empty = proven).

    Proves: the ``PartitionSpec`` the decomposition would build names
    only axes the constructed mesh has; no mesh axis shards two grid
    axes; the member axis (when required) exists, has extent >= 1 and
    never shards a grid axis (member sharding is halo-free by
    construction — a grid-axis mapping would be an undeclared
    exchange); the ``ppermute`` participant set equals the
    ``pmax``/``psum`` reduction set (both from
    :func:`~..parallel.mesh.reduce_axis_names`, the single source);
    sharded extents divide ``global_shape`` when given."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        MEMBER_AXIS,
        Decomposition,
        axis_extent,
        reduce_axis_names,
    )

    out: List[Tuple[Optional[int], str, object, object]] = []

    def bad(axis, what, expected, actual):
        out.append((axis, what, expected, actual))

    if member:
        if MEMBER_AXIS not in mesh_axes:
            bad(None, "ensemble mesh must carry a members axis",
                f"'{MEMBER_AXIS}' in mesh", sorted(mesh_axes))
            return out
        if mesh_axes[MEMBER_AXIS] < 1:
            bad(None, "member axis extent must be >= 1", ">= 1",
                mesh_axes[MEMBER_AXIS])
    seen: Dict[str, int] = {}
    for ax, nm in sorted(spatial.items()):
        names = tuple(nm) if isinstance(nm, (list, tuple)) else (nm,)
        if MEMBER_AXIS in names:
            bad(ax, "the members axis may not shard a grid axis "
                    "(member sharding is halo-free; a grid-axis "
                    "mapping would be an undeclared exchange)",
                "spatial mesh axes only", nm)
        for n in names:
            if n != MEMBER_AXIS and n not in mesh_axes:
                bad(ax, "spatial decomposition names a missing mesh "
                        "axis", f"one of {sorted(mesh_axes)}", n)
                continue
            if n in seen and seen[n] != ax:
                bad(ax, "mesh axis shards two grid axes (one ppermute "
                        "neighborhood cannot serve two array "
                        "dimensions)",
                    f"{n!r} on one grid axis", f"axes {seen[n]} and {ax}")
            seen[n] = ax
        if ndim is not None and not (0 <= ax < ndim):
            bad(ax, "spatial decomposition maps a grid axis outside "
                    "the array rank", f"0 <= axis < {ndim}", ax)
    clean = {
        ax: nm for ax, nm in spatial.items()
        if not any(
            n == MEMBER_AXIS or n not in mesh_axes
            for n in (tuple(nm) if isinstance(nm, (list, tuple))
                      else (nm,))
        )
    }
    decomp = Decomposition.of(clean)
    # single-source reduction/ppermute participant set: the pmax/psum
    # axis names the step would reduce over must be exactly the axes
    # the halo exchange ppermutes over (extent > 1)
    reduce_set = set(reduce_axis_names(decomp, mesh_axes))
    permute_set = set()
    for ax, nm in decomp.axes:
        names = nm if isinstance(nm, tuple) else (nm,)
        if axis_extent(mesh_axes, nm) > 1:
            permute_set.update(n for n in names if mesh_axes.get(n, 1) > 1)
    if reduce_set != permute_set:
        bad(None, "pmax/psum reduction axes disagree with the ppermute "
                  "participant set (a reduction spanning different "
                  "shards than the exchange is silent corruption)",
            sorted(permute_set), sorted(reduce_set))
    if global_shape is not None:
        for ax, nm in decomp.axes:
            parts = axis_extent(mesh_axes, nm)
            if ax < len(global_shape) and global_shape[ax] % parts:
                bad(ax, "sharded extent does not divide the grid axis",
                    f"{global_shape[ax]} % {parts} == 0",
                    global_shape[ax] % parts)
    return out


def default_sharding_cases() -> List[ShardingCase]:
    """The mesh layouts the CLI grammar (``parse_mesh_spec`` /
    ``parse_ensemble_mesh``) and the dispatch admit, as static cases:
    slab/pencil/block spatial meshes, the multi-host compound z axis,
    and the member(-x-spatial) ensemble meshes of PR 11."""
    return [
        ShardingCase("slab[dz=4]", {"dz": 4}, {0: "dz"},
                     global_shape=(48, 16, 16)),
        ShardingCase("slab2d[dy=2]", {"dy": 2}, {0: "dy"}, ndim=2,
                     global_shape=(32, 32)),
        ShardingCase("pencil[dz=2,dy=2]", {"dz": 2, "dy": 2},
                     {0: "dz", 1: "dy"}, global_shape=(24, 16, 16)),
        ShardingCase("block[dz=2,dy=2,dx=2]",
                     {"dz": 2, "dy": 2, "dx": 2},
                     {0: "dz", 1: "dy", 2: "dx"},
                     global_shape=(16, 16, 16)),
        ShardingCase("multihost[dz_dcn=2,dz_ici=4]",
                     {"dz_dcn": 2, "dz_ici": 4},
                     {0: ("dz_dcn", "dz_ici")},
                     global_shape=(24, 16, 16)),
        # the in-kernel remote-DMA rung rides the same z-slab layout;
        # registered as its own case so the registry records that the
        # dma transport's participant ring IS the slab ppermute set
        ShardingCase("slab[dz=2,exchange=dma]", {"dz": 2}, {0: "dz"},
                     global_shape=(48, 16, 16)),
        ShardingCase("ensemble[members=8]", {"members": 8}, {},
                     member=True),
        ShardingCase("ensemble[members=4,dz=2]",
                     {"members": 4, "dz": 2}, {0: "dz"}, member=True,
                     global_shape=(24, 16, 16)),
    ]


def verify_sharding_cases(
    cases: Optional[Sequence[ShardingCase]] = None,
) -> Tuple[List[str], List[CollectiveViolation]]:
    """Run the registry; returns ``(proven_case_names, violations)``."""
    proven: List[str] = []
    violations: List[CollectiveViolation] = []
    for case in cases if cases is not None else default_sharding_cases():
        rows = mesh_layout_violations(
            case.name, case.mesh_axes, case.spatial, ndim=case.ndim,
            member=case.member, global_shape=case.global_shape,
        )
        if not rows:
            proven.append(case.name)
        for axis, what, expected, actual in rows:
            ax = "-" if axis is None else str(axis)
            violations.append(CollectiveViolation(
                rule="sharding-spec",
                path=case.name,
                line=0,
                site=f"axis {ax}",
                message=f"{what}: expected {expected}, got {actual}",
            ))
    return proven, violations


# --------------------------------------------------------------------- #
# Static schedule (alphabet + chains) and the dynamic cross-check
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class TagTemplate:
    kind: str  # barrier | agree
    template: str  # 'ckptd-begin:*' / 'checkpoint'

    def match(self, tag) -> Optional[Tuple[str, ...]]:
        """Captured wildcard values when ``tag`` matches, else None
        (a non-wildcard template captures ``()``)."""
        if not isinstance(tag, str):
            return None
        pattern = "^" + ".*".join(
            re.escape(p) for p in self.template.split("*")
        ) + "$"
        m = re.match(pattern, tag)
        if m is None:
            return None
        # re-capture the wildcard spans for chain-instance keying
        cap_pattern = "^" + "(.*)".join(
            re.escape(p) for p in self.template.split("*")
        ) + "$"
        cm = re.match(cap_pattern, tag)
        return tuple(cm.groups()) if cm else ()


@dataclasses.dataclass
class StaticSchedule:
    """What the extractor proved the code CAN rendezvous on."""

    alphabet: List[TagTemplate]
    #: ordered same-function same-guard tag sequences that any single
    #: execution must respect (e.g. the ckptd begin/shards/commit
    #: barriers of the checkpoint-commit protocol)
    chains: List[List[TagTemplate]]

    def lookup(self, kind: str, tag) -> Optional[TagTemplate]:
        for t in self.alphabet:
            if t.kind == kind and t.match(tag) is not None:
                return t
        return None


def static_schedule(root: Optional[str] = None) -> StaticSchedule:
    """Extract the package's barrier/agree schedule: the tag alphabet
    and the straight-line chains (sites sharing one innermost function
    and one guard context, ordered by source line)."""
    alphabet: Dict[Tuple[str, str], TagTemplate] = {}
    groups: Dict[Tuple[str, str, Tuple[str, ...]], List[CollectiveSite]] = {}
    for mod in iter_modules(_root_or_package(root)):
        for site in extract_sites(mod):
            if site.kind not in ("barrier", "agree") or site.tag is None:
                continue
            key = (site.kind, site.tag)
            if key not in alphabet:
                alphabet[key] = TagTemplate(site.kind, site.tag)
            groups.setdefault(
                (site.path, site.function, site.guards), []
            ).append(site)
    chains = []
    for sites in groups.values():
        if len(sites) < 2:
            continue
        chain = [
            TagTemplate(s.kind, s.tag)
            for s in sorted(sites, key=lambda s: s.line)
        ]
        chains.append(chain)
    return StaticSchedule(
        alphabet=list(alphabet.values()), chains=chains
    )


def collective_sequence(events: Iterable[dict]) -> List[Tuple[str, str]]:
    """Project a loaded telemetry stream onto the collective alphabet:
    ``('barrier', tag)`` for ``sync:barrier`` events, ``('agree', tag)``
    for ``resilience:agree`` — the measured per-rank schedule."""
    seq = []
    for e in events:
        if e.get("kind") == "sync" and e.get("name") == "barrier":
            seq.append(("barrier", e.get("tag")))
        elif e.get("kind") == "resilience" and e.get("name") == "agree":
            seq.append(("agree", e.get("tag")))
    return seq


def halo_counter_profile(events: Iterable[dict]) -> Dict[tuple, int]:
    """Multiset of traced halo-exchange sites per stream — identical
    across ranks when every rank traced the same programs."""
    from multigpu_advectiondiffusion_tpu.parallel.halo import (
        exchange_spec,
        remote_dma_spec,
    )

    # BOTH transports: ppermute counters and the in-kernel remote-DMA
    # counters — a dma-mode stream profiles rank-uniform without the
    # verifier reading the absent ppermute pair as a divergence
    names = set(exchange_spec()["counters"])
    names |= set(remote_dma_spec()["counters"])
    out: Dict[tuple, int] = {}
    for e in events:
        if e.get("kind") == "counter" and e.get("name") in names:
            mesh_axis = e.get("mesh_axis")
            if isinstance(mesh_axis, list):  # compound (multi-host) axis
                mesh_axis = tuple(mesh_axis)
            key = (e.get("name"), e.get("axis"), mesh_axis)
            out[key] = out.get(key, 0) + 1
    return out


def verify_trace(
    sequences: Dict[object, List[Tuple[str, str]]],
    schedule: Optional[StaticSchedule] = None,
) -> List[str]:
    """Prove measured per-rank collective sequences are a linearization
    of the static schedule. Returns problem strings (empty = proven):

    * every measured tag matches a statically extracted site (the
      analysis models the code that actually ran);
    * every rank measured the SAME sequence (rank-uniform execution —
      the property the static pass proves, observed);
    * every chain's tags appear in chain order per concrete instance
      (``ckptd-begin:<dir>`` strictly before ``ckptd-shards:<dir>``
      before ``ckptd-commit:<dir>``, cycling per checkpoint).
    """
    if schedule is None:
        schedule = static_schedule()
    problems: List[str] = []
    for rank, seq in sorted(sequences.items(), key=lambda kv: str(kv[0])):
        for kind, tag in seq:
            if schedule.lookup(kind, tag) is None:
                problems.append(
                    f"rank {rank}: measured {kind} tag {tag!r} matches "
                    "no statically extracted call site"
                )
    ranks = sorted(sequences, key=str)
    if len(ranks) > 1:
        base = sequences[ranks[0]]
        for rank in ranks[1:]:
            seq = sequences[rank]
            if seq != base:
                n = min(len(seq), len(base))
                at = next(
                    (i for i in range(n) if seq[i] != base[i]), n
                )
                a = base[at] if at < len(base) else "<end>"
                b = seq[at] if at < len(seq) else "<end>"
                problems.append(
                    f"ranks {ranks[0]} and {rank} measured divergent "
                    f"collective sequences at position {at}: "
                    f"{a} vs {b}"
                )
    for chain in schedule.chains:
        for rank, seq in sorted(
            sequences.items(), key=lambda kv: str(kv[0])
        ):
            by_instance: Dict[tuple, List[int]] = {}
            for kind, tag in seq:
                for pos, t in enumerate(chain):
                    if t.kind != kind:
                        continue
                    caps = t.match(tag)
                    if caps is not None:
                        by_instance.setdefault(caps, []).append(pos)
                        break
            for caps, poss in by_instance.items():
                want = [
                    i % len(chain) for i in range(len(poss))
                ]
                if poss != want:
                    names = [t.template for t in chain]
                    problems.append(
                        f"rank {rank}: chain {names} instance "
                        f"{caps!r} measured out of order: positions "
                        f"{poss}, expected {want}"
                    )
    return problems


# --------------------------------------------------------------------- #
# Whole-tree pass
# --------------------------------------------------------------------- #
def _root_or_package(root: Optional[str]) -> str:
    import os

    if root is not None:
        return root
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _call_graph(mods: List[ParsedModule]) -> Dict[str, Set[str]]:
    """Name-level call graph: function name -> terminal names it
    calls. Resolution is by terminal name (conservative: homonyms
    over-connect, which can only make MORE sites reachable — the safe
    direction for a dead-rendezvous check)."""
    graph: Dict[str, Set[str]] = {}
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            calls = graph.setdefault(node.name, set())
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _terminal_name(sub.func)
                    if name:
                        calls.add(name)
    return graph


def _reachable(graph: Dict[str, Set[str]]) -> Set[str]:
    seen: Set[str] = set()
    stack = [e for e in ENTRY_POINTS if e in graph]
    while stack:
        fn = stack.pop()
        if fn in seen:
            continue
        seen.add(fn)
        for callee in graph.get(fn, ()):
            if callee in graph and callee not in seen:
                stack.append(callee)
    return seen


def verify_tree(
    root: Optional[str] = None,
    cases: Optional[Sequence[ShardingCase]] = None,
) -> CollectiveReport:
    """The full static pass over a package tree: extract every
    collective site, then prove tag uniqueness, join consistency,
    declared-metadata drift and entry-point reachability (the last two
    only against the installed package — fixture trees have no
    declarations to drift from), plus the sharding-case registry.

    Rank-guard violations per se are the job of the registered lint
    rules (``rank-divergent-collective`` / ``rank-divergent-effect``),
    which run in the same ``tpucfd-check`` invocation; this pass owns
    the cross-module and whole-schedule properties."""
    is_package = root is None
    mods = list(iter_modules(_root_or_package(root)))
    report = CollectiveReport()
    by_tag: Dict[Tuple[str, str], List[CollectiveSite]] = {}
    mod_of: Dict[str, ParsedModule] = {m.relpath: m for m in mods}
    for mod in mods:
        sites = extract_sites(mod)
        report.sites.extend(sites)
        for site in sites:
            if site.kind in ("barrier", "agree") and site.tag is not None:
                by_tag.setdefault((site.kind, site.tag), []).append(site)
        report.violations.extend(_divergent_joins(mod))

    # tag uniqueness: one rendezvous tag = one call site (a shadowed
    # tag makes two distinct rendezvous points indistinguishable to
    # the watchdog's suspect attribution and to verify_trace's chains)
    for (kind, tag), sites in sorted(by_tag.items()):
        if len(sites) < 2:
            continue
        for site in sites[1:]:
            mod = mod_of.get(site.path)
            if mod is not None and mod.suppressed(
                site.line, "duplicate-collective-tag"
            ):
                continue
            first = sites[0]
            report.violations.append(CollectiveViolation(
                rule="duplicate-collective-tag",
                path=site.path,
                line=site.line,
                site=f"{kind}:{tag}",
                message=(
                    f"{kind} tag {tag!r} already issued at "
                    f"{first.path}:{first.line} — every rendezvous tag "
                    "must be unique per call site"
                ),
            ))

    if is_package:
        report.violations.extend(_declared_tag_drift(by_tag))
        report.violations.extend(_declared_remote_dma_drift(report.sites))
        graph = _call_graph(mods)
        reached = _reachable(graph)
        report.reachable_functions = len(reached)
        for site in report.sites:
            if site.kind not in ("barrier", "agree"):
                continue
            if site.function != "<module>" and site.function not in reached:
                report.violations.append(CollectiveViolation(
                    rule="unreachable-collective",
                    path=site.path,
                    line=site.line,
                    site=f"{site.kind}:{site.tag}",
                    message=(
                        f"rendezvous in {site.function}() is not "
                        "reachable from any entry point — dead "
                        "collective code escapes the dynamic "
                        "cross-check; delete it or add the entry point"
                    ),
                ))

    proven, sharding = verify_sharding_cases(cases)
    report.cases_proven = proven
    report.violations.extend(sharding)
    report.chains = len(static_schedule(root).chains) if report.sites else 0
    return report


def _branch_schedule(mod: ParsedModule,
                     stmts: Sequence[ast.AST]) -> List[Tuple[str, str]]:
    out = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                kind = COLLECTIVE_CALLS.get(
                    _terminal_name(node.func) or ""
                )
                if kind is not None:
                    tag = None
                    if kind in ("barrier", "agree") and node.args:
                        tag = _tag_template(node.args[0])
                    out.append((kind, tag or "<dynamic>"))
    return out


def _divergent_joins(mod: ParsedModule) -> List[CollectiveViolation]:
    """Rank-dependent ``if`` statements whose two paths carry different
    collective schedules: the ranks taking each branch arrive at the
    join point having executed different rendezvous — the deadlock (or,
    inside shard_map, corruption) the MPI reference can only discover
    by hanging."""
    taint = tainted_names(mod)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.If):
            continue
        if not _expr_rank_dependent(node.test, taint.names_for(node)):
            continue
        body = _branch_schedule(mod, node.body)
        orelse = _branch_schedule(mod, node.orelse)
        if body == orelse:
            continue
        if mod.suppressed(node.lineno, "divergent-join"):
            continue
        out.append(CollectiveViolation(
            rule="divergent-join",
            path=mod.relpath,
            line=node.lineno,
            site=f"if {ast.unparse(node.test)}",
            message=(
                "branches of a rank-dependent conditional carry "
                f"different collective schedules: {body or 'none'} vs "
                f"{orelse or 'none'} — ranks reach the join point "
                "having executed different rendezvous"
            ),
        ))
    return out


def _declared_remote_dma_drift(
    sites: Sequence[CollectiveSite],
) -> List[CollectiveViolation]:
    """Both-directions drift guard for the in-kernel remote-DMA
    transport: the kernel's ``make_async_remote_copy`` sites and the
    declared metadata (``multihost.collective_spec()['remote_dma']``,
    sourced from ``parallel.halo.remote_dma_spec``) must agree — the
    dma rung replaced the ppermute site, and the registry must KNOW
    that, or the dynamic cross-check would read a dma stream's missing
    ppermute counters as a stale expectation."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import (
        collective_spec,
    )

    declared = collective_spec().get("remote_dma")
    dma_sites = [s for s in sites if s.kind == "remote_dma"]
    out: List[CollectiveViolation] = []
    if dma_sites and not declared:
        s = dma_sites[0]
        out.append(CollectiveViolation(
            rule="undeclared-remote-dma",
            path=s.path,
            line=s.line,
            site="remote_dma",
            message=(
                "in-kernel remote-DMA site has no declared transport "
                "metadata (multihost.collective_spec()['remote_dma'] "
                "/ parallel.halo.remote_dma_spec) — register it like "
                "a stencil_spec field"
            ),
        ))
    if declared and not dma_sites:
        out.append(CollectiveViolation(
            rule="stale-remote-dma",
            path="parallel/halo.py",
            line=0,
            site="remote_dma",
            message=(
                "declared remote-DMA transport has no "
                "make_async_remote_copy site — stale collective "
                "metadata"
            ),
        ))
    return out


def _declared_tag_drift(
    by_tag: Dict[Tuple[str, str], List[CollectiveSite]],
) -> List[CollectiveViolation]:
    """Both-directions drift guard between the extracted tag namespaces
    and the issuing modules' declared collective metadata (the
    ``stencil_spec()`` discipline): an undeclared tag is schema drift;
    a declared-but-never-issued tag is a stale contract."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import (
        collective_spec,
    )

    declared = collective_spec()
    out = []
    for kind in ("barrier", "agree"):
        extracted = {tag for (k, tag) in by_tag if k == kind}
        known = set(declared.get(kind, ()))
        for tag in sorted(extracted - known):
            site = by_tag[(kind, tag)][0]
            out.append(CollectiveViolation(
                rule="undeclared-collective-tag",
                path=site.path,
                line=site.line,
                site=f"{kind}:{tag}",
                message=(
                    f"{kind} tag {tag!r} is not declared in the "
                    "issuing layer's collective metadata "
                    "(multihost.collective_spec) — register it like a "
                    "stencil_spec field"
                ),
            ))
        for tag in sorted(known - extracted):
            out.append(CollectiveViolation(
                rule="stale-collective-tag",
                path="parallel/multihost.py",
                line=0,
                site=f"{kind}:{tag}",
                message=(
                    f"declared {kind} tag {tag!r} has no issuing call "
                    "site — stale collective metadata"
                ),
            ))
    return out
