"""AST rule engine: parse each package module once, run pluggable
rules over it, collect structured violations.

This generalizes the regex scan ``telemetry/schema.scan_emitted``
shipped with (one hard-coded pattern, one consumer) into the framework
every project invariant registers against: a :class:`Rule` sees a
:class:`ParsedModule` (source + AST + parent links) and yields
:class:`Violation` rows; the engine handles file walking, parsing,
suppression pragmas and aggregation, so a new invariant is ONE rule
class — not a new scanner.

Suppression: a violation whose source line (or the line above it)
carries ``tpucfd-check: allow[<rule-name>]`` is dropped — the pragma
is the audited opt-out (e.g. the torn-checkpoint fault injector
*deliberately* writes non-atomically).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Type


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule breach at one source location."""

    rule: str
    path: str  # package-relative where possible
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class ParsedModule:
    """One source file, parsed once and shared by every rule."""

    def __init__(self, path: str, root: str):
        self.path = path
        self.relpath = os.path.relpath(path, root)
        with open(path) as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        # parent links: rules climb from a call site to its enclosing
        # function without re-walking the tree
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST):
        """Nearest FunctionDef/AsyncFunctionDef ancestor (None at
        module level)."""
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parent(cur)
        return None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        tag = f"tpucfd-check: allow[{rule}]"
        return tag in self.line_text(lineno) or tag in self.line_text(
            lineno - 1
        )


class Rule:
    """One statically checkable project invariant."""

    name: str = ""
    description: str = ""

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        raise NotImplementedError

    def violation(self, mod: ParsedModule, node: ast.AST,
                  message: str) -> Violation:
        return Violation(
            rule=self.name,
            path=mod.relpath,
            line=getattr(node, "lineno", 0),
            message=message,
        )


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: add a rule to the engine's default set."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} declares no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    """Registered rules (importing :mod:`rules` populates this)."""
    # the domain rules live in a sibling module; importing it here
    # makes the registry complete for every consumer
    from multigpu_advectiondiffusion_tpu.analysis import rules  # noqa: F401

    return dict(_REGISTRY)


def iter_modules(root: str) -> Iterable[ParsedModule]:
    """Parse every ``.py`` under ``root`` (skipping ``__pycache__``),
    sorted for deterministic reports. Unparseable files are the
    caller's bug — a SyntaxError propagates loudly."""
    paths = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    for path in sorted(paths):
        yield ParsedModule(path, root)


def run_rules(
    root: Optional[str] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Violation]:
    """Run ``rules`` (default: every registered rule) over the package
    tree at ``root`` (default: the installed package). Returns the
    surviving (non-suppressed) violations, sorted by location."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if rules is None:
        rules = [cls() for cls in all_rules().values()]
    out: List[Violation] = []
    for mod in iter_modules(root):
        for rule in rules:
            for v in rule.check(mod):
                if not mod.suppressed(v.line, v.rule):
                    out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))
