"""``python -m multigpu_advectiondiffusion_tpu.analysis`` — the
standalone ``tpucfd-check`` entry (also: the main CLI's ``check``
subcommand)."""

import sys

from multigpu_advectiondiffusion_tpu.analysis.cli import main

sys.exit(main())
