"""Runtime sanitizer: opt-in ``jax.experimental.checkify``
instrumentation of the dispatch programs (the ``--checkify`` CLI mode).

What ``cuda-memcheck`` was to the reference's kernels, this is to the
steppers: every jitted block program is rebuilt as
``jit(checkify(fn))`` with NaN / division-by-zero / out-of-bounds
checks discharged into the compiled program; the wrapper inspects the
functionalized error after every dispatch and raises
:class:`~.resilience.errors.SanitizerError` — a
:class:`SolverDivergedError` subclass, so the supervisor's existing
rollback/retry path recovers it with no new plumbing. The divergence
sentinel sees a NaN only when the chunk-boundary norm probe runs; the
sanitizer names the offending primitive at the step that produced it —
the fault-injection suite's second oracle.

Scope: single-device programs (``shard_map`` carries no checkify
rules — a meshed solver under ``--checkify`` fails loudly at
construction, pin semantics). Proven on the generic-XLA rung; Pallas
kernels are opaque to checkify (their interiors add no checks), so the
e2e guarantees ride ``impl='xla'``.

Off by default; ``configure(enabled=True)`` (or ``--checkify``) arms it
process-wide. The error-set selection maps the familiar sanitizer
names onto checkify's sets: ``nan`` -> ``nan_checks``, ``div`` ->
``div_checks``, ``oob`` -> ``index_checks``.
"""

from __future__ import annotations

from typing import Iterable, Optional

_DEFAULT_ERRORS = ("nan", "div", "oob")

_state = {
    "enabled": False,
    "errors": tuple(_DEFAULT_ERRORS),
}


def configure(enabled: Optional[bool] = None,
              errors: Optional[Iterable[str]] = None) -> None:
    """Arm/disarm the sanitizer process-wide; ``errors`` selects the
    check classes (subset of ``nan``/``div``/``oob``)."""
    if enabled is not None:
        _state["enabled"] = bool(enabled)
    if errors is not None:
        errors = tuple(errors)
        unknown = sorted(set(errors) - set(_DEFAULT_ERRORS))
        if unknown:
            raise ValueError(
                f"unknown checkify error class(es) {unknown}; "
                f"choose from {_DEFAULT_ERRORS}"
            )
        if not errors:
            raise ValueError("empty error set would check nothing")
        _state["errors"] = errors


def enabled() -> bool:
    return bool(_state["enabled"])


def error_names() -> tuple:
    return tuple(_state["errors"])


def _error_set():
    from jax.experimental import checkify as _ck

    sets = {
        "nan": _ck.nan_checks,
        "div": _ck.div_checks,
        "oob": _ck.index_checks,
    }
    out = None
    for name in _state["errors"]:
        out = sets[name] if out is None else out | sets[name]
    return out


def checked_jit(fn):
    """``jit(checkify(fn))`` returning the original signature: the
    wrapper unwraps the functionalized error on every call and raises
    :class:`SanitizerError` (through the supervisor's rollback path)
    when a check tripped. The host read of the error payload happens at
    the dispatch boundary the caller was about to sync at anyway (the
    supervisor's chunk cadence)."""
    import jax
    from jax.experimental import checkify as _ck

    jitted = jax.jit(_ck.checkify(fn, errors=_error_set()))

    def call(*args, **kwargs):
        err, out = jitted(*args, **kwargs)
        raise_if_tripped(err)
        return out

    return call


def raise_if_tripped(err) -> None:
    """Inspect a checkify error pytree; no-op when clean."""
    msg = err.get()
    if msg is None:
        return
    from multigpu_advectiondiffusion_tpu import telemetry
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        SanitizerError,
    )

    telemetry.event("sanitizer", "trip", message=str(msg),
                    errors=list(_state["errors"]))
    raise SanitizerError(str(msg))
