"""Domain lint rules for the AST engine (:mod:`framework`).

Seven invariants, each previously enforced in exactly one hand-written
place (or not at all):

* ``closure-constant`` — the PR 9 ``build_local`` contract: a scalar a
  solver declares member-varying (readable from ``overrides``) must
  enter its traced closures as an operand, never re-read from the
  config inside the closure (a closure constant cannot vary along the
  vmapped member axis — the batched run silently computes every member
  with member 0's physics);
* ``host-sync-in-traced`` — ``.item()`` / ``.block_until_ready()`` /
  ``np.asarray`` and friends inside functions that are traced
  (arguments to ``jit``/``vmap``/``fori_loop``/``while_loop``/
  ``pallas_call``/``shard_map``...): a host sync inside traced code is
  either a tracer error at runtime or a silent per-step device->host
  round trip;
* ``raw-artifact-write`` — ``open(..., 'w')`` of a persistent artifact
  outside the tempfile + ``os.replace`` atomic-publish discipline the
  checkpoint/cache/summary writers follow (append-mode streams are
  exempt: a JSONL tail is not a torn-write hazard);
* ``unregistered-emission`` — telemetry ``.event(kind, name)`` /
  ``.counter(name)`` call sites the schema registry
  (``telemetry/schema.EVENT_REGISTRY``) does not know — the guard
  against silent schema drift, now one rule of the shared engine
  instead of a private regex scanner;
* ``rank-divergent-collective`` — a collective entry point (barrier /
  agree / ppermute / psum / allgather / shard_map) under
  ``process_index()``-dependent control flow: the MPI deadlock class —
  one rank arrives at the rendezvous, its peer never will (taint
  analysis shared with :mod:`collective_verify`, which owns the
  cross-module schedule properties);
* ``rank-divergent-effect`` — a persistent write or telemetry emission
  inside a ``process_index()``-guarded branch without the audited
  allow-pragma: the classic "rank 0 wrote the checkpoint, rank 1
  committed it" hazard class. Intentional single-writer sites (the
  coordinator's gathered-output publishes, the commit-marker protocol)
  carry ``# tpucfd-check: allow[rank-divergent-effect]`` on the guard
  with a comment stating why they are safe;
* ``registry-completeness`` — a ``register_model()``'d solver class
  missing any method of the plugin registration contract
  (``models/registry.REQUIRED_SOLVER_CONTRACT``: ``stencil_spec`` /
  ``diagnostics_spec`` / ``ensemble_operands`` / ``cfl_rule``) in its
  own class body: a half-wired plugin fails statically (and at
  ``register_model``), never at dispatch.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set, Tuple

from multigpu_advectiondiffusion_tpu.analysis.framework import (
    ParsedModule,
    Rule,
    Violation,
    iter_modules,
    register,
)


def _terminal_name(func: ast.AST) -> Optional[str]:
    """``jax.lax.fori_loop`` -> ``fori_loop``; ``open`` -> ``open``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# --------------------------------------------------------------------- #
# raw-artifact-write
# --------------------------------------------------------------------- #
@register
class RawArtifactWriteRule(Rule):
    name = "raw-artifact-write"
    description = (
        "open(..., 'w') of a persistent artifact without the tempfile + "
        "os.replace atomic-publish discipline (a crash/preemption leaves "
        "a torn file where readers expect a complete one)"
    )

    _OPENERS = ("open", "fdopen")

    def _mode_of(self, call: ast.Call) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg == "mode":
                return _literal_str(kw.value)
        if len(call.args) >= 2:
            return _literal_str(call.args[1])
        return None

    def _has_atomic_publish(self, scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "replace"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("os", "_os")
            ):
                return True
        return False

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _terminal_name(node.func)
            if fname not in self._OPENERS:
                continue
            if fname == "open" and not isinstance(node.func, ast.Name):
                continue  # method .open() on some object: out of scope
            mode = self._mode_of(node)
            if mode is None or not any(c in mode for c in "wx"):
                continue  # reads and append-only streams are fine
            scope = mod.enclosing_function(node) or mod.tree
            if self._has_atomic_publish(scope):
                continue
            yield self.violation(
                mod, node,
                f"open(..., {mode!r}) writes a persistent artifact "
                "without tempfile + os.replace (use "
                "utils.io.atomic_write_text or publish via os.replace "
                "in the same function)",
            )


# --------------------------------------------------------------------- #
# unregistered-emission (+ the reusable scanner telemetry/schema wraps)
# --------------------------------------------------------------------- #
def _emission_calls(mod: ParsedModule):
    """Yield ``(node, kind, name_or_None)`` for ``.event(...)`` sites
    with a literal kind, and ``(node, None, counter_name)`` for
    ``.counter(...)`` sites with a literal name. Dynamic kinds (a
    variable) are skipped — the kind itself is then the call site's
    contract, unresolvable statically (same semantics as the regex
    scanner this replaces)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr == "event" and node.args:
            kind = _literal_str(node.args[0])
            if kind is None:
                continue
            name = (
                _literal_str(node.args[1]) if len(node.args) >= 2 else None
            )
            yield node, kind, name
        elif node.func.attr == "counter" and node.args:
            cname = _literal_str(node.args[0])
            if cname is not None:
                yield node, None, cname


def scan_emission_sites(
    root: str,
) -> Tuple[Set[Tuple[str, Optional[str]]], Set[str]]:
    """AST scan of every emission site under ``root``: returns
    ``(event_pairs, counter_names)`` — the engine-backed implementation
    of ``telemetry/schema.scan_emitted`` (same contract: pair name is
    ``None`` when the call site passes a variable)."""
    pairs: Set[Tuple[str, Optional[str]]] = set()
    counters: Set[str] = set()
    for mod in iter_modules(root):
        for _node, kind, name in _emission_calls(mod):
            if kind is None:
                counters.add(name)
            else:
                pairs.add((kind, name))
    return pairs, counters


@register
class UnregisteredEmissionRule(Rule):
    name = "unregistered-emission"
    description = (
        "telemetry .event(kind, name)/.counter(name) call site not "
        "covered by telemetry/schema.EVENT_REGISTRY / COUNTER_NAMES "
        "(silent schema drift: consumers learn about the new event six "
        "months later)"
    )

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        from multigpu_advectiondiffusion_tpu.telemetry import schema

        for node, kind, name in _emission_calls(mod):
            if kind is None:
                if name not in schema.COUNTER_NAMES:
                    yield self.violation(
                        mod, node,
                        f"counter {name!r} missing from "
                        "telemetry/schema.COUNTER_NAMES",
                    )
            elif not schema.registered(kind, name):
                yield self.violation(
                    mod, node,
                    f"event {kind}:{name} not registered in "
                    "telemetry/schema.EVENT_REGISTRY (register it and "
                    "document it in README's event table)",
                )


# --------------------------------------------------------------------- #
# host-sync-in-traced
# --------------------------------------------------------------------- #
#: call names whose function-valued arguments are traced by jax
_TRACE_ENTRIES = {
    "jit", "vmap", "pmap", "checkify", "grad", "value_and_grad",
    "fori_loop", "while_loop", "scan", "cond", "switch",
    "pallas_call", "shard_map", "remat", "custom_vjp", "custom_jvp",
    "named_call",
}
#: decorator names that trace the function they decorate
_TRACE_DECORATORS = {"jit", "vmap", "pmap", "when", "custom_vjp",
                     "custom_jvp", "remat"}
#: methods whose nested function defs are traced by construction
#: (build_local's rhs/dt_fn/post closures run inside the jitted step)
_TRACED_METHODS = {"build_local"}

_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_HOST_ARRAY_MODULES = {"np", "numpy", "onp"}


@register
class HostSyncInTracedRule(Rule):
    name = "host-sync-in-traced"
    description = (
        "host-synchronizing call (.item()/.block_until_ready()/"
        ".tolist()/np.asarray/jax.device_get) inside traced code — a "
        "tracer error or a silent per-step device->host round trip"
    )

    def _traced_nodes(self, mod: ParsedModule):
        traced = set()
        traced_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                if _terminal_name(node.func) in _TRACE_ENTRIES:
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Lambda):
                            traced.add(arg)
                        elif isinstance(arg, ast.Name):
                            traced_names.add(arg.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = (
                        dec.func if isinstance(dec, ast.Call) else dec
                    )
                    if _terminal_name(target) in _TRACE_DECORATORS:
                        traced.add(node)
                if node.name in _TRACED_METHODS:
                    # closures built here ARE the traced physics; the
                    # method body itself runs at trace time
                    for sub in ast.walk(node):
                        if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.Lambda)
                        ):
                            traced.add(sub)
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in traced_names
            ):
                traced.add(node)
        # everything defined inside a traced function is traced too
        closure = set()
        for fn in traced:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.Lambda)):
                    closure.add(sub)
        return closure

    def _sync_calls(self, fn: ast.AST):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _SYNC_METHODS
            ):
                yield node, f".{func.attr}()"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in ("asarray", "array")
                and isinstance(func.value, ast.Name)
                and func.value.id in _HOST_ARRAY_MODULES
            ):
                yield node, f"{func.value.id}.{func.attr}(...)"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "device_get"
            ):
                yield node, "jax.device_get(...)"

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        seen = set()
        for fn in self._traced_nodes(mod):
            for node, what in self._sync_calls(fn):
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.violation(
                    mod, node,
                    f"{what} inside traced code (hot path): hoist the "
                    "sync out of the traced function or thread the "
                    "value in as an operand",
                )


# --------------------------------------------------------------------- #
# rank-divergent-collective / rank-divergent-effect (taint analysis
# shared with analysis/collective_verify, which owns the cross-module
# schedule properties: duplicate tags, divergent joins, declared-tag
# drift, sharding cases, the dynamic trace cross-check)
# --------------------------------------------------------------------- #
def _suppressed_at(mod: ParsedModule, rule: str, node: ast.AST,
                   guards) -> bool:
    """Pragma on the offending call, or — the audited idiom — on any
    enclosing rank-dependent guard line (one audit covers the whole
    single-writer block instead of one pragma per write)."""
    if mod.suppressed(node.lineno, rule):
        return True
    return any(mod.suppressed(line, rule) for line, _ in guards)


@register
class RankDivergentCollectiveRule(Rule):
    name = "rank-divergent-collective"
    description = (
        "collective entry point (barrier/agree/ppermute/psum/"
        "allgather/shard_map) under process_index()-dependent control "
        "flow — the MPI deadlock class: one rank arrives at the "
        "rendezvous, its peer never will"
    )

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        from multigpu_advectiondiffusion_tpu.analysis.collective_verify import (  # noqa: E501
            COLLECTIVE_CALLS,
            rank_guards,
            tainted_names,
        )

        tainted = tainted_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = COLLECTIVE_CALLS.get(_terminal_name(node.func) or "")
            if kind is None:
                continue
            guards = rank_guards(mod, node, tainted)
            if not guards:
                continue
            if _suppressed_at(mod, self.name, node, guards):
                continue
            line, test = guards[0]
            yield self.violation(
                mod, node,
                f"{kind} collective under the rank-dependent guard "
                f"`if {test}` (line {line}): ranks that skip the "
                "branch never reach this rendezvous — hoist the "
                "collective out of the guard or make the guard "
                "rank-uniform",
            )


@register
class RankDivergentEffectRule(Rule):
    name = "rank-divergent-effect"
    description = (
        "persistent write or telemetry emission inside a "
        "process_index()-guarded branch without the audited "
        "allow-pragma — the 'rank 0 wrote the checkpoint, rank 1 "
        "committed it' hazard class"
    )

    #: writer helpers whose call IS a persistent effect
    _WRITERS = {
        "save_binary", "save_checkpoint", "save_checkpoint_sharded",
        "atomic_write_text", "write_json",
    }
    _FS_MUTATORS = {"replace", "remove", "unlink", "rename"}

    def _effects(self, mod: ParsedModule):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = _terminal_name(func)
            if name in ("open", "fdopen"):
                mode = None
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = _literal_str(kw.value)
                if mode is None and len(node.args) >= 2:
                    mode = _literal_str(node.args[1])
                if mode and any(c in mode for c in "wx"):
                    yield node, f"open(..., {mode!r})"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in self._FS_MUTATORS
                and isinstance(func.value, ast.Name)
                and func.value.id in ("os", "_os")
            ):
                yield node, f"os.{func.attr}(...)"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in ("event", "counter")
            ):
                yield node, f".{func.attr}(...) telemetry emission"
            elif name in self._WRITERS:
                yield node, f"{name}(...)"

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        from multigpu_advectiondiffusion_tpu.analysis.collective_verify import (  # noqa: E501
            rank_guards,
            tainted_names,
        )

        tainted = tainted_names(mod)
        for node, what in self._effects(mod):
            guards = rank_guards(mod, node, tainted)
            if not guards:
                continue
            if _suppressed_at(mod, self.name, node, guards):
                continue
            line, test = guards[0]
            yield self.violation(
                mod, node,
                f"{what} under the rank-dependent guard `if {test}` "
                f"(line {line}): a peer that skips the branch sees a "
                "world where the artifact/event both exists and "
                "doesn't — audit it with the allow-pragma on the "
                "guard (stating why single-writer is safe) or make "
                "the effect rank-uniform",
            )


# --------------------------------------------------------------------- #
# registry-completeness
# --------------------------------------------------------------------- #
@register
class RegistryCompletenessRule(Rule):
    name = "registry-completeness"
    description = (
        "a register_model()'d solver class must declare the full "
        "plugin contract (stencil_spec/diagnostics_spec/"
        "ensemble_operands/cfl_rule) in its own class body — a "
        "half-wired plugin must fail statically, not at dispatch "
        "(the static twin of models/registry.register_model's "
        "runtime check)"
    )

    def _spec_solver_name(self, call: ast.Call) -> Optional[str]:
        """The solver class name a register_model(...) call binds:
        the ``solver_cls=Name`` keyword of the call itself or of a
        nested ModelSpec(...) constructor."""
        for node in ast.walk(call):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg == "solver_cls" and isinstance(
                    kw.value, ast.Name
                ):
                    return kw.value.id
        return None

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        from multigpu_advectiondiffusion_tpu.models.registry import (
            REQUIRED_SOLVER_CONTRACT,
        )

        classes = {
            n.name: n
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.ClassDef)
        }
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) != "register_model":
                continue
            solver_name = self._spec_solver_name(node)
            if solver_name is None:
                continue  # dynamic spec: runtime check still applies
            cls = classes.get(solver_name)
            if cls is None:
                continue  # class from another module: out of AST scope
            declared = {
                b.name
                for b in cls.body
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            missing = [
                m for m in REQUIRED_SOLVER_CONTRACT if m not in declared
            ]
            if missing:
                yield self.violation(
                    mod, node,
                    f"registered solver {solver_name} does not declare "
                    f"contract method(s) {missing} in its class body — "
                    "every plugin must ship the full "
                    "stencil_spec/diagnostics_spec/ensemble_operands/"
                    "cfl_rule contract (models/registry."
                    "REQUIRED_SOLVER_CONTRACT)",
                )


# --------------------------------------------------------------------- #
# closure-constant
# --------------------------------------------------------------------- #
@register
class ClosureConstantRule(Rule):
    name = "closure-constant"
    description = (
        "a build_local closure reads a member-varying scalar straight "
        "from the config instead of the overrides-threaded local (PR 9 "
        "contract: the batched ensemble dispatch vmaps ONE compiled "
        "program over members — a closure constant silently runs every "
        "member with member 0's physics)"
    )

    def _override_names(self, fn: ast.FunctionDef) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare) and any(
                isinstance(op, ast.In) for op in node.ops
            ):
                lit = _literal_str(node.left)
                if lit is not None and any(
                    isinstance(c, ast.Name) and c.id == "overrides"
                    for c in node.comparators
                ):
                    names.add(lit)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == "overrides"
            ):
                lit = _literal_str(node.slice)
                if lit is not None:
                    names.add(lit)
        return names

    def _cfg_reads(self, fn: ast.AST, names: Set[str]):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Attribute) or node.attr not in names:
                continue
            base = node.value
            is_cfg = (
                isinstance(base, ast.Name) and base.id == "cfg"
            ) or (isinstance(base, ast.Attribute) and base.attr == "cfg")
            if is_cfg:
                yield node

    def check(self, mod: ParsedModule) -> Iterable[Violation]:
        for node in ast.walk(mod.tree):
            if (
                not isinstance(node, ast.FunctionDef)
                or node.name != "build_local"
            ):
                continue
            names = self._override_names(node)
            if not names:
                continue
            for fn in ast.walk(node):
                if fn is node or not isinstance(
                    fn, (ast.FunctionDef, ast.Lambda)
                ):
                    continue
                for read in self._cfg_reads(fn, names):
                    yield self.violation(
                        mod, read,
                        f"closure captures cfg.{read.attr} — "
                        f"{read.attr!r} is a member-varying override; "
                        "read the overrides-threaded local instead",
                    )
