"""Stencil/halo consistency verifier — this domain's race detector.

A stale-halo read is to a halo-exchange stencil code what a data race
is to threaded CUDA: silently wrong cells near shard boundaries,
invisible until a norm drifts. ``cuda-memcheck``/racecheck found the
reference's races dynamically; here the contract is simple enough to
prove *statically*: every kernel declares its stencil radius
(``stencil_spec()``, the old ``R = 3``-style constants promoted to
queryable metadata), and this module proves — for every (rung, order,
k) combination the dispatch's eligibility gates admit — that

* the per-refresh ghost depth serves the fused trapezoid
  (``ghost_depth >= fused_stages * stage_radius``),
* the exchange moves exactly ``k * ghost_depth`` rows
  (``steps_per_exchange`` contract, ``parallel/halo.py``),
* the padded layout stores what the exchange writes
  (``core_offsets`` / ``padded_shape`` arithmetic, per axis),
* a shard's core is thick enough to SERVE the exchange
  (``interior[0] >= k*G`` — the ``exchange_ghosts`` runtime guard,
  proven before any program runs),
* the slab rung's built call windows match the re-derived trapezoid:
  k=1 full-core / three-call split; deep blocks shrinking by
  ``(k-1-j)*G`` margins per in-block step, step 0 consuming exactly
  the exchanged buffer (``fused_slab_run._build_deep_calls``).

Failures name the exact kernel/axis/depth. Consumed by ``tpucfd-check``
(CLI), ``out/lint_gate.sh`` and ``tests/test_analysis.py``; the tests
additionally prove an injected off-by-one ghost depth fails loudly.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional


@dataclasses.dataclass(frozen=True)
class HaloViolation:
    """One broken stencil/halo invariant, named precisely."""

    kernel: str
    axis: Optional[int]
    what: str
    expected: object
    actual: object

    def __str__(self) -> str:
        ax = "-" if self.axis is None else str(self.axis)
        return (
            f"[halo] kernel={self.kernel} axis={ax}: {self.what}: "
            f"expected {self.expected}, got {self.actual}"
        )


@dataclasses.dataclass
class ComboResult:
    """One (rung, order, k) combination's verdict."""

    name: str
    admitted: bool
    reason: Optional[str] = None  # decline reason when not admitted
    violations: List[HaloViolation] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass
class HaloReport:
    combos: List[ComboResult] = dataclasses.field(default_factory=list)
    constant_violations: List[HaloViolation] = dataclasses.field(
        default_factory=list
    )

    @property
    def violations(self) -> List[HaloViolation]:
        out = list(self.constant_violations)
        for c in self.combos:
            out.extend(c.violations)
        return out

    @property
    def checked(self) -> int:
        return sum(1 for c in self.combos if c.admitted)

    @property
    def ok(self) -> bool:
        return not self.violations


# --------------------------------------------------------------------- #
# Instance battery
# --------------------------------------------------------------------- #
def verify_stepper(stepper, kernel: Optional[str] = None
                   ) -> List[HaloViolation]:
    """Prove one stepper instance's declared stencil metadata
    consistent with its ghost/exchange/layout arithmetic. Returns the
    violations (empty = proven)."""
    spec = stepper.stencil_spec()
    kern = kernel or spec.get("kernel") or type(stepper).__name__
    out: List[HaloViolation] = []

    def bad(axis, what, expected, actual):
        out.append(HaloViolation(kern, axis, what, expected, actual))

    h = spec["stage_radius"]
    stages = spec["fused_stages"]
    G = spec["ghost_depth"]
    depth = spec["exchange_depth"]
    k = spec["steps_per_exchange"]
    if h < 1:
        bad(None, "stage radius must be >= 1", ">= 1", h)
        return out
    if G < stages * h:
        bad(0, "ghost depth cannot serve the fused-stage trapezoid",
            f">= {stages} * {h} = {stages * h}", G)
    if depth is not None and depth != k * G:
        bad(0, "exchange depth violates the k-step contract (k * G)",
            k * G, depth)

    # storage declaration (ISSUE 16): the HBM-buffer/wire dtype every
    # declared byte count derives from. Required of every rung, and
    # proven twice over: the declared bytes-per-cell must equal the
    # declared dtype's itemsize, and the declaration must match the
    # instance's actual buffer dtype — a drift in either direction
    # means the halo/DMA byte accounting no longer describes the wire
    # (a bf16 rung billed at 4 B/cell, or worse, the reverse).
    sdecl = spec.get("storage_dtype")
    bpc = spec.get("bytes_per_cell")
    if sdecl is None or bpc is None:
        bad(None, "stencil spec must declare storage_dtype and "
                  "bytes_per_cell (every halo/DMA byte count derives "
                  "from the storage declaration)",
            "storage_dtype + bytes_per_cell",
            {"storage_dtype": sdecl, "bytes_per_cell": bpc})
    else:
        import jax.numpy as jnp

        item = int(jnp.dtype(sdecl).itemsize)
        if item != int(bpc):
            bad(None, "declared bytes_per_cell disagrees with the "
                      "storage dtype's itemsize", item, bpc)
        buf = getattr(stepper, "dtype", None)
        if buf is not None and jnp.dtype(buf) != jnp.dtype(sdecl):
            bad(None, "declared storage dtype disagrees with the "
                      "instance's buffer dtype",
                str(jnp.dtype(buf)), str(sdecl))

    interior = tuple(getattr(stepper, "interior_shape", ()))
    padded = tuple(getattr(stepper, "padded_shape", ()))
    offs = getattr(stepper, "core_offsets", None)
    if interior and padded:
        lead_pad = depth if depth is not None else G
        if padded[0] < interior[0] + 2 * lead_pad:
            bad(0, "padded layout too thin for the declared ghost rows",
                f">= {interior[0]} + 2 * {lead_pad}", padded[0])
        if offs is not None:
            if depth is not None and offs[0] != depth:
                bad(0, "core offset must equal the exchange depth "
                       "(the exchange writes the rows above/below "
                       "the core)", depth, offs[0])
            for ax in range(len(interior)):
                if offs[ax] + interior[ax] > padded[ax]:
                    bad(ax, "core window exceeds the padded layout",
                        f"offset {offs[ax]} + interior {interior[ax]} "
                        f"<= {padded[ax]}",
                        offs[ax] + interior[ax])
    sharded = bool(getattr(stepper, "sharded", False))
    if sharded and depth is not None and interior:
        # exchange_ghosts raises at trace time when a shard cannot
        # serve the requested depth from its core — prove it up front
        if interior[0] < depth:
            bad(0, "shard core too thin to serve the exchange "
                   "(parallel/halo.exchange_ghosts would raise)",
                f"interior z >= {depth}", interior[0])
    # B-folded member grid axis (ISSUE 11): a batched rung must declare
    # the member axis HALO-FREE — members are independent problems, so
    # any nonzero member-axis stencil reach is a cross-member read, the
    # exact stale-halo class this verifier exists to rule out — and the
    # fold must never compose with spatial sharding in one program
    # (the per-step ghost refresh cannot cross the fold).
    members = int(spec.get("members", 1) or 1)
    mh = spec.get("member_halo")
    if members > 1:
        if mh != 0:
            bad(None, "member axis of a B-folded grid must be "
                      "halo-free (members are independent problems)",
                0, mh)
        if sharded:
            bad(0, "B-folded member grid cannot compose with spatial "
                   "sharding in one program", "unsharded", "sharded")
    elif mh not in (None, 0):
        bad(None, "declared member-axis halo must be 0", 0, mh)
    out.extend(_verify_remote_dma(stepper, kern, spec))
    out.extend(_verify_slab_windows(stepper, kern, spec))
    return out


def _verify_remote_dma(stepper, kern: str, spec) -> List[HaloViolation]:
    """Validate a declared in-kernel remote-DMA exchange window
    (``stencil_spec()['remote_dma']``, ROADMAP item 2's contract,
    landed ahead of the kernel — every shipped rung declares None).

    The declaration an in-kernel exchange must satisfy before any
    hardware run: the pushed window moves EXACTLY the rows the XLA
    exchange moved (``window_rows == exchange_depth`` — fewer is a
    stale-ghost read, more lands over live core rows: silent
    corruption either way), it pushes along the slab axis only
    (``axis == 0``, the one decomposition the slab rung serves), it is
    at least double-buffered (``buffers >= 2`` — a single landing
    buffer serializes the neighbor push against the compute it exists
    to overlap, and worse, lets a fast neighbor overwrite rows the
    local step is still reading), and it is declared on a sharded
    instance (an unsharded stepper has no neighbor to push to)."""
    dma = spec.get("remote_dma")
    out: List[HaloViolation] = []
    if dma is None:
        return out

    def bad(axis, what, expected, actual):
        out.append(HaloViolation(kern, axis, what, expected, actual))

    if not isinstance(dma, dict):
        bad(None, "remote_dma declaration must be a dict",
            "{'axis', 'window_rows', 'buffers'}", type(dma).__name__)
        return out
    missing = sorted(
        {"axis", "window_rows", "buffers"} - set(dma)
    )
    if missing:
        bad(None, "remote_dma declaration is missing fields",
            "axis/window_rows/buffers", missing)
        return out
    depth = spec["exchange_depth"]
    if dma["axis"] != 0:
        bad(dma["axis"], "remote DMA must push along the slab "
                         "decomposition axis", 0, dma["axis"])
    if dma["window_rows"] != depth:
        bad(0, "remote-DMA window disagrees with the exchange depth "
               "(fewer rows = stale ghosts; more = the push lands "
               "over live core rows)", depth, dma["window_rows"])
    if dma["buffers"] < 2:
        bad(0, "remote-DMA landing zone must be at least "
               "double-buffered (a single buffer serializes the push "
               "against the compute it overlaps, and a fast neighbor "
               "overwrites rows still being read)", ">= 2",
            dma["buffers"])
    if not bool(getattr(stepper, "sharded", False)):
        bad(None, "remote DMA declared on an unsharded stepper "
                  "(no neighbor to push to)", "sharded", "unsharded")
    # --- send/recv window disjointness + semaphore pairing (the
    # shipped kernel's full declaration; minimal declarations that
    # predate the kernel carry only axis/window_rows/buffers) ---
    interior = tuple(getattr(stepper, "interior_shape", ()) or ())
    core = None
    if interior and depth is not None:
        core = (depth, depth + interior[0])  # padded rows the shard computes
    rows = dma["window_rows"]
    for side, win in zip(("lo", "hi"), dma.get("send_windows") or ()):
        lo, hi = int(win[0]), int(win[1])
        if hi - lo != rows:
            bad(0, f"send window ({side}) width disagrees with the "
                   "declared push size", rows, hi - lo)
        if core is not None and not (core[0] <= lo and hi <= core[1]):
            bad(0, f"send window ({side}) reads outside the shard's "
                   "own core (a push sourcing ghost rows forwards a "
                   "neighbor's data as if it were this shard's)",
                f"within core [{core[0]}, {core[1]})", f"[{lo}, {hi})")
    for side, win in zip(("lo", "hi"), dma.get("recv_windows") or ()):
        lo, hi = int(win[0]), int(win[1])
        if hi - lo != rows:
            bad(0, f"recv window ({side}) width disagrees with the "
                   "declared push size", rows, hi - lo)
        if core is not None and not (hi <= core[0] or lo >= core[1]):
            # THE disjointness proof: pushed rows must never land over
            # rows the receiving shard computes — an overlap is the
            # silent-corruption race this mode turns a hang into
            bad(0, f"recv window ({side}) overlaps the receiver's "
                   "core rows (a neighbor's push would land over rows "
                   "the local step is still computing)",
                f"disjoint from core [{core[0]}, {core[1]})",
                f"[{lo}, {hi})")
    sems = dma.get("semaphores")
    if sems is not None:
        have = set(sems)
        if not {"send", "recv"} <= have:
            bad(0, "remote-DMA semaphores must pair a send and a recv "
                   "(an unpaired copy either never signals the "
                   "receiver or never releases the source rows)",
                "('send', 'recv')", tuple(sems))
    return out


# --------------------------------------------------------------------- #
# Member-sharded ensemble meshes (ISSUE 11)
# --------------------------------------------------------------------- #
def verify_member_mesh(name: str, mesh_axes: dict,
                       spatial: dict) -> ComboResult:
    """Statically prove a members(-x-spatial) ensemble mesh layout:
    the ``members`` axis exists, shards ONLY the batched state's
    leading member axis (never a grid axis — member sharding is
    halo-free by construction, so a member axis inside the spatial
    decomposition would be an undeclared exchange), and every spatial
    axis keeps its existing per-subgroup exchange contract (nothing
    about the spatial halo arithmetic changes under the fold).

    Since the collective-schedule round this is a thin wrapper over
    the ONE registry-driven mesh-layout pass
    (``analysis/collective_verify.mesh_layout_violations``), which
    additionally proves PartitionSpec/ppermute/reduction-set
    consistency for the spatial layouts the CLI admits."""
    from multigpu_advectiondiffusion_tpu.analysis.collective_verify import (
        mesh_layout_violations,
    )

    res = ComboResult(name=name, admitted=True)
    for axis, what, expected, actual in mesh_layout_violations(
        name, mesh_axes, spatial, member=True
    ):
        res.violations.append(
            HaloViolation(name, axis, what, expected, actual)
        )
    return res


def default_member_meshes():
    """The ensemble mesh layouts the dispatch admits, as static
    (name, mesh_axes, spatial-mapping) cases — members-only sharding
    and the members x z-slab composition (ROADMAP item 1's two
    rungs)."""
    return [
        ("ensemble-mesh[members=8]", {"members": 8}, {}),
        ("ensemble-mesh[members=4,dz=2]", {"members": 4, "dz": 2},
         {0: "dz"}),
    ]


def _expected_slab_windows(stepper, spec):
    """Re-derive the slab rung's call windows from the contract alone
    (interior/padded + stencil_spec + the shared block picker): the
    list of ``(z_out0, rows_out, ghost_src)`` the schedule must build,
    in construction order — k=1 full-core or three-call split; deep
    blocks with the ``(k-1-j)*G`` trapezoid margins."""
    G = spec["ghost_depth"]
    k = spec["steps_per_exchange"]
    depth = spec["exchange_depth"]
    lz = stepper.interior_shape[0]
    pz = stepper.padded_shape[0]
    bz, n_slabs = stepper.bz, stepper.n_slabs
    exp = []
    if k == 1:
        if stepper.overlap_split:
            exp.append((G + bz, (n_slabs - 2) * bz, None))      # interior
            exp.append((G, bz, "lo"))                            # bottom
            exp.append((G + (n_slabs - 1) * bz, bz, "hi"))       # top
        else:
            exp.append((G, n_slabs * bz, None))
        return exp
    # deep schedule: one call per in-block step j, windows shrinking by
    # G per side; step 0's box must cover exactly the exchanged buffer
    for j in range(k):
        ext = lz + 2 * (k - 1 - j) * G
        exp.append(((j + 1) * G, ext, None))
    if stepper.overlap_split:
        ext_i = lz - 2 * G
        exp.append((G + depth, ext_i, None))                     # interior
        bz_e = stepper._pick_call_bz(depth)
        for i in range(depth // bz_e):
            exp.append((G + i * bz_e, bz_e, "lo"))
        for i in range(depth // bz_e):
            exp.append((pz - G - depth + i * bz_e, bz_e, "hi"))
    return exp


def _verify_slab_windows(stepper, kern: str, spec) -> List[HaloViolation]:
    """The BlockSpec window arithmetic of the slab rung's sharded
    calls: recorded-at-construction windows vs the re-derived
    trapezoid. Non-slab steppers (no window ledger) verify vacuously."""
    windows = list(getattr(stepper, "_call_windows", ()) or ())
    out: List[HaloViolation] = []
    if not windows:
        return out

    def bad(what, expected, actual):
        out.append(HaloViolation(kern, 0, what, expected, actual))

    G = spec["ghost_depth"]
    depth = spec["exchange_depth"]
    pz = stepper.padded_shape[0]
    lz = stepper.interior_shape[0]
    for w in windows:
        rows = w["bz"] * w["n_grid"]
        box_lo = w["z_out0"] - G
        box_hi = w["z_out0"] + rows + G
        if box_lo < 0 or box_hi > pz:
            bad("call box reads outside the padded buffer",
                f"[0, {pz})", f"[{box_lo}, {box_hi})")
        if w["ghost_src"] is not None:
            # edge calls splice op_rows rows of the exchanged operand
            # into the box — the splice must stay inside the operand's
            # depth rows and inside the box
            if not (0 < w["op_rows"] <= w["bz"] + 2 * G):
                bad("ghost call consumes a nonsensical operand slice",
                    f"1..{w['bz'] + 2 * G} rows", w["op_rows"])
            if not (0 <= w["g_start"]
                    and w["g_start"] + w["op_rows"] <= depth):
                bad("ghost operand slice exceeds the exchanged depth",
                    f"within [0, {depth})",
                    f"[{w['g_start']}, {w['g_start'] + w['op_rows']})")
    expected = _expected_slab_windows(stepper, spec)
    actual = [
        (w["z_out0"], w["bz"] * w["n_grid"], w["ghost_src"])
        for w in windows
    ]
    if expected != actual:
        bad("built call windows disagree with the re-derived "
            "trapezoid schedule (z_out0, rows, ghost_src)",
            expected, actual)
    # the union of the final in-block step's output must be exactly the
    # core: rows [depth, depth + lz)
    k = spec["steps_per_exchange"]
    if k > 1:
        last = expected[k - 1]
        if (last[0], last[1]) != (depth, lz):
            bad("final in-block step does not write exactly the core",
                (depth, lz), (last[0], last[1]))
    return out


# --------------------------------------------------------------------- #
# Constants cross-check (first principles vs the shipped constants)
# --------------------------------------------------------------------- #
def verify_constants() -> List[HaloViolation]:
    """Prove the radius constants against the discretizations they
    describe: WENO order o reconstructs from an ``(o+1)//2``-wide
    one-sided stencil; the O4 second derivative is 5 taps per axis
    (radius ``len(coeffs)//2``); the slab/step fused ghosts are the
    3-stage trapezoid of those radii."""
    out: List[HaloViolation] = []
    from multigpu_advectiondiffusion_tpu.ops.laplacian import D2_STENCILS
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        MARGIN,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        _G_DIFF,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
        O4_COEFFS,
        R,
    )
    from multigpu_advectiondiffusion_tpu.ops.weno import HALO

    for order, r in HALO.items():
        want = (order + 1) // 2
        if r != want:
            out.append(HaloViolation(
                f"weno{order}", None,
                "WENO halo disagrees with the reconstruction width",
                want, r,
            ))
    if R != len(O4_COEFFS) // 2:
        out.append(HaloViolation(
            "pallas-laplacian", None,
            "O4 radius disagrees with its coefficient count",
            len(O4_COEFFS) // 2, R,
        ))
    for order, (coefs, radius, _denom) in D2_STENCILS.items():
        if len(coefs) != order + 1:
            out.append(HaloViolation(
                f"laplacian-o{order}", None,
                "generic D2 stencil width disagrees with its order",
                order + 1, len(coefs),
            ))
        if radius != len(coefs) // 2:
            # the generic path pads by this declared radius — a drift
            # here is exactly the stale-ghost read the verifier exists
            # to rule out
            out.append(HaloViolation(
                f"laplacian-o{order}", None,
                "declared pad radius disagrees with the tap count",
                len(coefs) // 2, radius,
            ))
    if _G_DIFF != 3 * R:
        out.append(HaloViolation(
            "fused-whole-run-slab", 0,
            "slab diffusion ghost depth is not the 3-stage trapezoid",
            3 * R, _G_DIFF,
        ))
    if MARGIN < max(HALO.values()):
        out.append(HaloViolation(
            "fused-stage", 1,
            "Burgers y margin cannot host the widest WENO halo",
            f">= {max(HALO.values())}", MARGIN,
        ))
    return out


# --------------------------------------------------------------------- #
# The admitted (rung, order, k) matrix
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class Combo:
    name: str
    build: Callable[[], object]


def _spacing(n):
    return (0.1,) * n


def _diffusion_combos() -> List[Combo]:
    """The diffusion family's admitted (rung, order, k) battery."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (
        ShardedFusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
        FusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (
        FusedDiffusion2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (  # noqa: E501
        StepFusedDiffusionStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunDiffusionStepper,
    )

    f32 = jnp.float32
    combos: List[Combo] = []

    def diff3d(shape=(24, 10, 12), **kw):
        return FusedDiffusionStepper(
            shape, f32, _spacing(3), [1.0] * 3, 1e-4, 2, 0.0, **kw
        )

    combos.append(Combo("diffusion3d-stage", diff3d))
    combos.append(Combo(
        "diffusion3d-stage[sharded]",
        lambda: diff3d(global_shape=(48, 10, 12)),
    ))
    # bf16-storage / f32-compute rung (ISSUE 16): the buffer (and every
    # wire byte) is bf16, the facing state f32 — the verifier proves
    # the 2 B/cell declaration against the instance's buffer dtype
    combos.append(Combo(
        "diffusion3d-stage[bf16]",
        lambda: FusedDiffusionStepper(
            (24, 10, 12), jnp.bfloat16, _spacing(3), [1.0] * 3, 1e-4,
            2, 0.0, storage_dtype=f32,
        ),
    ))
    combos.append(Combo(
        "diffusion3d-step",
        lambda: StepFusedDiffusionStepper(
            (24, 10, 12), f32, _spacing(3), [1.0] * 3, 1e-4, 2, 0.0
        ),
    ))
    combos.append(Combo(
        "diffusion2d-whole-run",
        lambda: FusedDiffusion2DStepper(
            (32, 32), f32, _spacing(2), [1.0] * 2, 1e-4, 2, 0.0
        ),
    ))
    combos.append(Combo(
        "diffusion2d-stage[sharded]",
        lambda: ShardedFusedDiffusion2DStepper(
            (16, 32), f32, _spacing(2), [1.0] * 2, 1e-4, 2, 0.0,
            global_shape=(32, 32),
        ),
    ))

    def slab_diff(k=1, split=False, shape=(24, 10, 12), sharded=True,
                  members=1, dma=False, dtype=f32, storage=None):
        kw = {}
        if dma:
            kw = {"exchange": "dma", "mesh_axis": "dz", "num_shards": 2}
        if storage is not None:
            kw["storage_dtype"] = storage
        return SlabRunDiffusionStepper(
            shape, dtype, _spacing(3), [1.0] * 3, 1e-4, 2, 0.0,
            global_shape=(shape[0] * 2,) + shape[1:] if sharded else None,
            overlap_split=split, steps_per_exchange=k, members=members,
            **kw,
        )

    combos.append(Combo(
        "slab-diffusion[unsharded]",
        lambda: slab_diff(sharded=False),
    ))
    # B-folded member grid axis (ISSUE 11): batched instances must
    # prove the member axis halo-free and decline spatial sharding
    for B in (2, 4):
        combos.append(Combo(
            f"slab-diffusion[B={B}]",
            lambda B=B: slab_diff(sharded=False, members=B),
        ))
    combos.append(Combo(
        "slab-diffusion[B=4,sharded]",  # must DECLINE (constructor gate)
        lambda: slab_diff(members=4),
    ))
    for k in (1, 2, 3):
        combos.append(Combo(
            f"slab-diffusion[k={k}]", lambda k=k: slab_diff(k=k)
        ))
        combos.append(Combo(
            f"slab-diffusion[k={k},split]",
            lambda k=k: slab_diff(k=k, split=True),
        ))
        # in-kernel remote-DMA transport (ISSUE 13): the shipped
        # declaration — window arithmetic, send/recv disjointness,
        # semaphore pairing — proven per admitted cadence
        combos.append(Combo(
            f"slab-diffusion[k={k},dma]",
            lambda k=k: slab_diff(k=k, dma=True),
        ))
    # bf16 storage on the whole-run slab rung (ISSUE 16): the collective
    # and remote-DMA transports both push bf16 slabs, so the window /
    # disjointness contracts re-prove with 2 B/cell storage declared
    combos.append(Combo(
        "slab-diffusion[bf16]",
        lambda: slab_diff(dtype=jnp.bfloat16, storage=f32),
    ))
    combos.append(Combo(
        "slab-diffusion[bf16,dma]",
        lambda: slab_diff(dma=True, dtype=jnp.bfloat16, storage=f32),
    ))
    return combos


def _burgers_combos() -> List[Combo]:
    """The Burgers family's admitted (rung, order, k) battery."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.ops.flux import burgers as _burg
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused2d_sharded import (
        ShardedFusedBurgers2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        FusedBurgersStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (
        FusedBurgers2DStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunBurgersStepper,
    )

    f32 = jnp.float32
    combos: List[Combo] = []
    for order in (5, 7):
        def burg3d(order=order, **kw):
            return FusedBurgersStepper(
                (12, 16, 64), f32, _spacing(3), _burg(), "js", 0.0,
                dt=1e-3, order=order, **kw,
            )

        combos.append(Combo(f"burgers3d-stage[o{order}]", burg3d))
        combos.append(Combo(
            f"burgers3d-stage[o{order},sharded]",
            lambda order=order: burg3d(
                order=order, global_shape=(24, 16, 64)
            ),
        ))
        combos.append(Combo(
            f"burgers2d-stage[o{order},sharded]",
            lambda order=order: ShardedFusedBurgers2DStepper(
                (16, 64), f32, _spacing(2), _burg(), "js", 0.0,
                dt=1e-3, global_shape=(32, 64), order=order,
            ),
        ))
        combos.append(Combo(
            f"burgers2d-whole-run[o{order}]",
            lambda order=order: FusedBurgers2DStepper(
                (32, 64), f32, _spacing(2), _burg(), "js", 0.0,
                dt=1e-3, order=order,
            ),
        ))

        def slab_burg(k=1, split=False, order=order, dma=False,
                      dtype=f32, storage=None):
            shape = (36, 16, 64)
            kw = {}
            if dma:
                kw = {"exchange": "dma", "mesh_axis": "dz",
                      "num_shards": 2}
            if storage is not None:
                kw["storage_dtype"] = storage
            return SlabRunBurgersStepper(
                shape, dtype, _spacing(3), _burg(), "js", 0.0, 1e-3,
                global_shape=(72,) + shape[1:], order=order,
                overlap_split=split, steps_per_exchange=k, **kw,
            )

        combos.append(Combo(
            f"slab-burgers[o{order},unsharded]",
            lambda order=order: SlabRunBurgersStepper(
                (36, 16, 64), f32, _spacing(3), _burg(), "js", 0.0,
                1e-3, order=order,
            ),
        ))
        combos.append(Combo(
            f"slab-burgers[o{order},B=4]",
            lambda order=order: SlabRunBurgersStepper(
                (36, 16, 64), f32, _spacing(3), _burg(), "js", 0.0,
                1e-3, order=order, members=4,
            ),
        ))
        for k in (1, 2, 3):
            combos.append(Combo(
                f"slab-burgers[o{order},k={k}]",
                lambda k=k, order=order: slab_burg(k=k, order=order),
            ))
            combos.append(Combo(
                f"slab-burgers[o{order},k={k},split]",
                lambda k=k, order=order: slab_burg(
                    k=k, split=True, order=order
                ),
            ))
            combos.append(Combo(
                f"slab-burgers[o{order},k={k},dma]",
                lambda k=k, order=order: slab_burg(
                    k=k, dma=True, order=order
                ),
            ))
        # bf16 storage (ISSUE 16): Burgers' only fused bf16 rung is the
        # whole-run slab — proven per WENO order with 2 B/cell declared
        combos.append(Combo(
            f"slab-burgers[o{order},bf16]",
            lambda order=order: slab_burg(
                order=order, dtype=jnp.bfloat16, storage=f32
            ),
        ))
    return combos


def _adr_combos() -> List[Combo]:
    """The ADR family's battery (ISSUE 15): the fused per-stage rung
    at its stencil radius = max(advective upwind 1, diffusive O4 2)
    taps — constant-K, variable-K, and the shard-local instance."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_adr import (
        FusedADRStepper,
    )

    f32 = jnp.float32

    def adr3d(dtype=f32, **kw):
        return FusedADRStepper(
            (24, 10, 12), dtype, _spacing(3), 1.0, (0.5, 0.25, 0.0),
            0.3, 1e-4, 2, 0.0, **kw,
        )

    return [
        Combo("adr3d-stage", adr3d),
        Combo("adr3d-stage[varK]",
              lambda: adr3d(kappa_variation=0.2)),
        Combo("adr3d-stage[sharded]",
              lambda: adr3d(kappa_variation=0.2,
                            global_shape=(48, 10, 12))),
        # bf16 storage / f32 compute (ISSUE 16)
        Combo("adr3d-stage[bf16]",
              lambda: adr3d(dtype=jnp.bfloat16, storage_dtype=f32)),
    ]


#: family name -> combo battery builder. Resolved against the solver
#: registry (models/registry.py): a REGISTERED family missing here is
#: a coverage FAILURE in verify_all, never a silent gap.
FAMILY_COMBOS = {
    "diffusion": _diffusion_combos,
    "burgers": _burgers_combos,
    "adr": _adr_combos,
}

#: expected combo-matrix size per family — asserted by verify_all, so
#: a combo that silently falls out of a battery (a dropped k, order or
#: coefficient mode) is a counted coverage failure, not a quiet shrink
#: (ISSUE 15 satellite).
EXPECTED_FAMILY_COMBOS = {
    "diffusion": 21,  # 6 stage/step/2d (incl bf16) + 1 unsharded slab
    #                 + 3 B-fold + 3k x {plain, split, dma}
    #                 + 2 bf16 slab (collective, dma)
    "burgers": 32,    # 2 orders x (4 stage/2d + 2 slab + 3k x 3 modes
    #                 + 1 bf16 slab)
    "adr": 4,         # per-stage: const-K, var-K, sharded, bf16
}


def family_combos():
    """``(combos_by_family, missing_families)``: every registered
    solver family's battery, resolved through the registry — the halo
    verifier's matrix derives from registration, not from a hand-kept
    list."""
    from multigpu_advectiondiffusion_tpu.models import registry

    by_family = {}
    missing = []
    for name in registry.names():
        builder = FAMILY_COMBOS.get(name)
        if builder is None:
            missing.append(name)
            continue
        by_family[name] = builder()
    return by_family, missing


def default_combos() -> List[Combo]:
    """Every registered family's battery, flattened (the historical
    API; coverage/count accounting lives in :func:`verify_all`)."""
    by_family, _ = family_combos()
    out: List[Combo] = []
    for combos in by_family.values():
        out.extend(combos)
    return out


def verify_all(combos: Optional[List[Combo]] = None) -> HaloReport:
    """Run the battery over every admitted combination; declined
    combinations (a constructor gate raised, as the dispatch would)
    are recorded with their reason, not silently dropped. The default
    battery resolves the combo matrix through the solver registry:
    a registered family with NO battery, or a battery whose size
    drifted from :data:`EXPECTED_FAMILY_COMBOS`, is a coverage
    violation. It also proves the ensemble mesh layouts
    (:func:`default_member_meshes`) member-axis-halo-free."""
    report = HaloReport(constant_violations=verify_constants())
    if combos is None:
        by_family, missing = family_combos()
        for fam in missing:
            report.constant_violations.append(HaloViolation(
                f"registry[{fam}]", None,
                "registered solver family has no halo-verifier combo "
                "battery (FAMILY_COMBOS) — a new family must prove its "
                "rungs, not skip the matrix",
                "a FAMILY_COMBOS entry", "missing",
            ))
        run_list: List[Combo] = []
        for fam, fam_combos in by_family.items():
            expected = EXPECTED_FAMILY_COMBOS.get(fam)
            if expected is not None and len(fam_combos) != expected:
                report.constant_violations.append(HaloViolation(
                    f"registry[{fam}]", None,
                    "combo-matrix size drifted (a silently dropped "
                    "combination is a coverage failure)",
                    expected, len(fam_combos),
                ))
            run_list.extend(fam_combos)
    else:
        run_list = combos
    for combo in run_list:
        res = ComboResult(name=combo.name, admitted=True)
        try:
            stepper = combo.build()
        except ValueError as exc:
            res.admitted = False
            res.reason = str(exc)
            report.combos.append(res)
            continue
        res.violations = verify_stepper(stepper, kernel=combo.name)
        report.combos.append(res)
    if combos is None:
        for name, mesh_axes, spatial in default_member_meshes():
            report.combos.append(
                verify_member_mesh(name, mesh_axes, spatial)
            )
    return report
