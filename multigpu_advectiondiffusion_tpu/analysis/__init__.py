"""Project static analysis (`tpucfd-check`): the machine-checked half
of nine PRs of hand-enforced invariants.

Three layers (ISSUE 10):

* :mod:`framework` + :mod:`rules` — an AST rule engine (the
  generalization of ``telemetry/schema.scan_emitted``) with domain lint
  rules: closure-captured physics constants in ``build_local``
  closures, host-sync calls inside traced code, non-atomic persistent
  artifact writes, unregistered telemetry emission sites;
* :mod:`halo_verify` — the stencil/halo consistency verifier, this
  domain's race detector: proves ghost depth G, exchange depth k*G and
  the slab trapezoid margins ``(k-1-j)*G`` mutually sufficient for
  every (rung, order, k) combination the dispatch admits;
* :mod:`sanitizer` — opt-in ``jax.experimental.checkify``
  instrumentation of the steppers (``--checkify``), surfacing NaN /
  div-by-zero / OOB through the supervisor's rollback path.

CLI: ``python -m multigpu_advectiondiffusion_tpu.analysis`` (or the
``check`` subcommand of the main CLI); CI gate: ``out/lint_gate.sh``.
"""

from multigpu_advectiondiffusion_tpu.analysis.framework import (  # noqa: F401
    ParsedModule,
    Rule,
    Violation,
    all_rules,
    iter_modules,
    run_rules,
)
