"""Project static analysis (`tpucfd-check`): the machine-checked half
of ten PRs of hand-enforced invariants.

Four layers (ISSUE 10 + the collective-schedule round, ISSUE 12):

* :mod:`framework` + :mod:`rules` — an AST rule engine (the
  generalization of ``telemetry/schema.scan_emitted``) with domain lint
  rules: closure-captured physics constants in ``build_local``
  closures, host-sync calls inside traced code, non-atomic persistent
  artifact writes, unregistered telemetry emission sites, collectives
  and persistent effects under ``process_index()``-dependent control
  flow;
* :mod:`halo_verify` — the stencil/halo consistency verifier, this
  domain's race detector: proves ghost depth G, exchange depth k*G,
  the slab trapezoid margins ``(k-1-j)*G`` and any declared in-kernel
  remote-DMA window mutually sufficient for every (rung, order, k)
  combination the dispatch admits;
* :mod:`collective_verify` — the collective-schedule & SPMD
  consistency verifier, the distributed analogue of the halo pass
  (MUST/ISP-style MPI verification, statically): extracts every
  barrier/agree/ppermute/reduce/shard_map site, proves tag uniqueness,
  join consistency and declared-metadata drift, proves the sharding
  registry (PartitionSpec axes vs mesh, member-axis rules), and
  cross-checks the static schedule against measured 2-proc telemetry
  streams so the analysis cannot drift from the code it models;
* :mod:`sanitizer` — opt-in ``jax.experimental.checkify``
  instrumentation of the steppers (``--checkify``), surfacing NaN /
  div-by-zero / OOB through the supervisor's rollback path.

CLI: ``python -m multigpu_advectiondiffusion_tpu.analysis`` (or the
``check`` subcommand of the main CLI); CI gate: ``out/lint_gate.sh``.
"""

from multigpu_advectiondiffusion_tpu.analysis.framework import (  # noqa: F401
    ParsedModule,
    Rule,
    Violation,
    all_rules,
    iter_modules,
    run_rules,
)
