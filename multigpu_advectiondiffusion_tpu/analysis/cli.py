"""``tpucfd-check``: the static-analysis CLI.

    python -m multigpu_advectiondiffusion_tpu.analysis          # full check
    python -m multigpu_advectiondiffusion_tpu.cli check          # same
    ... check --selftest         # every rule must trip on its seeded
                                 # fixture; the halo verifier must fail
                                 # an injected off-by-one ghost depth
    ... check --json             # machine-readable report
    ... check --list-rules       # the rule table

Exit codes: 0 clean, 1 violations (or a failed selftest), 2 usage.
Wired into CI by ``out/lint_gate.sh`` (clean-tree pass + selftest) and
run over the installed package by the tier-1 ``tests/test_analysis.py``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def configure_parser(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", default=None, metavar="DIR",
                   help="package tree to lint (default: the installed "
                        "multigpu_advectiondiffusion_tpu package)")
    p.add_argument("--rules", default=None, metavar="NAME[,NAME...]",
                   help="run only these lint rules (default: all)")
    p.add_argument("--skip-lint", action="store_true",
                   help="skip the AST lint rules (halo verifier only)")
    p.add_argument("--skip-halo", action="store_true",
                   help="skip the stencil/halo verifier (lint only)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--selftest", action="store_true",
                   help="prove every rule trips on its seeded violation "
                        "fixture (and passes the clean twin), and the "
                        "halo verifier fails an injected off-by-one "
                        "ghost depth")
    p.set_defaults(fn=run)


def _selected_rules(arg: Optional[str]):
    from multigpu_advectiondiffusion_tpu.analysis import all_rules

    registry = all_rules()
    if not arg:
        return [cls() for cls in registry.values()]
    out = []
    for name in arg.split(","):
        name = name.strip()
        if name not in registry:
            raise SystemExit(
                f"unknown rule {name!r}; known: {sorted(registry)}"
            )
        out.append(registry[name]())
    return out


def selftest(out=print) -> bool:
    """Every rule trips on its seeded fixture and passes the clean
    twin; the halo verifier proves the shipped combos and fails an
    injected off-by-one ghost depth naming kernel/axis/depth."""
    import tempfile

    from multigpu_advectiondiffusion_tpu.analysis import all_rules, run_rules
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify
    from multigpu_advectiondiffusion_tpu.analysis.fixtures import (
        RULE_FIXTURES,
    )
    from multigpu_advectiondiffusion_tpu.utils.io import atomic_write_text

    ok = True
    registry = all_rules()
    missing = sorted(set(registry) - set(RULE_FIXTURES))
    if missing:
        out(f"FAIL: rule(s) without a seeded fixture: {missing}")
        ok = False
    for name, pair in sorted(RULE_FIXTURES.items()):
        if name not in registry:
            out(f"FAIL: fixture for unknown rule {name!r}")
            ok = False
            continue
        rule = registry[name]()
        for flavor, src in (("bad", pair["bad"]), ("good", pair["good"])):
            with tempfile.TemporaryDirectory() as d:
                atomic_write_text(f"{d}/fixture_{flavor}.py", src)
                hits = [
                    v for v in run_rules(d, rules=[rule])
                    if v.rule == name
                ]
            if flavor == "bad" and not hits:
                out(f"FAIL: rule {name} did not trip on its seeded "
                    "violation fixture")
                ok = False
            elif flavor == "good" and hits:
                out(f"FAIL: rule {name} false-positives on its clean "
                    f"twin: {[str(v) for v in hits]}")
                ok = False
            else:
                out(f"  ok: {name} [{flavor}]")
    # halo verifier: shipped combos prove clean...
    report = halo_verify.verify_all()
    if not report.ok:
        out("FAIL: halo verifier flags the shipped tree:")
        for v in report.violations:
            out(f"  {v}")
        ok = False
    else:
        out(f"  ok: halo verifier ({report.checked} combos clean)")
    # ...and an injected off-by-one ghost depth fails, named
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2]"
    )
    stepper = combo.build()
    stepper.exchange_depth += 1
    injected = halo_verify.verify_stepper(stepper, kernel=combo.name)
    if not injected:
        out("FAIL: halo verifier passed an injected off-by-one ghost "
            "depth")
        ok = False
    elif not any(v.axis == 0 for v in injected):
        out("FAIL: halo violation does not name the offending axis")
        ok = False
    else:
        out(f"  ok: injected off-by-one trips ({len(injected)} "
            f"violations, e.g. {injected[0]})")
    out("selftest: " + ("PASS" if ok else "FAIL"))
    return ok


def run(args) -> Optional[bool]:
    """Entry point for both the ``check`` subcommand and the module
    CLI. Returns ``False`` (CLI failure) on violations."""
    from multigpu_advectiondiffusion_tpu.analysis import all_rules, run_rules
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {' '.join(cls.description.split())}")
        print("halo-verify: stencil/halo consistency verifier — proves "
              "ghost depth G, exchange depth k*G and the slab trapezoid "
              "margins (k-1-j)*G sufficient for every admitted "
              "(rung, order, k) combination")
        return None
    if args.selftest:
        return True if selftest() else False

    problems: List[str] = []
    lint = []
    if not args.skip_lint:
        lint = run_rules(args.root, rules=_selected_rules(args.rules))
        problems.extend(str(v) for v in lint)
    halo = None
    if not args.skip_halo:
        halo = halo_verify.verify_all()
        problems.extend(str(v) for v in halo.violations)

    if args.json:
        print(json.dumps({
            "lint": [vars(v) for v in lint],
            "halo": {
                "checked": halo.checked if halo else 0,
                "declined": [
                    {"name": c.name, "reason": c.reason}
                    for c in (halo.combos if halo else [])
                    if not c.admitted
                ],
                "violations": [vars(v) for v in halo.violations]
                if halo else [],
            },
            "ok": not problems,
        }, indent=2))
    else:
        for line in problems:
            print(line)
        checked = halo.checked if halo else 0
        print(
            f"tpucfd-check: {len(problems)} violation(s); "
            f"halo combos proven: {checked}"
            + ("" if problems else " — clean")
        )
    return False if problems else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpucfd-check",
        description="project static analysis: AST lint rules + "
                    "stencil/halo consistency verifier",
    )
    configure_parser(ap)
    args = ap.parse_args(argv)
    return 1 if run(args) is False else 0


if __name__ == "__main__":
    sys.exit(main())
