"""``tpucfd-check``: the static-analysis CLI.

    python -m multigpu_advectiondiffusion_tpu.analysis          # full check
    python -m multigpu_advectiondiffusion_tpu.cli check          # same
    ... check --selftest         # every rule must trip on its seeded
                                 # fixture; the halo verifier must fail
                                 # an injected off-by-one ghost depth;
                                 # the collective verifier must fail
                                 # seeded deadlock/sharding fixtures
    ... check --json             # machine-readable report
    ... check --list-rules       # the rule table
    ... check --schedule-trace events_p0.jsonl events_p1.jsonl
                                 # prove measured per-rank collective
                                 # sequences are a linearization of
                                 # the static schedule

Exit codes: 0 clean, 1 violations (or a failed selftest), 2 usage.
Wired into CI by ``out/lint_gate.sh`` (clean-tree pass + selftest) and
run over the installed package by the tier-1 ``tests/test_analysis.py``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def configure_parser(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", default=None, metavar="DIR",
                   help="package tree to lint (default: the installed "
                        "multigpu_advectiondiffusion_tpu package)")
    p.add_argument("--rules", default=None, metavar="NAME[,NAME...]",
                   help="run only these lint rules (default: all)")
    p.add_argument("--skip-lint", action="store_true",
                   help="skip the AST lint rules (halo verifier only)")
    p.add_argument("--skip-halo", action="store_true",
                   help="skip the stencil/halo verifier (lint only)")
    p.add_argument("--skip-collective", action="store_true",
                   help="skip the collective-schedule & sharding "
                        "verifier")
    p.add_argument("--schedule-trace", nargs="+", default=None,
                   metavar="EVENTS.jsonl",
                   help="dynamic cross-check: prove the per-rank "
                        "collective sequences measured in these "
                        "telemetry streams are a linearization of the "
                        "statically extracted schedule")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--selftest", action="store_true",
                   help="prove every rule trips on its seeded violation "
                        "fixture (and passes the clean twin), and the "
                        "halo verifier fails an injected off-by-one "
                        "ghost depth")
    p.set_defaults(fn=run)


def _selected_rules(arg: Optional[str]):
    from multigpu_advectiondiffusion_tpu.analysis import all_rules

    registry = all_rules()
    if not arg:
        return [cls() for cls in registry.values()]
    out = []
    for name in arg.split(","):
        name = name.strip()
        if name not in registry:
            raise SystemExit(
                f"unknown rule {name!r}; known: {sorted(registry)}"
            )
        out.append(registry[name]())
    return out


def selftest(out=print) -> bool:
    """Every rule trips on its seeded fixture and passes the clean
    twin; the halo verifier proves the shipped combos and fails an
    injected off-by-one ghost depth naming kernel/axis/depth; the
    collective verifier proves the shipped tree and fails seeded
    deadlock (duplicate-tag / divergent-join), sharding and
    remote-DMA fixtures naming file/line/tag; the trace cross-check
    rejects a non-linearized measured sequence."""
    import tempfile

    from multigpu_advectiondiffusion_tpu.analysis import all_rules, run_rules
    from multigpu_advectiondiffusion_tpu.analysis import (
        collective_verify,
        halo_verify,
    )
    from multigpu_advectiondiffusion_tpu.analysis.fixtures import (
        RULE_FIXTURES,
    )
    from multigpu_advectiondiffusion_tpu.utils.io import atomic_write_text

    ok = True
    registry = all_rules()
    missing = sorted(set(registry) - set(RULE_FIXTURES))
    if missing:
        out(f"FAIL: rule(s) without a seeded fixture: {missing}")
        ok = False
    for name, pair in sorted(RULE_FIXTURES.items()):
        if name not in registry:
            out(f"FAIL: fixture for unknown rule {name!r}")
            ok = False
            continue
        rule = registry[name]()
        for flavor, src in (("bad", pair["bad"]), ("good", pair["good"])):
            with tempfile.TemporaryDirectory() as d:
                atomic_write_text(f"{d}/fixture_{flavor}.py", src)
                hits = [
                    v for v in run_rules(d, rules=[rule])
                    if v.rule == name
                ]
            if flavor == "bad" and not hits:
                out(f"FAIL: rule {name} did not trip on its seeded "
                    "violation fixture")
                ok = False
            elif flavor == "good" and hits:
                out(f"FAIL: rule {name} false-positives on its clean "
                    f"twin: {[str(v) for v in hits]}")
                ok = False
            else:
                out(f"  ok: {name} [{flavor}]")
    # halo verifier: shipped combos prove clean...
    report = halo_verify.verify_all()
    if not report.ok:
        out("FAIL: halo verifier flags the shipped tree:")
        for v in report.violations:
            out(f"  {v}")
        ok = False
    else:
        out(f"  ok: halo verifier ({report.checked} combos clean)")
    # ...and an injected off-by-one ghost depth fails, named
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2]"
    )
    stepper = combo.build()
    stepper.exchange_depth += 1
    injected = halo_verify.verify_stepper(stepper, kernel=combo.name)
    if not injected:
        out("FAIL: halo verifier passed an injected off-by-one ghost "
            "depth")
        ok = False
    elif not any(v.axis == 0 for v in injected):
        out("FAIL: halo violation does not name the offending axis")
        ok = False
    else:
        out(f"  ok: injected off-by-one trips ({len(injected)} "
            f"violations, e.g. {injected[0]})")
    # collective verifier: the shipped tree proves rank-uniform...
    coll = collective_verify.verify_tree()
    if not coll.ok:
        out("FAIL: collective verifier flags the shipped tree:")
        for v in coll.violations:
            out(f"  {v}")
        ok = False
    else:
        out(f"  ok: collective verifier ({len(coll.sites)} sites, "
            f"{len(coll.cases_proven)} sharding cases clean)")
    # ...a seeded duplicate-tag pair fails, naming file/line/tag...
    with tempfile.TemporaryDirectory() as d:
        atomic_write_text(
            f"{d}/writer_a.py",
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n\n"
            "def commit_a():\n"
            "    multihost.barrier('shared-commit')\n",
        )
        atomic_write_text(
            f"{d}/writer_b.py",
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n\n"
            "def commit_b():\n"
            "    multihost.barrier('shared-commit')\n",
        )
        dup = collective_verify.verify_tree(root=d)
    hits = [v for v in dup.violations
            if v.rule == "duplicate-collective-tag"]
    if not hits or "shared-commit" not in hits[0].site:
        out("FAIL: duplicate-tag fixture did not trip naming the tag")
        ok = False
    else:
        out(f"  ok: seeded duplicate tag trips ({hits[0]})")
    # ...a seeded rank-divergent join fails, naming the guard...
    with tempfile.TemporaryDirectory() as d:
        atomic_write_text(
            f"{d}/joiner.py",
            "import jax\n"
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n\n"
            "def desync():\n"
            "    if jax.process_index() == 0:\n"
            "        multihost.agree('coord-only', [1.0])\n"
            "    else:\n"
            "        multihost.barrier('worker-only')\n",
        )
        join = collective_verify.verify_tree(root=d)
    hits = [v for v in join.violations if v.rule == "divergent-join"]
    if not hits:
        out("FAIL: divergent-join fixture did not trip")
        ok = False
    else:
        out(f"  ok: seeded divergent join trips ({hits[0]})")
    # ...the sharding pass fails a bad PartitionSpec axis and a
    # member-axis-in-spatial layout...
    bad_cases = [
        collective_verify.ShardingCase(
            "selftest-bad-axis", {"dz": 2}, {0: "zd"},
        ),
        collective_verify.ShardingCase(
            "selftest-member-in-spatial", {"members": 4, "dz": 2},
            {0: "members"}, member=True,
        ),
    ]
    _, sharding = collective_verify.verify_sharding_cases(bad_cases)
    named = {v.path for v in sharding}
    if {c.name for c in bad_cases} - named:
        out("FAIL: sharding fixtures did not all trip: "
            f"{sorted(named)}")
        ok = False
    else:
        out(f"  ok: seeded sharding fixtures trip "
            f"({len(sharding)} violations)")
    # ...a declared remote-DMA window is validated against the
    # exchange depth (ROADMAP item 2's contract, proven ahead of the
    # kernel)...
    stepper = combo.build()
    depth = stepper.exchange_depth
    stepper.remote_dma = {"axis": 0, "window_rows": depth, "buffers": 2}
    if halo_verify.verify_stepper(stepper, kernel=combo.name):
        out("FAIL: a consistent remote-DMA declaration was rejected")
        ok = False
    stepper.remote_dma = {"axis": 0, "window_rows": depth - 1,
                          "buffers": 1}
    dma = halo_verify.verify_stepper(stepper, kernel=combo.name)
    if len(dma) < 2:
        out("FAIL: inconsistent remote-DMA declaration passed")
        ok = False
    else:
        out(f"  ok: bad remote-DMA window trips ({dma[0]})")
    # ...the SHIPPED dma rung's declaration proves clean, and an
    # injected overlapping recv window — a neighbor push landing over
    # rows the receiver is still computing, the silent-corruption race
    # — is rejected naming kernel/axis/rows
    dma_combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2,dma]"
    )
    shipped = dma_combo.build()
    if halo_verify.verify_stepper(shipped, kernel=dma_combo.name):
        out("FAIL: the shipped in-kernel dma declaration was rejected")
        ok = False
    depth = shipped.exchange_depth
    shipped.remote_dma = dict(shipped.remote_dma)
    shipped.remote_dma["recv_windows"] = (
        (depth, 2 * depth),  # lands ON core rows — must be rejected
        shipped.remote_dma["recv_windows"][1],
    )
    overlap = halo_verify.verify_stepper(shipped, kernel=dma_combo.name)
    named = [v for v in overlap if "overlaps the receiver's core"
             in v.what]
    if not named:
        out("FAIL: overlapping dma recv window was not rejected")
        ok = False
    elif named[0].axis != 0 or str(depth) not in str(named[0]):
        out("FAIL: overlapping-window violation does not name "
            "axis/rows")
        ok = False
    else:
        out(f"  ok: overlapping dma window trips ({named[0]})")
    # ...and the dynamic cross-check rejects a non-linearization
    schedule = collective_verify.static_schedule()
    good = [("barrier", "ckptd-begin:/r"),
            ("barrier", "ckptd-shards:/r"),
            ("barrier", "ckptd-commit:/r"),
            ("agree", "checkpoint")]
    if collective_verify.verify_trace({0: good, 1: list(good)},
                                      schedule):
        out("FAIL: trace cross-check rejected a valid linearization")
        ok = False
    shuffled = [good[0], good[2], good[1], good[3]]
    if not collective_verify.verify_trace({0: shuffled, 1: shuffled},
                                          schedule):
        out("FAIL: trace cross-check passed an out-of-order commit "
            "protocol")
        ok = False
    elif not collective_verify.verify_trace({0: good, 1: shuffled},
                                            schedule):
        out("FAIL: trace cross-check passed rank-divergent sequences")
        ok = False
    else:
        out("  ok: trace cross-check rejects non-linearizations")
    out("selftest: " + ("PASS" if ok else "FAIL"))
    return ok


def _run_schedule_trace(args) -> Optional[bool]:
    """The dynamic cross-check as a CLI verb: load per-rank telemetry
    streams, project them onto the collective alphabet and prove the
    measured sequences linearize the static schedule."""
    from multigpu_advectiondiffusion_tpu.analysis import collective_verify

    sequences, profiles = {}, {}
    for i, path in enumerate(args.schedule_trace):
        with open(path) as f:
            events = [json.loads(line) for line in f if line.strip()]
        sequences[i] = collective_verify.collective_sequence(events)
        profiles[i] = collective_verify.halo_counter_profile(events)
    schedule = collective_verify.static_schedule(args.root)
    problems = collective_verify.verify_trace(sequences, schedule)
    ranks = sorted(profiles)
    for r in ranks[1:]:
        if profiles[r] != profiles[ranks[0]]:
            problems.append(
                f"ranks {ranks[0]} and {r} traced different halo-"
                f"exchange site profiles: {profiles[ranks[0]]} vs "
                f"{profiles[r]}"
            )
    for line in problems:
        print(line)
    n = sum(len(s) for s in sequences.values())
    print(
        f"schedule-trace: {len(problems)} problem(s); {n} measured "
        f"collective(s) across {len(sequences)} stream(s) vs "
        f"{len(schedule.alphabet)} static tag template(s), "
        f"{len(schedule.chains)} chain(s)"
        + ("" if problems else " — linearization proven")
    )
    return False if problems else None


def run(args) -> Optional[bool]:
    """Entry point for both the ``check`` subcommand and the module
    CLI. Returns ``False`` (CLI failure) on violations."""
    from multigpu_advectiondiffusion_tpu.analysis import all_rules, run_rules
    from multigpu_advectiondiffusion_tpu.analysis import (
        collective_verify,
        halo_verify,
    )

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {' '.join(cls.description.split())}")
        print("halo-verify: stencil/halo consistency verifier — proves "
              "ghost depth G, exchange depth k*G, the slab trapezoid "
              "margins (k-1-j)*G and any declared remote-DMA window "
              "sufficient for every admitted (rung, order, k) "
              "combination")
        print("collective-verify: collective-schedule & SPMD "
              "consistency verifier — extracts every barrier/agree/"
              "ppermute/reduce/shard_map site, proves tag uniqueness, "
              "rank-uniform joins, declared-tag drift, entry-point "
              "reachability and the sharding-case registry "
              "(PartitionSpec axes vs mesh, member-axis rules); "
              "--schedule-trace cross-checks measured streams")
        return None
    if args.selftest:
        return True if selftest() else False
    if args.schedule_trace:
        return _run_schedule_trace(args)

    problems: List[str] = []
    lint = []
    if not args.skip_lint:
        lint = run_rules(args.root, rules=_selected_rules(args.rules))
        problems.extend(str(v) for v in lint)
    halo = None
    if not args.skip_halo:
        halo = halo_verify.verify_all()
        problems.extend(str(v) for v in halo.violations)
    coll = None
    if not args.skip_collective:
        coll = collective_verify.verify_tree(args.root)
        problems.extend(str(v) for v in coll.violations)

    if args.json:
        print(json.dumps({
            "lint": [vars(v) for v in lint],
            "halo": {
                "checked": halo.checked if halo else 0,
                "declined": [
                    {"name": c.name, "reason": c.reason}
                    for c in (halo.combos if halo else [])
                    if not c.admitted
                ],
                "violations": [vars(v) for v in halo.violations]
                if halo else [],
            },
            "collective": {
                "sites": len(coll.sites) if coll else 0,
                "chains": coll.chains if coll else 0,
                "cases_proven": coll.cases_proven if coll else [],
                "violations": [vars(v) for v in coll.violations]
                if coll else [],
            },
            "ok": not problems,
        }, indent=2))
    else:
        for line in problems:
            print(line)
        checked = halo.checked if halo else 0
        sites = len(coll.sites) if coll else 0
        cases = len(coll.cases_proven) if coll else 0
        print(
            f"tpucfd-check: {len(problems)} violation(s); "
            f"halo combos proven: {checked}; collective sites: "
            f"{sites}; sharding cases proven: {cases}"
            + ("" if problems else " — clean")
        )
    return False if problems else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpucfd-check",
        description="project static analysis: AST lint rules + "
                    "stencil/halo consistency verifier",
    )
    configure_parser(ap)
    args = ap.parse_args(argv)
    return 1 if run(args) is False else 0


if __name__ == "__main__":
    sys.exit(main())
