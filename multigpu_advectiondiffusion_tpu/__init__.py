"""TPU-native advection–diffusion framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
CUDA+MPI codebase ``cfd-learner/MultiGPU_AdvectionDiffusion``:

* Heat/diffusion equation ``u_t = K lap(u)`` with a 4th-order central
  Laplacian (13-point in 3-D) and SSP-RK3 time stepping
  (reference: ``MultiGPU/Diffusion3d_Baseline``,
  ``Matlab_Prototipes/DiffusionNd``).
* Inviscid/viscous Burgers equation ``u_t + div(u^2/2) = nu lap(u)`` with
  5th/7th-order WENO flux reconstruction and Lax–Friedrichs splitting
  (reference: ``MultiGPU/Burgers3d_Baseline``, ``SingleGPU/Burgers3d_WENO5*``,
  ``Matlab_Prototipes/InviscidBurgersNd``).

Where the reference scales with 1 MPI rank per GPU, host-staged halo
exchanges and five CUDA streams, this framework scales with a
``jax.sharding.Mesh`` + ``shard_map`` step whose halo exchange is
``jax.lax.ppermute`` over ICI, and relies on XLA's async collectives for
compute/communication overlap.
"""

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.core.bc import Boundary
from multigpu_advectiondiffusion_tpu.models import registry
from multigpu_advectiondiffusion_tpu.models.state import (
    EnsembleState,
    SolverState,
)
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver
from multigpu_advectiondiffusion_tpu import telemetry

__version__ = "0.1.0"

__all__ = [
    "Grid",
    "Boundary",
    "SolverState",
    "EnsembleState",
    "EnsembleSolver",
    "registry",
    "telemetry",
    "__version__",
]

# every registered solver family's config/solver classes export here —
# derived from models/registry.py (a new family registers itself; this
# list is never edited by hand)
for _spec in registry.specs():
    for _cls in (_spec.config_cls, _spec.solver_cls):
        globals()[_cls.__name__] = _cls
        __all__.append(_cls.__name__)
del _spec, _cls
