"""Slice plotting — the matplotlib replacement for the reference's MATLAB
visualization layer (``myplot.m`` slice renders and the k-Wave-derived
``getColorMap.m`` per project, e.g.
``MultiGPU/Burgers3d_Baseline/getColorMap.m:1-25``).

Headless-safe (Agg backend); every function returns the figure and can
write a PNG, mirroring ``Run.m``'s ``print('-dpng', ...)`` step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def kwave_colormap(n: int = 256):
    """Diverging dark-red -> white -> dark-blue map in the style of the
    k-Wave ``getColorMap`` the reference embeds (re-derived from its
    anchor colors, not copied point data)."""
    from matplotlib.colors import LinearSegmentedColormap

    anchors = [
        (0.0, (0.30, 0.00, 0.00)),
        (0.25, (0.85, 0.10, 0.00)),
        (0.45, (1.00, 0.80, 0.30)),
        (0.50, (1.00, 1.00, 1.00)),
        (0.55, (0.30, 0.80, 1.00)),
        (0.75, (0.00, 0.10, 0.85)),
        (1.0, (0.00, 0.00, 0.30)),
    ]
    return LinearSegmentedColormap.from_list("kwave_like", anchors, N=n)


def plot_field(
    u,
    grid=None,
    slices: Optional[Sequence[float]] = None,
    title: str = "",
    path: Optional[str] = None,
    cmap=None,
):
    """Render a 1-D line, 2-D image, or 3-D orthogonal slice panel.

    The 3-D panel shows the mid-planes (z, y, x) like ``myplot.m``'s
    ``slice(...,xcenter,ycenter,zcenter)`` view.
    """
    plt = _mpl()
    u = np.asarray(u)
    cmap = cmap or kwave_colormap()
    extent = None

    if u.ndim == 1:
        fig, ax = plt.subplots(figsize=(6, 4))
        x = np.linspace(*grid.bounds[0], u.shape[0]) if grid else np.arange(len(u))
        ax.plot(x, u, "-o", ms=2)
        ax.set_xlabel("x")
        ax.set_ylabel("u")
    elif u.ndim == 2:
        fig, ax = plt.subplots(figsize=(6, 5))
        if grid is not None:
            (ylo, yhi), (xlo, xhi) = grid.bounds
            extent = (xlo, xhi, ylo, yhi)
        im = ax.imshow(u, origin="lower", extent=extent, cmap=cmap)
        fig.colorbar(im, ax=ax, shrink=0.85)
        ax.set_xlabel("x")
        ax.set_ylabel("y")
    else:
        fig, axes = plt.subplots(1, 3, figsize=(14, 4))
        nz, ny, nx = u.shape
        panes = [
            (u[nz // 2], "z mid-plane", "x", "y"),
            (u[:, ny // 2], "y mid-plane", "x", "z"),
            (u[:, :, nx // 2], "x mid-plane", "y", "z"),
        ]
        vmin, vmax = float(u.min()), float(u.max())
        for ax, (sl, name, xl, yl) in zip(axes, panes):
            im = ax.imshow(sl, origin="lower", cmap=cmap, vmin=vmin, vmax=vmax)
            ax.set_title(name)
            ax.set_xlabel(xl)
            ax.set_ylabel(yl)
        fig.colorbar(im, ax=list(axes), shrink=0.85)

    if title:
        fig.suptitle(title)
    if path:
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
    return fig


def plot_comparison(u, u_exact, grid=None, title="", path=None):
    """Numeric vs exact side-by-side plus the error field
    (``heat3d.m:81-103`` subplot layout)."""
    plt = _mpl()
    u = np.asarray(u)
    ue = np.asarray(u_exact)
    err = np.abs(u - ue)
    if u.ndim == 3:
        u, ue, err = (a[a.shape[0] // 2] for a in (u, ue, err))
    if u.ndim == 1:
        fig, ax = plt.subplots(figsize=(7, 4))
        ax.plot(u, label="numeric")
        ax.plot(ue, "--", label="exact")
        ax.plot(err, ":", label="|error|")
        ax.legend()
    else:
        fig, axes = plt.subplots(1, 3, figsize=(14, 4))
        cmap = kwave_colormap()
        for ax, (field, name) in zip(
            axes, [(u, "numeric"), (ue, "exact"), (err, "|error|")]
        ):
            im = ax.imshow(field, origin="lower", cmap=cmap)
            ax.set_title(name)
            fig.colorbar(im, ax=ax, shrink=0.8)
    if title:
        fig.suptitle(title)
    if path:
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
    return fig


def plot_convergence(rows, order, path, title="OOA study"):
    """Loglog error-vs-h figure for a grid-refinement study — the
    archived-figure half of ``TestingAccuracy.m:51-70``'s
    ``TestAccuracy.fig``. ``rows`` are the convergence CLI's dicts
    (``h``/``l1``/``linf``); a reference slope-``order`` line anchors
    the eye."""
    plt = _mpl()
    h = np.array([r["h"] for r in rows], dtype=float)
    l1 = np.array([r["l1"] for r in rows], dtype=float)
    linf = np.array([r["linf"] for r in rows], dtype=float)
    fig, ax = plt.subplots(figsize=(5, 4))
    ax.loglog(h, l1, "o-", label="L1")
    ax.loglog(h, linf, "s-", label="Linf")
    ref = l1[0] * (h / h[0]) ** order
    ax.loglog(h, ref, "k--", linewidth=0.8, label=f"slope {order}")
    ax.set_xlabel("h")
    ax.set_ylabel("error")
    ax.set_title(title)
    ax.legend()
    ax.grid(True, which="both", alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path
