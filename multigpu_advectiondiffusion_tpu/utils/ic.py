"""Initial-condition library.

Union of the reference's IC sources:

* ``Init_domain`` cases {1: square jump, 2: zeros, 3: Gaussian}
  (``MultiGPU/Diffusion3d_Baseline/Tools.c:124-175``) with
  ``GAUSSIAN_DISTRIBUTION(x,y,z) = exp(-(x²+y²+z²)/0.1)``
  (``DiffusionMPICUDA.h:58``);
* the 2-D spherical discontinuity (``MultiGPU/Diffusion2d_Baseline/Tools.c``);
* the 10-case 1-D menu of ``Matlab_Prototipes/InviscidBurgersNd/CommonIC.m``;
* the analytic heat-kernel Gaussian used by the accuracy tests
  (``heat3d.m:33``: ``exp(-r²/(4 D t0))``).

All ICs are functions of a :class:`Grid` returning an array of the grid's
shape; 1-D profiles broadcast along x when applied to 2-D/3-D grids.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.grid import Grid


def _x_profile(grid: Grid, dtype, fn) -> jnp.ndarray:
    """Apply a 1-D profile of x and broadcast over the remaining axes."""
    x = grid.coords(grid.ndim - 1, dtype)
    u = fn(x)
    return jnp.broadcast_to(u, grid.shape)


def _x_span(grid: Grid):
    lo, hi = grid.bounds[grid.ndim - 1]
    return lo, hi, hi - lo, 0.5 * (lo + hi)


def rectangular_pulse(a: float, b: float, x: jnp.ndarray) -> jnp.ndarray:
    """MATLAB ``rectangularPulse``: 1 inside (a,b), 1/2 at the edges."""
    inside = ((x > a) & (x < b)).astype(x.dtype)
    edge = ((x == a) | (x == b)).astype(x.dtype)
    return inside + 0.5 * edge


# ---------------------------------------------------------------------- #
# N-dimensional ICs
# ---------------------------------------------------------------------- #
def gaussian(grid: Grid, dtype=jnp.float32, amplitude=1.0, width=0.1):
    """``amp * exp(-r²/width)`` — DiffusionMPICUDA.h:58 and LFWENO5FDM3d.m:58."""
    return (amplitude * jnp.exp(-grid.radius_sq(dtype) / width)).astype(dtype)


def heat_kernel(grid: Grid, dtype=jnp.float32, t0=0.1, diffusivity=1.0):
    """Gaussian that solves the heat equation exactly (heat3d.m:33)."""
    return jnp.exp(-grid.radius_sq(dtype) / (4.0 * diffusivity * t0)).astype(dtype)


def heat_kernel_radial(grid: Grid, dtype=jnp.float32, t0=1.0, diffusivity=1.0):
    """Radial Gaussian ``exp(-r^2/(4 D t0))`` over the innermost (r) axis —
    the axisymmetric IC/exact-solution pair (heat2d_axisymmetric.m:11-14,
    uncentered r coordinate)."""
    r = grid.coords(grid.ndim - 1, dtype)
    u = jnp.exp(-(r * r) / (4.0 * diffusivity * t0))
    return jnp.broadcast_to(u, grid.shape).astype(dtype)


def square_jump(grid: Grid, dtype=jnp.float32, inside=1.0, outside=0.0):
    """Index-based central box jump (Init_domain case 1, Tools.c:129-144)."""
    u = jnp.full(grid.shape, outside, dtype=dtype)
    mask = None
    for ax, n in enumerate(grid.shape):
        idx = jnp.arange(n)
        m = (idx >= n // 4) & (idx < 3 * n // 4)
        shp = [1] * grid.ndim
        shp[ax] = n
        m = jnp.reshape(m, shp)
        mask = m if mask is None else (mask & m)
    return jnp.where(mask, jnp.asarray(inside, dtype), u)


def zeros(grid: Grid, dtype=jnp.float32):
    return jnp.zeros(grid.shape, dtype=dtype)


def spherical_jump(grid: Grid, dtype=jnp.float32, radius=0.2, inside=1.0, outside=0.0):
    """Discontinuity at ``r < radius`` (MultiGPU/Diffusion2d_Baseline/Tools.c IC 3)."""
    r2 = grid.radius_sq(dtype)
    return jnp.where(r2 < radius * radius, inside, outside).astype(dtype)


# ---------------------------------------------------------------------- #
# CommonIC.m 1-D menu (broadcast along x for higher dims)
# ---------------------------------------------------------------------- #
def gaussian_advection(grid: Grid, dtype=jnp.float32):
    _, _, _, xmid = _x_span(grid)
    return _x_profile(grid, dtype, lambda x: jnp.exp(-20.0 * (x - xmid) ** 2))


def gaussian_diffusion(grid: Grid, dtype=jnp.float32, mu=0.01):
    _, _, _, xmid = _x_span(grid)
    return _x_profile(grid, dtype, lambda x: jnp.exp(-((x - xmid) ** 2) / (4 * mu)))


def sine(grid: Grid, dtype=jnp.float32):
    return _x_profile(grid, dtype, lambda x: jnp.sin(jnp.pi * x))


def lifted_sine(grid: Grid, dtype=jnp.float32):
    return _x_profile(grid, dtype, lambda x: 0.5 - jnp.sin(jnp.pi * x))


def tanh_viscous(grid: Grid, dtype=jnp.float32, mu=0.02):
    return _x_profile(grid, dtype, lambda x: 0.5 * (1.0 - jnp.tanh(x / (4 * mu))))


def riemann(grid: Grid, dtype=jnp.float32, left=2.0, right=1.0):
    _, _, _, xmid = _x_span(grid)
    return _x_profile(
        grid, dtype, lambda x: jnp.where(x <= xmid, left, right).astype(dtype)
    )


def tanh_profile(grid: Grid, dtype=jnp.float32):
    a, b, _, _ = _x_span(grid)

    def fn(x):
        xi = 8.0 / (b - a) * (x - a) - 4.0
        return 0.5 * (jnp.tanh(-4.0 * xi) + 1.0)

    return _x_profile(grid, dtype, fn)


def square_jump_1d(grid: Grid, dtype=jnp.float32):
    _, _, Lx, xmid = _x_span(grid)
    return _x_profile(
        grid,
        dtype,
        lambda x: rectangular_pulse(xmid - 0.1 * Lx, xmid + 0.1 * Lx, x) + 1.0,
    )


def displaced_square_jump(grid: Grid, dtype=jnp.float32):
    _, _, Lx, _ = _x_span(grid)
    xmid = -0.25  # CommonIC.m:63 overrides the midpoint
    return _x_profile(
        grid,
        dtype,
        lambda x: rectangular_pulse(xmid - 0.125 * Lx, xmid + 0.125 * Lx, x) + 1.0,
    )


def trapezoidal(grid: Grid, dtype=jnp.float32):
    """Oleg's trapezoidal (CommonIC.m:67)."""
    _, _, Lx, xmid = _x_span(grid)
    return _x_profile(
        grid,
        dtype,
        lambda x: jnp.exp(-x)
        * rectangular_pulse(xmid - 0.1 * Lx, xmid + 0.1 * Lx, x)
        * jnp.exp(0.1),
    )


REGISTRY: Dict[str, Callable] = {
    "gaussian": gaussian,
    "heat_kernel": heat_kernel,
    "heat_kernel_radial": heat_kernel_radial,
    "square_jump": square_jump,
    "zeros": zeros,
    "spherical_jump": spherical_jump,
    "gaussian_advection": gaussian_advection,
    "gaussian_diffusion": gaussian_diffusion,
    "sine": sine,
    "lifted_sine": lifted_sine,
    "tanh_viscous": tanh_viscous,
    "riemann": riemann,
    "tanh": tanh_profile,
    "square_jump_1d": square_jump_1d,
    "displaced_square_jump": displaced_square_jump,
    "trapezoidal": trapezoidal,
}

# CommonIC.m case-number aliases (1..10)
COMMON_IC_CASES = {
    1: "gaussian_advection",
    2: "gaussian_diffusion",
    3: "sine",
    4: "lifted_sine",
    5: "tanh_viscous",
    6: "riemann",
    7: "tanh",
    8: "square_jump_1d",
    9: "displaced_square_jump",
    10: "trapezoidal",
}


def initial_condition(name, grid: Grid, dtype=jnp.float32, **params) -> jnp.ndarray:
    """Look up an IC by name (or CommonIC case number) and evaluate it."""
    if isinstance(name, int):
        name = COMMON_IC_CASES[name]
    if callable(name):
        return jnp.asarray(name(grid, dtype, **params), dtype=dtype)
    if name not in REGISTRY:
        raise ValueError(f"unknown IC {name!r}; available: {sorted(REGISTRY)}")
    return REGISTRY[name](grid, dtype=dtype, **params)
