"""Profiling and tracing helpers.

TPU replacement for the reference's profiling layer (SURVEY §5): per-rank
``nvprof`` wrapping (``Diffusion3d_Baseline/profile.sh:2``) becomes
``jax.profiler`` traces viewable in TensorBoard/Perfetto, and the
MPI_Wtime double-barrier walltime sandwich (``main.c:139-147,184-187``)
becomes :class:`Stopwatch` segments around ``block_until_ready``.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Dict, Optional

import jax


def _fetch_sync(x):
    if hasattr(x, "dtype"):
        from multigpu_advectiondiffusion_tpu.bench.timing import sync

        sync(x)


# jax.profiler supports exactly one trace per process; this flag makes
# trace() idempotent (a nested/duplicate request no-ops instead of
# raising) and lets the recovery path below distinguish "we hold the
# trace" from "someone else leaked one".
_trace_active = [False]


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace: ``with trace('/tmp/trace'): run(...)``.

    View with TensorBoard (profile plugin) or Perfetto.

    Exception-safe and idempotent: the trace is stopped on EVERY exit
    path (an exception raised mid-solve can never leak an open
    ``jax.profiler`` trace that poisons the process's next
    ``start_trace``); a nested ``trace()`` inside an active one is a
    no-op (one capture, the outer owner closes it); and if a *previous*
    context leaked an open trace anyway (e.g. a hard-killed thread),
    the stale trace is stopped and the capture retried once instead of
    failing every later profiling request in the process.
    """
    if _trace_active[0]:
        yield  # nested request: the outer trace already captures this
        return
    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        # a leaked open trace from a poisoned predecessor: close it and
        # retry once — a second failure is a real error and propagates
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        jax.profiler.start_trace(log_dir)
    _trace_active[0] = True
    try:
        yield
    finally:
        _trace_active[0] = False
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass  # teardown must never mask the body's exception


class Stopwatch:
    """Named walltime segments (HtD/compute/DtH in the reference's
    summary become e.g. init/compile/solve/io here)."""

    def __init__(self):
        self.segments: Dict[str, float] = {}

    @contextlib.contextmanager
    def segment(self, name: str, sync: Optional[object] = None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                # Host-fetch sync, not block_until_ready — see bench/timing.py
                # for why the latter is untrustworthy on tunneled platforms.
                jax.tree.map(_fetch_sync, sync)
            self.segments[name] = (
                self.segments.get(name, 0.0) + time.perf_counter() - t0
            )

    def report(self) -> str:
        total = sum(self.segments.values())
        lines = [f"{'segment':<16} {'seconds':>10} {'share':>7}"]
        for name, s in self.segments.items():
            share = 100.0 * s / total if total else 0.0
            lines.append(f"{name:<16} {s:>10.4f} {share:>6.1f}%")
        lines.append(f"{'total':<16} {total:>10.4f}")
        return "\n".join(lines)


class annotate:
    """Named ``jax.profiler.TraceAnnotation`` span, usable two ways:

    * decorator — ``@annotate("solve")`` wraps the function in the span
      (``functools.wraps`` preserved, so profiler timelines and
      tracebacks keep the wrapped function's name/docstring);
    * context manager — ``with annotate("halo-exchange"): ...`` labels
      an ad-hoc host-side region (e.g. one supervised chunk) in the
      captured trace.
    """

    def __init__(self, name: str):
        self.name = name
        self._span = None

    def __call__(self, fn):
        @functools.wraps(fn)
        def inner(*a, **k):
            with jax.profiler.TraceAnnotation(self.name):
                return fn(*a, **k)

        return inner

    def __enter__(self):
        self._span = jax.profiler.TraceAnnotation(self.name)
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        span, self._span = self._span, None
        return span.__exit__(*exc)
