"""Environment-variable platform selection for entry points.

Site customizations may pin ``jax_platforms`` via ``jax.config`` at
interpreter startup, which silently outranks the ``JAX_PLATFORMS`` env
var; every CLI/benchmark entry point calls :func:`honor_platform_env`
first so users who export ``JAX_PLATFORMS=cpu`` (e.g. to run the
examples on a virtual device mesh) get what they asked for.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
