"""Run summaries: the reference's ``PrintSummary`` block plus JSON.

The reference prints a human-readable perf block per run
(``MultiGPU/Diffusion3d_Baseline/Tools.c:255-269``: grid, iterations,
wall seconds, GFLOPS) and the author then hand-copies the numbers into
``Run.m`` header comments. Here the same block is printed AND written as
machine-readable JSON (the benchmark-registry upgrade of SURVEY §5).
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
from typing import Optional

import jax

from multigpu_advectiondiffusion_tpu.utils.metrics import (
    gflops_reference_convention,
    mlups,
)

# Version of the summary JSON layout. Bumped whenever fields change
# meaning or move, so downstream BENCH tooling can branch instead of
# guessing. History: 1 = implicit pre-schema layout (PRs 0-2);
# 2 = adds schema/cost_model/roofline_pct/mass_drift; 3 = adds the
# measured-introspection blocks (memory watermarks, per-executable XLA
# cost capture: memory/xla fields); 4 = surfaces the in-situ physics
# diagnostics block (observable trajectory, violations, baseline) at
# the top level — the science gate's input.
SUMMARY_SCHEMA = 4


@dataclasses.dataclass
class RunSummary:
    name: str
    grid_xyz: tuple
    iters: int
    stages: int
    seconds: float
    dt: float
    t_final: float
    devices: int = 1
    dtype: str = "float32"
    error_l1: Optional[float] = None
    error_l2: Optional[float] = None
    error_linf: Optional[float] = None
    compile_seconds: Optional[float] = None
    # host I/O (snapshots/checkpoints) excluded from `seconds`; periodic-
    # output runs would otherwise fold disk time into the solve rate
    io_seconds: Optional[float] = None
    # which kernel strategy actually executed (SolverBase.engaged_path):
    # impl requested, stepper engaged, overlap schedule, fallback reason —
    # the what-ran contract of the reference's PrintSummary
    # (MultiGPU/Diffusion3d_Baseline/Tools.c:255-269)
    engaged: Optional[dict] = None
    # supervised-run facts (resilience.SupervisorReport.to_dict): sentinel
    # cadence/probes, rollback-retry events, preemption — absent on
    # unsupervised runs
    resilience: Optional[dict] = None
    # static per-rung cost model (telemetry.costmodel.summarize_run):
    # HBM bytes / FLOPs per step for the ENGAGED stepper plus the
    # roofline-efficiency percentage of the measured rate
    cost_model: Optional[dict] = None
    # measured device-memory watermarks (telemetry.xprof): run-level
    # peak bytes in use, backend limit and headroom, sample source
    # (device_stats | live_arrays) — absent when nothing sampled
    memory: Optional[dict] = None
    # measured XLA introspection (telemetry.xprof.measured_summary):
    # the primary executable's XLA-reported bytes/FLOPs per step next
    # to the cost model's prediction (ratio + tolerance-band flag),
    # achieved rates vs the configured peaks, compile seconds — absent
    # when no executable was captured (TPUCFD_XPROF=0)
    xla: Optional[dict] = None

    @property
    def num_cells(self) -> int:
        n = 1
        for s in self.grid_xyz:
            n *= s
        return n

    @property
    def mlups(self) -> float:
        return mlups(self.num_cells, self.iters, self.stages, self.seconds)

    @property
    def gflops(self) -> float:
        return gflops_reference_convention(
            self.num_cells, self.iters, self.seconds, self.stages
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["schema"] = SUMMARY_SCHEMA
        d["mlups"] = round(self.mlups, 3)
        d["gflops_reference_convention"] = round(self.gflops, 4)
        d["backend"] = jax.default_backend()
        d["platform"] = platform.machine()
        # headline derived fields surfaced top-level (BENCH tooling reads
        # these without digging into the nested blocks)
        if self.cost_model is not None:
            d["roofline_pct"] = self.cost_model.get("roofline_pct")
        if self.resilience is not None:
            d["mass_drift"] = self.resilience.get("mass_drift")
            # the in-situ diagnostics block (SupervisorReport) surfaces
            # top-level: the science gate's extractor reads it without
            # knowing the resilience layout
            if self.resilience.get("diagnostics") is not None:
                d["diagnostics"] = self.resilience["diagnostics"]
        return d

    def print_block(self) -> None:
        """Human block in the spirit of PrintSummary (Tools.c:255-269)."""
        g = "x".join(str(s) for s in self.grid_xyz)
        print("=" * 60)
        print(f" {self.name}")
        print("=" * 60)
        print(f" grid               : {g} ({self.num_cells:,} cells)")
        print(f" devices            : {self.devices} [{jax.default_backend()}]")
        print(f" dtype              : {self.dtype}")
        if self.engaged is not None:
            e = self.engaged
            line = f"{e['stepper']} (impl={e['impl']}"
            if e.get("overlap"):
                line += f", overlap={e['overlap']}"
            if e.get("steps_per_exchange", 1) != 1:
                line += f", steps/exchange={e['steps_per_exchange']}"
            if e.get("exchange", "collective") != "collective":
                line += f", exchange={e['exchange']}"
            if e.get("precision", "native") != "native":
                line += (
                    f", precision={e['precision']}"
                    f" [storage {e.get('storage_dtype')}]"
                )
            line += ")"
            print(f" kernel path        : {line}")
            if e.get("tuned"):
                t = e["tuned"]
                print(
                    f" tuned dispatch     : {t.get('source')}"
                    + (
                        f" ({t.get('mlups')} MLUPS measured)"
                        if t.get("mlups")
                        else ""
                    )
                )
            if e.get("fallback"):
                print(f" fused fallback     : {e['fallback']}")
            for ev in e.get("degraded") or ():
                print(
                    f" ladder degraded    : {ev['from']} -> {ev['to']} "
                    f"({ev['reason']})"
                )
        print(f" iterations         : {self.iters} x {self.stages} RK stages")
        print(f" dt (last)          : {self.dt:.6e}")
        print(f" simulated time     : {self.t_final:.6f}")
        if self.compile_seconds is not None:
            print(f" compile time       : {self.compile_seconds:.3f} s")
        print(f" wall time          : {self.seconds:.4f} s")
        if self.io_seconds is not None:
            print(f" I/O time (excl.)   : {self.io_seconds:.4f} s")
        if self.resilience is not None:
            r = self.resilience
            line = (
                f"probes={r.get('probes', 0)} "
                f"(every {r.get('sentinel_every', 0)} steps), "
                f"retries={r.get('retries', 0)}"
            )
            if r.get("preempted"):
                line += ", PREEMPTED"
            print(f" resilience         : {line}")
            if r.get("mass_drift") is not None:
                print(
                    f" mass drift         : {r['mass_drift']:+.3e} "
                    "(rel., vs initial state)"
                )
            for ev in r.get("events") or ():
                print(
                    f"   rollback         : step {ev['step']} "
                    f"({ev['reason']}) -> it={ev['rollback_to_it']}, "
                    f"{ev['action']}"
                )
            diag = r.get("diagnostics")
            if diag is not None:
                traj = diag.get("trajectory") or []
                viols = diag.get("violations") or []
                line = (
                    f"{len(traj)} point(s), "
                    f"{len(diag.get('observables') or [])} observable(s)"
                    f", rules={','.join(diag.get('rules') or []) or '-'}"
                )
                if viols:
                    line += f", {len(viols)} VIOLATION(S)"
                print(f" physics diag       : {line}")
                for v in viols[:5]:
                    print(
                        f"   violation        : step {v['step']} "
                        f"[{v['rule']}] {v['message']}"
                    )
        print(f" MLUPS              : {self.mlups:.1f}")
        print(f" GFLOPS (ref conv.) : {self.gflops:.3f}")
        if self.cost_model is not None and self.cost_model.get(
            "roofline_pct"
        ) is not None:
            c = self.cost_model
            print(
                f" roofline           : {c['roofline_pct']:.1f}% of the "
                f"{c['bound']} roof "
                f"({c.get('achieved_gbs', 0)} GB/s, "
                f"{c.get('achieved_gflops', 0)} GFLOP/s modeled)"
            )
        if self.xla is not None:
            x = self.xla
            line = (
                f"{x.get('xla_bytes_per_step', 0):,.0f} B/step, "
                f"{x.get('xla_flops_per_step', 0):,.0f} FLOP/step "
                f"(compile {x.get('compile_seconds', 0):.3f} s)"
            )
            print(f" xla measured       : {line}")
            ratio = x.get("model_bytes_ratio")
            if ratio is not None:
                flag = (
                    "ok" if x.get("bytes_within_tolerance")
                    else "DISCREPANT"
                )
                print(
                    f" model/measured B   : {ratio:.2f}x ({flag}, "
                    f"band {x.get('tolerance_factor')}x)"
                )
        if self.memory is not None:
            m = self.memory
            line = (
                f"peak {m.get('peak_bytes_in_use', 0):,} B in use "
                f"[{m.get('source')}]"
            )
            if m.get("headroom_bytes") is not None:
                line += f", headroom {m['headroom_bytes']:,} B"
            print(f" device memory      : {line}")
        if self.error_l1 is not None:
            print(
                f" error L1/L2/Linf   : {self.error_l1:.4e} / "
                f"{self.error_l2:.4e} / {self.error_linf:.4e}"
            )
        print("=" * 60)

    def write_json(self, path: str) -> None:
        """Atomic write (tmp + ``os.replace``, the checkpoint writers'
        pattern): a reader — or a preempted run — never sees a
        half-written summary."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
