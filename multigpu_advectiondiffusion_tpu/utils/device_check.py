"""Startup device/topology validation and debug dumps.

TPU equivalents of the reference's ``Util.cu`` host utilities:

* ``device_scan``   — ``DeviceScan`` (``Util.cu:32-38``): enumerate
  accelerators with platform/kind/memory stats.
* ``topology_check`` — ``MPIDeviceCheck``+``AssignDevices``
  (``Util.cu:43-74``): assert the requested mesh fits the attached
  devices before any allocation (the reference exits when ranks exceed
  GPUs; here the mesh factory raises, this adds the human-readable scan).
* ``memory_report`` — ``PrintGPUmemory``/``ECCCheck`` stand-in
  (``Kernels.cu:358-384``, ``Util.cu:79-93``): per-device memory stats.
  ECC itself has no TPU user-visible control; HBM ECC is always on.
"""

from __future__ import annotations

from typing import Optional

import jax


def device_scan(verbose: bool = True):
    """List attached accelerator devices (DeviceScan analog)."""
    devs = jax.devices()
    rows = []
    for d in devs:
        row = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "?"),
            "process": getattr(d, "process_index", 0),
        }
        rows.append(row)
    if verbose:
        print(f"-- device scan: {len(devs)} device(s), "
              f"backend={jax.default_backend()}")
        for r in rows:
            print(f"   [{r['id']}] {r['platform']}:{r['kind']} "
                  f"(process {r['process']})")
    return rows


def memory_report(verbose: bool = True):
    """Per-device memory stats where the backend exposes them."""
    rows = []
    for d in jax.devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # backend without memory_stats (e.g. CPU)
            pass
        row = {
            "id": d.id,
            "bytes_in_use": stats.get("bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
        rows.append(row)
        if verbose and row["bytes_limit"]:
            used = row["bytes_in_use"] / 1e9
            lim = row["bytes_limit"] / 1e9
            print(f"   [{d.id}] {used:.2f} / {lim:.2f} GB in use")
    return rows


def topology_check(mesh_sizes: dict, devices: Optional[list] = None) -> None:
    """Fail fast when the requested mesh exceeds the attached devices
    (MPIDeviceCheck analog: 'Currently only can handle at most as many
    ranks as GPUs', Util.cu:50-57)."""
    import math

    devs = devices if devices is not None else jax.devices()
    need = math.prod(mesh_sizes.values())
    if need > len(devs):
        raise RuntimeError(
            f"mesh {mesh_sizes} needs {need} devices but only "
            f"{len(devs)} attached ({jax.default_backend()}); "
            f"reduce the mesh or attach more devices"
        )
