from multigpu_advectiondiffusion_tpu.utils import ic, io, metrics

__all__ = ["ic", "io", "metrics"]
