"""Error norms and performance metrics.

* Error norms mirror the MATLAB post-processing
  (``heat3d.m:106-109``): ``L1 = prod(dx) * sum|e|``,
  ``L2 = sqrt(prod(dx) * sum e^2)``, ``Linf = max|e|``.
* ``CalcGflops`` is the reference's derived cell-update-rate metric
  (``MultiGPU/Diffusion3d_Baseline/Tools.c:247-250``:
  ``3 * iters * nx*ny*nz * FLOPS * 1e-9 / t`` with ``FLOPS = 8``).
  MLUPS (= million lattice updates / s) is the hardware-neutral version
  used for TPU-vs-GPU comparison (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax.numpy as jnp

REFERENCE_FLOPS_PER_CELL = 8.0  # DiffusionMPICUDA.h:52


@dataclasses.dataclass(frozen=True)
class ErrorNorms:
    l1: float
    l2: float
    linf: float

    def __iter__(self):
        return iter((self.l1, self.l2, self.linf))


def error_norms(u, u_exact, spacing: Sequence[float]) -> ErrorNorms:
    vol = math.prod(spacing)
    err = jnp.abs(jnp.asarray(u, jnp.float64 if u.dtype == jnp.float64 else jnp.float32)
                  - u_exact)
    l1 = vol * jnp.sum(err)
    l2 = jnp.sqrt(vol * jnp.sum(err * err))
    linf = jnp.max(err)
    return ErrorNorms(float(l1), float(l2), float(linf))


def mlups(num_cells: int, iters: int, stages: int, seconds: float) -> float:
    """Million lattice (cell) updates per second, counting RK stages."""
    return num_cells * iters * stages / seconds / 1e6


def gflops_reference_convention(
    num_cells: int, iters: int, seconds: float, stages: int = 3
) -> float:
    """The reference's ``CalcGflops`` (Tools.c:247-250)."""
    return stages * iters * num_cells * REFERENCE_FLOPS_PER_CELL * 1e-9 / seconds


def observed_order(coarse_norm: float, fine_norm: float, ratio: float = 2.0) -> float:
    """Order of accuracy between two refinement levels
    (``TestingAccuracy.m:43-47``)."""
    return math.log(coarse_norm / fine_norm) / math.log(ratio)
