"""Field I/O: reference-compatible binaries, npz checkpoints, summaries.

* ``save_binary`` writes the float32 raw layout of ``SaveBinary3D``
  (``MultiGPU/Diffusion3d_Baseline/Tools.c:91-119``): x fastest, then y,
  then z — exactly ``u.ravel()`` for this framework's ``(z, y, x)``
  arrays — loadable by the reference's ``Run.m`` harness via
  ``fread(fID,[1,nx*ny*nz],'float')``.
* ``save_ascii`` mirrors ``Save3D`` (``Tools.c:68-86``), one ``%g`` per line.
* npz checkpoints add what the reference lacks (SURVEY §5): restartable
  state (u, t, it) with grid metadata.

A native C implementation of the binary writer (``native/io_native.cpp``)
is used automatically when built; the numpy path is the fallback.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import numpy as np

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.state import SolverState

_native = None


def atomic_write_text(path: str, text: str) -> None:
    """Publish a small text/JSON artifact atomically (tempfile in the
    destination directory + ``os.replace`` — the checkpoint writers'
    discipline, shared so one-off report writers don't hand-roll a
    torn-write window). The ``raw-artifact-write`` lint rule
    (``analysis/rules.py``) points violators here."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix="." + os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _io_event(name: str, path: str, nbytes: int, seconds: float, **fields):
    """Telemetry record of one completed write (no-op when no sink is
    installed) — checkpoint and snapshot I/O becomes attributable in the
    event stream instead of folding into one wall-clock number."""
    from multigpu_advectiondiffusion_tpu import telemetry

    sink = telemetry.get_sink()
    if sink.active:
        sink.event(
            "io", name, path=path, bytes=int(nbytes),
            seconds=round(seconds, 6), **fields,
        )


def _load_native():
    global _native
    if _native is not None:
        return _native
    import ctypes

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "..", "native", "libtpucfd_io.so"),
        os.path.join(here, "native", "libtpucfd_io.so"),
    ):
        if os.path.exists(cand):
            lib = ctypes.CDLL(cand)
            lib.save_binary_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.save_binary_f32.restype = ctypes.c_int
            lib.writer_create.argtypes = [ctypes.c_size_t]
            lib.writer_create.restype = ctypes.c_void_p
            lib.writer_submit.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.writer_submit.restype = ctypes.c_int
            lib.writer_flush.argtypes = [ctypes.c_void_p]
            lib.writer_flush.restype = ctypes.c_int
            lib.writer_destroy.argtypes = [ctypes.c_void_p]
            try:  # added with the checkpoint runtime; absent in old builds
                lib.checkpoint_save.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_void_p,
                    ctypes.c_uint32,
                    ctypes.c_uint32,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.c_double,
                    ctypes.c_int64,
                ]
                lib.checkpoint_save.restype = ctypes.c_int
                lib.checkpoint_load_header.argtypes = [
                    ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_double),
                    ctypes.POINTER(ctypes.c_int64),
                ]
                lib.checkpoint_load_header.restype = ctypes.c_int
                lib.checkpoint_load_payload.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                ]
                lib.checkpoint_load_payload.restype = ctypes.c_int
            except AttributeError:
                pass
            _native = lib
            return lib
    _native = False
    return False


class AsyncBinaryWriter:
    """Double-buffered background snapshot writer (native thread when
    ``native/libtpucfd_io.so`` is built, synchronous fallback otherwise).

    The solver keeps stepping while the previous snapshot drains to disk —
    the role the reference's pinned host buffers + DtH copy staging played
    for output (``main.c:89-114,312-343``).
    """

    def __init__(self, queue_slots: int = 2):
        self._lib = _load_native() or None
        self._handle = (
            self._lib.writer_create(queue_slots) if self._lib else None
        )

    def submit(self, u, path: str) -> None:
        arr = np.ascontiguousarray(np.asarray(u, dtype=np.float32)).ravel()
        if self._handle:
            import ctypes

            rc = self._lib.writer_submit(
                self._handle,
                path.encode(),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                arr.size,
            )
            if rc != 0:
                raise IOError(f"async writer failed for {path}")
        else:
            arr.tofile(path)

    def flush(self) -> None:
        if self._handle and self._lib.writer_flush(self._handle) != 0:
            raise IOError("async writer flush reported an error")

    def close(self) -> None:
        if self._handle:
            self._lib.writer_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()
        self.close()


class SnapshotStreamer:
    """Downsampled field-snapshot stream: atomic, rotation-capped,
    background-written.

    Wraps :class:`AsyncBinaryWriter` so the solver keeps stepping while
    a snapshot drains, and adds the three properties raw ``submit``
    lacks:

    * **atomic** — bytes land in a ``.tmp`` sibling and are renamed to
      ``snap_NNNNNN.bin`` only after the async writer flushed them, so
      a reader (or a crash) never sees a torn snapshot;
    * **downsampled** — ``stride`` > 1 strides every axis
      (``u[::s, ::s, ...]``) before writing: visual-inspection
      snapshots of a large run cost ``1/s^d`` of the field's bytes;
    * **rotation-capped** — ``max_bytes`` > 0 bounds the TOTAL bytes of
      published snapshots (the ``--metrics-max-bytes`` discipline for
      fields): oldest snapshots are deleted first, the newest always
      survives even when it alone exceeds the cap.

    Every published snapshot emits an ``io:snapshot_write`` event
    (path, bytes, drain seconds, iteration, stride). The pending
    snapshot is published at the NEXT :meth:`write` or at
    :meth:`close` — one write stays in flight, preserving the double
    buffer's compute/IO overlap.
    """

    def __init__(self, directory: str, stride: int = 1,
                 max_bytes: int = 0, prefix: str = "snap_"):
        if stride < 1:
            raise ValueError(f"snapshot stride must be >= 1, got {stride}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.stride = int(stride)
        self.max_bytes = int(max_bytes)
        self.prefix = prefix
        self._writer = AsyncBinaryWriter()
        self._pending = []  # (tmp, final, nbytes, iteration)
        self._published = []  # (final, nbytes), oldest first

    def write(self, u, iteration: int) -> str:
        """Queue one snapshot; returns the final path it will publish
        under. Publishes (flush + rename + rotate) whatever was pending
        first, so at most one write is in flight."""
        self.publish_pending()
        arr = np.ascontiguousarray(
            np.asarray(u, dtype=np.float32)[
                (slice(None, None, self.stride),) * np.ndim(u)
            ]
        )
        final = os.path.join(
            self.directory, f"{self.prefix}{int(iteration):06d}.bin"
        )
        tmp = f"{final}.tmp.{os.getpid()}"
        self._writer.submit(arr, tmp)
        self._pending.append((tmp, final, arr.nbytes, int(iteration)))
        return final

    def publish_pending(self) -> None:
        """Drain the async writer and atomically publish every pending
        snapshot (rename + ``io:snapshot_write`` event), then rotate."""
        if not self._pending:
            return
        import time as _time

        t0 = _time.perf_counter()
        self._writer.flush()
        drain_s = _time.perf_counter() - t0
        for tmp, final, nbytes, iteration in self._pending:
            os.replace(tmp, final)
            # seconds = the synchronous drain cost (≈0 when the
            # background writer already finished during compute)
            _io_event(
                "snapshot_write", final, nbytes,
                drain_s / len(self._pending),
                iteration=iteration, stride=self.stride,
            )
            self._published.append((final, nbytes))
        self._pending.clear()
        self._rotate()

    def _rotate(self) -> None:
        if self.max_bytes <= 0:
            return
        total = sum(n for _, n in self._published)
        while total > self.max_bytes and len(self._published) > 1:
            stale, nbytes = self._published.pop(0)
            try:
                os.remove(stale)
            except FileNotFoundError:
                pass
            total -= nbytes

    def close(self) -> None:
        self.publish_pending()
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_binary(u, path: str) -> None:
    """Write float32 raw binary, reference ``SaveBinary3D`` layout."""
    import time as _time

    t0 = _time.perf_counter()
    arr = np.asarray(u, dtype=np.float32).ravel()
    lib = _load_native()
    if lib:
        import ctypes

        buf = np.ascontiguousarray(arr)
        rc = lib.save_binary_f32(
            path.encode(),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            buf.size,
        )
        if rc == 0:
            _io_event("binary_write", path, arr.nbytes,
                      _time.perf_counter() - t0)
            return
    arr.tofile(path)
    _io_event("binary_write", path, arr.nbytes, _time.perf_counter() - t0)


def print_field(u, file=None) -> None:
    """Console dump of a field, one ``%8.2f``-style row per line — the
    debugging role of ``Print2D/Print3D`` (``Tools.c:32-63``); 3-D arrays
    print as z-slices separated by blank lines."""
    import sys

    out = file or sys.stdout
    arr = np.atleast_2d(np.asarray(u))
    planes = arr.reshape((-1,) + arr.shape[-2:])
    for k, plane in enumerate(planes):
        if k:
            out.write("\n")
        for row in plane:
            out.write(" ".join(f"{v:8.2f}" for v in row) + "\n")


def load_binary(path: str, shape) -> np.ndarray:
    return np.fromfile(path, dtype=np.float32).reshape(shape)


def save_ascii(u, path: str) -> None:
    """One value per line, ``%g`` format (``Save3D``, Tools.c:68-86).

    Both paths (native writer and Python fallback) write a tmp file and
    publish with ``os.replace`` — the atomic-write discipline the lint
    gate enforces (a preempted run must not leave a torn artifact where
    the reference harness expects a complete one)."""
    arr = np.ascontiguousarray(np.asarray(u, dtype=np.float64)).ravel()
    tmp = f"{path}.tmp.{os.getpid()}"
    lib = _load_native()
    if lib:
        import ctypes

        lib.save_ascii_f64.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
        ]
        lib.save_ascii_f64.restype = ctypes.c_int
        if lib.save_ascii_f64(
            tmp.encode(),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            arr.size,
        ) == 0:
            os.replace(tmp, path)
            return
    with open(tmp, "w") as f:
        for v in arr:
            f.write(f"{v:g}\n")
    os.replace(tmp, path)


# --------------------------------------------------------------------- #
# Checkpoint format (.ckpt): 64-byte header + raw payload + CRC32.
#
# Layout (little-endian), mirrored bit-for-bit by
# ``native/checkpoint_native.cpp`` — the bytes are identical whether the
# native library is built or not:
#   0: magic "TPCFDCKP"        8s
#   8: version                 u32 (=1)
#  12: dtype code              u32 (0=f32, 1=f64)
#  16: ndim                    u32 (<=4)
#  20: shape[4]                4*u32 (unused dims = 1)
#  36: padding                 4 bytes (keeps t 8-aligned)
#  40: t                       f64
#  48: iteration               i64
#  56: crc32(payload)          u32 (zlib polynomial)
#  60: reserved                4 bytes
#  64: payload
#
# Saves are atomic (tmp + rename) and loads CRC-verify the payload — the
# resume-safety the reference cannot offer (it has no restart at all).
# --------------------------------------------------------------------- #
_CKPT_MAGIC = b"TPCFDCKP"
_CKPT_STRUCT = "<8sIII4I4xdqI4x"  # one layout constant: writer and reader cannot drift
_CKPT_VERSION = 1
_CKPT_DTYPES = {0: np.float32, 1: np.float64}
_CKPT_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}


def _ckpt_header(arr: np.ndarray, t: float, it: int, crc: int) -> bytes:
    import struct

    shape4 = list(arr.shape) + [1] * (4 - arr.ndim)
    return struct.pack(
        _CKPT_STRUCT,
        _CKPT_MAGIC,
        _CKPT_VERSION,
        _CKPT_CODES[arr.dtype],
        arr.ndim,
        *shape4,
        float(t),
        int(it),
        crc,
    )


def _restored_state(u, t, it) -> SolverState:
    """Rebuild a loaded state under the ``SolverState.create`` dtype
    contract: ``t`` tracks ``u``'s precision (f64 only for f64 fields)
    and ``it`` is int32. The header stores ``t`` as a double, and under
    ``jax_enable_x64`` a bare ``jnp.asarray(float)`` would resurrect it
    as f64 — which changes the final clamped ``dt = t_end - t`` rounding
    on resume, so a checkpointed run would no longer be bit-identical
    to an uninterrupted one."""
    import jax.numpy as jnp

    u = jnp.asarray(u)
    rdt = jnp.float64 if u.dtype == jnp.float64 else jnp.float32
    return SolverState(
        u=u,
        t=jnp.asarray(t, dtype=rdt),
        it=jnp.asarray(it, dtype=jnp.int32),
    )


def _save_ckpt(path: str, state: SolverState) -> None:
    import ctypes
    import zlib

    arr = np.ascontiguousarray(np.asarray(state.u))
    if arr.dtype not in _CKPT_CODES or not 1 <= arr.ndim <= 4:
        raise ValueError(f"checkpoint supports 1-4D f32/f64, got {arr.dtype}")
    t, it = float(state.t), int(state.it)

    lib = _load_native()
    if lib and hasattr(lib, "checkpoint_save"):
        shape = (ctypes.c_uint32 * 4)(*(list(arr.shape) + [1] * 4)[:4])
        rc = lib.checkpoint_save(
            path.encode(),
            arr.ctypes.data_as(ctypes.c_void_p),
            _CKPT_CODES[arr.dtype],
            arr.ndim,
            shape,
            t,
            it,
        )
        if rc == 0:
            return
    payload = arr.tobytes()
    header = _ckpt_header(arr, t, it, zlib.crc32(payload))
    # writer-unique tmp name: concurrent writers of the same target (a
    # replicated shard in a multi-process sharded save) must not truncate
    # each other's in-flight tmp; last atomic rename wins with a complete
    # file either way
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _load_ckpt(path: str) -> SolverState:
    import struct
    import zlib

    import jax.numpy as jnp

    lib = _load_native()
    if lib and hasattr(lib, "checkpoint_load_header"):
        return _load_ckpt_native(lib, path)

    with open(path, "rb") as f:
        header = f.read(64)
        if len(header) != 64:
            raise IOError(f"truncated checkpoint header: {path}")
        (magic, version, code, ndim, s0, s1, s2, s3, t, it, crc) = (
            struct.unpack(_CKPT_STRUCT, header)
        )
        if magic != _CKPT_MAGIC or version != _CKPT_VERSION:
            raise IOError(f"not a framework checkpoint: {path}")
        if code not in _CKPT_DTYPES or not 1 <= ndim <= 4:
            raise IOError(f"corrupt checkpoint header: {path}")
        shape = (s0, s1, s2, s3)[:ndim]
        dtype = np.dtype(_CKPT_DTYPES[code])
        nbytes = int(np.prod(shape)) * dtype.itemsize
        payload = f.read(nbytes)
    if len(payload) != nbytes:
        raise IOError(f"truncated checkpoint payload: {path}")
    if zlib.crc32(payload) != crc:
        raise IOError(f"checkpoint CRC mismatch (corrupt file): {path}")
    u = np.frombuffer(payload, dtype=dtype).reshape(shape)
    return _restored_state(u, t, it)


def _load_ckpt_native(lib, path: str) -> SolverState:
    """Native loader: header parse + CRC-verified payload read in C."""
    import ctypes

    import jax.numpy as jnp

    code = ctypes.c_uint32()
    ndim = ctypes.c_uint32()
    shape4 = (ctypes.c_uint32 * 4)()
    t = ctypes.c_double()
    it = ctypes.c_int64()
    rc = lib.checkpoint_load_header(
        path.encode(), ctypes.byref(code), ctypes.byref(ndim), shape4,
        ctypes.byref(t), ctypes.byref(it),
    )
    if rc == -3:
        raise IOError(f"not a framework checkpoint: {path}")
    if rc != 0:
        raise IOError(f"truncated checkpoint header: {path}")
    shape = tuple(shape4[: ndim.value])
    dtype = np.dtype(_CKPT_DTYPES[code.value])
    out = np.empty(shape, dtype=dtype)
    rc = lib.checkpoint_load_payload(
        path.encode(), out.ctypes.data_as(ctypes.c_void_p), out.nbytes
    )
    if rc == -2:
        raise IOError(f"checkpoint CRC mismatch (corrupt file): {path}")
    if rc != 0:
        raise IOError(f"truncated checkpoint payload: {path}")
    return _restored_state(out, t.value, it.value)


def save_checkpoint(
    path: str,
    state: SolverState,
    grid: Optional[Grid] = None,
    physics: Optional[dict] = None,
):
    """Restartable state. ``.npz`` paths keep the legacy numpy container;
    anything else uses the framework ``.ckpt`` format (atomic write +
    CRC-verified payload, native-accelerated when ``native/`` is built).
    Grid metadata — plus the run's key ``physics`` parameters, so a resume
    can refuse a silently-different configuration — rides in a
    ``<path>.json`` sidecar for ``.ckpt`` (the array shape itself is
    already in the binary header).

    Scale limit (documented, not hidden): a *sharded* state is gathered
    to one host (``np.asarray`` on the global ``jax.Array``) before
    writing — fine at reference scale (the reference's own gather does
    the same over MPI, ``main.c:326-335``, and has no restart at all),
    but a multi-host run whose global array exceeds one host's memory
    needs a per-shard format this writer does not implement."""
    import time as _time

    t0 = _time.perf_counter()
    meta = {}
    if grid is not None:
        meta = {"shape": list(grid.shape), "bounds": [list(b) for b in grid.bounds]}
    if physics is not None:
        meta["physics"] = physics
    if not path.endswith(".npz"):
        _save_ckpt(path, state)
        if meta:
            tmp = path + ".json.tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, path + ".json")
    else:
        np.savez(
            path,
            u=np.asarray(state.u),
            t=np.asarray(state.t),
            it=np.asarray(state.it),
            meta=json.dumps(meta),
        )
    _io_event(
        "checkpoint_write", path, getattr(state.u, "nbytes", 0),
        _time.perf_counter() - t0, iteration=int(state.it),
    )


def load_checkpoint(path: str, sharding=None) -> SolverState:
    import jax.numpy as jnp

    if os.path.isdir(path):
        return load_checkpoint_sharded(path, sharding=sharding)
    if not path.endswith(".npz"):
        st = _load_ckpt(path)
    else:
        with np.load(path, allow_pickle=False) as z:
            st = _restored_state(z["u"], z["t"], z["it"])
    if sharding is not None:
        # single-file checkpoints load as one host array; honor the
        # requested placement here so direct API callers get the same
        # contract as the .ckptd directory path
        import jax

        st = SolverState(u=jax.device_put(st.u, sharding), t=st.t, it=st.it)
    return st


# --------------------------------------------------------------------- #
# Per-shard checkpointing: each process writes only the shards it
# addresses — no gather to one host — plus a manifest describing the
# global layout, so a resume can reassemble the state under ANY mesh /
# decomposition (each loading process reads only the file regions
# overlapping its own shards). This lifts the documented scale limit of
# save_checkpoint's gather (and exceeds the reference, whose MPI gather
# to rank 0 is its only output path and which has no restart at all,
# MultiGPU/Diffusion3d_Baseline/main.c:326-335).
#
# Layout of a sharded checkpoint DIRECTORY (suffix ``.ckptd``):
#   manifest.json          global shape/dtype/t/it + grid/physics meta
#   manifest_p<K>.json     process K's shard list ({file, start, shape})
#   shard_<start...>.ckpt  one standard .ckpt per distinct shard block
#   COMMIT                 the durability marker, written LAST (after a
#                          cross-process barrier proved every shard and
#                          manifest landed) — a directory without it is
#                          a torn or in-progress write and is never
#                          loaded, verified, or auto-resumed from
# --------------------------------------------------------------------- #

_CKPTD_COMMIT = "COMMIT"

#: declared barrier-tag namespace of the sharded checkpoint-commit
#: protocol (queryable collective metadata, aggregated by
#: ``parallel.multihost.collective_spec``; ``*`` = the checkpoint
#: directory interpolation). Order matters: it IS the commit
#: protocol's schedule, and the collective-schedule verifier's dynamic
#: cross-check asserts every measured instance respects it
#: (begin -> shards -> commit, per directory).
CKPTD_BARRIER_TAGS = (
    "ckptd-begin:*",
    "ckptd-shards:*",
    "ckptd-commit:*",
)


def save_checkpoint_sharded(
    directory: str,
    state: SolverState,
    grid: Optional[Grid] = None,
    physics: Optional[dict] = None,
) -> None:
    """Write ``state`` as a per-shard checkpoint directory.

    Every process writes the shards it *owns* as ordinary ``.ckpt``
    files (atomic, CRC-verified) named by their global start offsets,
    plus a per-process manifest; the coordinator also writes the global
    ``manifest.json``. A block replicated across several devices is
    owned by the lowest-ranked device holding it (computed from the
    sharding's full placement map, identically on every process), so
    exactly one process writes each distinct block — no cross-process
    write collisions by construction."""
    import time as _time

    import jax

    from multigpu_advectiondiffusion_tpu.parallel import multihost

    t0 = _time.perf_counter()
    os.makedirs(directory, exist_ok=True)
    # Overwriting an earlier checkpoint of the same name: invalidate its
    # COMMIT marker FIRST (and barrier, so no peer starts rewriting
    # shards while a reader could still see the stale commit) — the
    # directory is complete-or-uncommitted at every instant.
    commit_path = os.path.join(directory, _CKPTD_COMMIT)
    multi = jax.process_count() > 1
    # Safe rank divergence: invalidating the stale COMMIT marker is a
    # single-writer action by design (two ranks racing the same unlink
    # is the bug), and the ckptd-begin barrier below orders it before
    # any peer touches a shard byte.
    # tpucfd-check: allow[rank-divergent-effect]
    if jax.process_index() == 0:
        try:
            os.remove(commit_path)
        except FileNotFoundError:
            pass
    if multi:
        multihost.barrier(f"ckptd-begin:{directory}")
    u = state.u
    shards = getattr(u, "addressable_shards", None)
    if shards is None:  # plain array: one full-extent shard
        arr = np.asarray(u)
        blocks = [((0,) * arr.ndim, arr)]
        gshape = arr.shape
        dtype = arr.dtype
    else:
        gshape = tuple(u.shape)
        dtype = np.dtype(u.dtype)
        # owner of each distinct block = lowest (process_index, id)
        # device holding it, from the global placement map every
        # process computes identically
        owner = {}
        for dev, idx in u.sharding.devices_indices_map(gshape).items():
            start = tuple((sl.start or 0) for sl in idx)
            rank = (dev.process_index, dev.id)
            if start not in owner or rank < owner[start]:
                owner[start] = rank
        blocks = []
        for sh in shards:
            start = tuple((idx.start or 0) for idx in sh.index)
            dev = sh.device
            if owner[start] == (dev.process_index, dev.id):
                blocks.append((start, np.asarray(sh.data)))

    t, it = float(state.t), int(state.it)
    entries = []
    for start, arr in blocks:
        fname = "shard_" + "_".join(map(str, start)) + ".ckpt"
        _save_ckpt(
            os.path.join(directory, fname),
            SolverState(u=arr, t=np.float64(t), it=np.int64(it)),
        )
        entries.append(
            {"file": fname, "start": list(start), "shape": list(arr.shape)}
        )

    pid = jax.process_index()
    tmp = os.path.join(directory, f"manifest_p{pid}.json.tmp")
    with open(tmp, "w") as f:
        json.dump({"process": pid, "shards": entries}, f)
    os.replace(tmp, os.path.join(directory, f"manifest_p{pid}.json"))

    # The COMMIT marker is the checkpoint's commit record: it must
    # appear only after EVERY process's shards and manifests are on
    # disk (else a directory can look complete while peers are still
    # writing — losing the complete-or-absent guarantee the single-file
    # format gets from its atomic rename). Barrier, coordinator writes
    # manifest.json then COMMIT, barrier again so no process returns
    # (and possibly loads) before the commit landed. The barriers are
    # timeout-wrapped when a rank watchdog is installed — a peer dying
    # mid-checkpoint surfaces as RankFailureError, not a silent hang.
    if multi:
        multihost.barrier(f"ckptd-shards:{directory}")
    # Safe rank divergence: the global manifest and the COMMIT marker
    # have exactly one writer by design; the ckptd-shards barrier
    # above guarantees every peer's shards are on disk first, and the
    # ckptd-commit barrier below holds every peer until the commit
    # landed — the "rank 0 wrote it, rank 1 committed it" hazard this
    # rule exists for cannot occur between the two barriers.
    # tpucfd-check: allow[rank-divergent-effect]
    if pid == 0:
        meta = {
            "global_shape": list(gshape),
            "dtype": str(np.dtype(dtype)),
            "t": t,
            "it": it,
            "num_processes": jax.process_count(),
        }
        if grid is not None:
            meta["shape"] = list(grid.shape)
            meta["bounds"] = [list(b) for b in grid.bounds]
        if physics is not None:
            meta["physics"] = physics
        tmp = os.path.join(directory, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(directory, "manifest.json"))
        # COMMIT last: its presence asserts every earlier artifact
        tmp = commit_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"it": it, "t": t, "num_processes": jax.process_count()},
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, commit_path)
    if multi:
        multihost.barrier(f"ckptd-commit:{directory}")
    _io_event(
        "checkpoint_write", directory,
        sum(arr.nbytes for _, arr in blocks),
        _time.perf_counter() - t0,
        iteration=it, sharded=True, shards=len(blocks),
    )


def _shard_desc(e: dict) -> str:
    """Human identification of one manifest shard entry: file name plus
    the global index region it covers — every sharded-checkpoint error
    names the exact shard and offsets so a multi-TB resume failure is
    actionable without forensics."""
    stop = [s + n for s, n in zip(e["start"], e["shape"])]
    region = "x".join(
        f"[{s}:{t})" for s, t in zip(e["start"], stop)
    )
    return f"{e['file']} (global offsets {region})"


def _validate_tiling(directory: str, gshape, entries) -> None:
    """The manifest's shard set must tile the global index space
    EXACTLY: every shard in bounds, no pairwise overlaps, no gaps.
    (Disjoint + in-bounds + total cell count equal is an exact cover;
    the previous cell-count-only check let an overlap and a gap cancel
    — precisely the kind of torn/hand-edited manifest the resume path
    must refuse.)"""
    ndim = len(gshape)
    total = int(np.prod(gshape))
    covered = 0
    for e in entries:
        start, shape = e["start"], e["shape"]
        if len(start) != ndim or len(shape) != ndim or any(
            s < 0 or n <= 0 or s + n > g
            for s, n, g in zip(start, shape, gshape)
        ):
            raise IOError(
                f"sharded checkpoint {directory}: shard {_shard_desc(e)}"
                f" lies outside the global shape {tuple(gshape)}"
            )
        covered += int(np.prod(shape))
    for i, a in enumerate(entries):
        for b in entries[i + 1:]:
            if all(
                max(a["start"][k], b["start"][k])
                < min(a["start"][k] + a["shape"][k],
                      b["start"][k] + b["shape"][k])
                for k in range(ndim)
            ):
                raise IOError(
                    f"sharded checkpoint {directory}: manifest shards "
                    f"overlap: {_shard_desc(a)} and {_shard_desc(b)}"
                )
    if covered != total:
        raise IOError(
            f"sharded checkpoint {directory} does not tile the global "
            f"array: shards cover {covered} of {total} cells (gap in "
            "the manifest); present shards: "
            + "; ".join(_shard_desc(e) for e in entries)
        )


def _sharded_manifest(directory: str):
    """(meta, entries): the global manifest plus the union of every
    process manifest's shard entries, deduplicated by start offset and
    validated to tile the global array exactly (no gaps, no overlaps).
    Requires the COMMIT marker — a directory without one is a torn or
    in-progress write. A shard listed by a manifest but absent on disk
    raises an error naming the missing file(s) and the global offsets
    they should cover."""
    import glob as _glob

    if not os.path.exists(os.path.join(directory, _CKPTD_COMMIT)):
        raise IOError(
            f"sharded checkpoint {directory} has no COMMIT marker "
            "(torn or in-progress write)"
        )
    with open(os.path.join(directory, "manifest.json")) as f:
        meta = json.load(f)
    entries, seen = [], set()
    for mpath in sorted(_glob.glob(os.path.join(directory, "manifest_p*.json"))):
        with open(mpath) as f:
            for e in json.load(f)["shards"]:
                key = tuple(e["start"])
                if key not in seen:
                    seen.add(key)
                    entries.append(e)
    missing = [
        e for e in entries
        if not os.path.exists(os.path.join(directory, e["file"]))
    ]
    if missing:
        raise IOError(
            f"sharded checkpoint {directory} is missing "
            f"{len(missing)} shard file(s): "
            + "; ".join(_shard_desc(e) for e in missing)
        )
    _validate_tiling(directory, tuple(meta["global_shape"]), entries)
    return meta, entries


def _assemble_block(directory, entries, dtype, start, shape, cache=None):
    """Assemble the global block ``[start, start+shape)`` from the shard
    files overlapping it (each read in full, CRC-verified; ``cache``
    memoizes reads across blocks so a D-device load does O(S) file
    reads, not O(D x S))."""
    block = np.empty(shape, dtype=dtype)
    filled = 0
    for e in entries:
        es, esh = e["start"], e["shape"]
        lo = [max(start[i], es[i]) for i in range(len(shape))]
        hi = [
            min(start[i] + shape[i], es[i] + esh[i])
            for i in range(len(shape))
        ]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        if cache is not None and e["file"] in cache:
            src_arr = cache[e["file"]]
        else:
            try:
                src_arr = np.asarray(
                    _load_ckpt(os.path.join(directory, e["file"])).u
                )
            except (IOError, OSError) as err:
                # name the exact shard + its global offsets, not a bare
                # "CRC mismatch" — the one unreadable file of a multi-TB
                # directory must be identifiable from the error alone
                raise IOError(
                    f"sharded checkpoint {directory}: shard "
                    f"{_shard_desc(e)} is unreadable: {err}"
                ) from err
            if cache is not None:
                cache[e["file"]] = src_arr
        src_sl = tuple(
            slice(lo[i] - es[i], hi[i] - es[i]) for i in range(len(shape))
        )
        dst_sl = tuple(
            slice(lo[i] - start[i], hi[i] - start[i])
            for i in range(len(shape))
        )
        block[dst_sl] = src_arr[src_sl]
        filled += int(np.prod([h - l for l, h in zip(lo, hi)]))
    if filled != int(np.prod(shape)):
        raise IOError(
            f"sharded checkpoint {directory} does not cover block "
            f"start={start} shape={shape}"
        )
    return block


def load_checkpoint_sharded(directory: str, sharding=None) -> SolverState:
    """Load a per-shard checkpoint directory.

    With ``sharding`` (any ``NamedSharding`` — the mesh/decomposition may
    differ from the one that saved): each process reads only the file
    regions overlapping its own addressable shards and assembles a
    global ``jax.Array`` via ``make_array_from_single_device_arrays`` —
    the global state never materializes on one host. Without
    ``sharding``: assembles the full array locally (single-host use)."""
    import jax
    import jax.numpy as jnp

    meta, entries = _sharded_manifest(directory)
    gshape = tuple(meta["global_shape"])
    dtype = np.dtype(meta["dtype"])
    # scalar dtypes follow the SolverState.create contract (see
    # _restored_state) so a sharded resume stays bit-identical too
    rdt = jnp.float64 if dtype == np.float64 else jnp.float32
    t = jnp.asarray(meta["t"], dtype=rdt)
    it = jnp.asarray(int(meta["it"]), dtype=jnp.int32)

    if sharding is None:
        u = _assemble_block(directory, entries, dtype, (0,) * len(gshape),
                            gshape)
        return SolverState(u=jnp.asarray(u), t=t, it=it)

    arrays = []
    cache, block_cache = {}, {}
    for dev, idx in sharding.addressable_devices_indices_map(gshape).items():
        start = tuple((sl.start or 0) for sl in idx)
        shape = tuple(
            (sl.stop if sl.stop is not None else gshape[i]) - (sl.start or 0)
            for i, sl in enumerate(idx)
        )
        if (start, shape) not in block_cache:  # replicated devices share
            block_cache[(start, shape)] = _assemble_block(
                directory, entries, dtype, start, shape, cache=cache
            )
        arrays.append(jax.device_put(block_cache[(start, shape)], dev))
    u = jax.make_array_from_single_device_arrays(gshape, sharding, arrays)
    return SolverState(u=u, t=t, it=it)


def verify_checkpoint(path: str) -> None:
    """Full integrity check without constructing device arrays: header
    parse + payload CRC32 for ``.ckpt``, archive read for ``.npz``, and
    for a ``.ckptd`` directory the COMMIT marker, the manifest's exact
    tiling of the global index space (no gaps, no overlaps, nothing out
    of bounds) plus every shard's CRC (errors name the exact shard file
    and its global offsets). Raises ``IOError``/``ValueError`` on any
    defect; the ``--resume auto`` scan (``resilience/recovery.py``)
    uses this to skip corrupt candidates."""
    import struct
    import zlib

    if os.path.isdir(path):
        _, entries = _sharded_manifest(path)
        for e in entries:
            try:
                verify_checkpoint(os.path.join(path, e["file"]))
            except (IOError, OSError) as err:
                raise IOError(
                    f"sharded checkpoint {path}: shard {_shard_desc(e)} "
                    f"failed verification: {err}"
                ) from err
        return
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=False) as z:
            for key in ("u", "t", "it"):
                if key not in z:
                    raise IOError(f"npz checkpoint missing {key!r}: {path}")
                z[key]  # zip-member CRC is checked on read
        return
    with open(path, "rb") as f:
        header = f.read(64)
        if len(header) != 64:
            raise IOError(f"truncated checkpoint header: {path}")
        (magic, version, code, ndim, s0, s1, s2, s3, _t, _it, crc) = (
            struct.unpack(_CKPT_STRUCT, header)
        )
        if magic != _CKPT_MAGIC or version != _CKPT_VERSION:
            raise IOError(f"not a framework checkpoint: {path}")
        if code not in _CKPT_DTYPES or not 1 <= ndim <= 4:
            raise IOError(f"corrupt checkpoint header: {path}")
        shape = (s0, s1, s2, s3)[:ndim]
        nbytes = int(np.prod(shape)) * np.dtype(_CKPT_DTYPES[code]).itemsize
        payload = f.read(nbytes)
    if len(payload) != nbytes:
        raise IOError(f"truncated checkpoint payload: {path}")
    if zlib.crc32(payload) != crc:
        raise IOError(f"checkpoint CRC mismatch (corrupt file): {path}")


def read_checkpoint_meta(path: str) -> Optional[dict]:
    """Grid metadata recorded with a checkpoint, or ``None`` if absent.

    ``.npz`` checkpoints embed it in the archive's ``meta`` field;
    ``.ckpt`` checkpoints carry it in the ``<path>.json`` sidecar;
    sharded checkpoint directories carry it in ``manifest.json``.
    """
    if os.path.isdir(path):
        mpath = os.path.join(path, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as f:
                return json.load(f)
        return None
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=False) as z:
            if "meta" not in z:
                return None
            meta = json.loads(str(z["meta"]))
            return meta or None
    sidecar = path + ".json"
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            return json.load(f)
    return None


def rotate_checkpoints(directory: str, keep: int, prefix: str = "checkpoint_"):
    """Delete all but the newest ``keep`` checkpoints in ``directory``
    (matched by ``prefix`` + a known checkpoint extension), oldest first
    by filename — the zero-padded iteration number makes name order the
    write order, deterministic where mtime granularity is not. Metadata
    sidecars follow their checkpoint. Keeps disk use bounded on long runs
    with ``--checkpoint-every``."""
    if keep <= 0:
        return
    def _iteration(name: str):
        stem = name[len(prefix):].rsplit(".", 1)[0]
        return int(stem) if stem.isdigit() else None

    names = sorted(
        (
            name
            for name in os.listdir(directory)
            if name.startswith(prefix)
            # .ckptd: per-shard checkpoint directories rotate like files
            and name.endswith((".ckpt", ".npz", ".ckptd"))
            # only rotation-managed files (purely numeric iteration stem);
            # a user file like checkpoint_best.ckpt must never be deleted
            and _iteration(name) is not None
        ),
        key=lambda n: (_iteration(n), n),  # numeric order survives a
        # digit-count rollover past the %06d padding
    )
    for stale in names[:-keep]:
        full = os.path.join(directory, stale)
        # ENOENT-tolerant: after a multi-process sharded save every
        # process rotates the shared directory; a peer deleting the same
        # stale entry first is success, not an error
        if os.path.isdir(full):
            import shutil

            shutil.rmtree(full, ignore_errors=True)
        else:
            try:
                os.remove(full)
            except FileNotFoundError:
                pass
        try:
            os.remove(full + ".json")
        except FileNotFoundError:
            pass
