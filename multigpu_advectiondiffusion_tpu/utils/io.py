"""Field I/O: reference-compatible binaries, npz checkpoints, summaries.

* ``save_binary`` writes the float32 raw layout of ``SaveBinary3D``
  (``MultiGPU/Diffusion3d_Baseline/Tools.c:91-119``): x fastest, then y,
  then z — exactly ``u.ravel()`` for this framework's ``(z, y, x)``
  arrays — loadable by the reference's ``Run.m`` harness via
  ``fread(fID,[1,nx*ny*nz],'float')``.
* ``save_ascii`` mirrors ``Save3D`` (``Tools.c:68-86``), one ``%g`` per line.
* npz checkpoints add what the reference lacks (SURVEY §5): restartable
  state (u, t, it) with grid metadata.

A native C implementation of the binary writer (``native/io_native.cpp``)
is used automatically when built; the numpy path is the fallback.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.state import SolverState

_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    import ctypes

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "..", "native", "libtpucfd_io.so"),
        os.path.join(here, "native", "libtpucfd_io.so"),
    ):
        if os.path.exists(cand):
            lib = ctypes.CDLL(cand)
            lib.save_binary_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.save_binary_f32.restype = ctypes.c_int
            lib.writer_create.argtypes = [ctypes.c_size_t]
            lib.writer_create.restype = ctypes.c_void_p
            lib.writer_submit.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.writer_submit.restype = ctypes.c_int
            lib.writer_flush.argtypes = [ctypes.c_void_p]
            lib.writer_flush.restype = ctypes.c_int
            lib.writer_destroy.argtypes = [ctypes.c_void_p]
            try:  # added with the checkpoint runtime; absent in old builds
                lib.checkpoint_save.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_void_p,
                    ctypes.c_uint32,
                    ctypes.c_uint32,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.c_double,
                    ctypes.c_int64,
                ]
                lib.checkpoint_save.restype = ctypes.c_int
                lib.checkpoint_load_header.argtypes = [
                    ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_double),
                    ctypes.POINTER(ctypes.c_int64),
                ]
                lib.checkpoint_load_header.restype = ctypes.c_int
                lib.checkpoint_load_payload.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                ]
                lib.checkpoint_load_payload.restype = ctypes.c_int
            except AttributeError:
                pass
            _native = lib
            return lib
    _native = False
    return False


class AsyncBinaryWriter:
    """Double-buffered background snapshot writer (native thread when
    ``native/libtpucfd_io.so`` is built, synchronous fallback otherwise).

    The solver keeps stepping while the previous snapshot drains to disk —
    the role the reference's pinned host buffers + DtH copy staging played
    for output (``main.c:89-114,312-343``).
    """

    def __init__(self, queue_slots: int = 2):
        self._lib = _load_native() or None
        self._handle = (
            self._lib.writer_create(queue_slots) if self._lib else None
        )

    def submit(self, u, path: str) -> None:
        arr = np.ascontiguousarray(np.asarray(u, dtype=np.float32)).ravel()
        if self._handle:
            import ctypes

            rc = self._lib.writer_submit(
                self._handle,
                path.encode(),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                arr.size,
            )
            if rc != 0:
                raise IOError(f"async writer failed for {path}")
        else:
            arr.tofile(path)

    def flush(self) -> None:
        if self._handle and self._lib.writer_flush(self._handle) != 0:
            raise IOError("async writer flush reported an error")

    def close(self) -> None:
        if self._handle:
            self._lib.writer_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()
        self.close()


def save_binary(u, path: str) -> None:
    """Write float32 raw binary, reference ``SaveBinary3D`` layout."""
    arr = np.asarray(u, dtype=np.float32).ravel()
    lib = _load_native()
    if lib:
        import ctypes

        buf = np.ascontiguousarray(arr)
        rc = lib.save_binary_f32(
            path.encode(),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            buf.size,
        )
        if rc == 0:
            return
    arr.tofile(path)


def print_field(u, file=None) -> None:
    """Console dump of a field, one ``%8.2f``-style row per line — the
    debugging role of ``Print2D/Print3D`` (``Tools.c:32-63``); 3-D arrays
    print as z-slices separated by blank lines."""
    import sys

    out = file or sys.stdout
    arr = np.atleast_2d(np.asarray(u))
    planes = arr.reshape((-1,) + arr.shape[-2:])
    for k, plane in enumerate(planes):
        if k:
            out.write("\n")
        for row in plane:
            out.write(" ".join(f"{v:8.2f}" for v in row) + "\n")


def load_binary(path: str, shape) -> np.ndarray:
    return np.fromfile(path, dtype=np.float32).reshape(shape)


def save_ascii(u, path: str) -> None:
    """One value per line, ``%g`` format (``Save3D``, Tools.c:68-86)."""
    arr = np.ascontiguousarray(np.asarray(u, dtype=np.float64)).ravel()
    lib = _load_native()
    if lib:
        import ctypes

        lib.save_ascii_f64.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
        ]
        lib.save_ascii_f64.restype = ctypes.c_int
        if lib.save_ascii_f64(
            path.encode(),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            arr.size,
        ) == 0:
            return
    with open(path, "w") as f:
        for v in arr:
            f.write(f"{v:g}\n")


# --------------------------------------------------------------------- #
# Checkpoint format (.ckpt): 64-byte header + raw payload + CRC32.
#
# Layout (little-endian), mirrored bit-for-bit by
# ``native/checkpoint_native.cpp`` — the bytes are identical whether the
# native library is built or not:
#   0: magic "TPCFDCKP"        8s
#   8: version                 u32 (=1)
#  12: dtype code              u32 (0=f32, 1=f64)
#  16: ndim                    u32 (<=4)
#  20: shape[4]                4*u32 (unused dims = 1)
#  36: padding                 4 bytes (keeps t 8-aligned)
#  40: t                       f64
#  48: iteration               i64
#  56: crc32(payload)          u32 (zlib polynomial)
#  60: reserved                4 bytes
#  64: payload
#
# Saves are atomic (tmp + rename) and loads CRC-verify the payload — the
# resume-safety the reference cannot offer (it has no restart at all).
# --------------------------------------------------------------------- #
_CKPT_MAGIC = b"TPCFDCKP"
_CKPT_STRUCT = "<8sIII4I4xdqI4x"  # one layout constant: writer and reader cannot drift
_CKPT_VERSION = 1
_CKPT_DTYPES = {0: np.float32, 1: np.float64}
_CKPT_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}


def _ckpt_header(arr: np.ndarray, t: float, it: int, crc: int) -> bytes:
    import struct

    shape4 = list(arr.shape) + [1] * (4 - arr.ndim)
    return struct.pack(
        _CKPT_STRUCT,
        _CKPT_MAGIC,
        _CKPT_VERSION,
        _CKPT_CODES[arr.dtype],
        arr.ndim,
        *shape4,
        float(t),
        int(it),
        crc,
    )


def _save_ckpt(path: str, state: SolverState) -> None:
    import ctypes
    import zlib

    arr = np.ascontiguousarray(np.asarray(state.u))
    if arr.dtype not in _CKPT_CODES or not 1 <= arr.ndim <= 4:
        raise ValueError(f"checkpoint supports 1-4D f32/f64, got {arr.dtype}")
    t, it = float(state.t), int(state.it)

    lib = _load_native()
    if lib and hasattr(lib, "checkpoint_save"):
        shape = (ctypes.c_uint32 * 4)(*(list(arr.shape) + [1] * 4)[:4])
        rc = lib.checkpoint_save(
            path.encode(),
            arr.ctypes.data_as(ctypes.c_void_p),
            _CKPT_CODES[arr.dtype],
            arr.ndim,
            shape,
            t,
            it,
        )
        if rc == 0:
            return
    payload = arr.tobytes()
    header = _ckpt_header(arr, t, it, zlib.crc32(payload))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _load_ckpt(path: str) -> SolverState:
    import struct
    import zlib

    import jax.numpy as jnp

    lib = _load_native()
    if lib and hasattr(lib, "checkpoint_load_header"):
        return _load_ckpt_native(lib, path)

    with open(path, "rb") as f:
        header = f.read(64)
        if len(header) != 64:
            raise IOError(f"truncated checkpoint header: {path}")
        (magic, version, code, ndim, s0, s1, s2, s3, t, it, crc) = (
            struct.unpack(_CKPT_STRUCT, header)
        )
        if magic != _CKPT_MAGIC or version != _CKPT_VERSION:
            raise IOError(f"not a framework checkpoint: {path}")
        if code not in _CKPT_DTYPES or not 1 <= ndim <= 4:
            raise IOError(f"corrupt checkpoint header: {path}")
        shape = (s0, s1, s2, s3)[:ndim]
        dtype = np.dtype(_CKPT_DTYPES[code])
        nbytes = int(np.prod(shape)) * dtype.itemsize
        payload = f.read(nbytes)
    if len(payload) != nbytes:
        raise IOError(f"truncated checkpoint payload: {path}")
    if zlib.crc32(payload) != crc:
        raise IOError(f"checkpoint CRC mismatch (corrupt file): {path}")
    u = np.frombuffer(payload, dtype=dtype).reshape(shape)
    return SolverState(u=jnp.asarray(u), t=jnp.asarray(t), it=jnp.asarray(it))


def _load_ckpt_native(lib, path: str) -> SolverState:
    """Native loader: header parse + CRC-verified payload read in C."""
    import ctypes

    import jax.numpy as jnp

    code = ctypes.c_uint32()
    ndim = ctypes.c_uint32()
    shape4 = (ctypes.c_uint32 * 4)()
    t = ctypes.c_double()
    it = ctypes.c_int64()
    rc = lib.checkpoint_load_header(
        path.encode(), ctypes.byref(code), ctypes.byref(ndim), shape4,
        ctypes.byref(t), ctypes.byref(it),
    )
    if rc == -3:
        raise IOError(f"not a framework checkpoint: {path}")
    if rc != 0:
        raise IOError(f"truncated checkpoint header: {path}")
    shape = tuple(shape4[: ndim.value])
    dtype = np.dtype(_CKPT_DTYPES[code.value])
    out = np.empty(shape, dtype=dtype)
    rc = lib.checkpoint_load_payload(
        path.encode(), out.ctypes.data_as(ctypes.c_void_p), out.nbytes
    )
    if rc == -2:
        raise IOError(f"checkpoint CRC mismatch (corrupt file): {path}")
    if rc != 0:
        raise IOError(f"truncated checkpoint payload: {path}")
    return SolverState(
        u=jnp.asarray(out), t=jnp.asarray(t.value), it=jnp.asarray(it.value)
    )


def save_checkpoint(
    path: str,
    state: SolverState,
    grid: Optional[Grid] = None,
    physics: Optional[dict] = None,
):
    """Restartable state. ``.npz`` paths keep the legacy numpy container;
    anything else uses the framework ``.ckpt`` format (atomic write +
    CRC-verified payload, native-accelerated when ``native/`` is built).
    Grid metadata — plus the run's key ``physics`` parameters, so a resume
    can refuse a silently-different configuration — rides in a
    ``<path>.json`` sidecar for ``.ckpt`` (the array shape itself is
    already in the binary header).

    Scale limit (documented, not hidden): a *sharded* state is gathered
    to one host (``np.asarray`` on the global ``jax.Array``) before
    writing — fine at reference scale (the reference's own gather does
    the same over MPI, ``main.c:326-335``, and has no restart at all),
    but a multi-host run whose global array exceeds one host's memory
    needs a per-shard format this writer does not implement."""
    meta = {}
    if grid is not None:
        meta = {"shape": list(grid.shape), "bounds": [list(b) for b in grid.bounds]}
    if physics is not None:
        meta["physics"] = physics
    if not path.endswith(".npz"):
        _save_ckpt(path, state)
        if meta:
            tmp = path + ".json.tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, path + ".json")
        return
    np.savez(
        path,
        u=np.asarray(state.u),
        t=np.asarray(state.t),
        it=np.asarray(state.it),
        meta=json.dumps(meta),
    )


def load_checkpoint(path: str) -> SolverState:
    import jax.numpy as jnp

    if not path.endswith(".npz"):
        return _load_ckpt(path)
    with np.load(path, allow_pickle=False) as z:
        return SolverState(
            u=jnp.asarray(z["u"]), t=jnp.asarray(z["t"]), it=jnp.asarray(z["it"])
        )


def read_checkpoint_meta(path: str) -> Optional[dict]:
    """Grid metadata recorded with a checkpoint, or ``None`` if absent.

    ``.npz`` checkpoints embed it in the archive's ``meta`` field;
    ``.ckpt`` checkpoints carry it in the ``<path>.json`` sidecar.
    """
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=False) as z:
            if "meta" not in z:
                return None
            meta = json.loads(str(z["meta"]))
            return meta or None
    sidecar = path + ".json"
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            return json.load(f)
    return None


def rotate_checkpoints(directory: str, keep: int, prefix: str = "checkpoint_"):
    """Delete all but the newest ``keep`` checkpoints in ``directory``
    (matched by ``prefix`` + a known checkpoint extension), oldest first
    by filename — the zero-padded iteration number makes name order the
    write order, deterministic where mtime granularity is not. Metadata
    sidecars follow their checkpoint. Keeps disk use bounded on long runs
    with ``--checkpoint-every``."""
    if keep <= 0:
        return
    def _iteration(name: str):
        stem = name[len(prefix):].rsplit(".", 1)[0]
        return int(stem) if stem.isdigit() else None

    names = sorted(
        (
            name
            for name in os.listdir(directory)
            if name.startswith(prefix)
            and name.endswith((".ckpt", ".npz"))
            # only rotation-managed files (purely numeric iteration stem);
            # a user file like checkpoint_best.ckpt must never be deleted
            and _iteration(name) is not None
        ),
        key=lambda n: (_iteration(n), n),  # numeric order survives a
        # digit-count rollover past the %06d padding
    )
    for stale in names[:-keep]:
        os.remove(os.path.join(directory, stale))
        sidecar = os.path.join(directory, stale + ".json")
        if os.path.exists(sidecar):
            os.remove(sidecar)
