"""Field I/O: reference-compatible binaries, npz checkpoints, summaries.

* ``save_binary`` writes the float32 raw layout of ``SaveBinary3D``
  (``MultiGPU/Diffusion3d_Baseline/Tools.c:91-119``): x fastest, then y,
  then z — exactly ``u.ravel()`` for this framework's ``(z, y, x)``
  arrays — loadable by the reference's ``Run.m`` harness via
  ``fread(fID,[1,nx*ny*nz],'float')``.
* ``save_ascii`` mirrors ``Save3D`` (``Tools.c:68-86``), one ``%g`` per line.
* npz checkpoints add what the reference lacks (SURVEY §5): restartable
  state (u, t, it) with grid metadata.

A native C implementation of the binary writer (``native/io_native.cpp``)
is used automatically when built; the numpy path is the fallback.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from multigpu_advectiondiffusion_tpu.core.grid import Grid
from multigpu_advectiondiffusion_tpu.models.state import SolverState

_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    import ctypes

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "..", "native", "libtpucfd_io.so"),
        os.path.join(here, "native", "libtpucfd_io.so"),
    ):
        if os.path.exists(cand):
            lib = ctypes.CDLL(cand)
            lib.save_binary_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.save_binary_f32.restype = ctypes.c_int
            lib.writer_create.argtypes = [ctypes.c_size_t]
            lib.writer_create.restype = ctypes.c_void_p
            lib.writer_submit.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
            ]
            lib.writer_submit.restype = ctypes.c_int
            lib.writer_flush.argtypes = [ctypes.c_void_p]
            lib.writer_flush.restype = ctypes.c_int
            lib.writer_destroy.argtypes = [ctypes.c_void_p]
            _native = lib
            return lib
    _native = False
    return False


class AsyncBinaryWriter:
    """Double-buffered background snapshot writer (native thread when
    ``native/libtpucfd_io.so`` is built, synchronous fallback otherwise).

    The solver keeps stepping while the previous snapshot drains to disk —
    the role the reference's pinned host buffers + DtH copy staging played
    for output (``main.c:89-114,312-343``).
    """

    def __init__(self, queue_slots: int = 2):
        self._lib = _load_native() or None
        self._handle = (
            self._lib.writer_create(queue_slots) if self._lib else None
        )

    def submit(self, u, path: str) -> None:
        arr = np.ascontiguousarray(np.asarray(u, dtype=np.float32)).ravel()
        if self._handle:
            import ctypes

            rc = self._lib.writer_submit(
                self._handle,
                path.encode(),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                arr.size,
            )
            if rc != 0:
                raise IOError(f"async writer failed for {path}")
        else:
            arr.tofile(path)

    def flush(self) -> None:
        if self._handle and self._lib.writer_flush(self._handle) != 0:
            raise IOError("async writer flush reported an error")

    def close(self) -> None:
        if self._handle:
            self._lib.writer_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()
        self.close()


def save_binary(u, path: str) -> None:
    """Write float32 raw binary, reference ``SaveBinary3D`` layout."""
    arr = np.asarray(u, dtype=np.float32).ravel()
    lib = _load_native()
    if lib:
        import ctypes

        buf = np.ascontiguousarray(arr)
        rc = lib.save_binary_f32(
            path.encode(),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            buf.size,
        )
        if rc == 0:
            return
    arr.tofile(path)


def load_binary(path: str, shape) -> np.ndarray:
    return np.fromfile(path, dtype=np.float32).reshape(shape)


def save_ascii(u, path: str) -> None:
    """One value per line, ``%g`` format (``Save3D``, Tools.c:68-86)."""
    arr = np.ascontiguousarray(np.asarray(u, dtype=np.float64)).ravel()
    lib = _load_native()
    if lib:
        import ctypes

        lib.save_ascii_f64.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
        ]
        lib.save_ascii_f64.restype = ctypes.c_int
        if lib.save_ascii_f64(
            path.encode(),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            arr.size,
        ) == 0:
            return
    with open(path, "w") as f:
        for v in arr:
            f.write(f"{v:g}\n")


def save_checkpoint(path: str, state: SolverState, grid: Optional[Grid] = None):
    meta = {}
    if grid is not None:
        meta = {"shape": list(grid.shape), "bounds": [list(b) for b in grid.bounds]}
    np.savez(
        path,
        u=np.asarray(state.u),
        t=np.asarray(state.t),
        it=np.asarray(state.it),
        meta=json.dumps(meta),
    )


def load_checkpoint(path: str) -> SolverState:
    import jax.numpy as jnp

    with np.load(path, allow_pickle=False) as z:
        return SolverState(
            u=jnp.asarray(z["u"]), t=jnp.asarray(z["t"]), it=jnp.asarray(z["it"])
        )
