"""In-situ physics diagnostics: the numerical-health layer.

The reference's MATLAB ``Run.m`` harness plots fields and eyeballs
every solver change against known solutions; this package is the
machine-checked counterpart:

* :mod:`physics` — the per-solver observable registry (conservation
  budgets, total variation, max-principle bounds, spectral tail) whose
  observables are fused into the divergence sentinel's ONE jitted
  mesh-aware probe (``resilience/sentinel.py``) so the whole suite
  costs at most one extra HBM pass and zero extra compiled programs,
  plus the tolerance-guarded violation rules and the Gaussian-diffusion
  decay-rate fit;
* :mod:`compare` — the science regression gate: diff two rounds'
  diagnostic trajectories with per-observable tolerance bands and exit
  nonzero on drift (``out/science_gate.sh`` is the wrapper; the
  numerics analog of ``bench/compare.py``).
"""

from multigpu_advectiondiffusion_tpu.diagnostics.physics import (  # noqa: F401
    Observable,
    ViolationRule,
    check_violations,
    gaussian_decay_fit,
    max_principle_rule,
    observables_for,
    rules_for,
    tv_monotone_rule,
)
