"""Per-solver physics-observable registry + violation rules.

The design constraint is the one the TPU scientific-computing framework
(PAPERS arXiv 2108.11076) imposes on its own analysis observables:
diagnostics are computed *on device, inside the program that is already
running* — here, fused into the divergence sentinel's single jitted
mesh-aware probe (``resilience/sentinel.py make_health_probe``), so the
whole suite rides the probe's existing HBM pass and adds ZERO extra
compiled programs (proven by ``tests/test_diagnostics.py``'s
compile-count test).

An :class:`Observable` contributes device-side scalar reductions (the
shard-local ``local`` closure runs inside the probe's jitted block; its
raw values are reduced across the mesh by the solver's own
``mesh_reduce_sum``/``mesh_reduce_max``) plus a host-side ``finalize``
mapping raw reductions to named physical quantities. A
:class:`ViolationRule` is a host-side tolerance check of the finalized
stats against the baseline armed on the initial state — the supervisor
turns breaches into ``phys:violation`` events (and, under
``--diag-strict``, into the rollback path).

Standard suite (every solver):

* conservation budgets — ``mass`` (the sentinel's own ∫u), ``l1``
  (∫|u|), ``energy`` (∫u²), ``l2``/``max_abs`` (the sentinel's own);
* ``tv`` — total variation, summed over axes. Computed shard-local
  (jumps across shard interfaces are excluded — bounded by the
  interface values, well inside the monotonicity tolerance);
* ``spectral_tail`` — the fraction of spectral energy in the top third
  of wavenumbers along the innermost axis: the cheapest
  under-resolution detector (a resolved field's tail decays; energy
  piling up at the grid cutoff precedes the blow-ups the divergence
  sentinel only sees later). Registered only when the innermost axis
  is unsharded (the rFFT is a local op there).

Per-solver additions come from ``SolverBase.diagnostics_spec()``:
diffusion registers the maximum-principle rule (pure diffusion with
clamped boundaries can create no new extremum), WENO Burgers the
TV-monotonicity rule (essentially non-oscillatory ⇒ total variation
bounded by the initial data's), and the Gaussian-diffusion workload the
analytic amplitude decay rate ``-d/2`` the measured fit
(:func:`gaussian_decay_fit`) reads against.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Observable:
    """One fused diagnostic: device-side scalar contributions + the
    host-side mapping to named physical quantities.

    ``local(u)`` runs inside the probe's jitted block on the f32
    shard-local field and returns a ``(len(keys),)`` vector; all of an
    observable's scalars share one ``reduction`` ("sum" via
    ``mesh_reduce_sum``, "max" via ``mesh_reduce_max``). ``finalize``
    maps the dict of globally-reduced raw scalars to the dict of final
    values (volume scaling, derived ratios); default = identity on
    ``keys``."""

    name: str
    keys: Tuple[str, ...]
    reduction: str  # "sum" | "max"
    local: Callable
    finalize: Optional[Callable] = None  # (solver, raw: dict) -> dict
    # names of the FINALIZED values (what lands in stats/trajectories);
    # None = same as ``keys`` (identity finalize / per-key scaling)
    outputs: Optional[Tuple[str, ...]] = None

    @property
    def output_keys(self) -> Tuple[str, ...]:
        return self.outputs if self.outputs is not None else self.keys

    def finalize_raw(self, solver, raw: Dict[str, float]) -> Dict[str, float]:
        if self.finalize is None:
            return {k: raw[k] for k in self.keys}
        return self.finalize(solver, raw)


@dataclasses.dataclass(frozen=True)
class ViolationRule:
    """Host-side tolerance check of finalized stats vs the armed
    baseline. ``check(stats, baseline, tolerance)`` returns a violation
    message, or ``None`` when the invariant holds."""

    name: str
    tolerance: float
    check: Callable


# --------------------------------------------------------------------- #
# The standard fused observable suite
# --------------------------------------------------------------------- #
def _tv_local(u):
    """Shard-local total variation: sum over axes of |forward diff|."""
    import jax.numpy as jnp

    tv = jnp.zeros((), jnp.float32)
    for ax in range(u.ndim):
        tv = tv + jnp.sum(jnp.abs(jnp.diff(u, axis=ax)))
    return jnp.stack([tv])


def _spectral_local(u):
    """Spectral energy (total, high-wavenumber tail) along the innermost
    axis — |rfft|² summed over the top third of wavenumbers and over
    everything; the ratio is derived host-side from the two psums."""
    import jax.numpy as jnp

    spec = jnp.abs(jnp.fft.rfft(u, axis=-1)) ** 2
    k = spec.shape[-1]
    cut = max(1, (2 * k) // 3)
    return jnp.stack(
        [jnp.sum(spec), jnp.sum(spec[..., cut:])]
    ).astype(jnp.float32)


def standard_observables(solver) -> List[Observable]:
    """The suite every solver gets; per-solver extras ride
    ``diagnostics_spec()['observables']``."""
    vol = math.prod(solver.grid.spacing)

    def _vol_scale(key):
        def fin(_solver, raw, _k=key, _v=vol):
            return {_k: _v * raw[_k]}

        return fin

    def _l1_local(u):
        import jax.numpy as jnp

        return jnp.stack([jnp.sum(jnp.abs(u))])

    def _energy_local(u):
        import jax.numpy as jnp

        return jnp.stack([jnp.sum(u * u)])

    def _spec_finalize(_solver, raw):
        total = raw["spec_total"]
        tail = raw["spec_hi"]
        ratio = tail / total if total > 0 and math.isfinite(total) else 0.0
        return {"spectral_tail": ratio}

    obs = [
        Observable("l1", ("l1",), "sum", _l1_local, _vol_scale("l1")),
        Observable("energy", ("energy",), "sum", _energy_local,
                   _vol_scale("energy")),
        Observable("tv", ("tv",), "sum", _tv_local),
    ]
    # the rFFT is local only along an unsharded axis; skip the detector
    # (rather than gather) when the innermost axis is decomposed
    innermost = solver.grid.ndim - 1
    if innermost not in solver._sharded_axes() and (
        solver.grid.shape[-1] >= 8
    ):
        obs.append(
            Observable("spectral", ("spec_total", "spec_hi"), "sum",
                       _spectral_local, _spec_finalize,
                       outputs=("spectral_tail",))
        )
    return obs


def observables_for(solver) -> List[Observable]:
    """The fused diagnostic suite for one solver: the standard set plus
    whatever ``solver.diagnostics_spec()`` registers."""
    spec = diagnostics_spec(solver)
    return standard_observables(solver) + list(spec.get("observables", ()))


def diagnostics_spec(solver) -> dict:
    spec = getattr(solver, "diagnostics_spec", None)
    return spec() if callable(spec) else {}


def rules_for(solver) -> List[ViolationRule]:
    return list(diagnostics_spec(solver).get("rules", ()))


def meta_for(solver) -> dict:
    """Per-solver fields riding every ``phys:diag`` event (solver class,
    ndim, the analytic decay rate where one exists) — what the trace
    analyzer's physics section keys its fits on. ``storage_dtype``
    records the precision the state was STORED at (ISSUE 16): the
    science gate (``diagnostics/compare``) widens its tolerance bands
    per storage dtype, so a bf16-storage round is judged against bf16
    truncation, never against f32 round-off."""
    meta = {"solver": type(solver).__name__, "ndim": solver.grid.ndim}
    storage = getattr(solver, "storage_dtype", None)
    if storage is not None:
        meta["storage_dtype"] = str(storage)
    meta.update(diagnostics_spec(solver).get("meta", {}))
    return meta


# --------------------------------------------------------------------- #
# Violation rules
# --------------------------------------------------------------------- #
def max_principle_rule(tolerance: float = 1e-3) -> ViolationRule:
    """Pure diffusion with clamped/zero-gradient boundaries satisfies
    the discrete maximum principle up to the 4th-order stencil's
    non-monotone wiggle: no new global extremum beyond the initial
    field's, within ``tolerance`` of the initial range."""

    def check(stats, baseline, tol):
        scale = max(
            1.0, abs(baseline.get("max", 0.0)), abs(baseline.get("min", 0.0))
        )
        band = tol * scale
        if stats["max"] > baseline["max"] + band:
            return (
                f"maximum principle: max {stats['max']:.6g} exceeds "
                f"initial max {baseline['max']:.6g} + {band:.3g}"
            )
        if stats["min"] < baseline["min"] - band:
            return (
                f"maximum principle: min {stats['min']:.6g} undercuts "
                f"initial min {baseline['min']:.6g} - {band:.3g}"
            )
        return None

    return ViolationRule("max_principle", tolerance, check)


def positivity_rule(tolerance: float = 1e-3) -> ViolationRule:
    """Nonnegative initial data stays nonnegative under
    advection–diffusion with a monotone advective flux and K(x) > 0
    (linear decay only shrinks it) — up to the O4 diffusive stencil's
    non-monotone wiggle, hence the tolerance band. Vacuous for signed
    initial data (the max-principle rule covers it there)."""

    def check(stats, baseline, tol):
        if baseline.get("min", 0.0) < 0.0:
            return None  # signed data: positivity is not a property
        scale = max(1.0, abs(baseline.get("max", 0.0)))
        if stats["min"] < -tol * scale:
            return (
                f"positivity: min {stats['min']:.6g} fell below "
                f"-{tol * scale:.3g} from nonnegative initial data"
            )
        return None

    return ViolationRule("positivity", tolerance, check)


def tv_monotone_rule(tolerance: float = 0.05) -> ViolationRule:
    """WENO on a scalar conservation law is essentially non-oscillatory:
    total variation stays bounded by the initial data's (the 'E' in
    ENO). Growth past ``tolerance`` (relative) means spurious
    oscillation — the regression the smooth-case convergence order
    cannot see."""

    def check(stats, baseline, tol):
        tv0 = baseline.get("tv")
        tv = stats.get("tv")
        if tv0 is None or tv is None:
            return None
        bound = tv0 * (1.0 + tol) + 1e-12
        if tv > bound:
            return (
                f"TV monotonicity: total variation {tv:.6g} grew past "
                f"the initial {tv0:.6g} (+{100 * tol:.1f}% tolerance)"
            )
        return None

    return ViolationRule("tv_monotone", tolerance, check)


def check_violations(
    rules: Sequence[ViolationRule], stats: dict, baseline: Optional[dict]
) -> List[dict]:
    """Evaluate every rule; returns violation records (empty = clean)."""
    if not baseline:
        return []
    out = []
    for rule in rules:
        msg = rule.check(stats, baseline, rule.tolerance)
        if msg:
            out.append(
                {"rule": rule.name, "message": msg,
                 "tolerance": rule.tolerance}
            )
    return out


# --------------------------------------------------------------------- #
# Gaussian-diffusion decay-rate fit
# --------------------------------------------------------------------- #
def gaussian_decay_fit(
    times: Sequence[float], maxima: Sequence[float],
    analytic_rate: Optional[float] = None,
) -> Optional[dict]:
    """Least-squares slope of ``log(max u)`` vs ``log t`` over a
    diagnostic trajectory.

    The heat-kernel workload's exact amplitude is
    ``(t0/t)^{d/2}`` — a straight line of slope ``-d/2`` in log-log —
    so the fitted slope is a *measured* decay rate read directly
    against the analytic one (the machine-checked version of the
    ``Run.m`` harness eyeballing the decaying field plots). ``None``
    when fewer than 3 usable (t>0, max>0) points exist."""
    pts = [
        (math.log(t), math.log(m))
        for t, m in zip(times, maxima)
        if t > 0 and m > 0 and math.isfinite(m)
    ]
    if len(pts) < 3:
        return None
    n = len(pts)
    mx = sum(x for x, _ in pts) / n
    my = sum(y for _, y in pts) / n
    var = sum((x - mx) ** 2 for x, _ in pts)
    if var <= 0:
        return None
    cov = sum((x - mx) * (y - my) for x, y in pts)
    slope = cov / var
    out = {"measured_rate": slope, "points": n}
    if analytic_rate is not None:
        out["analytic_rate"] = float(analytic_rate)
        out["rel_err"] = abs(slope - analytic_rate) / max(
            abs(analytic_rate), 1e-30
        )
    return out
