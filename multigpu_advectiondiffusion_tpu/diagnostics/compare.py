"""Science regression gate: diff diagnostic trajectories between rounds.

``bench/compare.py`` gates *throughput* between rounds; nothing gated
the *numerics* — a perturbed stencil coefficient, a wrong dt, a broken
flux split can leave MLUPS (and even smooth-case convergence order)
intact while silently changing the physics. This module is the
numerics gate: it diffs the per-observable diagnostic trajectories two
rounds recorded (the supervisor's ``phys:diag`` suite, landed in
``summary.json``'s ``diagnostics`` block) with per-observable relative
tolerance bands, and exits nonzero on drift.

Artifact format (produced by ``--extract`` from one or more
``summary.json`` files; the committed rounds are ``SCIENCE_r0*.json``)::

    {"schema": 1,
     "runs": {"diffusion3d": {
         "meta": {"solver": "DiffusionSolver", "ndim": 3, ...},
         "observables": {"mass": [[step, value], ...], ...}}}}

Comparison: trajectories align on common step indices; per observable
the deviation is ``max_t |new - old| / max_t |old|`` (trajectory-scale
relative — robust near zero crossings) and must sit inside the
observable's band (:data:`TOLERANCE_BANDS`, default
:data:`DEFAULT_BAND`). A run or observable present in the old round but
absent from the new one is a coverage regression and fails; new ones
are reported as ``added`` and never fail. ``time`` is itself an
observable — a dt change drifts the time trajectory at fixed step
indices and trips the gate even when the fields look plausible.

Usage::

    python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \\
        --extract run_a/summary.json run_b/summary.json -o NEW.json
    python -m multigpu_advectiondiffusion_tpu.diagnostics.compare \\
        NEW.json SCIENCE_r01.json

Wrapper: ``out/science_gate.sh`` (canonical rounds + the
injected-perturbation self-test).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

ARTIFACT_SCHEMA = 1

#: Per-observable relative tolerance bands. Conserved quantities sit at
#: round-off; decaying budgets and the TV/spectral detectors get wider
#: bands (platform-dependent reduction order, f32 accumulation).
TOLERANCE_BANDS: Dict[str, float] = {
    "mass": 1e-6,
    "time": 1e-6,
    "l1": 1e-4,
    "l2": 1e-4,
    "energy": 1e-4,
    "max_abs": 1e-4,
    "max": 1e-4,
    "min": 1e-3,
    "tv": 1e-3,
    "spectral_tail": 5e-3,
}
DEFAULT_BAND = 1e-3

#: Per-STORAGE-dtype tolerance bands (ISSUE 16): a run whose state was
#: stored in bfloat16 (``precision='bf16'`` — the run meta carries
#: ``storage_dtype``) truncates every field to an 8-bit mantissa per
#: step, so cross-round deviations sit at a few bf16 round-offs
#: (~4e-3), not f32's 1e-7 — judging such a round against the f32
#: bands would fail every healthy run, and judging f32 rounds against
#: bf16 bands would wave real drift through. ``time`` keeps its tight
#: band on purpose: dt arithmetic stays f32 under bf16 storage, so a
#: drifting time trajectory is a schedule bug at ANY storage
#: precision. Explicit ``--band`` overrides still win.
STORAGE_TOLERANCE_BANDS: Dict[str, Dict[str, float]] = {
    "bfloat16": {
        "mass": 5e-3,
        "time": 1e-6,
        "l1": 2e-2,
        "l2": 2e-2,
        "energy": 2e-2,
        "max_abs": 2e-2,
        "max": 2e-2,
        "min": 5e-2,
        "tv": 5e-2,
        "spectral_tail": 1e-1,
    },
}
STORAGE_DEFAULT_BAND: Dict[str, float] = {"bfloat16": 5e-2}

#: Observables excluded from gating: ``mass_drift`` is the difference
#: of two near-equal numbers (its relative scale is meaningless — the
#: ``mass`` trajectory itself gates conservation).
SKIP_OBSERVABLES = {"mass_drift"}


# --------------------------------------------------------------------- #
# Extraction: summary.json -> round artifact
# --------------------------------------------------------------------- #
def extract_run(summary: dict) -> Optional[dict]:
    """One summary.json dict -> a run entry, or ``None`` when the run
    recorded no diagnostics (unsupervised / --diag-every absent)."""
    diag = summary.get("diagnostics") or (
        (summary.get("resilience") or {}).get("diagnostics")
    )
    if not diag or not diag.get("trajectory"):
        return None
    observables: Dict[str, List[list]] = {}
    for point in diag["trajectory"]:
        step = point.get("step")
        if step is None:
            continue
        for key, value in point.items():
            if key == "step" or key in SKIP_OBSERVABLES:
                continue
            if isinstance(value, (int, float)):
                observables.setdefault(key, []).append(
                    [int(step), float(value)]
                )
    if not observables:
        return None
    return {"meta": dict(diag.get("meta") or {}), "observables": observables}


def extract(summary_paths: List[str]) -> dict:
    """Several runs' summary.json files -> one round artifact."""
    runs = {}
    for path in summary_paths:
        with open(path) as f:
            summary = json.load(f)
        entry = extract_run(summary)
        if entry is None:
            raise SystemExit(
                f"{path}: no diagnostic trajectory (run it supervised "
                "with --sentinel-every and --diag-every)"
            )
        runs[summary.get("name", path)] = entry
    return {"schema": ARTIFACT_SCHEMA, "runs": runs}


def load_round(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "runs" not in obj:
        raise SystemExit(f"{path}: not a science-round artifact")
    return obj


# --------------------------------------------------------------------- #
# Comparison
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class GateRow:
    run: str
    observable: str
    status: str  # ok | drift | missing | added | no_overlap
    deviation: Optional[float] = None
    band: Optional[float] = None
    steps: int = 0

    def line(self) -> str:
        name = f"{self.run}/{self.observable}"
        if self.status in ("missing", "added", "no_overlap"):
            return f"  {self.status.upper():>10}  {name}"
        tag = "DRIFT" if self.status == "drift" else "ok"
        return (
            f"  {tag:>10}  {name}: max rel deviation "
            f"{self.deviation:.3e} (band {self.band:.1e}, "
            f"{self.steps} step(s))"
        )


@dataclasses.dataclass
class GateResult:
    rows: List[GateRow]
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def regressions(self) -> List[GateRow]:
        return [r for r in self.rows
                if r.status in ("drift", "missing", "no_overlap")]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "rows": [dataclasses.asdict(r) for r in self.rows],
            "notes": list(self.notes),
        }

    def format_text(self) -> str:
        lines = ["science gate:"]
        lines += [r.line() for r in self.rows]
        for note in self.notes:
            lines.append(f"        note  {note}")
        lines.append(
            "science gate: PASS"
            if self.ok
            else f"science gate: FAIL ({len(self.regressions)} "
                 "regression(s))"
        )
        return "\n".join(lines)


def _band_for(observable: str, bands: Dict[str, float],
              default_band: float) -> float:
    return bands.get(observable, default_band)


def _storage_dtype(*entries: Optional[dict]) -> Optional[str]:
    """The storage dtype a run's state lived in, from the diagnostics
    meta either round recorded (new wins — it reflects the config
    under test). ``None`` = native storage (compute dtype)."""
    for entry in entries:
        dtype = ((entry or {}).get("meta") or {}).get("storage_dtype")
        if dtype is not None:
            return str(dtype)
    return None


def _bands_for_run(
    storage: Optional[str],
    overrides: Optional[Dict[str, float]],
    default_band: float,
) -> Tuple[Dict[str, float], float]:
    """Resolve the (band table, default) for one run. Precedence per
    observable: explicit ``--band`` override > the storage dtype's
    table (:data:`STORAGE_TOLERANCE_BANDS`) > the base f32 bands."""
    bands = dict(TOLERANCE_BANDS)
    if storage in STORAGE_TOLERANCE_BANDS:
        bands.update(STORAGE_TOLERANCE_BANDS[storage])
        default_band = STORAGE_DEFAULT_BAND.get(storage, default_band)
    bands.update(overrides or {})
    return bands, default_band


def compare(
    new_round: dict,
    old_round: dict,
    bands: Optional[Dict[str, float]] = None,
    default_band: float = DEFAULT_BAND,
) -> GateResult:
    """Per-(run, observable) trajectory diff of two rounds."""
    overrides = dict(bands or {})
    rows: List[GateRow] = []
    notes: List[str] = []
    old_runs = old_round.get("runs", {})
    new_runs = new_round.get("runs", {})
    for run in sorted(set(old_runs) | set(new_runs)):
        old = old_runs.get(run)
        new = new_runs.get(run)
        if old is None:
            rows.append(GateRow(run, "*", "added"))
            continue
        if new is None:
            rows.append(GateRow(run, "*", "missing"))
            continue
        storage = _storage_dtype(new, old)
        run_bands, run_default = _bands_for_run(
            storage, overrides, default_band
        )
        if storage in STORAGE_TOLERANCE_BANDS:
            notes.append(
                f"{run}: {storage} storage — per-dtype tolerance "
                "bands in effect"
            )
        old_obs = old.get("observables", {})
        new_obs = new.get("observables", {})
        for obs in sorted(set(old_obs) | set(new_obs)):
            if obs in SKIP_OBSERVABLES:
                continue
            if obs not in old_obs:
                notes.append(f"{run}/{obs}: new observable (added)")
                continue
            if obs not in new_obs:
                rows.append(GateRow(run, obs, "missing"))
                continue
            old_t = {int(s): float(v) for s, v in old_obs[obs]}
            new_t = {int(s): float(v) for s, v in new_obs[obs]}
            common = sorted(set(old_t) & set(new_t))
            if not common:
                rows.append(GateRow(run, obs, "no_overlap"))
                continue
            scale = max(abs(old_t[s]) for s in common)
            dev = max(abs(new_t[s] - old_t[s]) for s in common) / max(
                scale, 1e-30
            )
            band = _band_for(obs, run_bands, run_default)
            rows.append(
                GateRow(
                    run, obs,
                    "drift" if dev > band else "ok",
                    deviation=round(dev, 10), band=band,
                    steps=len(common),
                )
            )
    result = GateResult(rows, notes=notes)
    # the verdict is itself telemetry when a sink is installed (the
    # soak/CI hook's stream records every gate run it performed)
    from multigpu_advectiondiffusion_tpu import telemetry

    sink = telemetry.get_sink()
    if sink.active:
        sink.event(
            "science", "gate",
            ok=result.ok, regressions=len(result.regressions),
            rows=len(result.rows),
        )
    return result


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #
def main(argv=None) -> None:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="multigpu_advectiondiffusion_tpu.diagnostics.compare",
        description="science regression gate: diff diagnostic "
                    "trajectories between rounds (nonzero exit on "
                    "drift)",
    )
    ap.add_argument("new", nargs="?", default=None,
                    help="fresh round artifact (see --extract)")
    ap.add_argument("old", nargs="?", default=None,
                    help="prior round to diff against (e.g. the newest "
                         "SCIENCE_r0*.json)")
    ap.add_argument("--extract", nargs="+", default=None,
                    metavar="SUMMARY",
                    help="build a round artifact from one or more "
                         "summary.json files (runs recorded with "
                         "--diag-every) instead of comparing")
    ap.add_argument("-o", "--out", default=None, metavar="PATH",
                    help="write the extracted artifact (with --extract) "
                         "or the JSON result (compare mode) to PATH")
    ap.add_argument("--band", action="append", default=[],
                    metavar="OBS=TOL",
                    help="override one observable's relative tolerance "
                         "band (repeatable)")
    ap.add_argument("--default-band", type=float, default=DEFAULT_BAND,
                    help="band for observables without a specific entry "
                         f"(default {DEFAULT_BAND})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    args = ap.parse_args(argv)

    if args.extract:
        if args.new is not None or args.old is not None:
            ap.error("--extract takes summary.json paths only")
        artifact = extract(args.extract)
        text = json.dumps(artifact, indent=1)
        if args.out:
            from multigpu_advectiondiffusion_tpu.utils.io import (
                atomic_write_text,
            )

            atomic_write_text(args.out, text + "\n")
            print(
                f"science round: {len(artifact['runs'])} run(s) -> "
                f"{args.out}"
            )
        else:
            print(text)
        return

    if not args.new or not args.old:
        ap.error("provide NEW and OLD round artifacts (or --extract)")
    bands = {}
    for spec in args.band:
        name, _, val = spec.partition("=")
        try:
            bands[name.strip()] = float(val)
        except ValueError:
            ap.error(f"bad --band {spec!r} (want OBS=TOL)")
    result = compare(
        load_round(args.new), load_round(args.old),
        bands=bands, default_band=args.default_band,
    )
    if args.out:
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        atomic_write_text(args.out, json.dumps(result.to_dict(), indent=2))
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
    else:
        print(result.format_text())
    if not result.ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
