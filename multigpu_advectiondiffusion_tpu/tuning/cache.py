"""Persisted tuning decisions: an atomic JSON key-value store.

The autotuner's measurements are expensive (seconds of device time per
key) and its decisions must be *reproducible*: the same
``(solver, shape, dtype, mesh, backend)`` key resolves to the same rung
and exchange cadence on every later run, without re-measurement, until
the cache is deleted or re-tuned. That makes the file itself the
artifact: one JSON object per key, with full candidate provenance, so a
published bench rate can be audited back to the measurements that
selected its configuration.

Writes are atomic (tempfile + ``os.replace`` in the destination
directory, same discipline as ``utils/io.py`` checkpoints and
``RunSummary.write_json``) and read-modify-write under a process-local
lock; a corrupt or truncated file is treated as empty rather than
poisoning every later run.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

CACHE_SCHEMA = 1

# default location; TPUCFD_TUNING_CACHE / --tuning-cache override
_DEFAULT_PATH = os.path.join(
    "~", ".cache", "multigpu_advectiondiffusion_tpu", "tuning.json"
)


def default_path() -> str:
    env = os.environ.get("TPUCFD_TUNING_CACHE")
    return env if env else os.path.expanduser(_DEFAULT_PATH)


class TuningCache:
    """Atomic JSON decision store, keyed by the autotuner's key string."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_path()
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ #
    def _read(self) -> dict:
        try:
            with open(self.path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return {}
        except (json.JSONDecodeError, OSError, ValueError):
            # corrupt/truncated cache: a miss, not a crash — the next
            # decision rewrites the file atomically
            return {}
        if not isinstance(data, dict) or data.get("schema") != CACHE_SCHEMA:
            return {}
        entries = data.get("entries")
        return entries if isinstance(entries, dict) else {}

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            entry = self._read().get(key)
        return dict(entry) if isinstance(entry, dict) else None

    def put(self, key: str, decision: dict) -> None:
        """Read-modify-write with an atomic replace; concurrent writers
        last-write-win per key but never leave a torn file."""
        with self._lock:
            entries = self._read()
            entries[key] = decision
            payload = {"schema": CACHE_SCHEMA, "entries": entries}
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=d, prefix=".tuning_", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            finally:
                if os.path.exists(tmp):  # replace failed
                    os.unlink(tmp)

    def entries(self) -> dict:
        with self._lock:
            return self._read()
