"""Measured autotuned dispatch (``impl="auto"``).

The subsystem behind the ``--tune`` / ``--tuning-cache`` CLI surface:

* :mod:`autotuner` — candidate enumeration over (stepper rung x
  communication-avoiding exchange cadence k), cost-model pruning,
  median-of-reps measurement, ``tune:*`` telemetry;
* :mod:`cache` — the atomic, persisted JSON decision store that makes
  ``impl="auto"`` reproducible: one measurement per key, every later
  run resolves from disk.

``resolve`` is the dispatch entry point (``models/base.SolverBase``
calls it when ``cfg.impl == "auto"``):

* cache hit -> the persisted decision, no device time;
* miss with tuning enabled (:func:`configure` ``enabled=True``, the
  CLI's ``--tune``, or ``TPUCFD_TUNE=1``) -> measure, persist, return;
* miss with tuning disabled -> the best-available heuristic
  (``impl="pallas"``, per-step cadence) plus a ``tune:fallback`` event —
  auto never blocks a run on measurement the user didn't ask for.
"""

from __future__ import annotations

import os
from typing import Optional

from multigpu_advectiondiffusion_tpu.tuning import aot_cache  # noqa: F401
from multigpu_advectiondiffusion_tpu.tuning import autotuner  # noqa: F401
from multigpu_advectiondiffusion_tpu.tuning.autotuner import (  # noqa: F401
    autotune,
    candidates,
    ensemble_candidates,
    make_key,
    measure_candidate,
    measure_ensemble_candidate,
    modeled_step_seconds,
)
from multigpu_advectiondiffusion_tpu.tuning.cache import (  # noqa: F401
    TuningCache,
    default_path,
)

__all__ = [
    "TuningCache",
    "aot_cache",
    "autotune",
    "candidates",
    "configure",
    "default_path",
    "ensemble_candidates",
    "make_key",
    "measure_candidate",
    "measure_ensemble_candidate",
    "modeled_step_seconds",
    "resolve",
    "tuning_enabled",
]

# process-wide tuner configuration (the CLI/bench surface writes it
# before building solvers; env vars override nothing set explicitly)
_state = {
    "path": None,       # cache file; None -> cache.default_path()
    "enabled": None,    # measure on miss; None -> TPUCFD_TUNE env
    "iters": None,      # measurement iterations; None -> TPUCFD_TUNE_ITERS
    "reps": None,       # timing repetitions; None -> TPUCFD_TUNE_REPS
    "prune_ratio": None,  # None -> TPUCFD_TUNE_PRUNE
}


def configure(
    cache_path: Optional[str] = None,
    enabled: Optional[bool] = None,
    measure_iters: Optional[int] = None,
    measure_reps: Optional[int] = None,
    prune_ratio: Optional[float] = None,
) -> None:
    """Set the process-wide tuner knobs; ``None`` leaves a knob as-is."""
    if cache_path is not None:
        _state["path"] = cache_path
    if enabled is not None:
        _state["enabled"] = bool(enabled)
    if measure_iters is not None:
        _state["iters"] = int(measure_iters)
    if measure_reps is not None:
        _state["reps"] = int(measure_reps)
    if prune_ratio is not None:
        _state["prune_ratio"] = float(prune_ratio)


def tuning_enabled() -> bool:
    if _state["enabled"] is not None:
        return _state["enabled"]
    return os.environ.get("TPUCFD_TUNE", "").lower() in ("1", "true", "yes")


def cache_path() -> str:
    return _state["path"] or default_path()


def _measure_params():
    iters = _state["iters"] or autotuner._env_int("TPUCFD_TUNE_ITERS", 12)
    reps = _state["reps"] or autotuner._env_int("TPUCFD_TUNE_REPS", 3)
    prune = _state["prune_ratio"] or float(
        os.environ.get("TPUCFD_TUNE_PRUNE", "2.0")
    )
    return max(1, iters), max(1, reps), prune


def resolve(solver_cls, cfg, mesh, decomp, ensemble: int = 1) -> dict:
    """Resolve ``impl="auto"`` for one solver construction; see the
    module docstring for the hit/miss/disabled contract. ``ensemble``
    is the batched-engine member count — part of the key, so a B=64
    decision is never served to a B=1 run (and vice versa)."""
    import jax

    backend = jax.default_backend()
    key = make_key(solver_cls, cfg, mesh, decomp, backend,
                   ensemble=ensemble)
    cache = TuningCache(cache_path())
    hit = cache.get(key)
    autotuner._emit("lookup", key=key, hit=hit is not None,
                    cache=cache.path)
    if hit is not None:
        hit["source"] = "cache"
        return hit
    if not tuning_enabled():
        decision = {
            "impl": "pallas",
            "steps_per_exchange": 1,
            "exchange": "collective",
            "source": "untuned-heuristic",
            "key": key,
        }
        autotuner._emit(
            "fallback", key=key, impl="pallas",
            reason="no cached decision and tuning not enabled "
                   "(--tune / TPUCFD_TUNE=1)",
        )
        return decision
    iters, reps, prune = _measure_params()
    return autotune(solver_cls, cfg, mesh, decomp, cache, key,
                    iters, reps, prune, ensemble=ensemble)
