"""Measured rung + exchange-cadence selection (``impl="auto"``).

PR 1 left the stepper ladder's top-rung selection to a deliberately
conservative static model ("deep grids keep the measured per-stage
default until a TPU session measures the slab rung"), and the
communication-avoiding k-step schedule adds a second axis — exchange
cadence — no static model prices credibly across interconnects. This
module replaces that last hand-tuned heuristic with *measurement*:

1. build the candidate list for the config's ``(rung, k)`` space —
   ``fused-stage`` at the per-step cadence plus the slab rung at every
   k the shard can serve;
2. seed with the PR 3 cost model (``telemetry/costmodel``): modeled
   step time = max(HBM, FLOP) roofline x the deep-halo recompute factor
   + the exchange latency/bandwidth term — candidates far off the
   modeled best are pruned before any device time is spent. The peak
   rates behind that roofline consult the measured calibration record
   (``telemetry/calibration.py``) ahead of the env-assumed defaults,
   so once any run has demonstrated real bandwidth on this rig the
   pruning runs on measured rather than assumed peaks (the
   ``tune:candidates`` event carries the provenance);
3. time the survivors with the bench harness's own ``timed_run``
   (median-of-reps, same sync discipline as every published number);
4. persist the winner to the atomic JSON cache (``tuning/cache.py``),
   keyed by ``(solver, shape, dtype, mesh, backend)`` — the same key
   resolves to the same decision forever after, without re-measurement.

Every lookup, measurement, pruning and decision is a ``tune:*``
telemetry event, so a tuned bench row is auditable from the stream.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

from multigpu_advectiondiffusion_tpu.telemetry import costmodel
from multigpu_advectiondiffusion_tpu.tuning.cache import TuningCache

# candidate chunk lengths for the comm-avoiding schedule (1 = per-step)
K_CANDIDATES = (1, 2, 4, 8)


def _emit(name: str, **fields) -> None:
    from multigpu_advectiondiffusion_tpu import telemetry

    telemetry.event("tune", name, **fields)


def _fused_halo(kind: str, cfg) -> int:
    """Per-step fused ghost depth G = 3h of the config's stencil,
    resolved through the registry's ``stage_radius`` hook (legacy
    literal fallback for unregistered config doubles)."""
    from multigpu_advectiondiffusion_tpu.models import registry

    spec = registry.spec_for_config(cfg)
    if spec is not None and spec.stage_radius is not None:
        return 3 * int(spec.stage_radius(cfg))
    if kind == "diffusion":
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R

        return 3 * R
    from multigpu_advectiondiffusion_tpu.ops.weno import HALO

    return 3 * HALO[getattr(cfg, "weno_order", 5)]


def _mesh_tokens(mesh, decomp):
    if mesh is None:
        return "mesh=1"
    sizes = ",".join(f"{n}:{s}" for n, s in mesh.shape.items())
    if decomp is None:
        # members-only ensemble meshes carry no spatial decomposition
        return f"mesh={sizes};decomp=-"
    axes = ",".join(
        f"{ax}:{'|'.join(nm) if isinstance(nm, tuple) else nm}"
        for ax, nm in decomp.axes
    )
    return f"mesh={sizes};decomp={axes}"


def make_key(solver_cls, cfg, mesh, decomp, backend: str,
             ensemble: int = 1) -> str:
    """The tuning key: everything that changes which ``(rung, k)`` wins.
    Kernel-strategy knobs that the tuner itself decides (impl,
    steps_per_exchange) are excluded; physics scalars that do not change
    kernel structure (diffusivity value, flux params) are too.
    ``ensemble`` is the batched-engine member count B — a B=64 decision
    (amortized dispatch, different winning rung economics) must never
    be served to a B=1 run, so it is a first-class key dimension."""
    kind = costmodel.solver_kind(cfg) or type(cfg).__name__
    shape = "x".join(map(str, cfg.grid.shape))
    parts = [
        solver_cls.__name__,
        kind,
        f"shape={shape}",
        f"dtype={cfg.dtype}",
        # storage precision (ISSUE 16): a bf16-storage decision (half
        # the HBM/wire bytes — different winning rung economics) must
        # never be served to a native-precision run, and vice versa
        f"prec={getattr(cfg, 'precision', 'native') or 'native'}",
        f"integ={cfg.integrator}",
        f"overlap={getattr(cfg, 'overlap', None)}",
        _mesh_tokens(mesh, decomp),
        f"backend={backend}",
        f"ens={max(1, int(ensemble))}",
    ]
    from multigpu_advectiondiffusion_tpu.models import registry

    spec = registry.spec_for_config(cfg)
    if spec is not None and spec.key_extras is not None:
        # family-specific key parts come from the registration spec —
        # a third model brings its own, never edits this function
        parts += [str(p) for p in spec.key_extras(cfg)]
    elif kind == "burgers":
        parts += [
            f"weno={cfg.weno_order}-{cfg.weno_variant}",
            f"adaptive={bool(cfg.adaptive_dt)}",
            f"viscous={bool(getattr(cfg, 'nu', 0.0))}",
        ]
    elif kind == "diffusion":
        parts += [
            f"order={getattr(cfg, 'order', 4)}",
            f"geom={getattr(cfg, 'geometry', 'cartesian')}",
        ]
    return "|".join(parts)


def _zslab_only(solver) -> bool:
    sharded = solver._sharded_axes()
    return bool(sharded) and all(ax == 0 for ax in sharded)


def candidates(solver_cls, cfg, mesh, decomp) -> list:
    """``[{"impl", "steps_per_exchange"}, ...]`` the config can engage.

    A probe solver (impl="pallas") answers the eligibility questions the
    dispatch layer already owns — the tuner never re-implements VMEM /
    dtype / decomposition gates, it asks them."""
    probe = solver_cls(
        dataclasses.replace(
            cfg, impl="pallas", steps_per_exchange=1,
            exchange="collective",
        ),
        mesh=mesh,
        decomp=decomp,
    )
    kind = costmodel.solver_kind(cfg)
    out = [{"impl": "pallas", "steps_per_exchange": 1,
            "exchange": "collective"}]
    fused = probe._fused_stepper()
    if fused is None or probe.grid.ndim != 3 or kind is None:
        return out  # heuristic best-available is the only candidate
    fixed_dt = not getattr(cfg, "adaptive_dt", False)
    out = [{"impl": "pallas_stage", "steps_per_exchange": 1,
            "exchange": "collective"}]
    slab_ok = fixed_dt
    if slab_ok:
        # slab eligibility via the dispatch's own gate: a pinned probe
        # either engages the slab rung or raises/declines
        try:
            pin = solver_cls(
                dataclasses.replace(
                    cfg, impl="pallas_slab", steps_per_exchange=1,
                    exchange="collective",
                ),
                mesh=mesh,
                decomp=decomp,
            )
            slab_ok = (
                pin.engaged_path()["stepper"] == "fused-whole-run-slab"
            )
        except ValueError:
            slab_ok = False
    if not slab_ok:
        return out
    out.append({"impl": "pallas_slab", "steps_per_exchange": 1,
                "exchange": "collective"})
    if mesh is not None and _zslab_only(probe):
        lz = probe.decomp.local_shape(mesh, cfg.grid.shape)[0]
        G = _fused_halo(kind, cfg)
        for k in K_CANDIDATES[1:]:
            if lz >= k * G:
                out.append({"impl": "pallas_slab",
                            "steps_per_exchange": k,
                            "exchange": "collective"})
        # in-kernel remote-DMA rung (exchange='dma'): eligibility is
        # asked from the dispatch's own gates (backend, single-axis
        # mesh, uniform dma block viability) by constructing a pinned
        # probe per servable cadence — a raise means the combo cannot
        # engage. The rung has no credible static cost model (its
        # point is comm/compute overlap the roofline cannot see), so
        # it is never pruned: it enters the decision only by WINNING
        # measurements.
        for k in K_CANDIDATES:
            if lz < k * G:
                continue
            try:
                pin = solver_cls(
                    dataclasses.replace(
                        cfg, impl="pallas_slab", steps_per_exchange=k,
                        exchange="dma",
                    ),
                    mesh=mesh,
                    decomp=decomp,
                )
                eng = pin.engaged_path()
            except ValueError:
                continue
            if (
                eng["stepper"] == "fused-whole-run-slab"
                and eng.get("exchange") == "dma"
            ):
                out.append({"impl": "pallas_slab",
                            "steps_per_exchange": k,
                            "exchange": "dma"})
    return out


def modeled_step_seconds(cfg, lshape, cand, devices: int,
                         backend: str) -> Optional[float]:
    """Cost-model seconds for ONE step of one shard under a candidate —
    the pruning metric. None when the model has no opinion (the
    candidate is then never pruned)."""
    import numpy as np

    kind = costmodel.solver_kind(cfg)
    if kind is None:
        return None
    if cand.get("exchange", "collective") == "dma":
        # the in-kernel rung's value is overlap the per-step roofline
        # cannot price; no opinion -> never pruned, always measured
        return None
    stepper = {
        "pallas_slab": "fused-whole-run-slab",
        "pallas_stage": "fused-stage",
    }.get(cand["impl"])
    if stepper is None:
        return None
    kwargs = costmodel.solver_cost_kwargs(cfg)
    itemsize = np.dtype(cfg.dtype).itemsize
    cost = costmodel.step_cost(kind, lshape, itemsize, stepper, **kwargs)
    peak_b, peak_f = costmodel.peak_rates(backend)
    t = max(
        cost.hbm_bytes / peak_b if peak_b else 0.0,
        cost.flops / peak_f if peak_f else 0.0,
    )
    k = cand["steps_per_exchange"]
    G = _fused_halo(kind, cfg)
    if stepper == "fused-whole-run-slab" and k > 1:
        t *= costmodel.deep_halo_recompute_factor(lshape[0], G, k)
    if devices > 1:
        plane = itemsize
        for n in lshape[1:]:
            plane *= n
        if stepper == "fused-stage":
            # one h-deep refresh per RK stage
            h = G // 3
            t += costmodel.halo_exchange_seconds(
                3 * 2 * h * plane, messages=3, backend=backend
            )
        else:
            # one k*G-deep exchange per k steps: same bytes per step,
            # 1/k of the messages — the comm-avoiding tradeoff
            t += costmodel.halo_exchange_seconds(
                2 * G * plane, messages=1.0 / k, backend=backend
            )
    return t


def measure_candidate(solver_cls, cfg, mesh, decomp, cand,
                      iters: int, reps: int) -> dict:
    """Median-of-reps MLUPS of one candidate, via the bench harness's
    own timing discipline (``bench/timing.timed_run``)."""
    from multigpu_advectiondiffusion_tpu.bench.timing import timed_run
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    solver = solver_cls(
        dataclasses.replace(
            cfg,
            impl=cand["impl"],
            steps_per_exchange=cand["steps_per_exchange"],
            exchange=cand.get("exchange", "collective"),
        ),
        mesh=mesh,
        decomp=decomp,
    )
    timing = timed_run(solver, solver.initial_state(), iters, reps=reps)
    rate = mlups(
        cfg.grid.num_cells, iters, STAGES[cfg.integrator],
        timing.median_seconds,
    )
    return {
        "mlups": round(rate, 2),
        "seconds": round(timing.median_seconds, 6),
        "spread": round(timing.spread, 4),
        "engaged": solver.engaged_path()["stepper"],
    }


def ensemble_candidates(solver_cls, cfg, mesh, decomp,
                        members: int) -> list:
    """The rung space the batched engine serves at ``B = members``,
    asked from the dispatch's own eligibility gates (the tuner never
    re-implements them): the generic vmapped rung always serves; the
    fused-stage vmap and the B-folded slab rung serve where an
    unsharded-spatial probe engages them. A members x spatial mesh
    (``decomp`` with real extents) serves the generic rung only —
    spatially sharded fused steppers decline the member axis."""
    out = [{"impl": "xla", "steps_per_exchange": 1}]
    if decomp is not None and bool(decomp.axes):
        return out
    for impl, label in (
        ("pallas_stage", "fused-stage"),
        ("pallas_slab", "fused-whole-run-slab"),
    ):
        try:
            probe = solver_cls(
                dataclasses.replace(cfg, impl=impl, steps_per_exchange=1)
            )
            fused = probe._fused_stepper()
        except ValueError:
            continue
        if fused is not None and fused.engaged_label == label:
            out.append({"impl": impl, "steps_per_exchange": 1})
    return out


def measure_ensemble_candidate(solver_cls, cfg, mesh, decomp, cand,
                               members: int, iters: int,
                               reps: int) -> dict:
    """Median-of-reps MLUPS*members of one candidate MEASURED AT THE
    ACTUAL B — one wall-timed batched dispatch (launch overhead
    included: amortizing it is the point), B identical uniform-physics
    members, under the caller's mesh."""
    import statistics
    import time as _time

    from multigpu_advectiondiffusion_tpu.bench.timing import sync
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    es = EnsembleSolver(
        solver_cls,
        dataclasses.replace(
            cfg, impl=cand["impl"], steps_per_exchange=1
        ),
        members, mesh=mesh, decomp=decomp,
    )
    est = es.initial_state()
    sync(es.run(est, iters).u)  # compile + warm-up, untimed
    times = []
    for _ in range(max(1, reps)):
        t0 = _time.perf_counter()
        sync(es.run(est, iters).u)
        times.append(_time.perf_counter() - t0)
    med = statistics.median(times)
    rate = mlups(
        cfg.grid.num_cells * members, iters, STAGES[cfg.integrator], med
    )
    return {
        "mlups": round(rate, 2),
        "seconds": round(med, 6),
        "spread": round(
            (max(times) - min(times)) / med if med > 0 else 0.0, 4
        ),
        "engaged": es.engaged_path()["stepper"],
    }


def autotune(solver_cls, cfg, mesh, decomp, cache: TuningCache, key: str,
             iters: int, reps: int, prune_ratio: float,
             ensemble: int = 1) -> dict:
    """Measure the pruned candidate space and persist the winner.
    ``ensemble > 1`` measures the BATCHED candidate space at the
    actual B (generic vmap / fused-stage vmap / B-folded slab, under
    the caller's members mesh) — no single-run proxy; every
    ``tune:measure`` row carries the member count."""
    import jax

    backend = jax.default_backend()
    devices = 1 if mesh is None else mesh.devices.size
    if ensemble > 1:
        return _autotune_ensemble(
            solver_cls, cfg, mesh, decomp, cache, key, iters, reps,
            ensemble, backend, devices,
        )
    lshape = (
        cfg.grid.shape
        if mesh is None
        else decomp.local_shape(mesh, cfg.grid.shape)
    )
    cands = candidates(solver_cls, cfg, mesh, decomp)
    best_model = None
    for c in cands:
        t = modeled_step_seconds(cfg, lshape, c, devices, backend)
        c["modeled_us"] = None if t is None else round(t * 1e6, 3)
        if t is not None and (best_model is None or t < best_model):
            best_model = t
    for c in cands:
        # cost-model pruning: never prune the per-step baseline (k=1 on
        # the modeled-best rung family keeps the comparison honest) or
        # model-less candidates
        c["pruned"] = bool(
            best_model is not None
            and c["modeled_us"] is not None
            and c["steps_per_exchange"] > 1
            and c["modeled_us"] > prune_ratio * best_model * 1e6
        )
    _emit(
        "candidates", key=key,
        # pruning-peak provenance: modeled_us was computed against
        # these rates — "calibrated" means a measured peak
        # (telemetry/calibration.py) replaced the env/default
        # assumption, i.e. the tuner pruned with measured numbers
        peaks=costmodel.peak_info(backend),
        considered=[
            {k: c.get(k) for k in ("impl", "steps_per_exchange",
                                   "exchange", "modeled_us", "pruned")}
            for c in cands
        ],
    )
    live = [c for c in cands if not c["pruned"]]
    measured = []
    if len(live) == 1:
        choice = dict(live[0])
        choice["source"] = "static"  # nothing to race: no device time
    else:
        for c in live:
            try:
                m = measure_candidate(
                    solver_cls, cfg, mesh, decomp, c, iters, reps
                )
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                c["error"] = f"{type(exc).__name__}: {exc}"[:200]
                _emit("measure", key=key, impl=c["impl"],
                      steps_per_exchange=c["steps_per_exchange"],
                      exchange=c.get("exchange", "collective"),
                      error=c["error"])
                continue
            c.update(m)
            measured.append(c)
            _emit("measure", key=key, impl=c["impl"],
                  steps_per_exchange=c["steps_per_exchange"],
                  exchange=c.get("exchange", "collective"),
                  mlups=m["mlups"], seconds=m["seconds"])
        if not measured:
            raise RuntimeError(
                f"autotune: every candidate failed for key {key}"
            )
        choice = dict(max(measured, key=lambda c: c["mlups"]))
        choice["source"] = "measured"
    decision = {
        "impl": choice["impl"],
        "steps_per_exchange": choice["steps_per_exchange"],
        "exchange": choice.get("exchange", "collective"),
        "mlups": choice.get("mlups"),
        "source": choice["source"],
        "backend": backend,
        "devices": devices,
        "ensemble": max(1, int(ensemble)),
        "key": key,
        "tuner": {"iters": iters, "reps": reps,
                  "prune_ratio": prune_ratio},
        "candidates": [
            {
                k: c.get(k)
                for k in ("impl", "steps_per_exchange", "exchange",
                          "modeled_us", "pruned", "mlups", "seconds",
                          "spread", "engaged", "error")
                if k in c
            }
            for c in cands
        ],
        "created": time.time(),
    }
    cache.put(key, decision)
    _emit(
        "decision", key=key, impl=decision["impl"],
        steps_per_exchange=decision["steps_per_exchange"],
        exchange=decision["exchange"],
        mlups=decision["mlups"], source=decision["source"],
        cache=cache.path,
    )
    return decision


def _autotune_ensemble(solver_cls, cfg, mesh, decomp, cache, key,
                       iters, reps, ensemble, backend, devices):
    """The batched half of :func:`autotune`: enumerate the rungs the
    ensemble engine serves, MEASURE each at the actual B under the
    caller's mesh, persist the winner. The cost model has no batched
    opinion (its per-step roofline does not price vmap/fold overheads
    or dispatch amortization), so nothing is pruned — every candidate
    is raced, and the ``tune:measure`` rows carry B so a published
    batched decision is auditable from the stream."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        member_extent,
    )

    B = max(1, int(ensemble))
    msh = member_extent(mesh)
    cands = ensemble_candidates(solver_cls, cfg, mesh, decomp, B)
    _emit(
        "candidates", key=key, ensemble=B, member_sharding=msh,
        considered=[
            {k: c[k] for k in ("impl", "steps_per_exchange")}
            for c in cands
        ],
    )
    measured = []
    for c in cands:
        try:
            m = measure_ensemble_candidate(
                solver_cls, cfg, mesh, decomp, c, B, iters, reps
            )
        except Exception as exc:  # noqa: BLE001 — recorded, not fatal
            c["error"] = f"{type(exc).__name__}: {exc}"[:200]
            _emit("measure", key=key, impl=c["impl"],
                  steps_per_exchange=c["steps_per_exchange"],
                  ensemble=B, error=c["error"])
            continue
        c.update(m)
        measured.append(c)
        _emit("measure", key=key, impl=c["impl"],
              steps_per_exchange=c["steps_per_exchange"],
              ensemble=B, member_sharding=msh,
              mlups=m["mlups"], seconds=m["seconds"],
              engaged=m["engaged"])
    if not measured:
        raise RuntimeError(
            f"autotune: every batched candidate failed for key {key}"
        )
    choice = dict(max(measured, key=lambda c: c["mlups"]))
    choice["source"] = "measured"
    decision = {
        "impl": choice["impl"],
        "steps_per_exchange": 1,
        "exchange": "collective",
        "mlups": choice.get("mlups"),
        "source": "measured",
        "backend": backend,
        "devices": devices,
        "ensemble": B,
        "member_sharding": msh,
        "engaged": choice.get("engaged"),
        "key": key,
        "tuner": {"iters": iters, "reps": reps, "batched": True},
        "candidates": [
            {
                k: c.get(k)
                for k in ("impl", "steps_per_exchange", "mlups",
                          "seconds", "spread", "engaged", "error")
                if k in c
            }
            for c in cands
        ],
        "created": time.time(),
    }
    cache.put(key, decision)
    _emit(
        "decision", key=key, impl=decision["impl"],
        steps_per_exchange=1, mlups=decision["mlups"],
        source="measured", ensemble=B, member_sharding=msh,
        cache=cache.path,
    )
    return decision


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default
