"""Persistent AOT executable cache: a repeat request never recompiles.

The batched ensemble engine makes one compiled executable serve B
members — this module makes it serve every *process* that asks for the
same program again. Keyed like the tuner's decision cache
(``tuning/cache.py``) plus the program axes the tuner abstracts over —
``(solver, shape, dtype, mesh, impl, steps_per_exchange, program key
incl. the ensemble B, argument avals, backend/device kind, jax
version)`` — each entry is one ``jax.experimental.serialize_executable``
blob written atomically (tempfile + ``os.replace``, the
``tuning/cache.py`` discipline). A corrupt, stale (different jax/
backend/devices) or mismatched entry is a MISS, never a crash.

Wired through ``models/base.SolverBase._compiled`` ->
``telemetry/xprof.wrap_dispatch``: on the first call of a dispatch
program the introspection wrapper consults this store before paying
``lower().compile()``; a hit deserializes the executable (milliseconds)
and the ``xla:cost`` event records ``compile_seconds_saved`` — the
compile seconds the original build paid, now skipped. Every lookup is
an ``aot_cache:{hit,miss}`` event and every write an
``aot_cache:store``, so a warm run is auditable from the stream
(``out/ensemble_gate.sh`` gates exactly that).

Opt-in: set ``TPUCFD_AOT_CACHE=DIR`` (or the CLI ``--aot-cache DIR`` /
:func:`configure`) — executables are per-machine artifacts, so the
store never engages implicitly.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import time
from typing import Optional

AOT_SCHEMA = 1
ENV_PATH = "TPUCFD_AOT_CACHE"

# process-wide configuration (the CLI writes it before building
# solvers); the env var is the fallback, like the tuning cache
_state = {"dir": None, "enabled": None}


def configure(cache_dir: Optional[str] = None,
              enabled: Optional[bool] = None) -> None:
    """Set the process-wide AOT-cache knobs; ``None`` leaves one as-is.
    Pointing at a directory implies enablement."""
    if cache_dir is not None:
        _state["dir"] = cache_dir
        if enabled is None and _state["enabled"] is None:
            _state["enabled"] = True
    if enabled is not None:
        _state["enabled"] = bool(enabled)


def cache_dir() -> Optional[str]:
    return _state["dir"] or os.environ.get(ENV_PATH) or None


def enabled() -> bool:
    if _state["enabled"] is not None:
        return _state["enabled"] and cache_dir() is not None
    return bool(cache_dir())


def _emit(name: str, **fields) -> None:
    from multigpu_advectiondiffusion_tpu import telemetry

    telemetry.event("aot_cache", name, **fields)


def _environment_facts() -> dict:
    """Everything about THIS process a serialized executable is only
    valid under — a mismatch on load is staleness, i.e. a miss."""
    import jax

    try:
        kinds = sorted({d.device_kind for d in jax.local_devices()})
    except Exception:  # noqa: BLE001 — facts degrade, never crash
        kinds = []
    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kinds": kinds,
        "process_count": jax.process_count(),
    }


def _physics_fingerprint(cfg) -> str:
    """Hash of the config fields that bake into a compiled program's
    CONSTANTS (diffusivity/nu/cfl/bc/weno/...). The tuner's key
    deliberately abstracts over physics scalars — two runs differing
    only in K share a kernel *choice* — but they do NOT share an
    *executable*: dt (= c·dx²/K for diffusion) is a compiled-in
    constant, so a K=0.7 run deserializing a K=1.0 blob would march
    the wrong clock. Same skip set as ``cli.drivers.physics_meta``
    plus the grid (its shape already keys via the tuner/avals)."""
    import dataclasses
    import json

    skip = {"grid", "ic", "ic_params", "impl", "overlap",
            "steps_per_exchange", "exchange"}
    out = {}
    for f in dataclasses.fields(cfg):
        if f.name in skip:
            continue
        v = getattr(cfg, f.name)
        if isinstance(v, tuple):
            v = list(v)
        try:
            json.dumps(v)
        except TypeError:
            continue  # non-serializable (callable source term): unkeyed
        out[f.name] = v
    body = json.dumps(out, sort_keys=True)
    return hashlib.sha256(body.encode()).hexdigest()[:16]


def dispatch_key(solver, program_key, steps=None,
                 donate: bool = False) -> str:
    """The cache key for one dispatch-cache entry: the tuner's config
    key (solver, shape, dtype, integrator, mesh, backend — and, for the
    ensemble programs, the member count B riding ``program_key``) plus
    the physics fingerprint (scalars like K/nu/cfl compile into the
    executable as constants — the cross-job sharing the scheduler's
    per-root cache makes possible is exactly where that collision
    bites), the program identity and the compile-relevant kernel
    knobs. The caller (``xprof``) appends the argument-aval
    fingerprint at first call, when the concrete operands exist."""
    import jax

    from multigpu_advectiondiffusion_tpu.tuning.autotuner import make_key

    try:
        base = make_key(
            type(solver), solver.cfg, solver.mesh, solver.decomp,
            jax.default_backend(),
        )
    except Exception:  # noqa: BLE001 — an unkeyable config just misses
        base = type(solver).__name__
    try:
        phys = _physics_fingerprint(solver.cfg)
    except Exception:  # noqa: BLE001 — an unkeyable config just misses
        phys = "?"
    # storage dtype + compensation carry (ISSUE 16): the tuner key
    # (``base``) already separates precision modes, but the carry
    # toggle (core.dtypes.bf16_carry_enabled) changes the compiled
    # generic-loop program WITHOUT changing the config — an entry
    # compiled carry-on must never be served to a carry-off process
    storage = getattr(solver, "storage_dtype", None)
    storage = str(storage) if storage is not None else str(
        getattr(solver.cfg, "dtype", "?")
    )
    carry = int(bool(getattr(solver, "_bf16_carry", True)))
    return "|".join([
        base,
        f"impl={getattr(solver.cfg, 'impl', 'xla')}",
        f"k={int(getattr(solver.cfg, 'steps_per_exchange', 1) or 1)}",
        f"ex={getattr(solver.cfg, 'exchange', 'collective')}",
        f"storage={storage}",
        f"carry={carry}",
        f"phys={phys}",
        f"prog={program_key}",
        f"steps={steps}",
        # buffer donation (ISSUE 19): a donated program aliases its
        # state operand into the output — a different executable than
        # the undonated build, so the bit is part of the identity (a
        # donated blob deserialized into an undonated dispatch would
        # free buffers the caller still holds)
        f"donate={int(bool(donate))}",
    ])


def aval_fingerprint(args) -> str:
    """Shape/dtype fingerprint of the call's operand pytree — the same
    program key with different avals is a different executable."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return ";".join(
        f"{tuple(getattr(a, 'shape', ()))}:"
        f"{getattr(getattr(a, 'dtype', None), 'name', type(a).__name__)}"
        for a in leaves
    )


def _entry_path(root: str, key: str) -> str:
    h = hashlib.sha256(key.encode()).hexdigest()[:32]
    return os.path.join(root, f"{h}.aot")


def load(key: str, args):
    """Resolve ``key`` (+ the args' aval fingerprint) against the
    store. Returns ``(compiled, meta)`` on a hit, ``None`` on any kind
    of miss — absent, corrupt, stale environment, mismatched key or
    avals, or a deserialization failure. Emits ``aot_cache:{hit,miss}``
    either way."""
    root = cache_dir()
    if not root:
        return None
    path = _entry_path(root, key)
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
    except FileNotFoundError:
        _emit("miss", key=key, reason="absent", path=path)
        return None
    except Exception as exc:  # noqa: BLE001 — corrupt entry = miss
        _emit("miss", key=key, reason=f"corrupt: {exc}"[:200], path=path)
        return None
    try:
        if entry.get("schema") != AOT_SCHEMA:
            raise ValueError(f"schema {entry.get('schema')}")
        if entry.get("key") != key:
            raise ValueError("key hash collision")
        env = _environment_facts()
        if entry.get("environment") != env:
            raise ValueError(
                f"stale environment {entry.get('environment')} != {env}"
            )
        fp = aval_fingerprint(args)
        if entry.get("avals") != fp:
            raise ValueError("operand avals differ")
        from jax.experimental import serialize_executable as se

        blob, in_tree, out_tree = entry["payload"]
        compiled = se.deserialize_and_load(blob, in_tree, out_tree)
    except Exception as exc:  # noqa: BLE001 — stale entry = miss
        _emit("miss", key=key, reason=f"stale: {exc}"[:200], path=path)
        return None
    meta = {
        "compile_seconds_saved": float(entry.get("compile_seconds", 0.0)),
        "load_seconds": time.perf_counter() - t0,
        "path": path,
    }
    _emit(
        "hit", key=key, path=path,
        load_seconds=round(meta["load_seconds"], 6),
        compile_seconds_saved=round(meta["compile_seconds_saved"], 6),
    )
    return compiled, meta


def store(key: str, args, compiled, compile_seconds: float) -> bool:
    """Serialize ``compiled`` under ``key`` with an atomic replace;
    failures are recorded (``aot_cache:store`` with
    ``persisted=False``), never raised — a backend that cannot
    serialize degrades to the plain compile-every-process behavior."""
    root = cache_dir()
    if not root:
        return False
    path = _entry_path(root, key)
    try:
        from jax.experimental import serialize_executable as se

        payload = se.serialize(compiled)
        entry = {
            "schema": AOT_SCHEMA,
            "key": key,
            "environment": _environment_facts(),
            "avals": aval_fingerprint(args),
            "compile_seconds": float(compile_seconds),
            "created": time.time(),
            "payload": payload,
        }
        os.makedirs(root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=root, prefix=".aot_",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(entry, f)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):  # replace failed
                os.unlink(tmp)
    except Exception as exc:  # noqa: BLE001
        _emit("store", key=key, persisted=False,
              reason=f"{type(exc).__name__}: {exc}"[:200])
        return False
    _emit("store", key=key, persisted=True, path=path,
          bytes=os.path.getsize(path),
          compile_seconds=round(float(compile_seconds), 6))
    return True
