"""Trace analysis: merge per-process JSONL telemetry streams into one
cross-rank view of a run.

The sink (:mod:`telemetry.sink`) writes each process's events against
its OWN monotonic clock (``t`` = seconds since that process's sink
opened) — the per-rank ``nvprof`` output files of the reference's
``profile.sh``, machine-readable. This module is the merge/analysis
layer the reference never had:

* :func:`load_streams` reads one or many per-process JSONL files
  (rotated ``.1`` predecessors included), tolerating truncated tails —
  a crashed rank's stream is evidence, not a parse error;
* :func:`align_clocks` maps every stream onto one global timeline:
  coarse alignment from the ``meta:open`` wall-clock epoch, then a
  median-of-anchors refinement over events that are *synchronization
  points by construction* — ``dist_init:ok`` (every rank returns from
  the distributed join together), ``sync:barrier``, and
  ``resilience:agree`` (an allgather completes everywhere at the last
  arrival);
* :func:`build_spans` reconstructs each process's span forest from the
  ``begin``/``end`` pairs (explicit ``id``/``parent`` links — no stack
  guessing), keeping still-open spans from crashed runs;
* :func:`analyze` produces a :class:`TraceReport`: per-phase wall-clock
  breakdown (compile vs step vs halo vs checkpoint vs rollback), every
  run's measured throughput against the static cost-model roofline,
  the cross-rank critical path, the step-time outlier record, and the
  measured-vs-modeled introspection section
  (:func:`measured_introspection` — per-executable XLA bytes/flops
  against the cost model's prediction, achieved bandwidth against the
  configured peak, device-memory peaks per rank).

The Perfetto exporter (:mod:`telemetry.export`) consumes the same
aligned streams; ``tpucfd-trace`` (cli/trace.py) is the front end.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import statistics
from typing import Dict, List, Optional, Sequence, Tuple

# Span names the drivers emit for actual solve work (models/base.py
# _dispatch_span); the first such span per process is the untimed
# compile + warm-up call of cli/drivers.py.
SOLVE_SPAN_PREFIX = "solver."


# --------------------------------------------------------------------- #
# Loading
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class Stream:
    """One process's event stream (possibly reassembled from a rotated
    pair), plus its alignment onto the merged timeline."""

    path: str
    events: List[dict]
    proc: int
    # wall-clock epoch of this stream's monotonic t=0 (from meta:open /
    # sink:rotate wall_time); None when the stream carries no epoch
    epoch: Optional[float]
    # seconds added to a local ``t`` to place it on the global timeline
    offset: float = 0.0
    skipped_lines: int = 0

    def gt(self, ev: dict) -> float:
        """Global (aligned) time of one of this stream's events."""
        return self.offset + float(ev.get("t", 0.0))

    @property
    def t_last(self) -> float:
        return max((float(e.get("t", 0.0)) for e in self.events),
                   default=0.0)


def _parse_lines(text: str) -> Tuple[List[dict], int]:
    events, skipped = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            skipped += 1  # torn tail of a crashed rank: keep going
            continue
        if isinstance(ev, dict) and "kind" in ev:
            events.append(ev)
        else:
            skipped += 1
    return events, skipped


def load_stream(path: str, include_rotated: bool = True) -> Stream:
    """One JSONL file -> :class:`Stream`. When the sink's size-capped
    rotation left a ``<path>.1`` predecessor, its events are prepended
    (same monotonic clock — rotation never resets ``t``)."""
    texts = []
    prev = path + ".1"
    if include_rotated and os.path.exists(prev):
        with open(prev) as f:
            texts.append(f.read())
    with open(path) as f:
        texts.append(f.read())
    events: List[dict] = []
    skipped = 0
    for text in texts:
        evs, sk = _parse_lines(text)
        events.extend(evs)
        skipped += sk
    events.sort(key=lambda e: float(e.get("t", 0.0)))
    procs = [int(e.get("proc", 0)) for e in events]
    proc = max(set(procs), key=procs.count) if procs else 0
    epoch = None
    for ev in events:
        # meta:open (fresh sink) and sink:rotate (tail-only file after a
        # rotation) both record wall_time at a known local t
        if ev.get("wall_time") is not None and (
            (ev["kind"], ev["name"]) in (("meta", "open"), ("sink", "rotate"))
        ):
            epoch = float(ev["wall_time"]) - float(ev.get("t", 0.0))
            break
    return Stream(path=path, events=events, proc=proc, epoch=epoch,
                  skipped_lines=skipped)


def discover_streams(root: str) -> List[str]:
    """Every JSONL stream a service root owns: the top-level daemon /
    server sinks (``sched_events.jsonl``, ``serve_events.jsonl``,
    rank streams) AND the per-job namespaced streams under
    ``<root>/jobs/<id>/`` the scheduler gives each worker. Rotated
    ``.1`` segments are NOT listed — they ride along with their owner
    via :func:`load_stream`'s prepend, never as separate streams.
    ``journal.jsonl`` files are CRC-sealed write-ahead journals, not
    event streams: excluded, they have their own replay readers."""
    found = sorted(glob.glob(os.path.join(root, "*.jsonl")))
    found.extend(sorted(
        glob.glob(os.path.join(root, "jobs", "*", "*.jsonl"))
    ))
    return [f for f in found
            if os.path.basename(f) != "journal.jsonl"]


def load_streams(paths: Sequence[str]) -> List[Stream]:
    """Expand files/directories into Streams, one per JSONL file.
    A directory is treated as a service root: it contributes its
    top-level ``*.jsonl`` streams AND the scheduler's per-job streams
    under ``jobs/<id>/`` (:func:`discover_streams`); rotated ``.1``
    files ride along with their owner, never as separate streams."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(discover_streams(p))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no telemetry streams under {list(paths)!r}")
    return [load_stream(f) for f in files]


# --------------------------------------------------------------------- #
# Clock alignment
# --------------------------------------------------------------------- #
def _anchor_key(ev: dict) -> Optional[tuple]:
    """Key identifying a cross-rank synchronization event family; the
    k-th occurrence of a family on one rank matches the k-th on every
    other (all are emitted immediately after a completed collective)."""
    kind, name = ev.get("kind"), ev.get("name")
    if kind == "dist_init" and name == "ok":
        return ("dist_init", "ok")
    if kind == "sync" and name == "barrier":
        return ("sync", ev.get("tag"))
    if kind == "resilience" and name == "agree":
        return ("agree", ev.get("tag"))
    return None


def _anchors(stream: Stream) -> Dict[tuple, List[float]]:
    out: Dict[tuple, List[float]] = {}
    for ev in stream.events:
        key = _anchor_key(ev)
        if key is not None:
            out.setdefault(key, []).append(stream.gt(ev))
    return out


def align_clocks(streams: List[Stream]) -> dict:
    """Place every stream on one timeline (mutates ``stream.offset``).

    Coarse pass: offsets from each stream's wall-clock epoch (exact when
    all ranks share a host clock, NTP-close otherwise). Refinement:
    match sync-anchor families across ranks and shift each stream by
    the median anchor disagreement against the reference stream (lowest
    process index), so collective-completion events coincide. Returns
    alignment diagnostics (matched anchor counts, applied corrections,
    worst post-correction residual)."""
    if not streams:
        return {"streams": 0}
    epochs = [s.epoch for s in streams if s.epoch is not None]
    wall0 = min(epochs) if epochs else 0.0
    for s in streams:
        s.offset = (s.epoch - wall0) if s.epoch is not None else 0.0
    ref = min(streams, key=lambda s: (s.proc, s.path))
    ref_anchors = _anchors(ref)
    corrections: Dict[str, float] = {}
    matched: Dict[str, int] = {}
    residual = 0.0
    for s in streams:
        if s is ref:
            continue
        deltas = []
        for key, times in _anchors(s).items():
            for t_ref, t_s in zip(ref_anchors.get(key, ()), times):
                deltas.append(t_ref - t_s)
        if not deltas:
            matched[f"proc{s.proc}"] = 0
            continue
        corr = statistics.median(deltas)
        s.offset += corr
        corrections[f"proc{s.proc}"] = round(corr, 6)
        matched[f"proc{s.proc}"] = len(deltas)
        residual = max(
            residual, max(abs(d - corr) for d in deltas)
        )
    return {
        "streams": len(streams),
        "reference_proc": ref.proc,
        "matched_anchors": matched,
        "corrections_s": corrections,
        "max_residual_s": round(residual, 6),
    }


def merged_events(streams: List[Stream]) -> List[dict]:
    """All events on the aligned timeline, each annotated with ``gt``
    (global seconds) — the cross-rank interleaving, sorted."""
    out = []
    for s in streams:
        for ev in s.events:
            e = dict(ev)
            e["gt"] = round(s.gt(ev), 6)
            e["proc"] = s.proc if "proc" not in ev else ev["proc"]
            out.append(e)
    out.sort(key=lambda e: e["gt"])
    return out


# --------------------------------------------------------------------- #
# Span forest
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class Span:
    name: str
    proc: int
    sid: int
    parent: Optional[int]
    t0: float  # local stream time
    t1: Optional[float]  # None while open (crash evidence)
    fields: dict
    children: List["Span"] = dataclasses.field(default_factory=list)

    def seconds(self, t_last: float = 0.0) -> float:
        end = self.t1 if self.t1 is not None else max(t_last, self.t0)
        return max(0.0, end - self.t0)

    @property
    def open(self) -> bool:
        return self.t1 is None


_SPAN_META = {"t", "proc", "kind", "name", "phase", "id", "parent",
              "depth", "seconds"}


def build_spans(stream: Stream) -> List[Span]:
    """Reconstruct the span forest from begin/end pairs (explicit
    id/parent links). Returns the roots; spans whose end never arrived
    (a crashed/killed rank) stay open."""
    by_id: Dict[int, Span] = {}
    roots: List[Span] = []
    for ev in stream.events:
        if ev.get("kind") != "span":
            continue
        if ev.get("phase") == "begin":
            span = Span(
                name=ev.get("name", "?"),
                proc=stream.proc,
                sid=int(ev.get("id", -1)),
                parent=ev.get("parent"),
                t0=float(ev.get("t", 0.0)),
                t1=None,
                fields={k: v for k, v in ev.items()
                        if k not in _SPAN_META},
            )
            by_id[span.sid] = span
            parent = by_id.get(span.parent) if span.parent else None
            (parent.children if parent else roots).append(span)
        elif ev.get("phase") == "end":
            span = by_id.get(int(ev.get("id", -1)))
            if span is not None:
                span.t1 = float(ev.get("t", 0.0))
    return roots


def _walk(spans: List[Span]):
    for s in spans:
        yield s
        yield from _walk(s.children)


# --------------------------------------------------------------------- #
# Phase breakdown
# --------------------------------------------------------------------- #
def phase_breakdown(stream: Stream) -> dict:
    """Wall-clock accounting of one process's run: compile+warm-up (the
    first ``solver.*`` span — cli/drivers.py's untimed warm call), the
    solve itself (the remaining ``solver.*`` spans), checkpoint/file
    I/O (``io`` events' own ``seconds``), rollback re-execution (steps
    re-covered after each ``resilience:rollback``, priced at the
    measured per-step rate), and modeled halo-exchange time (traced
    per-execution bytes through the cost model's latency/bandwidth
    terms — modeled, not measured: the exchange runs inside the
    compiled program)."""
    roots = build_spans(stream)
    t_last = stream.t_last
    solve = [s for s in _walk(roots)
             if s.name.startswith(SOLVE_SPAN_PREFIX)]
    solve.sort(key=lambda s: s.t0)
    compile_s = solve[0].seconds(t_last) if solve else 0.0
    step_s = sum(s.seconds(t_last) for s in solve[1:])
    root = next((s for s in roots if s.name == "run_solver"), None)
    total_s = root.seconds(t_last) if root else t_last

    io_s = 0.0
    rollbacks = 0
    re_steps = 0
    steps_seen = 0
    chunk_step_times = []
    halo_bytes_per_exec = 0
    halo_sites = 0
    dma_bytes = 0
    dma_blocks = 0
    for ev in stream.events:
        kind, name = ev.get("kind"), ev.get("name")
        if kind == "io" and ev.get("seconds") is not None:
            io_s += float(ev["seconds"])
        elif kind == "resilience" and name == "rollback":
            rollbacks += 1
            re_steps += max(
                0, int(ev.get("step", 0)) - int(ev.get("rollback_to_it", 0))
            )
        elif kind == "progress" and name == "chunk":
            if ev.get("step_seconds"):
                chunk_step_times.append(float(ev["step_seconds"]))
            steps_seen = max(steps_seen, int(ev.get("step", 0)))
        elif kind == "physics" and name == "probe":
            steps_seen = max(steps_seen, int(ev.get("step", 0)))
        elif kind == "counter" and name == "halo.bytes_per_execution":
            halo_bytes_per_exec = max(
                halo_bytes_per_exec, int(ev.get("total", 0))
            )
        elif kind == "counter" and name == "halo.exchanges_traced":
            halo_sites = max(halo_sites, int(ev.get("total", 0)))
        elif kind == "halo" and name == "in_kernel":
            # in-kernel remote-DMA exchange (exchange='dma'): the
            # compiled program moves its ghost rows over ICI itself —
            # bytes/blocks arrive per traced run call, blocks folded
            # in, so no per-step scaling applies
            dma_bytes += int(ev.get("bytes_per_execution", 0))
            dma_blocks += int(ev.get("blocks", 0))

    per_step = statistics.median(chunk_step_times) if chunk_step_times \
        else None
    rollback_s = (re_steps * per_step) if per_step is not None else None
    halo_model_s = None
    if halo_bytes_per_exec and steps_seen:
        from multigpu_advectiondiffusion_tpu.telemetry import costmodel

        # per-execution bytes x executions (~steps) through the same
        # latency+bandwidth model the tuner prunes with
        halo_model_s = costmodel.halo_exchange_seconds(
            float(halo_bytes_per_exec) * steps_seen,
            messages=max(1, halo_sites) * steps_seen,
        )
    if dma_bytes:
        from multigpu_advectiondiffusion_tpu.telemetry import costmodel

        # in-kernel remote-DMA comm (halo:in_kernel events): bytes
        # arrive with the run call's blocks already folded in — the
        # phase breakdown attributes the dma rung's comm instead of
        # reading the absent ppermute counters as zero
        halo_model_s = (halo_model_s or 0.0) + (
            costmodel.halo_exchange_seconds(
                float(dma_bytes), messages=max(1, dma_blocks)
            )
        )
    accounted = compile_s + step_s + io_s
    return {
        "proc": stream.proc,
        "total_s": round(total_s, 6),
        "compile_s": round(compile_s, 6),
        "step_s": round(step_s, 6),
        "checkpoint_io_s": round(io_s, 6),
        "rollbacks": rollbacks,
        "rollback_steps_reexecuted": re_steps,
        "rollback_s_est": (
            round(rollback_s, 6) if rollback_s is not None else None
        ),
        "halo_model_s": (
            round(halo_model_s, 6) if halo_model_s is not None else None
        ),
        "other_s": round(max(0.0, total_s - accounted), 6),
        "open_spans": sum(1 for s in _walk(roots) if s.open),
    }


# --------------------------------------------------------------------- #
# Throughput vs roofline, critical path, outliers
# --------------------------------------------------------------------- #
def rung_throughput(streams: List[Stream]) -> List[dict]:
    """One row per ``summary`` event: the run's measured rate next to
    the static cost-model roofline of the rung that produced it."""
    rows = []
    for s in streams:
        for ev in s.events:
            if ev.get("kind") != "summary":
                continue
            rows.append({
                "proc": s.proc,
                "run": ev.get("name"),
                "stepper": ev.get("stepper"),
                "seconds": ev.get("seconds"),
                "mlups": ev.get("mlups"),
                "roofline_pct": ev.get("roofline_pct"),
                "mass_drift": ev.get("mass_drift"),
            })
    return rows


def critical_path(streams: List[Stream]) -> dict:
    """The chain of spans that bounds the merged run's wall clock: the
    rank whose root span ends last on the aligned timeline, descended
    through its longest children. Also reports every rank's root extent
    so cross-rank skew (stragglers) is visible at a glance."""
    per_proc = []
    bounding = None
    bounding_end = -1.0
    for s in streams:
        roots = build_spans(s)
        root = next((sp for sp in roots if sp.name == "run_solver"),
                    roots[0] if roots else None)
        if root is None:
            continue
        end = s.offset + (
            root.t1 if root.t1 is not None else s.t_last
        )
        per_proc.append({
            "proc": s.proc,
            "root": root.name,
            "begin_s": round(s.offset + root.t0, 6),
            "end_s": round(end, 6),
            "seconds": round(root.seconds(s.t_last), 6),
            "open": root.open,
        })
        if end > bounding_end:
            bounding_end = end
            bounding = (s, root)
    chain = []
    if bounding is not None:
        s, span = bounding
        while span is not None:
            chain.append({
                "proc": s.proc,
                "name": span.name,
                "seconds": round(span.seconds(s.t_last), 6),
                "stepper": span.fields.get("stepper"),
            })
            span = max(
                span.children,
                key=lambda c: c.seconds(s.t_last),
                default=None,
            )
    skew = 0.0
    if len(per_proc) > 1:
        ends = [p["end_s"] for p in per_proc]
        skew = max(ends) - min(ends)
    return {
        "ranks": sorted(per_proc, key=lambda p: p["proc"]),
        "critical_rank": bounding[0].proc if bounding else None,
        "chain": chain,
        "end_skew_s": round(skew, 6),
    }


def measured_introspection(streams: List[Stream]) -> dict:
    """The measured-vs-modeled section: per-executable ``xla:cost``
    captures (XLA-reported bytes/flops next to the cost model's
    per-step prediction, ratio flagged outside the tolerance band —
    discrepancies reported, not hidden), per-run ``xla:measured``
    reconciliations (achieved bandwidth vs the configured peak), and
    each rank's ``mem:watermark`` peak."""
    from multigpu_advectiondiffusion_tpu.telemetry.xprof import (
        tolerance_factor,
    )

    tol = tolerance_factor()
    executables = []
    runs = []
    memory: Dict[str, dict] = {}
    for s in streams:
        for ev in s.events:
            kind, name = ev.get("kind"), ev.get("name")
            if kind == "xla" and name == "cost":
                devices = max(1, int(ev.get("devices", 1) or 1))
                xla_bytes = float(ev.get("bytes_accessed", 0) or 0)
                xla_bytes *= devices
                model = ev.get("model_bytes_per_step")
                ratio = (
                    round(float(model) / xla_bytes, 4)
                    if model and xla_bytes > 0 else None
                )
                executables.append({
                    "proc": s.proc,
                    "key": ev.get("key"),
                    "stepper": ev.get("stepper"),
                    "steps": ev.get("steps"),
                    "xla_bytes": xla_bytes,
                    "xla_flops": float(ev.get("flops", 0) or 0) * devices,
                    "model_bytes": model,
                    "model_bytes_ratio": ratio,
                    "within_tolerance": (
                        bool(1.0 / tol <= ratio <= tol)
                        if ratio is not None else None
                    ),
                    "peak_bytes": ev.get("peak_bytes"),
                    "compile_seconds": ev.get("compile_seconds"),
                })
            elif kind == "xla" and name == "measured":
                runs.append({
                    "proc": s.proc,
                    "run": ev.get("run"),
                    "stepper": ev.get("stepper"),
                    "xla_bytes_per_step": ev.get("xla_bytes_per_step"),
                    "model_bytes_ratio": ev.get("model_bytes_ratio"),
                    "bytes_within_tolerance": ev.get(
                        "bytes_within_tolerance"
                    ),
                    "achieved_gbs": ev.get("achieved_gbs"),
                    "peak_gbs": ev.get("peak_gbs"),
                    "measured_bw_pct": ev.get("measured_bw_pct"),
                })
            elif kind == "mem" and name == "watermark":
                rec = memory.setdefault(
                    f"proc{s.proc}",
                    {"peak_bytes": 0, "limit_bytes": None,
                     "source": None, "samples": 0},
                )
                rec["samples"] += 1
                rec["peak_bytes"] = max(
                    rec["peak_bytes"], int(ev.get("peak_bytes", 0) or 0)
                )
                if ev.get("limit_bytes") is not None:
                    rec["limit_bytes"] = ev["limit_bytes"]
                rec["source"] = ev.get("source") or rec["source"]
    return {
        "tolerance_factor": tol,
        "executables": executables,
        "runs": runs,
        "memory": memory,
    }


_DIAG_META = {"t", "proc", "kind", "name", "gt", "step", "time",
              "solver", "ndim", "decay_rate_analytic"}


def physics_diagnostics(streams: List[Stream]) -> dict:
    """The physics section: per-rank in-situ diagnostic trajectories
    (``phys:diag`` events — the fused observable suite at the
    supervisor's ``--diag-every`` cadence), every tolerance-rule breach
    (``phys:violation``), and — for the Gaussian-diffusion workload,
    whose events carry the analytic rate — the measured amplitude
    decay-rate fit against it (``diagnostics/physics.py
    gaussian_decay_fit``): the machine-checked version of the reference
    ``Run.m`` harness eyeballing its decaying field plots."""
    from multigpu_advectiondiffusion_tpu.diagnostics.physics import (
        gaussian_decay_fit,
    )

    trajectories = []
    violations = []
    for s in streams:
        points = []
        meta: dict = {}
        for ev in s.events:
            if ev.get("kind") != "phys":
                continue
            if ev.get("name") == "diag":
                points.append(ev)
                for key in ("solver", "ndim", "decay_rate_analytic"):
                    if ev.get(key) is not None:
                        meta[key] = ev[key]
            elif ev.get("name") == "violation":
                violations.append({
                    "proc": s.proc,
                    "step": ev.get("step"),
                    "time": ev.get("time"),
                    "rule": ev.get("rule"),
                    "message": ev.get("message"),
                })
        if not points:
            continue
        observables = sorted({
            k for p in points for k, v in p.items()
            if k not in _DIAG_META and isinstance(v, (int, float))
        })
        entry = {
            "proc": s.proc,
            "solver": meta.get("solver"),
            "points": len(points),
            "observables": observables,
            "last": {
                k: points[-1].get(k)
                for k in observables
                if points[-1].get(k) is not None
            },
            "last_step": points[-1].get("step"),
        }
        if meta.get("decay_rate_analytic") is not None:
            fit = gaussian_decay_fit(
                [float(p.get("time", 0.0)) for p in points],
                [float(p.get("max", 0.0)) for p in points],
                analytic_rate=float(meta["decay_rate_analytic"]),
            )
            if fit is not None:
                entry["decay_fit"] = {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in fit.items()
                }
        trajectories.append(entry)
    return {"trajectories": trajectories, "violations": violations}


def perf_events(streams: List[Stream]) -> dict:
    """Step-time outlier record: every ``perf:outlier`` the live watch
    emitted, plus the final ``perf:histogram`` per process."""
    outliers = []
    histograms = {}
    for s in streams:
        for ev in s.events:
            if ev.get("kind") != "perf":
                continue
            if ev.get("name") == "outlier":
                outliers.append({
                    "proc": s.proc,
                    "gt": round(s.gt(ev), 6),
                    "step": ev.get("step"),
                    "step_seconds": ev.get("step_seconds"),
                    "threshold": ev.get("threshold"),
                    "median": ev.get("median"),
                })
            elif ev.get("name") == "histogram":
                histograms[f"proc{s.proc}"] = {
                    k: ev.get(k)
                    for k in ("edges", "counts", "chunks",
                              "median_step_s", "outliers")
                }
    return {"outliers": outliers, "histograms": histograms}


def scheduler_timeline(streams: List[Stream]) -> dict:
    """Queue timeline from a scheduler's ``sched:*``/``job:*`` events
    (``service/daemon.py`` streams them to ``sched_events.jsonl``):
    per-job state trajectories with aligned times, attempt counts,
    preemptions and retry classifications — the consumable view of the
    journal. Empty dict when the streams carry no scheduler events."""
    jobs: Dict[str, dict] = {}
    preempts = []
    recoveries = []

    def _job(jid) -> dict:
        return jobs.setdefault(jid, {
            "job": jid, "states": [], "attempts": 0, "priority": None,
            "retries": [], "warm": None, "final": None,
        })

    for s in streams:
        for ev in s.events:
            kind, name = ev.get("kind"), ev.get("name")
            if kind == "job":
                j = _job(ev.get("job"))
                gt = round(s.gt(ev), 6)
                if name == "submit":
                    j["priority"] = ev.get("priority")
                    j["states"].append({"t": gt, "state": "queued"})
                elif name == "state":
                    j["states"].append(
                        {"t": gt, "state": ev.get("to"),
                         "reason": ev.get("reason")}
                    )
                    j["final"] = ev.get("to")
                elif name == "start":
                    j["attempts"] = max(
                        j["attempts"], int(ev.get("attempt") or 0)
                    )
                    if j["warm"] is None:
                        j["warm"] = ev.get("warm")
            elif kind == "sched":
                if name == "preempt":
                    preempts.append({
                        "t": round(s.gt(ev), 6),
                        "victim": ev.get("victim"),
                        "for_job": ev.get("for_job"),
                        "blocked": ev.get("blocked"),
                    })
                elif name == "retry":
                    _job(ev.get("job"))["retries"].append({
                        "t": round(s.gt(ev), 6),
                        "policy": ev.get("policy"),
                        "dt_scale": ev.get("dt_scale"),
                    })
                elif name == "recover":
                    recoveries.append({
                        "t": round(s.gt(ev), 6),
                        "records": ev.get("records"),
                        "torn_lines": ev.get("torn_lines"),
                        "adopted": ev.get("adopted"),
                        "requeued": ev.get("requeued"),
                    })
    if not jobs and not recoveries:
        return {}
    for j in jobs.values():
        ts = [p["t"] for p in j["states"]]
        j["span_s"] = (
            round(max(ts) - min(ts), 6) if len(ts) > 1 else 0.0
        )
    return {
        "jobs": sorted(
            jobs.values(),
            key=lambda j: j["states"][0]["t"] if j["states"] else 0.0,
        ),
        "preemptions": preempts,
        "recoveries": recoveries,
    }


def request_timeline(streams: List[Stream]) -> dict:
    """Request-serving timeline from a request server's
    ``serve:*``/``req:*`` events (``service/server.py`` streams them to
    ``serve_events.jsonl``): per-request state trajectories with
    aligned times, batch membership, slice progress/occupancy, sheds,
    joins, preemptions, member-attributed divergences and recovery
    replays — the consumable view of the request journal. Empty dict
    when the streams carry no serving events."""
    reqs: Dict[str, dict] = {}
    batches: Dict[str, dict] = {}
    sheds = []
    preempts = []
    divergences = []
    recoveries = []
    hangs = []
    deadline_cancels = []
    drains = []

    def _req(rid) -> dict:
        return reqs.setdefault(rid, {
            "request": rid, "states": [], "priority": None, "key": None,
            "warm": None, "batches": [], "slices": None, "final": None,
            "seconds": None, "fail_reason": None,
        })

    for s in streams:
        for ev in s.events:
            kind, name = ev.get("kind"), ev.get("name")
            if kind == "req":
                r = _req(ev.get("job"))
                gt = round(s.gt(ev), 6)
                if name == "submit":
                    r["priority"] = ev.get("priority")
                    r["states"].append({"t": gt, "state": "received"})
                elif name == "state":
                    r["states"].append(
                        {"t": gt, "state": ev.get("to"),
                         "reason": ev.get("reason")}
                    )
                    r["final"] = ev.get("to")
                elif name == "done":
                    r["seconds"] = ev.get("seconds")
                    r["slices"] = ev.get("slices")
                elif name == "failed":
                    r["fail_reason"] = ev.get("reason")
                elif name == "deadline_cancel":
                    deadline_cancels.append({
                        "t": gt, "request": ev.get("job"),
                        "deadline_s": ev.get("deadline_s"),
                        "elapsed_s": ev.get("elapsed_s"),
                    })
            elif kind == "dispatch" and name == "hung":
                hangs.append({
                    "t": round(s.gt(ev), 6), "batch": ev.get("batch"),
                    "slice": ev.get("slice"),
                    "elapsed_s": ev.get("elapsed_s"),
                    "budget_s": ev.get("budget_s"),
                    "requests": ev.get("jobs"),
                })
            elif kind == "drain":
                drains.append({
                    "t": round(s.gt(ev), 6), "event": name,
                    "reason": ev.get("reason"),
                    "open": ev.get("open"),
                    "batch": ev.get("batch"),
                    "members": ev.get("members"),
                    "clean": ev.get("clean"),
                })
            elif kind == "serve":
                gt = round(s.gt(ev), 6)
                if name == "admit":
                    r = _req(ev.get("job"))
                    r["key"] = ev.get("key")
                    r["warm"] = ev.get("warm")
                elif name == "shed":
                    sheds.append({
                        "t": gt, "request": ev.get("job"),
                        "open": ev.get("open"), "bound": ev.get("bound"),
                        "retry_after_s": ev.get("retry_after_s"),
                    })
                elif name == "batch":
                    batches[ev.get("batch")] = {
                        "batch": ev.get("batch"), "t": gt,
                        "key": ev.get("key"),
                        "members": ev.get("members"),
                        "lanes": ev.get("lanes"),
                        "slices": 0, "occupancy": [],
                    }
                elif name == "slice":
                    b = batches.get(ev.get("batch"))
                    if b is not None:
                        b["slices"] = max(
                            b["slices"], int(ev.get("slice") or 0)
                        )
                        occ = ev.get("occupancy")
                        if occ is not None:
                            b["occupancy"].append(occ)
                elif name == "preempt":
                    preempts.append({
                        "t": gt, "batch": ev.get("batch"),
                        "for_request": ev.get("for_job"),
                        "parked": ev.get("parked"),
                    })
                elif name == "divergence":
                    divergences.append({
                        "t": gt, "batch": ev.get("batch"),
                        "requests": ev.get("jobs"),
                    })
                elif name == "recover":
                    recoveries.append({
                        "t": gt,
                        "records": ev.get("records"),
                        "torn_lines": ev.get("torn_lines"),
                        "requests": ev.get("requests"),
                        "requeued": ev.get("requeued"),
                        "failed": ev.get("failed"),
                    })
    if not reqs and not recoveries and not batches:
        return {}
    for r in reqs.values():
        ts = [p["t"] for p in r["states"]]
        r["span_s"] = (
            round(max(ts) - min(ts), 6) if len(ts) > 1 else 0.0
        )
        # batch membership is not carried per-request in the stream;
        # attribute by coalesce-key match
        r["batches"] = [
            b["batch"] for b in batches.values()
            if r["key"] is not None and b.get("key") == r["key"]
        ]
    mean_occ = None
    occs = [o for b in batches.values() for o in b["occupancy"]]
    if occs:
        mean_occ = round(sum(occs) / len(occs), 4)
    return {
        "requests": sorted(
            reqs.values(),
            key=lambda r: r["states"][0]["t"] if r["states"] else 0.0,
        ),
        "batches": sorted(batches.values(), key=lambda b: b["t"]),
        "sheds": sheds,
        "preemptions": preempts,
        "divergences": divergences,
        "recoveries": recoveries,
        "hangs": hangs,
        "deadline_cancels": deadline_cancels,
        "drains": drains,
        "mean_occupancy": mean_occ,
    }


# --------------------------------------------------------------------- #
# The report
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class TraceReport:
    streams: List[dict]
    alignment: dict
    phases: List[dict]
    rungs: List[dict]
    critical_path: dict
    perf: dict
    # measured executable introspection (xla:cost / xla:measured /
    # mem:watermark events) — empty lists/dicts on streams from runs
    # that predate the capture layer
    xla: dict = dataclasses.field(default_factory=dict)
    # in-situ physics diagnostics (phys:diag / phys:violation events):
    # per-rank observable trajectories, tolerance-rule breaches and the
    # Gaussian decay-rate fit — empty on undiagnosed runs
    physics: dict = dataclasses.field(default_factory=dict)
    # scheduler queue timeline (sched:*/job:* events from a service
    # daemon's stream) — empty on batch-mode streams
    queue: dict = dataclasses.field(default_factory=dict)
    # request-serving timeline (serve:*/req:* events from a request
    # server's stream) — empty on non-serving streams
    serving: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format_text(self) -> str:
        lines = []
        add = lines.append
        add("=" * 68)
        add(" tpucfd-trace: merged run analysis")
        add("=" * 68)
        for s in self.streams:
            note = (f", {s['skipped_lines']} unparseable line(s) skipped"
                    if s["skipped_lines"] else "")
            add(f" proc {s['proc']}: {s['path']} "
                f"({s['events']} events{note})")
        al = self.alignment
        if al.get("streams", 0) > 1:
            add(f" clock alignment    : ref proc {al['reference_proc']}, "
                f"anchors {al['matched_anchors']}, "
                f"corrections {al['corrections_s']} s, "
                f"residual {al['max_residual_s']} s")
        add("-" * 68)
        add(" phase breakdown (wall seconds per rank)")
        hdr = (f"   {'proc':>4} {'total':>9} {'compile':>9} {'step':>9} "
               f"{'ckpt io':>9} {'rollback':>9} {'halo~':>9} {'other':>9}")
        add(hdr)
        for p in self.phases:
            rb = p["rollback_s_est"]
            halo = p["halo_model_s"]
            add(
                f"   {p['proc']:>4} {p['total_s']:>9.3f} "
                f"{p['compile_s']:>9.3f} {p['step_s']:>9.3f} "
                f"{p['checkpoint_io_s']:>9.3f} "
                f"{(f'{rb:.3f}' if rb is not None else '-'):>9} "
                f"{(f'{halo:.3f}' if halo is not None else '-'):>9} "
                f"{p['other_s']:>9.3f}"
            )
            if p["rollbacks"]:
                add(f"        proc {p['proc']}: {p['rollbacks']} "
                    f"rollback(s), {p['rollback_steps_reexecuted']} "
                    "step(s) re-executed")
            if p["open_spans"]:
                add(f"        proc {p['proc']}: {p['open_spans']} span(s) "
                    "never closed (crashed/killed rank?)")
        add("   (compile = first solver call incl. warm-up; halo~ = "
            "modeled from traced bytes, runs inside the compiled step)")
        if self.rungs:
            add("-" * 68)
            add(" measured throughput vs cost-model roofline")
            add(f"   {'run':<24} {'stepper':<22} {'MLUPS':>9} "
                f"{'roofline':>9}")
            for r in self.rungs:
                roof = r.get("roofline_pct")
                add(
                    f"   {str(r['run']):<24} {str(r['stepper']):<22} "
                    f"{(r['mlups'] if r['mlups'] is not None else 0):>9} "
                    f"{(f'{roof:.1f}%' if roof is not None else '-'):>9}"
                )
        cp = self.critical_path
        if cp.get("ranks"):
            add("-" * 68)
            add(f" critical path (rank {cp['critical_rank']}; "
                f"cross-rank end skew {cp['end_skew_s']} s)")
            for i, hop in enumerate(cp["chain"]):
                extra = (f" [{hop['stepper']}]" if hop.get("stepper")
                         else "")
                add(f"   {'  ' * i}{hop['name']}{extra}: "
                    f"{hop['seconds']:.3f} s (proc {hop['proc']})")
        if self.perf.get("outliers"):
            add("-" * 68)
            add(f" step-time outliers ({len(self.perf['outliers'])})")
            for o in self.perf["outliers"][:20]:
                add(f"   proc {o['proc']} step {o['step']}: "
                    f"{o['step_seconds']:.4f} s/step "
                    f"(median {o['median']:.4f}, "
                    f"threshold {o['threshold']:.4f})")
        if self.xla.get("executables") or self.xla.get("memory"):
            add("-" * 68)
            add(" measured vs modeled (XLA executable introspection; "
                f"band {self.xla.get('tolerance_factor')}x)")
            if self.xla.get("executables"):
                add(f"   {'key':<18} {'stepper':<20} {'xla B/step':>12} "
                    f"{'model B':>12} {'ratio':>7} {'flag':>11}")
                for e in self.xla["executables"][:20]:
                    ratio = e.get("model_bytes_ratio")
                    flag = (
                        "-" if e.get("within_tolerance") is None
                        else ("ok" if e["within_tolerance"]
                              else "DISCREPANT")
                    )
                    add(
                        f"   {str(e.get('key'))[:18]:<18} "
                        f"{str(e.get('stepper'))[:20]:<20} "
                        f"{e.get('xla_bytes', 0):>12,.0f} "
                        f"{(e.get('model_bytes') or 0):>12,.0f} "
                        f"{(f'{ratio:.2f}' if ratio is not None else '-'):>7} "
                        f"{flag:>11}"
                    )
            for r in self.xla.get("runs", ()):
                bw = r.get("measured_bw_pct")
                add(
                    f"   run {r.get('run')}: achieved "
                    f"{r.get('achieved_gbs')} GB/s vs peak "
                    f"{r.get('peak_gbs')} GB/s"
                    + (f" ({bw}% of configured peak)"
                       if bw is not None else "")
                )
            for proc, m in sorted(self.xla.get("memory", {}).items()):
                line = (f"   {proc}: device-memory peak "
                        f"{m['peak_bytes']:,} B [{m['source']}]")
                if m.get("limit_bytes"):
                    line += (f", headroom "
                             f"{m['limit_bytes'] - m['peak_bytes']:,} B")
                add(line)
        if self.physics.get("trajectories"):
            add("-" * 68)
            add(" physics diagnostics (in-situ observable suite, "
                "phys:diag cadence)")
            for tr in self.physics["trajectories"]:
                add(f"   proc {tr['proc']} [{tr.get('solver')}]: "
                    f"{tr['points']} point(s), observables "
                    f"{', '.join(tr['observables'])}")
                last = tr.get("last") or {}
                shown = {
                    k: last[k]
                    for k in ("mass", "energy", "tv", "spectral_tail")
                    if last.get(k) is not None
                }
                if shown:
                    add("      last (step "
                        f"{tr.get('last_step')}): "
                        + ", ".join(f"{k}={v:.6g}"
                                    for k, v in shown.items()))
                fit = tr.get("decay_fit")
                if fit:
                    add(
                        "      Gaussian decay rate: measured "
                        f"{fit['measured_rate']:.4f} vs analytic "
                        f"{fit['analytic_rate']:.4f} "
                        f"({100 * fit['rel_err']:.2f}% off, "
                        f"{fit['points']} point(s))"
                    )
            viols = self.physics.get("violations") or []
            if viols:
                add(f"   violations ({len(viols)}):")
                for v in viols[:20]:
                    add(f"     proc {v['proc']} step {v['step']} "
                        f"[{v['rule']}]: {v['message']}")
            else:
                add("   no tolerance-rule violations")
        if self.queue.get("jobs") or self.queue.get("recoveries"):
            add("-" * 68)
            add(" job queue timeline (scheduler sched:*/job:* events)")
            for rc in self.queue.get("recoveries", ()):
                add(f"   t={rc['t']:.3f} recovery: "
                    f"{rc.get('records')} journal record(s), "
                    f"{rc.get('torn_lines')} torn, "
                    f"{rc.get('adopted')} adopted, "
                    f"{rc.get('requeued')} requeued")
            for j in self.queue.get("jobs", ()):
                chain = " -> ".join(
                    p["state"] for p in j["states"]
                ) or "?"
                warm = " [warm]" if j.get("warm") else ""
                add(f"   {j['job']} (pri {j.get('priority')}, "
                    f"{j['attempts']} attempt(s), "
                    f"{j['span_s']:.3f} s){warm}: {chain}")
                for r in j.get("retries", ()):
                    add(f"      retry [{r['policy']}] at t={r['t']:.3f}"
                        f" dt_scale={r.get('dt_scale')}")
            for p in self.queue.get("preemptions", ()):
                add(f"   preempt: {p['victim']} -> {p['for_job']} "
                    f"(blocked on {p.get('blocked')}) at t={p['t']:.3f}")
        sv = self.serving
        if sv.get("requests") or sv.get("recoveries"):
            add("-" * 68)
            add(" request serving timeline (server serve:*/req:* events)")
            for rc in sv.get("recoveries", ()):
                add(f"   t={rc['t']:.3f} recovery: "
                    f"{rc.get('records')} journal record(s), "
                    f"{rc.get('torn_lines')} torn, "
                    f"{rc.get('requeued')} requeued, "
                    f"{rc.get('failed')} failed")
            for r in sv.get("requests", ()):
                chain = " -> ".join(
                    p["state"] for p in r["states"]
                ) or "?"
                warm = " [warm]" if r.get("warm") else ""
                extra = ""
                if r.get("slices") is not None:
                    extra = f", {r['slices']} slice(s)"
                if r.get("fail_reason"):
                    extra += f", failed: {r['fail_reason']}"
                add(f"   {r['request']} (pri {r.get('priority')}, "
                    f"{r['span_s']:.3f} s{extra}){warm}: {chain}")
            for b in sv.get("batches", ()):
                occ = b.get("occupancy") or []
                occ_note = (
                    f", occupancy {min(occ):.2f}..{max(occ):.2f}"
                    if occ else ""
                )
                add(f"   batch {b['batch']} [{str(b.get('key'))[:40]}]: "
                    f"{b.get('members')} member(s) in "
                    f"{b.get('lanes')} lane(s), "
                    f"{b.get('slices')} slice(s){occ_note}")
            for sh in sv.get("sheds", ()):
                add(f"   shed: {sh['request']} at t={sh['t']:.3f} "
                    f"(open {sh.get('open')}/{sh.get('bound')}, "
                    f"retry after {sh.get('retry_after_s')} s)")
            for p in sv.get("preemptions", ()):
                add(f"   preempt: batch {p['batch']} parked "
                    f"{p.get('parked')} member(s) for "
                    f"{p['for_request']} at t={p['t']:.3f}")
            for d in sv.get("divergences", ()):
                add(f"   divergence: batch {d['batch']} failed "
                    f"{d.get('requests')} at t={d['t']:.3f}")
            for h in sv.get("hangs", ()):
                add(f"   hung dispatch: batch {h['batch']} slice "
                    f"{h.get('slice')} at t={h['t']:.3f} "
                    f"({h.get('elapsed_s')} s > budget "
                    f"{h.get('budget_s')} s), evacuated "
                    f"{h.get('requests')}")
            for c in sv.get("deadline_cancels", ()):
                add(f"   deadline cancel: {c['request']} at "
                    f"t={c['t']:.3f} (deadline {c.get('deadline_s')} "
                    f"s, elapsed {c.get('elapsed_s')} s)")
            for d in sv.get("drains", ()):
                detail = {
                    "start": f"reason={d.get('reason')} "
                             f"open={d.get('open')}",
                    "parked": f"batch={d.get('batch')} "
                              f"members={d.get('members')}",
                    "done": f"clean={d.get('clean')} "
                            f"open={d.get('open')}",
                }.get(d["event"], "")
                add(f"   drain {d['event']} at t={d['t']:.3f} "
                    f"{detail}".rstrip())
            if sv.get("mean_occupancy") is not None:
                add(f"   mean batch occupancy: {sv['mean_occupancy']}")
        add("=" * 68)
        return "\n".join(lines)


def analyze(paths: Sequence[str]) -> TraceReport:
    """Load, align and analyze one or many per-process streams."""
    streams = load_streams(paths)
    alignment = align_clocks(streams)
    return TraceReport(
        streams=[
            {
                "path": s.path,
                "proc": s.proc,
                "events": len(s.events),
                "offset_s": round(s.offset, 6),
                "skipped_lines": s.skipped_lines,
            }
            for s in streams
        ],
        alignment=alignment,
        phases=[phase_breakdown(s)
                for s in sorted(streams, key=lambda s: s.proc)],
        rungs=rung_throughput(streams),
        critical_path=critical_path(streams),
        perf=perf_events(streams),
        xla=measured_introspection(streams),
        physics=physics_diagnostics(streams),
        queue=scheduler_timeline(streams),
        serving=request_timeline(streams),
    )
