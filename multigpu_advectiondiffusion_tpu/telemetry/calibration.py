"""Measured-peak calibration: persisted demonstrated-capability rates.

The cost model's peak bandwidth/FLOP rates were *assumed* — per-backend
datasheet defaults, env-overridable (``costmodel.PEAKS``). Every
roofline percentage, every tuner pruning decision read against them.
This store closes the modeled-vs-measured gap the reference closed with
``nvprof`` counters: once a run's compiled executables report their own
XLA bytes/FLOPs (:mod:`telemetry.xprof`), the achieved bandwidth of the
run's *binding* resource is a measured lower bound on the hardware's
real, attainable peak — on a tunnel-shared HBM or a thermally limited
chip, a far more honest pruning denominator than the datasheet number.

Semantics — **demonstrated capability, max-merge**:

* :func:`observe` folds one run's achieved rate into the record for the
  backend family, keeping the MAX ever observed (a slow run never
  lowers the calibrated peak below a faster earlier one);
* :func:`lookup` returns the record the cost model consults —
  ``costmodel.peak_rates`` applies it OVER the env-assumed peaks
  (measured beats assumed; delete the file or set
  ``TPUCFD_CALIBRATION_PATH=off`` to fall back to assumptions);
* roofline percentages read against a calibrated peak are *relative to
  what the rig has demonstrated*, not to a datasheet — a later, faster
  run can momentarily read >100% until its own observation lands.

The record is an atomic JSON file keyed like the tuner's decision cache
(``tuning/cache.py`` discipline: tempfile + ``os.replace``,
read-modify-write under a process lock; corrupt file == empty, never a
crash). Default location sits next to the tuning cache;
``TPUCFD_CALIBRATION_PATH`` overrides (``off``/``0``/empty disables the
subsystem entirely). Every persisted update is a ``calib:update``
telemetry event, so a tuned/roofline number is auditable back to the
run that calibrated its denominator.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

CALIBRATION_SCHEMA = 1

ENV_PATH = "TPUCFD_CALIBRATION_PATH"
_DEFAULT_PATH = os.path.join(
    "~", ".cache", "multigpu_advectiondiffusion_tpu", "calibration.json"
)

_lock = threading.Lock()


def default_path() -> Optional[str]:
    """The store's file path, or ``None`` when calibration is disabled
    (``TPUCFD_CALIBRATION_PATH`` set to ``off``/``0``/empty)."""
    env = os.environ.get(ENV_PATH)
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "none", "disabled"):
            return None
        return env
    return os.path.expanduser(_DEFAULT_PATH)


def _read(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    except (json.JSONDecodeError, OSError, ValueError):
        return {}  # corrupt/truncated: a miss, not a crash
    if not isinstance(data, dict) or data.get("schema") != CALIBRATION_SCHEMA:
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def _write(path: str, entries: dict) -> None:
    payload = {"schema": CALIBRATION_SCHEMA, "entries": entries}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".calib_", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # replace failed
            os.unlink(tmp)


def lookup(backend: str, path: Optional[str] = None) -> Optional[dict]:
    """The calibration record for a backend family (``cpu``/``gpu``/
    ``tpu``), or ``None`` when absent or the subsystem is disabled.
    Keys: ``bytes_per_s``/``flops_per_s`` (max observed; either may be
    absent), ``samples``, ``updated`` (epoch), ``run``/``device_kind``
    provenance of the last improving observation."""
    path = path if path is not None else default_path()
    if not path:
        return None
    with _lock:
        entry = _read(path).get(backend)
    return dict(entry) if isinstance(entry, dict) else None


def observe(
    backend: str,
    bytes_per_s: Optional[float] = None,
    flops_per_s: Optional[float] = None,
    run: Optional[str] = None,
    device_kind: Optional[str] = None,
    path: Optional[str] = None,
) -> Optional[dict]:
    """Fold one run's achieved rate(s) into the backend's record
    (max-merge) and persist atomically; returns the updated record, or
    ``None`` when disabled / nothing to record. Emits one
    ``calib:update`` event (``persisted`` says whether a peak actually
    improved)."""
    path = path if path is not None else default_path()
    if not path or (not bytes_per_s and not flops_per_s):
        return None
    with _lock:
        entries = _read(path)
        entry = dict(entries.get(backend) or {})
        improved = False
        for key, val in (("bytes_per_s", bytes_per_s),
                         ("flops_per_s", flops_per_s)):
            if val is None or val <= 0:
                continue
            if float(val) > float(entry.get(key) or 0.0):
                entry[key] = float(val)
                improved = True
        entry["samples"] = int(entry.get("samples") or 0) + 1
        if improved:
            entry["updated"] = time.time()
            if run:
                entry["run"] = run
            if device_kind:
                entry["device_kind"] = device_kind
        entries[backend] = entry
        _write(path, entries)
    from multigpu_advectiondiffusion_tpu import telemetry

    telemetry.event(
        "calib", "update",
        backend=backend,
        bytes_per_s=entry.get("bytes_per_s"),
        flops_per_s=entry.get("flops_per_s"),
        samples=entry["samples"],
        persisted=improved,
        path=path,
    )
    return dict(entry)
