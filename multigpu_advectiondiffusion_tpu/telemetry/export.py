"""Chrome/Perfetto ``trace_event`` export of merged telemetry streams.

Converts the aligned per-process JSONL streams
(:mod:`telemetry.analyze`) into the Trace Event Format JSON that
``ui.perfetto.dev`` and ``chrome://tracing`` open directly — the
upgrade of hand-reading per-rank ``nvprof`` files in the Visual
Profiler (reference ``profile.sh``): one merged timeline where every
rank's spans, rollbacks, probes and counters sit on a shared clock.

Mapping (one JSON object per event, ``ts``/``dur`` in microseconds):

* span begin/end pairs  -> complete events (``ph="X"``) on the
  process's ``spans`` track; spans that never closed (killed rank)
  export as lone ``ph="B"`` begins — visible crash evidence;
* counters              -> ``ph="C"`` counter tracks (running total);
* every other kind      -> ``ph="i"`` instants on the ``events`` track,
  full payload in ``args``;
* per-stream metadata   -> ``ph="M"`` ``process_name``/``thread_name``
  records (``rank<K>``).

:func:`validate_trace` is the schema gate tests (and the exporter
itself) run over the produced object — export never silently emits a
file Perfetto would reject.
"""

from __future__ import annotations

import json
from typing import List, Sequence

from multigpu_advectiondiffusion_tpu.telemetry.analyze import (
    Stream,
    _walk,
    build_spans,
)

TID_SPANS = 1
TID_EVENTS = 2

_PH = {"X", "B", "E", "i", "C", "M"}


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 3)


def to_chrome_trace(streams: Sequence[Stream]) -> dict:
    """Aligned streams -> Trace Event Format object (JSON-ready)."""
    events: List[dict] = []
    for s in streams:
        pid = int(s.proc)
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"rank{pid}"},
        })
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid,
            "tid": TID_SPANS, "args": {"name": "spans"},
        })
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid,
            "tid": TID_EVENTS, "args": {"name": "events"},
        })
        for span in _walk(build_spans(s)):
            base = {
                "name": span.name,
                "cat": "span",
                "pid": pid,
                "tid": TID_SPANS,
                "ts": _us(s.offset + span.t0),
                "args": dict(span.fields),
            }
            if span.t1 is None:
                base["ph"] = "B"  # never closed: crash evidence
            else:
                base["ph"] = "X"
                base["dur"] = _us(span.t1 - span.t0)
            events.append(base)
        for ev in s.events:
            kind, name = ev.get("kind"), ev.get("name")
            ts = _us(s.gt(ev))
            if kind == "span":
                continue  # handled above
            if kind == "counter":
                events.append({
                    "ph": "C",
                    "name": name,
                    "cat": "counter",
                    "pid": pid,
                    "tid": 0,
                    "ts": ts,
                    "args": {"value": ev.get("total", 0)},
                })
                continue
            args = {k: v for k, v in ev.items()
                    if k not in ("t", "proc", "kind", "name")}
            events.append({
                "ph": "i",
                "s": "t",
                "name": f"{kind}:{name}",
                "cat": kind,
                "pid": pid,
                "tid": TID_EVENTS,
                "ts": ts,
                "args": args,
            })
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "tpucfd-trace",
            "streams": [s.path for s in streams],
        },
    }


def validate_trace(obj) -> List[str]:
    """Schema problems in a trace_event object (empty list = valid):
    the structural contract Perfetto's JSON importer requires."""
    problems: List[str] = []
    if not isinstance(obj, dict) or not isinstance(
        obj.get("traceEvents"), list
    ):
        return ["top level must be a dict with a traceEvents list"]
    for i, ev in enumerate(obj["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PH:
            problems.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str):
            problems.append(f"{where}: missing name")
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: missing numeric ts")
            if not isinstance(ev.get("pid"), int):
                problems.append(f"{where}: missing integer pid")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"{where}: X event missing dur")
        if ph == "X" and isinstance(ev.get("dur"), (int, float)) \
                and ev["dur"] < 0:
            problems.append(f"{where}: negative dur")
        if ph == "C" and not isinstance(ev.get("args"), dict):
            problems.append(f"{where}: C event missing args")
        if "args" in ev:
            try:
                json.dumps(ev["args"])
            except (TypeError, ValueError):
                problems.append(f"{where}: args not JSON-serializable")
    return problems


def write_chrome_trace(path: str, streams: Sequence[Stream]) -> dict:
    """Export ``streams`` to ``path`` as validated trace_event JSON;
    raises ``ValueError`` (listing the problems) rather than writing a
    file Perfetto would reject. Returns the exported object."""
    obj = to_chrome_trace(streams)
    problems = validate_trace(obj)
    if problems:
        raise ValueError(
            "refusing to write invalid trace_event JSON: "
            + "; ".join(problems[:5])
        )
    from multigpu_advectiondiffusion_tpu.utils.io import atomic_write_text

    # atomic publish: Perfetto must never load a half-written trace
    atomic_write_text(path, json.dumps(obj))
    return obj
