"""Event-schema registry: the single source of truth for what the
telemetry stream may contain.

Every event kind (and, where the set is closed, every event name) the
instrumented layers emit is registered here with its required fields.
Two consumers:

* :func:`validate_event` — structural validation of a live/loaded
  event (the analyzer and tests run it over real streams);
* :func:`scan_emitted` — a *static* scan of the package source for
  ``telemetry.event(<kind>, <name>, ...)`` / ``sink.event(...)`` /
  ``.counter(<name>, ...)`` call sites, so a tier-1 test
  (tests/test_schema.py) fails the moment someone emits a kind or name
  this registry (or README's event table) does not know — the guard
  against silent schema drift.

``None`` as a name key is the wildcard: the kind carries open-ended
names (``summary`` events are named after the run, ``crash`` events
after the exception type, ``tune`` names arrive via a variable).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Set, Tuple

# kind -> name -> required fields (beyond the sink's own t/proc/kind/
# name envelope). name None = wildcard for that kind.
EVENT_REGISTRY: Dict[str, Dict[Optional[str], Set[str]]] = {
    "meta": {"open": {"schema", "wall_time"}},
    "sink": {"rotate": {"schema", "wall_time", "previous",
                        "rotated_bytes"}},
    "span": {None: {"phase", "id", "depth"}},
    "counter": {None: {"inc", "total"}},
    "dispatch": {
        "build": {"key", "impl"},
        # hung-dispatch watchdog (ISSUE 20): a slice blew its
        # wall-clock budget — the batch is evacuated from its last
        # slice checkpoints and bisected until the poison member is
        # isolated and quarantined
        "hung": {"batch", "slice", "elapsed_s", "budget_s", "jobs"},
    },
    # solver-plugin registry (models/registry.py, ISSUE 15): CLI
    # --model resolution through the registry — one event per resolved
    # run naming the family and the generated subcommand
    "model": {"resolve": {"model", "ndim", "command"}},
    "ladder": {"degrade": {"from", "to", "reason"}},
    "physics": {"probe": {"step", "time"}},
    # in-situ physics diagnostics (diagnostics/physics.py via the
    # supervisor's --diag-every cadence): the fused observable suite
    # and tolerance-rule breaches
    "phys": {
        "diag": {"step", "time", "solver"},
        "violation": {"step", "time", "rule", "message", "tolerance"},
    },
    # the science regression gate's verdict (diagnostics/compare.py)
    "science": {"gate": {"ok", "regressions", "rows"}},
    # low-precision storage rung (models/base._validate_precision,
    # ISSUE 16): one event per solver constructed with
    # precision='bf16' — records the storage/compute dtype split and
    # whether the generic loop's compensation carry is armed
    # (core.dtypes.bf16_carry_enabled), so a carry-off run is visible
    # in the stream, not just in its error norms
    "precision": {
        "engage": {"storage_dtype", "compute_dtype", "carry"},
    },
    "resilience": {
        "sentinel_armed": {"cadence", "growth"},
        "rollback": {"retry", "step", "rollback_to_it", "action"},
        "retries_exhausted": {"step", "retries"},
        "preempt": {"step"},
        "agree": {"tag", "values"},
        "elastic_resume": {"checkpoint", "saved_processes", "processes"},
        # dt-backoff inheritance (--dt-scale): a scheduler-retried job
        # starts at the reduced step its failed attempt backed off to
        "dt_inherit": {"factor", "action"},
    },
    "rank": {
        "watchdog_armed": {"timeout", "interval", "processes"},
        "failure": {"reason", "exit_code"},
    },
    "sdc": {"detect": {"step", "mismatched_cells"}},
    "io": {
        "checkpoint_write": {"path", "bytes", "seconds"},
        "binary_write": {"path", "bytes", "seconds"},
        # SnapshotStreamer publishes (utils/io.py): downsampled field
        # snapshots, atomic + rotation-capped
        "snapshot_write": {"path", "bytes", "seconds", "iteration",
                           "stride"},
    },
    "dist_init": {
        "attempt": {"attempt", "attempts"},
        "retry": {"attempt", "backoff_seconds"},
        "ok": {"attempt"},
        "failed": {"attempts", "error"},
    },
    "sync": {"barrier": {"tag"}},
    # in-kernel remote-DMA halo exchange (ops/pallas/fused_slab_run
    # exchange='dma', recorded by parallel/halo.record_remote_dma): one
    # event per traced run call — the sharded whole-run program moves
    # its ghost rows over ICI from inside the Pallas kernel, so this
    # (plus the halo.dma_bytes_per_execution counter) is the ONLY
    # telemetry trace of that communication
    "halo": {
        "in_kernel": {"kernel", "axis", "depth", "blocks",
                      "bytes_per_execution"},
    },
    "tune": {
        "lookup": set(),
        "candidates": set(),
        "measure": set(),
        "decision": set(),
        "fallback": set(),
        None: set(),
    },
    # batched ensemble engine (models/base.run_ensemble /
    # advance_to_ensemble): one event per batched dispatch, carrying
    # the member count, the inner stepper (vmapped or B-folded), and —
    # since the mesh-scale round — the device placement (devices,
    # member_sharding, mesh), so a batched dispatch that silently fell
    # back to one device is visible in the stream
    "ensemble": {
        "dispatch": {"members", "stepper", "devices", "member_sharding"},
    },
    # persistent AOT executable cache (tuning/aot_cache.py): every
    # lookup is a hit or a (reasoned) miss, every write a store —
    # out/ensemble_gate.sh gates the warm-run hit on these
    "aot_cache": {
        "hit": {"key", "compile_seconds_saved"},
        "miss": {"key", "reason"},
        "store": {"key", "persisted"},
    },
    "progress": {"chunk": {"step", "steps_done", "step_seconds"}},
    "perf": {
        "outlier": {"step", "step_seconds", "median", "threshold"},
        "histogram": {"edges", "counts", "chunks"},
    },
    "summary": {None: {"seconds", "mlups"}},
    # measured introspection (telemetry/xprof.py): per-executable XLA
    # cost/memory capture at dispatch, and the per-run measured-vs-
    # modeled reconciliation
    "xla": {
        "cost": {"key", "flops", "bytes_accessed", "compile_seconds"},
        "measured": {"run", "xla_bytes_per_step", "xla_flops_per_step"},
    },
    # chunk-cadence device-memory watermarks (device.memory_stats or
    # the live-arrays census fallback)
    "mem": {"watermark": {"bytes_in_use", "peak_bytes", "source"}},
    # measured-peak calibration writes (telemetry/calibration.py)
    "calib": {"update": {"backend", "path", "persisted"}},
    # checkify sanitizer trips (analysis/sanitizer.py, --checkify): one
    # event per caught NaN/div0/OOB, before SanitizerError enters the
    # supervisor's rollback path
    "sanitizer": {"trip": {"message", "errors"}},
    # crash-safe multi-run scheduler (service/daemon.py): the daemon's
    # own decisions, streamed to <root>/sched_events.jsonl — recovery
    # replays, admission verdicts (warm/deferred), priority
    # preemptions, classified retries, journal-degradation warnings
    "sched": {
        "start": {"root", "max_concurrent", "device_budget"},
        "recover": {"records", "torn_lines", "jobs", "adopted",
                    "requeued", "completed"},
        "admit": {"job", "granted_devices", "warm"},
        "defer": {"job", "reason"},
        "preempt": {"victim", "for_job", "blocked"},
        "retry": {"job", "attempt", "policy", "dt_scale"},
        "adopt": {"job", "pid"},
        "journal_degraded": {"pending"},
        # hardened spool ingest (service/queue.ingest_spool): a torn or
        # corrupt mailbox entry is quarantined and reported, never fatal
        "spool_skip": {"file", "error"},
        "stop": {"reason", "states"},
    },
    # continuous-batching request server (service/server.py, ISSUE 17):
    # the daemon's own decisions, streamed to <root>/serve_events.jsonl
    # — recovery replays, per-request admission/shed verdicts, batch
    # formation, slice progress (the request timeline's spine), joins,
    # preemptions, member-attributed divergence, spool quarantines
    "serve": {
        "start": {"root", "max_batch", "slice_steps", "queue_bound",
                  "pipeline", "pipeline_depth", "donate",
                  "group_commit_s"},
        "recover": {"records", "torn_lines", "requests", "requeued",
                    "failed", "clean_shutdown"},
        "admit": {"job", "key", "warm"},
        "defer": {"job", "reason"},
        "shed": {"job", "open", "bound", "retry_after_s"},
        "batch": {"batch", "key", "members", "lanes"},
        # pipelined slices (ISSUE 19) additionally carry
        # stall_seconds / overlap_fraction / depth — optional here
        # because the synchronous loop's slices do not
        "slice": {"batch", "slice", "active", "done", "occupancy",
                  "seconds"},
        "join": {"batch", "waiting"},
        "preempt": {"batch", "for_job", "parked"},
        "divergence": {"batch", "jobs"},
        "spool_skip": {"file", "error"},
        "stop": {"reason", "states"},
        # stdlib HTTP ingestion adapter came up (service/http.py)
        "http": {"port"},
    },
    # zero-copy pipelined serving (ISSUE 19, service/server.py): the
    # overlap machinery's own trace — dispatch-ahead depth, the
    # non-blocking publish of finished lanes, every stall the pipeline
    # could not hide, speculative AOT prewarm verdicts, and the
    # per-batch device-idle accounting the bench's device_idle_frac
    # column and the serving perf gate read
    "pipeline": {
        "dispatch": {"batch", "slice", "depth"},
        "publish": {"batch", "slice", "lanes", "wait_seconds"},
        "stall": {"batch", "where", "seconds"},
        "prewarm": {"key", "status", "seconds"},
        "batch_idle": {"batch", "idle_fraction", "busy_seconds",
                       "wall_seconds", "slices"},
    },
    # per-request lifecycle in the server's stream: every journal
    # transition is mirrored as a req:state event so tpucfd-trace can
    # render the request timeline without reading the journal.
    # req:done/req:failed additionally carry deadline_s (optional —
    # only when the request declared one) so the metrics replay
    # adapter and offline SLO evaluation see the same verdicts the
    # live SloTracker saw
    "req": {
        "submit": {"job", "priority"},
        "state": {"job", "from", "to"},
        "done": {"job", "seconds", "slices"},
        "failed": {"job", "reason"},
        # deadline enforcement (ISSUE 20): a past-deadline request
        # cancelled at a slice boundary (its lane frozen, the rest of
        # the batch unperturbed); suppressed under --best-effort
        "deadline_cancel": {"job", "deadline_s", "elapsed_s"},
    },
    # single-writer lease (service/lease.py, ISSUE 20): exactly one
    # daemon per service root — acquisition (takeover=True when a
    # stale lease from a dead holder was reclaimed), the takeover's
    # forensics, and the release on clean shutdown/drain
    "lease": {
        "acquire": {"pid", "path", "takeover"},
        "takeover": {"pid", "prev_pid", "age_s"},
        "release": {"pid"},
    },
    # graceful drain & handover (ISSUE 20): admission stops, the
    # in-flight batch parks at its next slice boundary, the journal
    # gets the clean-shutdown marker, the lease releases — the
    # successor starts with zero replay-recovery work
    "drain": {
        "start": {"reason", "open"},
        "parked": {"batch", "members"},
        "done": {"clean", "open"},
    },
    # journal schema migration (service/journal.migrate_journal via
    # the ``migrate`` CLI verb, ISSUE 20)
    "journal": {
        "migrate": {"path", "migrated", "from_schema", "schema",
                    "records"},
    },
    # per-job lifecycle in the scheduler's stream, namespaced by job
    # id: every journal transition is mirrored as a job:state event so
    # tpucfd-trace can render the queue timeline without reading the
    # journal
    "job": {
        "submit": {"job", "priority"},
        "state": {"job", "from", "to"},
        "start": {"job", "attempt"},
        "exit": {"job", "rc", "seconds"},
    },
    # fleet metrics (telemetry/metrics.py, ISSUE 18): periodic atomic
    # registry snapshots (JSON + Prometheus text under a per-process
    # snapshot dir) and the server's read-only /metrics HTTP endpoint
    "metrics": {
        "snapshot": {"dir", "counters", "gauges", "histograms"},
        "serve": {"port"},
    },
    # SLO burn-rate engine (telemetry/metrics.SloTracker): multi-window
    # deadline-SLO evaluation over req:done/req:failed verdicts — an
    # alert on crossing a window's burn-rate threshold, a resolve when
    # every window clears; the request server also journals both as
    # note records so they survive the process
    "slo": {
        "alert": {"slo", "objective", "window_s", "burn_rate",
                  "threshold", "bad", "total"},
        "resolve": {"slo", "objective", "burn_rate"},
    },
    # tpucfd-status dashboard (cli/status.py): one event per rendered
    # frame when the status verb itself runs with --metrics
    "status": {"render": {"root", "requests", "jobs"}},
    "crash": {None: {"message"}},
}


def validate_event(ev: dict) -> List[str]:
    """Structural problems with one event dict (empty list = valid)."""
    problems = []
    for key in ("t", "proc", "kind", "name"):
        if key not in ev:
            problems.append(f"missing envelope field {key!r}")
    kind = ev.get("kind")
    if kind not in EVENT_REGISTRY:
        problems.append(f"unregistered kind {kind!r}")
        return problems
    names = EVENT_REGISTRY[kind]
    name = ev.get("name")
    if name in names:
        required = names[name]
    elif None in names:
        required = names[None]
    else:
        problems.append(f"unregistered name {name!r} for kind {kind!r}")
        return problems
    for field in required:
        if field not in ev:
            problems.append(f"{kind}:{name} missing field {field!r}")
    return problems


# Counter names the instrumented layers emit (halo.py).
COUNTER_NAMES: Set[str] = {
    "halo.exchanges_traced",
    "halo.bytes_per_execution",
    # in-kernel remote-DMA bytes (halo.record_remote_dma): the dma
    # rung's ICI payload per compiled execution, blocks folded in
    "halo.dma_bytes_per_execution",
    # fleet-metrics monotonic counters (telemetry/metrics.py, ISSUE
    # 18): the MetricsRegistry vocabulary the serving/scheduler hot
    # paths increment and the replay adapter re-derives — registered
    # here so the same drift guard covers both emission surfaces
    "serve_requests_received_total",
    "serve_requests_admitted_total",
    "serve_requests_done_total",
    "serve_requests_failed_total",
    "serve_requests_shed_total",
    "serve_requests_requeued_total",
    "serve_batches_formed_total",
    "serve_slices_total",
    "serve_deadline_met_total",
    "serve_deadline_missed_total",
    "serve_slo_alerts_total",
    "serve_slo_resolves_total",
    # zero-copy pipelined serving (ISSUE 19): dispatch-ahead launches,
    # and the speculative AOT prewarm's attempts/deserialization hits
    "serve_pipeline_dispatches_total",
    "serve_prewarm_total",
    "serve_prewarm_hits_total",
    # operational hardening (ISSUE 20): stale-lease takeovers, batches
    # parked by a graceful drain, hung-dispatch declarations, and
    # deadline cancellations at slice boundaries
    "serve_lease_takeovers_total",
    "serve_drain_parked_total",
    "serve_dispatch_hung_total",
    "serve_deadline_cancelled_total",
    "sched_jobs_submitted_total",
    "sched_jobs_admitted_total",
    "sched_job_exits_total",
    "sched_retries_total",
    "sched_preemptions_total",
}

def scan_emitted(
    root: Optional[str] = None,
) -> Tuple[Set[Tuple[str, Optional[str]]], Set[str]]:
    """Statically scan the package source for emission sites. Returns
    ``(event_pairs, counter_names)`` where each pair is
    ``(kind, name-or-None)`` — name ``None`` when the call site passes
    a variable. Test files are out of scope (they emit arbitrary
    events on purpose).

    Implemented on the shared AST rule engine
    (``analysis/rules.scan_emission_sites`` — the generalization of the
    regex scanner that used to live here): same contract, and the same
    extraction the ``unregistered-emission`` lint rule runs per module,
    so the tier-1 schema test and ``tpucfd-check`` cannot disagree
    about what counts as an emission site."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from multigpu_advectiondiffusion_tpu.analysis.rules import (
        scan_emission_sites,
    )

    return scan_emission_sites(root)


def registered(kind: str, name: Optional[str]) -> bool:
    """True when the (kind, name) pair — name possibly unknown — is
    covered by the registry."""
    names = EVENT_REGISTRY.get(kind)
    if names is None:
        return False
    if name is None:
        return True  # dynamic name: the kind itself is the contract
    return name in names or None in names
