"""Fleet metrics: process-local registry, exact cross-process merge,
snapshots, replay, and SLO burn-rate alerting (ISSUE 18).

The observability ladder so far instruments individual *runs* (events,
traces, XLA introspection, physics diagnostics); the scheduler (PR 14)
and the request server (PR 17) made this a long-lived *service* with
no aggregated surface an operator can watch. This module is that
surface:

* **Instruments** — monotonic :class:`Counter`, :class:`Gauge` (last
  value + running max), and :class:`Histogram` over FIXED
  log-boundary buckets. Fixed boundaries are the load-bearing design
  decision: every process buckets into the same edges
  (:data:`LOG_BUCKET_BOUNDS`), so merging two histograms is an
  elementwise integer add — EXACT, associative, order-independent —
  where merging two t-digest/sorted-sample summaries is neither.
  The price is quantile resolution: a quantile estimate is log-linear
  interpolation inside its bucket, so the worst-case relative error
  is one bucket's width, ``BUCKETS_PER_DECADE``-th root of 10 - 1
  (≈ 29% at the default 9 buckets/decade). Counts, sums, min/max and
  bucket totals stay exact.
* **Registry** (:class:`MetricsRegistry`) — the per-process instrument
  namespace. Fed two ways: first-class calls on the serving/scheduler
  hot paths (``service/server.py``, ``service/daemon.py``), and
  :func:`registry_from_events` — the replay adapter deriving the SAME
  instruments from any ``--metrics`` JSONL stream, so a historical
  run (or a crashed server's stream) is queryable with one codepath.
  Instrumented counters and replay-derived counters agree exactly-once
  by construction: both count the same emission sites.
* **Snapshots** — :meth:`MetricsRegistry.write_snapshot` publishes the
  registry atomically (``utils/io.atomic_write_text``) as both
  ``metrics.json`` (this module's schema) and ``metrics.prom``
  (Prometheus text exposition, scrapable by anything). A SIGKILL
  between writes leaves the previous snapshot intact — atomic rename
  is the whole point. :func:`merge_snapshot_dirs` unions the per-
  process snapshot directories a fleet leaves behind (one per rank /
  daemon / server incarnation): counters and histograms add exactly,
  gauges take the newest value and the running max.
* **SLO engine** (:class:`SloTracker`) — per-request deadline
  verdicts (``RequestSpec.deadline_s``) feed multi-window burn-rate
  evaluation (the SRE-workbook shape: a fast window catches a cliff,
  a slow window catches a smolder). Crossing a window's threshold
  yields an ``slo:alert``; clearing every window yields
  ``slo:resolve``. The request server emits these as registered
  events AND journals them, so an alert survives the process.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

METRICS_SCHEMA = 1

# --------------------------------------------------------------------- #
# Fixed log-boundary buckets
# --------------------------------------------------------------------- #
#: buckets per decade; the worst-case relative quantile error is
#: 10**(1/BUCKETS_PER_DECADE) - 1 (≈ 0.292 at 9)
BUCKETS_PER_DECADE = 9

#: decade span: 1e-6 .. 1e4 (microseconds to hours, in seconds — also
#: serves dimensionless ratios like occupancy and queue depths)
_LOG10_LO, _LOG10_HI = -6, 4

#: the one canonical boundary vector. Computed from the same integer
#: exponents on every process (same expression, same platform floats),
#: so two processes NEVER disagree about an edge and bucket merges are
#: exact elementwise adds.
LOG_BUCKET_BOUNDS = tuple(
    10.0 ** (k / BUCKETS_PER_DECADE)
    for k in range(_LOG10_LO * BUCKETS_PER_DECADE,
                   _LOG10_HI * BUCKETS_PER_DECADE + 1)
)

#: identifies the boundary vector inside snapshots, so a merge refuses
#: histograms bucketed against a different (incompatible) edge set
#: instead of silently adding misaligned counts
BOUNDS_KEY = (
    f"log{BUCKETS_PER_DECADE}[1e{_LOG10_LO},1e{_LOG10_HI}]"
)

#: documented worst-case relative quantile error of the fixed buckets
QUANTILE_REL_ERROR = 10.0 ** (1.0 / BUCKETS_PER_DECADE) - 1.0


class Counter:
    """Monotonic event count. Merge = add (exact)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += int(n)


class Gauge:
    """Last-observed value plus its running max (the watermark shape:
    queue depth *now* and the deepest it ever got)."""

    __slots__ = ("name", "value", "max")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None
        self.max: Optional[float] = None

    def set(self, v: float) -> None:
        v = float(v)
        self.value = v
        if self.max is None or v > self.max:
            self.max = v


class Histogram:
    """Fixed log-boundary-bucket histogram.

    ``counts[i]`` holds observations with
    ``bounds[i-1] < x <= bounds[i]``; ``counts[0]`` is the underflow
    bucket (``x <= bounds[0]``), ``counts[-1]`` the overflow. Because
    the boundaries are a module constant, :meth:`merge` is an exact
    elementwise add — the property the cross-process snapshot union
    rests on."""

    __slots__ = ("name", "bounds", "counts", "count", "sum",
                 "min", "max")

    def __init__(self, name: str,
                 bounds: Sequence[float] = LOG_BUCKET_BOUNDS):
        self.name = name
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # ------------------------------------------------------------------ #
    def _bucket(self, x: float) -> int:
        """Binary search for the first bound >= x."""
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if x <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, x: float) -> None:
        x = float(x)
        if x != x:  # NaN: refuse silently-poisoned quantiles
            return
        self.counts[self._bucket(x)] += 1
        self.count += 1
        self.sum += x
        if self.min is None or x < self.min:
            self.min = x
        if self.max is None or x > self.max:
            self.max = x

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError(
                f"histogram {self.name}: incompatible bucket bounds"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None
                                      or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None
                                      or other.max > self.max):
            self.max = other.max

    # ------------------------------------------------------------------ #
    def quantile(self, q: float) -> Optional[float]:
        """Quantile estimate by log-linear interpolation inside the
        containing bucket, clamped to the observed ``[min, max]``.
        Worst-case relative error: one bucket's width
        (:data:`QUANTILE_REL_ERROR`); counts/rank selection are exact.
        """
        if self.count == 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        rank = q * (self.count - 1) + 1  # 1-based target rank
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                frac = (rank - seen) / c
                if i == 0:
                    lo, hi = (self.min if self.min is not None
                              else 0.0), self.bounds[0]
                elif i == len(self.bounds):
                    lo = self.bounds[-1]
                    hi = self.max if self.max is not None else lo
                else:
                    lo, hi = self.bounds[i - 1], self.bounds[i]
                lo = max(lo, 1e-300)
                hi = max(hi, lo)
                est = lo * (hi / lo) ** frac if hi > lo else lo
                if self.min is not None:
                    est = max(est, self.min)
                if self.max is not None:
                    est = min(est, self.max)
                return est
            seen += c
        return self.max

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None


# --------------------------------------------------------------------- #
# Registry
# --------------------------------------------------------------------- #
class MetricsRegistry:
    """One process's instrument namespace. All accessors are
    get-or-create, so instrumentation sites never pre-declare."""

    def __init__(self, proc: str = ""):
        self.proc = proc or f"pid{os.getpid()}"
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    # ------------------------------------------------------------------ #
    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name)
        return h

    # ------------------------------------------------------------------ #
    def snapshot(self) -> dict:
        """The registry as one JSON-serializable dict (the snapshot
        file's schema; also what :func:`merge_snapshots` consumes)."""
        return {
            "schema": METRICS_SCHEMA,
            "proc": self.proc,
            "wall_time": round(time.time(), 6),
            "counters": {n: c.value
                         for n, c in sorted(self.counters.items())},
            "gauges": {
                n: {"value": g.value, "max": g.max}
                for n, g in sorted(self.gauges.items())
            },
            "histograms": {
                n: {
                    "bounds_key": BOUNDS_KEY,
                    "counts": list(h.counts),
                    "count": h.count,
                    "sum": h.sum,
                    "min": h.min,
                    "max": h.max,
                }
                for n, h in sorted(self.histograms.items())
                if h.bounds == LOG_BUCKET_BOUNDS
            },
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of the live registry."""
        return snapshot_to_prometheus(self.snapshot())

    def write_snapshot(self, directory: str) -> dict:
        """Atomically publish ``metrics.json`` + ``metrics.prom`` under
        ``directory`` (one directory per process incarnation — the
        merge unions them). Returns the snapshot dict. A crash between
        the two writes leaves BOTH previous files intact (atomic
        rename), so the last published snapshot is always parseable.
        """
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        os.makedirs(directory, exist_ok=True)
        snap = self.snapshot()
        atomic_write_text(
            os.path.join(directory, "metrics.json"),
            json.dumps(snap, sort_keys=True),
        )
        atomic_write_text(
            os.path.join(directory, "metrics.prom"),
            snapshot_to_prometheus(snap),
        )
        return snap


# --------------------------------------------------------------------- #
# Snapshot serialization / merge
# --------------------------------------------------------------------- #
def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return "tpucfd_" + s if not s.startswith("tpucfd_") else s


def _prom_num(v: float) -> str:
    if v != v:
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def snapshot_to_prometheus(snap: dict) -> str:
    """One snapshot dict -> Prometheus text exposition format."""
    lines: List[str] = []
    for name, value in sorted((snap.get("counters") or {}).items()):
        pn = _prom_name(name)
        if not pn.endswith("_total"):
            pn += "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {int(value)}")
    for name, g in sorted((snap.get("gauges") or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        if g.get("value") is not None:
            lines.append(f"{pn} {_prom_num(g['value'])}")
        if g.get("max") is not None:
            lines.append(f"# TYPE {pn}_max gauge")
            lines.append(f"{pn}_max {_prom_num(g['max'])}")
    for name, h in sorted((snap.get("histograms") or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        counts = h.get("counts") or []
        for i, bound in enumerate(LOG_BUCKET_BOUNDS):
            cum += counts[i] if i < len(counts) else 0
            lines.append(
                f'{pn}_bucket{{le="{repr(bound)}"}} {cum}'
            )
        lines.append(f'{pn}_bucket{{le="+Inf"}} {int(h.get("count", 0))}')
        lines.append(f"{pn}_sum {_prom_num(float(h.get('sum', 0.0)))}")
        lines.append(f"{pn}_count {int(h.get('count', 0))}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal parser of the exposition format this module writes:
    ``{sample_name or name{le=...}: value}``. The metrics gate uses it
    to prove a published ``metrics.prom`` actually parses."""
    samples: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, value = parts
        samples[name] = float(value)
    return samples


def snapshot_histogram(snap: dict, name: str) -> Optional[Histogram]:
    """Rehydrate one named histogram out of a snapshot dict (merged or
    single-process) so consumers query quantiles through the one
    shared codepath instead of re-deriving them."""
    h = (snap.get("histograms") or {}).get(name)
    if h is None:
        return None
    if h.get("bounds_key") != BOUNDS_KEY:
        raise ValueError(
            f"histogram {name}: snapshot bucketed against "
            f"{h.get('bounds_key')!r}, this build reads {BOUNDS_KEY!r}"
        )
    hist = Histogram(name)
    counts = [int(c) for c in (h.get("counts") or [])]
    if len(counts) != len(hist.counts):
        raise ValueError(f"histogram {name}: bucket count mismatch")
    hist.counts = counts
    hist.count = int(h.get("count", 0))
    hist.sum = float(h.get("sum", 0.0))
    hist.min = h.get("min")
    hist.max = h.get("max")
    return hist


def load_snapshot(path: str) -> dict:
    """Read one ``metrics.json`` snapshot (raises on a corrupt file —
    the gate's corruption selftest depends on that being loud)."""
    with open(path) as f:
        snap = json.load(f)
    if not isinstance(snap, dict) or "counters" not in snap:
        raise ValueError(f"not a metrics snapshot: {path}")
    return snap


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Union per-process snapshots into one fleet view.

    Counters and histogram buckets ADD (exact — each process counted
    disjoint local events against identical boundaries); gauges take
    the value from the newest snapshot (by ``wall_time``) and the max
    across all of them."""
    merged = MetricsRegistry(proc="merged")
    gauge_wall: Dict[str, float] = {}
    newest = 0.0
    procs = []
    for snap in snaps:
        wall = float(snap.get("wall_time") or 0.0)
        newest = max(newest, wall)
        procs.append(snap.get("proc") or "?")
        for name, value in (snap.get("counters") or {}).items():
            merged.counter(name).inc(int(value))
        for name, g in (snap.get("gauges") or {}).items():
            gauge = merged.gauge(name)
            if g.get("max") is not None:
                if gauge.max is None or g["max"] > gauge.max:
                    gauge.max = float(g["max"])
            if g.get("value") is not None and wall >= gauge_wall.get(
                name, -1.0
            ):
                gauge.value = float(g["value"])
                gauge_wall[name] = wall
        for name, h in (snap.get("histograms") or {}).items():
            if h.get("bounds_key") != BOUNDS_KEY:
                raise ValueError(
                    f"histogram {name}: snapshot bucketed against "
                    f"{h.get('bounds_key')!r}, this build merges "
                    f"{BOUNDS_KEY!r}"
                )
            hist = merged.histogram(name)
            other = Histogram(name)
            other.counts = [int(c) for c in (h.get("counts") or [])]
            if len(other.counts) != len(hist.counts):
                raise ValueError(
                    f"histogram {name}: bucket count mismatch"
                )
            other.count = int(h.get("count", 0))
            other.sum = float(h.get("sum", 0.0))
            other.min = h.get("min")
            other.max = h.get("max")
            hist.merge(other)
    out = merged.snapshot()
    out["wall_time"] = newest
    out["merged_procs"] = sorted(procs)
    return out


def merge_snapshot_dirs(root: str) -> dict:
    """Merge every ``<root>/*/metrics.json`` snapshot (one directory
    per rank/daemon/server incarnation). Corrupt snapshots are skipped
    and reported in the result's ``skipped`` list — a half-written
    file from a dying process must not take down the fleet view."""
    snaps, skipped = [], []
    if os.path.isdir(root):
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name, "metrics.json")
            if not os.path.isfile(path):
                continue
            try:
                snaps.append(load_snapshot(path))
            except (OSError, ValueError) as err:
                skipped.append(
                    f"{path}: {type(err).__name__}: {err}"[:200]
                )
    merged = merge_snapshots(snaps)
    merged["snapshots"] = len(snaps)
    merged["skipped"] = skipped
    return merged


# --------------------------------------------------------------------- #
# Replay adapter: --metrics JSONL stream -> the same registry
# --------------------------------------------------------------------- #
def registry_from_events(events: Iterable[dict],
                         proc: str = "replay") -> MetricsRegistry:
    """Derive the serving/scheduler instruments from an event stream.

    The adapter reads the SAME emission sites the live instruments
    hang off (``req:*`` / ``serve:*`` / ``sched:*`` / ``job:*`` /
    ``summary`` / ``mem:watermark``), so a replay-derived counter and
    an instrumented one agree exactly-once on any stream: both count
    one increment per emitted event. Historical ``--metrics`` files
    become queryable with the fleet's one quantile codepath."""
    reg = MetricsRegistry(proc=proc)
    for ev in events:
        kind, name = ev.get("kind"), ev.get("name")
        if kind == "req":
            if name == "submit":
                reg.counter("serve_requests_received_total").inc()
            elif name == "done":
                reg.counter("serve_requests_done_total").inc()
                if ev.get("seconds") is not None:
                    reg.histogram(
                        "serve_request_latency_seconds"
                    ).observe(float(ev["seconds"]))
                if ev.get("deadline_s") is not None and (
                    ev.get("seconds") is not None
                ):
                    met = float(ev["seconds"]) <= float(
                        ev["deadline_s"]
                    )
                    reg.counter(
                        "serve_deadline_met_total" if met
                        else "serve_deadline_missed_total"
                    ).inc()
            elif name == "failed":
                reg.counter("serve_requests_failed_total").inc()
            elif name == "state" and ev.get("to") == "requeued":
                reg.counter("serve_requests_requeued_total").inc()
            elif name == "deadline_cancel":
                reg.counter("serve_deadline_cancelled_total").inc()
        elif kind == "dispatch":
            if name == "hung":
                reg.counter("serve_dispatch_hung_total").inc()
        elif kind == "lease":
            if name == "takeover":
                reg.counter("serve_lease_takeovers_total").inc()
        elif kind == "drain":
            if name == "parked":
                reg.counter("serve_drain_parked_total").inc()
        elif kind == "serve":
            if name == "admit":
                reg.counter("serve_requests_admitted_total").inc()
            elif name == "shed":
                reg.counter("serve_requests_shed_total").inc()
            elif name == "batch":
                reg.counter("serve_batches_formed_total").inc()
            elif name == "slice":
                reg.counter("serve_slices_total").inc()
                if ev.get("seconds") is not None:
                    reg.histogram("serve_slice_seconds").observe(
                        float(ev["seconds"])
                    )
                if ev.get("occupancy") is not None:
                    reg.histogram("serve_batch_occupancy").observe(
                        float(ev["occupancy"])
                    )
        elif kind == "sched":
            if name == "admit":
                reg.counter("sched_jobs_admitted_total").inc()
            elif name == "retry":
                reg.counter("sched_retries_total").inc()
            elif name == "preempt":
                reg.counter("sched_preemptions_total").inc()
        elif kind == "job":
            if name == "submit":
                reg.counter("sched_jobs_submitted_total").inc()
            elif name == "exit":
                reg.counter("sched_job_exits_total").inc()
                if ev.get("seconds") is not None:
                    reg.histogram("sched_job_seconds").observe(
                        float(ev["seconds"])
                    )
        elif kind == "summary":
            # per-rung MLUPS gauge family from the run summaries that
            # already ride every --metrics stream
            if ev.get("mlups") is not None:
                reg.gauge("run_mlups").set(float(ev["mlups"]))
            if ev.get("seconds") is not None:
                reg.histogram("run_seconds").observe(
                    float(ev["seconds"])
                )
        elif kind == "mem" and name == "watermark":
            if ev.get("bytes_in_use") is not None:
                reg.gauge("mem_bytes_in_use").set(
                    float(ev["bytes_in_use"])
                )
            if ev.get("peak_bytes") is not None:
                reg.gauge("mem_peak_bytes").set(
                    float(ev["peak_bytes"])
                )
        elif kind == "io" and name in (
            "checkpoint_write", "snapshot_write", "binary_write"
        ):
            if ev.get("seconds") is not None:
                reg.histogram("io_write_seconds").observe(
                    float(ev["seconds"])
                )
    return reg


def registry_from_streams(paths: Sequence[str],
                          proc: str = "replay") -> MetricsRegistry:
    """Replay adapter over files/dirs/service roots — the stream
    discovery is :func:`telemetry.analyze.load_streams`' (daemon +
    per-job + server streams, rotated segments riding along)."""
    from multigpu_advectiondiffusion_tpu.telemetry.analyze import (
        load_streams,
    )

    reg = MetricsRegistry(proc=proc)
    for stream in load_streams(paths):
        other = registry_from_events(stream.events, proc=proc)
        for name, c in other.counters.items():
            reg.counter(name).inc(c.value)
        for name, g in other.gauges.items():
            if g.value is not None:
                reg.gauge(name).set(g.value)
            if g.max is not None:
                gg = reg.gauge(name)
                if gg.max is None or g.max > gg.max:
                    gg.max = g.max
        for name, h in other.histograms.items():
            reg.histogram(name).merge(h)
    return reg


# --------------------------------------------------------------------- #
# SLO engine: multi-window burn-rate alerting
# --------------------------------------------------------------------- #
#: default multi-window burn-rate policy (the SRE-workbook pairing,
#: scaled to serving cadence): (window seconds, burn-rate threshold,
#: minimum observations before the window may fire). A short window
#: catches a cliff within seconds; the long window catches a smolder
#: a cliff-sized window would alias away.
DEFAULT_SLO_WINDOWS = (
    (60.0, 14.4, 4),
    (600.0, 6.0, 8),
)


class SloTracker:
    """Deadline-SLO burn-rate evaluation over a sliding observation
    log.

    ``objective`` is the target good fraction (0.99 = 1% error
    budget). Each window's *burn rate* is
    ``(bad/total in window) / (1 - objective)`` — the rate the error
    budget is being spent at, 1.0 = exactly on budget. A window whose
    burn rate crosses its threshold (with at least ``min_count``
    observations, so one early miss cannot page) raises the alert; the
    alert resolves only when EVERY window is back under threshold.
    Alerts/resolves surface through the ``emit`` callback as
    ``slo:alert`` / ``slo:resolve`` payloads."""

    def __init__(self, name: str = "request_deadline",
                 objective: float = 0.99,
                 windows=DEFAULT_SLO_WINDOWS,
                 emit: Optional[Callable[[str, dict], None]] = None):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0,1): {objective}")
        self.name = name
        self.objective = float(objective)
        self.windows = tuple(
            (float(w), float(thr), int(mc)) for w, thr, mc in windows
        )
        self.emit = emit
        self._obs: List[tuple] = []  # (wall, ok) — pruned to max window
        self.firing = False
        self.alerts: List[dict] = []

    # ------------------------------------------------------------------ #
    def observe(self, ok: bool, wall: Optional[float] = None) -> None:
        wall = time.time() if wall is None else float(wall)
        self._obs.append((wall, bool(ok)))
        horizon = wall - max(w for w, _, _ in self.windows)
        while self._obs and self._obs[0][0] < horizon:
            self._obs.pop(0)

    def burn_rates(self, now: Optional[float] = None) -> List[dict]:
        """Per-window burn rates at ``now`` (diagnostics + the
        evaluation's input)."""
        now = time.time() if now is None else float(now)
        budget = 1.0 - self.objective
        out = []
        for window, threshold, min_count in self.windows:
            lo = now - window
            total = bad = 0
            for wall, ok in self._obs:
                if wall >= lo:
                    total += 1
                    if not ok:
                        bad += 1
            rate = ((bad / total) / budget) if total else 0.0
            out.append({
                "window_s": window,
                "threshold": threshold,
                "min_count": min_count,
                "total": total,
                "bad": bad,
                "burn_rate": round(rate, 4),
                "firing": total >= min_count and rate > threshold,
            })
        return out

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Run the multi-window evaluation; returns (and records) the
        alert/resolve payloads this call produced. Hysteresis: one
        alert per excursion, one resolve when every window clears."""
        rates = self.burn_rates(now)
        fired = [r for r in rates if r["firing"]]
        produced: List[dict] = []
        if fired and not self.firing:
            self.firing = True
            worst = max(fired, key=lambda r: r["burn_rate"])
            payload = {
                "slo": self.name,
                "objective": self.objective,
                "window_s": worst["window_s"],
                "burn_rate": worst["burn_rate"],
                "threshold": worst["threshold"],
                "bad": worst["bad"],
                "total": worst["total"],
            }
            self.alerts.append(payload)
            produced.append({"name": "alert", **payload})
            if self.emit is not None:
                self.emit("alert", payload)
        elif self.firing and not fired:
            self.firing = False
            payload = {
                "slo": self.name,
                "objective": self.objective,
                "burn_rate": max(
                    (r["burn_rate"] for r in rates), default=0.0
                ),
            }
            produced.append({"name": "resolve", **payload})
            if self.emit is not None:
                self.emit("resolve", payload)
        return produced


def evaluate_slo_stream(events: Iterable[dict],
                        name: str = "request_deadline",
                        objective: float = 0.99,
                        windows=DEFAULT_SLO_WINDOWS) -> dict:
    """Offline SLO evaluation of a serving event stream: feed every
    deadline-carrying ``req:done`` / ``req:failed`` verdict through
    the SAME tracker the live server runs, evaluating after each
    observation (so an alert fires exactly where it would have live).
    Returns the tracker's verdict: alerts raised, final burn rates."""
    tracker = SloTracker(name=name, objective=objective,
                         windows=windows)
    last_wall = None
    for ev in events:
        kind, evname = ev.get("kind"), ev.get("name")
        if kind != "req" or evname not in ("done", "failed"):
            continue
        if ev.get("deadline_s") is None:
            continue
        wall = ev.get("wall")
        if wall is None:
            # sink events carry monotonic t, not wall; use t as the
            # clock — windows only need relative spacing
            wall = float(ev.get("t", 0.0))
        last_wall = float(wall)
        if evname == "failed":
            ok = False
        else:
            seconds = ev.get("seconds")
            ok = seconds is not None and (
                float(seconds) <= float(ev["deadline_s"])
            )
        tracker.observe(ok, wall=last_wall)
        tracker.evaluate(now=last_wall)
    return {
        "slo": name,
        "objective": objective,
        "alerts": tracker.alerts,
        "firing": tracker.firing,
        "burn_rates": (
            tracker.burn_rates(now=last_wall) if last_wall is not None
            else tracker.burn_rates()
        ),
    }
